"""Node gRPC services (reference rpc/grpc/server/, node/node.go:819-861):

  cometbft.services.version.v1.VersionService/GetVersion
  cometbft.services.block.v1.BlockService/GetByHeight
  cometbft.services.block.v1.BlockService/GetLatestHeight   (server stream)
  cometbft.services.block_results.v1.BlockResultsService/GetBlockResults
  cometbft.services.pruning.v1.PruningService/*             (privileged)

grpcio is in the image but the protoc python plugin is not, so handlers
register generically with hand-written wire codecs (libs/protowire) —
same technique as abci/grpc.py; the bytes match the reference's
generated stubs (proto/cometbft/services/**).
"""

from __future__ import annotations

from concurrent import futures
from dataclasses import dataclass, field

from ..libs import protowire as pw
from .. import version as ver

VERSION_SVC = "cometbft.services.version.v1.VersionService"
BLOCK_SVC = "cometbft.services.block.v1.BlockService"
BLOCK_RESULTS_SVC = "cometbft.services.block_results.v1.BlockResultsService"
PRUNING_SVC = "cometbft.services.pruning.v1.PruningService"


# -- wire messages ----------------------------------------------------------

@dataclass
class Int64Message:
    """Any single-int64-field-1 message (heights)."""
    height: int = 0

    def to_proto(self) -> bytes:
        return pw.Writer().int_field(1, self.height).bytes()

    @staticmethod
    def from_proto(p: bytes) -> "Int64Message":
        r = pw.Reader(p)
        m = Int64Message()
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1 and w == pw.VARINT:
                m.height = r.read_int()
            else:
                r.skip(w)
        return m


@dataclass
class Empty:
    def to_proto(self) -> bytes:
        return b""

    @staticmethod
    def from_proto(p: bytes) -> "Empty":
        return Empty()


@dataclass
class GetByHeightResponse:
    block_id_proto: bytes = b""
    block_proto: bytes = b""

    def to_proto(self) -> bytes:
        return (pw.Writer().message_field(1, self.block_id_proto)
                .message_field(2, self.block_proto).bytes())

    @staticmethod
    def from_proto(p: bytes) -> "GetByHeightResponse":
        r = pw.Reader(p)
        m = GetByHeightResponse()
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1 and w == pw.BYTES:
                m.block_id_proto = r.read_bytes()
            elif f == 2 and w == pw.BYTES:
                m.block_proto = r.read_bytes()
            else:
                r.skip(w)
        return m


@dataclass
class GetBlockResultsResponse:
    height: int = 0
    tx_results: list = field(default_factory=list)        # proto bytes
    finalize_block_events: list = field(default_factory=list)
    validator_updates: list = field(default_factory=list)
    consensus_param_updates: bytes | None = None
    app_hash: bytes = b""

    def to_proto(self) -> bytes:
        w = pw.Writer().int_field(1, self.height)
        for t in self.tx_results:
            w.message_field(2, t)
        for e in self.finalize_block_events:
            w.message_field(3, e)
        for v in self.validator_updates:
            w.message_field(4, v)
        if self.consensus_param_updates is not None:
            w.message_field(5, self.consensus_param_updates)
        w.bytes_field(6, self.app_hash)
        return w.bytes()

    @staticmethod
    def from_proto(p: bytes) -> "GetBlockResultsResponse":
        r = pw.Reader(p)
        m = GetBlockResultsResponse()
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1 and w == pw.VARINT:
                m.height = r.read_int()
            elif f == 2 and w == pw.BYTES:
                m.tx_results.append(r.read_bytes())
            elif f == 3 and w == pw.BYTES:
                m.finalize_block_events.append(r.read_bytes())
            elif f == 4 and w == pw.BYTES:
                m.validator_updates.append(r.read_bytes())
            elif f == 5 and w == pw.BYTES:
                m.consensus_param_updates = r.read_bytes()
            elif f == 6 and w == pw.BYTES:
                m.app_hash = r.read_bytes()
            else:
                r.skip(w)
        return m


@dataclass
class GetVersionResponse:
    node: str = ""
    abci: str = ""
    p2p: int = 0
    block: int = 0

    def to_proto(self) -> bytes:
        return (pw.Writer().string_field(1, self.node)
                .string_field(2, self.abci)
                .uvarint_field(3, self.p2p)
                .uvarint_field(4, self.block).bytes())

    @staticmethod
    def from_proto(p: bytes) -> "GetVersionResponse":
        r = pw.Reader(p)
        m = GetVersionResponse()
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1 and w == pw.BYTES:
                m.node = r.read_string()
            elif f == 2 and w == pw.BYTES:
                m.abci = r.read_string()
            elif f == 3 and w == pw.VARINT:
                m.p2p = r.read_uvarint()
            elif f == 4 and w == pw.VARINT:
                m.block = r.read_uvarint()
            else:
                r.skip(w)
        return m


@dataclass
class UInt64Message:
    """Any single-uint64-field-1 message (retain heights)."""
    height: int = 0

    def to_proto(self) -> bytes:
        return pw.Writer().uvarint_field(1, self.height).bytes()

    @staticmethod
    def from_proto(p: bytes) -> "UInt64Message":
        r = pw.Reader(p)
        m = UInt64Message()
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1 and w == pw.VARINT:
                m.height = r.read_uvarint()
            else:
                r.skip(w)
        return m


@dataclass
class GetBlockRetainHeightResponse:
    app_retain_height: int = 0
    pruning_service_retain_height: int = 0

    def to_proto(self) -> bytes:
        return (pw.Writer().uvarint_field(1, self.app_retain_height)
                .uvarint_field(2, self.pruning_service_retain_height)
                .bytes())

    @staticmethod
    def from_proto(p: bytes) -> "GetBlockRetainHeightResponse":
        r = pw.Reader(p)
        m = GetBlockRetainHeightResponse()
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1 and w == pw.VARINT:
                m.app_retain_height = r.read_uvarint()
            elif f == 2 and w == pw.VARINT:
                m.pruning_service_retain_height = r.read_uvarint()
            else:
                r.skip(w)
        return m


# -- server -----------------------------------------------------------------

class _Handler:
    """One grpc.GenericRpcHandler over a {path: (kind, fn, deser, ser)}
    table; kind is 'unary' or 'stream'."""

    def __init__(self, table):
        self._table = table

    def service(self, hcd):
        import grpc

        entry = self._table.get(hcd.method)
        if entry is None:
            return None
        kind, fn, deser, ser = entry
        if kind == "stream":
            return grpc.unary_stream_rpc_method_handler(
                fn, request_deserializer=deser, response_serializer=ser)
        return grpc.unary_unary_rpc_method_handler(
            fn, request_deserializer=deser, response_serializer=ser)


def _ser(m) -> bytes:
    return m.to_proto()


class NodeGRPCServer:
    """Public node services over one listener (reference
    rpc/grpc/server/server.go Serve)."""

    def __init__(self, env, addr: str, max_workers: int = 8):
        import grpc

        self.env = env
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        table = {
            f"/{VERSION_SVC}/GetVersion":
                ("unary", self._get_version, Empty.from_proto, _ser),
            f"/{BLOCK_SVC}/GetByHeight":
                ("unary", self._get_by_height, Int64Message.from_proto, _ser),
            f"/{BLOCK_SVC}/GetLatestHeight":
                ("stream", self._get_latest_height, Empty.from_proto, _ser),
            f"/{BLOCK_RESULTS_SVC}/GetBlockResults":
                ("unary", self._get_block_results, Int64Message.from_proto,
                 _ser),
        }
        self._server.add_generic_rpc_handlers((_Handler(table),))
        host_port = addr[len("tcp://"):] if addr.startswith("tcp://") else addr
        self.port = self._server.add_insecure_port(host_port)

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=0.5)

    # -- handlers ----------------------------------------------------------

    def _get_version(self, req, ctx):
        return GetVersionResponse(
            node=ver.CMT_SEM_VER, abci=ver.ABCI_SEM_VER,
            p2p=ver.P2P_PROTOCOL, block=ver.BLOCK_PROTOCOL)

    def _get_by_height(self, req, ctx):
        import grpc

        bs = self.env.block_store
        height = req.height or bs.height()
        block = bs.load_block(height)
        if block is None:
            ctx.abort(grpc.StatusCode.NOT_FOUND,
                      f"no block at height {height}")
        meta = bs.load_block_meta(height)
        bid = meta.block_id if meta is not None else None
        return GetByHeightResponse(
            block_id_proto=bid.to_proto() if bid is not None else b"",
            block_proto=block.to_proto())

    def _get_latest_height(self, req, ctx):
        """Long-lived stream of committed heights (reference
        rpc/grpc/server/services/blockservice GetLatestHeight)."""
        from ..types import events as ev

        bus = self.env.event_bus
        subscriber = "grpc-latest-height-%d" % id(ctx)
        query = ev.query_for_event(ev.EVENT_NEW_BLOCK)
        sub = bus.subscribe(subscriber, query) if bus is not None else None
        try:
            yield Int64Message(self.env.block_store.height())
            while sub is not None and ctx.is_active() and \
                    not sub.canceled.is_set():
                msg = sub.next(timeout=0.25)
                if msg is None:
                    continue
                yield Int64Message(msg.data.block.header.height)
        finally:
            if sub is not None and bus is not None:
                bus.unsubscribe(subscriber, query)

    def _get_block_results(self, req, ctx):
        import grpc

        from ..abci import types as at

        env = self.env
        height = req.height or env.block_store.height()
        if height < env.block_store.base() or \
                height > env.block_store.height():
            ctx.abort(grpc.StatusCode.INVALID_ARGUMENT,
                      f"height {height} is not available")
        raw = env.state_store.load_finalize_block_response(height)
        if raw is None:
            ctx.abort(grpc.StatusCode.NOT_FOUND,
                      f"no results for height {height}")
        resp = at.FinalizeBlockResponse.from_proto(raw)
        return GetBlockResultsResponse(
            height=height,
            tx_results=[t.to_proto() for t in resp.tx_results],
            finalize_block_events=[e.to_proto() for e in resp.events],
            validator_updates=[v.to_proto() for v in resp.validator_updates],
            consensus_param_updates=resp.consensus_param_updates,
            app_hash=resp.app_hash)


class PrivilegedGRPCServer:
    """Data-companion pruning service on its OWN listener (reference
    node/node.go:846-861 separates the privileged listener)."""

    def __init__(self, env, addr: str, max_workers: int = 4):
        import grpc

        self.env = env
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        u = Int64Message  # noqa: F841
        t = {}
        for name, fn in [
            ("SetBlockRetainHeight", self._set_block_retain),
            ("GetBlockRetainHeight", self._get_block_retain),
            ("SetBlockResultsRetainHeight", self._set_results_retain),
            ("GetBlockResultsRetainHeight", self._get_results_retain),
            ("SetTxIndexerRetainHeight", self._set_tx_indexer_retain),
            ("GetTxIndexerRetainHeight", self._get_tx_indexer_retain),
            ("SetBlockIndexerRetainHeight", self._set_block_indexer_retain),
            ("GetBlockIndexerRetainHeight", self._get_block_indexer_retain),
        ]:
            deser = (UInt64Message.from_proto if name.startswith("Set")
                     else Empty.from_proto)
            t[f"/{PRUNING_SVC}/{name}"] = ("unary", fn, deser, _ser)
        self._server.add_generic_rpc_handlers((_Handler(t),))
        host_port = addr[len("tcp://"):] if addr.startswith("tcp://") else addr
        self.port = self._server.add_insecure_port(host_port)

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=0.5)

    def _pruner(self, ctx):
        import grpc

        p = self.env.pruner
        if p is None:
            ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                      "pruning service unavailable")
        return p

    def _set_block_retain(self, req, ctx):
        import grpc

        p = self._pruner(ctx)
        h = req.height
        if h <= 0 or h > self.env.block_store.height() + 1:
            ctx.abort(grpc.StatusCode.INVALID_ARGUMENT,
                      f"height must be in [1, chain height], got {h}")
        if not p.set_companion_block_retain_height(h):
            ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                      "cannot lower the companion retain height")
        return Empty()

    def _get_block_retain(self, req, ctx):
        p = self._pruner(ctx)
        return GetBlockRetainHeightResponse(
            app_retain_height=p.application_block_retain_height(),
            pruning_service_retain_height=p.companion_block_retain_height())

    def _set_results_retain(self, req, ctx):
        import grpc

        p = self._pruner(ctx)
        h = req.height
        if h <= 0 or h > self.env.block_store.height() + 1:
            ctx.abort(grpc.StatusCode.INVALID_ARGUMENT,
                      f"height must be in [1, chain height], got {h}")
        if not p.set_abci_res_retain_height(h):
            ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                      "cannot lower the block-results retain height")
        return Empty()

    def _get_results_retain(self, req, ctx):
        p = self._pruner(ctx)
        return UInt64Message(p.abci_res_retain_height())

    def _set_tx_indexer_retain(self, req, ctx):
        import grpc

        p = self._pruner(ctx)
        if not p.set_tx_indexer_retain_height(req.height):
            ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                      "cannot lower the tx-indexer retain height")
        return Empty()

    def _get_tx_indexer_retain(self, req, ctx):
        p = self._pruner(ctx)
        return UInt64Message(p.tx_indexer_retain_height())

    def _set_block_indexer_retain(self, req, ctx):
        import grpc

        p = self._pruner(ctx)
        if not p.set_block_indexer_retain_height(req.height):
            ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                      "cannot lower the block-indexer retain height")
        return Empty()

    def _get_block_indexer_retain(self, req, ctx):
        p = self._pruner(ctx)
        return UInt64Message(p.block_indexer_retain_height())


# -- typed client (tests, tooling) ------------------------------------------

class GRPCNodeClient:
    """Minimal typed client over the public + privileged services
    (reference rpc/grpc/client)."""

    def __init__(self, addr: str, timeout: float = 5.0):
        import grpc

        host_port = addr[len("tcp://"):] if addr.startswith("tcp://") else addr
        self._channel = grpc.insecure_channel(host_port)
        self.timeout = timeout

    def close(self) -> None:
        self._channel.close()

    def _unary(self, path, resp_cls):
        return self._channel.unary_unary(
            path, request_serializer=_ser,
            response_deserializer=resp_cls.from_proto)

    def get_version(self) -> GetVersionResponse:
        return self._unary(f"/{VERSION_SVC}/GetVersion",
                           GetVersionResponse)(Empty(), timeout=self.timeout)

    def get_block_by_height(self, height: int = 0) -> GetByHeightResponse:
        return self._unary(f"/{BLOCK_SVC}/GetByHeight", GetByHeightResponse)(
            Int64Message(height), timeout=self.timeout)

    def get_latest_height_stream(self):
        call = self._channel.unary_stream(
            f"/{BLOCK_SVC}/GetLatestHeight", request_serializer=_ser,
            response_deserializer=Int64Message.from_proto)
        return call(Empty())

    def get_block_results(self, height: int = 0) -> GetBlockResultsResponse:
        return self._unary(f"/{BLOCK_RESULTS_SVC}/GetBlockResults",
                           GetBlockResultsResponse)(
            Int64Message(height), timeout=self.timeout)

    # privileged
    def set_block_retain_height(self, h: int) -> None:
        self._unary(f"/{PRUNING_SVC}/SetBlockRetainHeight", Empty)(
            UInt64Message(h), timeout=self.timeout)

    def get_block_retain_height(self) -> GetBlockRetainHeightResponse:
        return self._unary(f"/{PRUNING_SVC}/GetBlockRetainHeight",
                           GetBlockRetainHeightResponse)(
            Empty(), timeout=self.timeout)

    def set_block_results_retain_height(self, h: int) -> None:
        self._unary(f"/{PRUNING_SVC}/SetBlockResultsRetainHeight", Empty)(
            UInt64Message(h), timeout=self.timeout)

    def get_block_results_retain_height(self) -> UInt64Message:
        return self._unary(f"/{PRUNING_SVC}/GetBlockResultsRetainHeight",
                           UInt64Message)(Empty(), timeout=self.timeout)

    def set_tx_indexer_retain_height(self, h: int) -> None:
        self._unary(f"/{PRUNING_SVC}/SetTxIndexerRetainHeight", Empty)(
            UInt64Message(h), timeout=self.timeout)

    def get_tx_indexer_retain_height(self) -> UInt64Message:
        return self._unary(f"/{PRUNING_SVC}/GetTxIndexerRetainHeight",
                           UInt64Message)(Empty(), timeout=self.timeout)

    def set_block_indexer_retain_height(self, h: int) -> None:
        self._unary(f"/{PRUNING_SVC}/SetBlockIndexerRetainHeight", Empty)(
            UInt64Message(h), timeout=self.timeout)

    def get_block_indexer_retain_height(self) -> UInt64Message:
        return self._unary(f"/{PRUNING_SVC}/GetBlockIndexerRetainHeight",
                           UInt64Message)(Empty(), timeout=self.timeout)
