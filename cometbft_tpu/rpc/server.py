"""JSON-RPC 2.0 over HTTP (reference rpc/jsonrpc/server/).

Accepts POST / with a JSON-RPC envelope and GET /<method>?arg=...
URI-style calls, like the reference's http_json_handler + uri handler.
The handler factory is shared with the light proxy (which serves a
different route table and no websocket)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse

from .core import ROUTES, Environment, RPCError

MAX_BODY_BYTES = 1_000_000


class RPCServer:
    def __init__(self, env: Environment, addr: str, routes=None,
                 with_websocket: bool = True):
        routes = ROUTES if routes is None else routes
        host, _, port = addr.rpartition(":")
        self._env = env

        def dispatch(method: str, params: dict, req_id) -> dict:
            attr = routes.get(method)
            if attr is None:
                return _err(req_id, -32601, f"method {method} not found")
            return _call_target(getattr(env, attr), params, req_id)

        self._httpd = ThreadingHTTPServer(
            (host or "127.0.0.1", int(port)),
            make_json_handler(dispatch, sorted(routes),
                              env=env if with_websocket else None))
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None
        self.bound_addr = "%s:%d" % self._httpd.server_address

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="rpc-http",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()
        # the lazily-created serving plane (rpc/core.py _lightserve)
        # owns a flusher thread; close is idempotent, so the public
        # and privileged servers sharing one Environment both calling
        # it is fine
        ls = getattr(self._env, "lightserve", None)
        if ls is not None:
            try:
                ls.close()
            except Exception:
                pass


def _err(req_id, code: int, message: str, data: str = "") -> dict:
    e = {"code": code, "message": message}
    if data:
        e["data"] = data
    return {"jsonrpc": "2.0", "id": req_id, "error": e}


def _coerce_params(params: dict) -> dict:
    """URI params arrive as strings; strip surrounding quotes the way
    the reference's uri handler tolerates."""
    out = {}
    for k, v in params.items():
        if isinstance(v, str) and len(v) >= 2 and \
                v[0] == '"' and v[-1] == '"':
            v = v[1:-1]
        out[k] = v
    return out


def _call_target(fn, params: dict, req_id) -> dict:
    """Invoke one handler with JSON-RPC error mapping."""
    try:
        return {"jsonrpc": "2.0", "id": req_id,
                "result": fn(**_coerce_params(params))}
    except RPCError as e:
        return _err(req_id, e.code, e.message, e.data)
    except TypeError as e:
        return _err(req_id, -32602, f"invalid params: {e}")
    except Exception as e:
        return _err(req_id, -32603, str(e))


def make_json_handler(dispatch, route_names, env=None):
    """HTTP handler over a `dispatch(method, params, id) -> response`
    function.  `env` (when given) enables the /websocket upgrade for
    event subscriptions; the light proxy passes env=None."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args) -> None:
            pass  # quiet

        # -- helpers -------------------------------------------------------
        def _reply(self, status: int, payload) -> None:
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _call(self, method: str, params: dict, req_id) -> dict:
            return dispatch(method, params, req_id)

        # -- JSON-RPC over POST -------------------------------------------
        def _call_envelope(self, req) -> dict:
            """One envelope -> one response; malformed shapes get
            -32600 instead of dropping the connection (the reference's
            jsonrpc server maps every decode failure to an error
            response, rpc/jsonrpc/server/http_json_handler.go)."""
            if not isinstance(req, dict):
                return _err(None, -32600,
                            f"invalid request: expected object, got "
                            f"{type(req).__name__}")
            method = req.get("method", "")
            if not isinstance(method, str):
                return _err(req.get("id"), -32600,
                            "invalid request: method must be a string")
            params = req.get("params") or {}
            if not isinstance(params, dict):
                return _err(req.get("id"), -32602,
                            "invalid params: expected object")
            return self._call(method, params, req.get("id"))

        def do_POST(self) -> None:  # noqa: N802
            try:
                length = int(self.headers.get("Content-Length", "0"))
            except ValueError:
                self._reply(400, _err(None, -32700,
                                      "invalid Content-Length"))
                return
            if length > MAX_BODY_BYTES:
                self._reply(413, {"error": "body too large"})
                return
            if length < 0:
                self._reply(400, _err(None, -32700,
                                      "invalid Content-Length"))
                return
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
            except (json.JSONDecodeError, UnicodeDecodeError):
                self._reply(400, _err(None, -32700, "parse error"))
                return
            if isinstance(req, list):  # batch
                if not req:            # JSON-RPC 2.0 §6: empty batch
                    self._reply(200, _err(None, -32600, "empty batch"))
                    return
                resp = [self._call_envelope(r) for r in req]
            else:
                resp = self._call_envelope(req)
            self._reply(200, resp)

        # -- WebSocket upgrade (reference ws_handler.go) -------------------
        def _do_websocket(self) -> None:
            from . import websocket as ws

            key = self.headers.get("Sec-WebSocket-Key", "")
            if not key:
                self._reply(400, {"error": "missing Sec-WebSocket-Key"})
                return
            self.send_response(101, "Switching Protocols")
            self.send_header("Upgrade", "websocket")
            self.send_header("Connection", "Upgrade")
            self.send_header("Sec-WebSocket-Accept", ws.accept_key(key))
            self.end_headers()
            self.close_connection = True
            session = ws.WSSession(
                env, self.rfile, self.wfile,
                "%s:%d" % self.client_address[:2], self._call)
            session.run()

        # -- URI-style GET -------------------------------------------------
        def do_GET(self) -> None:  # noqa: N802
            parsed = urlparse(self.path)
            method = parsed.path.strip("/")
            if env is not None and method == "websocket" and \
                    "upgrade" in self.headers.get("Connection", "").lower():
                self._do_websocket()
                return
            if method == "":
                # route listing (reference serves an HTML index)
                self._reply(200, {"jsonrpc": "2.0", "id": -1,
                                  "result": {"routes": list(route_names)}})
                return
            params = dict(parse_qsl(parsed.query))
            self._reply(200, self._call(method, params, -1))

    return Handler
