"""Minimal RFC 6455 WebSocket endpoint for JSON-RPC subscriptions
(reference rpc/jsonrpc/server/ws_handler.go + rpc/core/events.go).

The /websocket endpoint accepts the standard JSON-RPC routes plus
subscribe/unsubscribe/unsubscribe_all.  Event notifications are sent as
JSON-RPC responses carrying the ORIGINAL subscribe request id, the
reference's wire behavior (ws_handler.go sends rpctypes.RPCResponse
with the subscription's id for every event).

No external websocket dependency: the handshake (SHA-1 accept key) and
text/close/ping frames are implemented here — the server side of the
protocol is ~100 lines.
"""

from __future__ import annotations

import base64
import hashlib
import json
import struct
import threading


from ..libs import lockrank
from ..libs import pubsub
from ..types import events as ev
from . import serialize as ser

_GUID = b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

# opcodes
TEXT, CLOSE, PING, PONG = 0x1, 0x8, 0x9, 0xA


def accept_key(client_key: str) -> str:
    digest = hashlib.sha1(client_key.encode() + _GUID).digest()
    return base64.b64encode(digest).decode()


def write_frame(sock_lock, wfile, opcode: int, payload: bytes) -> None:
    """Server frames are unmasked."""
    head = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        head += bytes([n])
    elif n < (1 << 16):
        head += bytes([126]) + struct.pack(">H", n)
    else:
        head += bytes([127]) + struct.pack(">Q", n)
    with sock_lock:
        wfile.write(head + payload)
        wfile.flush()


def _read_raw_frame(rfile) -> tuple[bool, int, bytes] | None:
    """One wire frame -> (fin, opcode, payload); None on EOF/oversize."""
    head = rfile.read(2)
    if len(head) < 2:
        return None
    fin_op, mask_len = head
    fin = bool(fin_op & 0x80)
    opcode = fin_op & 0x0F
    masked = mask_len & 0x80
    n = mask_len & 0x7F
    if n == 126:
        n = struct.unpack(">H", rfile.read(2))[0]
    elif n == 127:
        n = struct.unpack(">Q", rfile.read(8))[0]
    if n > 1_000_000:
        return None
    mask = rfile.read(4) if masked else b""
    payload = rfile.read(n)
    if len(payload) < n:
        return None
    if masked:
        payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    return fin, opcode, payload


def read_frame(rfile) -> tuple[int, bytes] | None:
    """One client MESSAGE -> (opcode, payload), reassembling
    fragmented frames (FIN=0 + continuations, RFC 6455 §5.4).  Control
    frames (close/ping/pong) may interleave and are returned as-is."""
    first = _read_raw_frame(rfile)
    if first is None:
        return None
    fin, opcode, payload = first
    if fin:
        return opcode, payload
    parts = [payload]
    while True:
        nxt = _read_raw_frame(rfile)
        if nxt is None:
            return None
        nfin, nop, npay = nxt
        if nop == CLOSE:        # interleaved close ends the message too
            return nop, npay
        if nop & 0x8:           # other control frames: skip mid-message
            continue
        parts.append(npay)
        if nfin:
            return opcode, b"".join(parts)


def event_data_json(data) -> dict:
    """Typed event payload -> {type, value} envelope (libs/json type
    registry analog for the event types RPC clients consume)."""
    if isinstance(data, ev.EventDataTx):
        return {"type": "tendermint/event/Tx", "value": {
            "TxResult": {
                "height": str(data.height),
                "index": data.index,
                "tx": ser.b64(data.tx),
                "result": ser.exec_tx_result_json(data.result)
                if data.result else None,
            }}}
    if isinstance(data, ev.EventDataNewBlock):
        return {"type": "tendermint/event/NewBlock", "value": {
            "block": ser.block_json(data.block) if data.block else None,
            "block_id": ser.block_id_json(data.block_id)
            if data.block_id else None,
        }}
    if isinstance(data, ev.EventDataNewBlockHeader):
        return {"type": "tendermint/event/NewBlockHeader", "value": {
            "header": ser.header_json(data.header)
            if data.header else None}}
    if isinstance(data, ev.EventDataNewBlockEvents):
        return {"type": "tendermint/event/NewBlockEvents", "value": {
            "height": str(data.height),
            "events": [ser.event_json(e) for e in data.events],
            "num_txs": str(data.num_txs)}}
    # round-state style events and anything else: best-effort fields
    value = {}
    for k in ("height", "round", "step"):
        if hasattr(data, k):
            v = getattr(data, k)
            value[k] = str(v) if k == "height" else v
    return {"type": f"tendermint/event/{type(data).__name__}",
            "value": value}


class WSSession:
    """One upgraded connection: routes JSON-RPC, owns subscriptions."""

    def __init__(self, env, rfile, wfile, remote: str, call_fn):
        self.env = env
        self.rfile = rfile
        self.wfile = wfile
        self.subscriber = f"ws-{remote}"
        self._call = call_fn        # (method, params, id) -> response dict
        self._lock = lockrank.RankedLock("rpc.websocket")
        self._subs: dict[str, tuple[pubsub.Query, object]] = {}
        self._closed = threading.Event()

    # -- subscription plumbing --------------------------------------------

    def _send_json(self, payload: dict) -> None:
        try:
            write_frame(self._lock, self.wfile, TEXT,
                        json.dumps(payload).encode())
        except OSError:
            self._closed.set()

    def _pump(self, sub, query_str: str, req_id) -> None:
        while not self._closed.is_set() and not sub.canceled.is_set():
            msg = sub.next(timeout=0.1)
            if msg is None:
                continue
            self._send_json({
                "jsonrpc": "2.0", "id": req_id,
                "result": {
                    "query": query_str,
                    "data": event_data_json(msg.data),
                    "events": msg.events,
                }})

    def _subscribe(self, params: dict, req_id) -> dict:
        qs = str(params.get("query") or "")
        if not qs:
            return _err(req_id, -32602, "query is required")
        try:
            q = pubsub.Query.parse(qs)
        except pubsub.QueryError as e:
            return _err(req_id, -32602, f"invalid query: {e}")
        bus = self.env.event_bus
        if bus is None:
            return _err(req_id, -32603, "event bus unavailable")
        try:
            sub = bus.subscribe(self.subscriber, q, capacity=200)
        except ValueError as e:
            return _err(req_id, -32603, str(e))
        self._subs[qs] = (q, sub)
        threading.Thread(target=self._pump, args=(sub, qs, req_id),
                         daemon=True).start()
        return {"jsonrpc": "2.0", "id": req_id, "result": {}}

    def _unsubscribe(self, params: dict, req_id) -> dict:
        qs = str(params.get("query") or "")
        ent = self._subs.pop(qs, None)
        if ent is None:
            return _err(req_id, -32603, f"not subscribed to {qs!r}")
        try:
            self.env.event_bus.unsubscribe(self.subscriber, ent[0])
        except KeyError:
            pass
        return {"jsonrpc": "2.0", "id": req_id, "result": {}}

    def _unsubscribe_all(self, req_id) -> dict:
        self._subs.clear()
        try:
            self.env.event_bus.unsubscribe_all(self.subscriber)
        except KeyError:
            pass
        return {"jsonrpc": "2.0", "id": req_id, "result": {}}

    # -- main loop ---------------------------------------------------------

    def run(self) -> None:
        try:
            while not self._closed.is_set():
                frame = read_frame(self.rfile)
                if frame is None:
                    break
                opcode, payload = frame
                if opcode == CLOSE:
                    try:
                        write_frame(self._lock, self.wfile, CLOSE, payload[:2])
                    except OSError:
                        pass
                    break
                if opcode == PING:
                    write_frame(self._lock, self.wfile, PONG, payload)
                    continue
                if opcode != TEXT:
                    continue
                try:
                    req = json.loads(payload)
                except json.JSONDecodeError:
                    self._send_json(_err(None, -32700, "parse error"))
                    continue
                method = req.get("method", "")
                params = req.get("params") or {}
                req_id = req.get("id")
                if method == "subscribe":
                    self._send_json(self._subscribe(params, req_id))
                elif method == "unsubscribe":
                    self._send_json(self._unsubscribe(params, req_id))
                elif method == "unsubscribe_all":
                    self._send_json(self._unsubscribe_all(req_id))
                else:
                    self._send_json(self._call(method, params, req_id))
        finally:
            self._closed.set()
            try:
                self.env.event_bus.unsubscribe_all(self.subscriber)
            except Exception:
                pass


def _err(req_id, code: int, message: str) -> dict:
    return {"jsonrpc": "2.0", "id": req_id,
            "error": {"code": code, "message": message}}
