"""RPC route handlers over the node's stores and pools
(reference rpc/core/: env.go, routes.go, blocks.go, mempool.go,
status.go, consensus.go, net.go, abci.go, evidence.go).
"""

from __future__ import annotations

import base64
import threading
from dataclasses import dataclass, field

from ..abci import types as at
from ..libs import lockrank
from ..types import events as ev
from ..types.block import tx_hash
from . import serialize as ser


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(message)
        self.code = code
        self.message = message
        self.data = data


@dataclass
class Environment:
    """rpc/core/env.go Environment: everything the handlers reach."""
    state_store: object = None
    block_store: object = None
    consensus_state: object = None
    mempool: object = None
    evidence_pool: object = None
    p2p_switch: object = None
    event_bus: object = None
    genesis: object = None
    app_conns: object = None
    node_info: object = None
    config: object = None
    tx_indexer: object = None
    block_indexer: object = None
    pruner: object = None
    # Prometheus registry (libs/metrics.py Registry) when the node has
    # instrumentation on — the fleetobs snapshot spools its exposition
    metrics_registry: object = None
    # the light-client serving plane (cometbft_tpu/lightserve/):
    # created lazily on first light_sync/light_status call so every
    # Environment assembly (node, simnet, cmd inspect) serves the
    # routes without wiring changes; owners may also install one
    # eagerly.  RPCServer.stop() closes it.
    lightserve: object = None
    _lightserve_mtx: object = field(
        default_factory=lambda: lockrank.RankedLock("lightserve.session"))
    _subscribers: dict = field(default_factory=dict)

    # -- height helpers ----------------------------------------------------
    def _latest_height(self) -> int:
        return self.block_store.height()

    def _normalize_height(self, height) -> int:
        if height is None or height == "":
            return self._latest_height()
        h = int(height)
        if h <= 0:
            raise RPCError(-32603, f"height must be positive, got {h}")
        base = self.block_store.base()
        if h < base:
            raise RPCError(-32603,
                           f"height {h} below base height {base}")
        if h > self._latest_height():
            raise RPCError(
                -32603, f"height {h} above latest height "
                f"{self._latest_height()}")
        return h

    # -- info --------------------------------------------------------------
    def health(self) -> dict:
        return {}

    def status(self) -> dict:
        """rpc/core/status.go."""
        latest = self._latest_height()
        meta = self.block_store.load_block_meta(latest) \
            if latest > 0 else None
        base = self.block_store.base()
        base_meta = self.block_store.load_block_meta(base) \
            if base > 0 else None
        pv = self.consensus_state.priv_validator_pub_key \
            if self.consensus_state else None
        return {
            "node_info": {
                "protocol_version": {
                    "p2p": str(self.node_info.protocol_version.p2p),
                    "block": str(self.node_info.protocol_version.block),
                    "app": str(self.node_info.protocol_version.app),
                },
                "id": self.node_info.node_id,
                "listen_addr": self.node_info.listen_addr,
                "network": self.node_info.network,
                "version": self.node_info.version,
                "channels": self.node_info.channels.hex(),
                "moniker": self.node_info.moniker,
                "other": {"tx_index": self.node_info.tx_index,
                          "rpc_address": self.node_info.rpc_address},
            },
            "sync_info": {
                "latest_block_hash": ser.hex_upper(
                    meta.block_id.hash) if meta else "",
                "latest_app_hash": ser.hex_upper(
                    meta.header.app_hash) if meta else "",
                "latest_block_height": str(latest),
                "latest_block_time": meta.header.time.rfc3339()
                if meta else "",
                "earliest_block_hash": ser.hex_upper(
                    base_meta.block_id.hash) if base_meta else "",
                "earliest_block_height": str(base),
                "catching_up": False,
            },
            "validator_info": {
                "address": ser.hex_upper(pv.address()) if pv else "",
                "pub_key": {
                    "type": "tendermint/PubKeyEd25519",
                    "value": ser.b64(pv.bytes())} if pv else None,
                "voting_power": "0",
            },
        }

    def net_info(self) -> dict:
        peers = self.p2p_switch.peers.list() if self.p2p_switch else []
        return {
            "listening": True,
            "listeners": [self.p2p_switch.bound_addr or ""]
            if self.p2p_switch else [],
            "n_peers": str(len(peers)),
            "peers": [{
                "node_info": {"id": p.node_info.node_id,
                              "moniker": p.node_info.moniker},
                "is_outbound": p.outbound,
                "remote_ip": p.socket_addr,
            } for p in peers],
        }

    def genesis_(self) -> dict:
        import json
        return {"genesis": json.loads(self.genesis.to_json())}

    # -- blocks ------------------------------------------------------------
    def block(self, height=None) -> dict:
        h = self._normalize_height(height)
        block = self.block_store.load_block(h)
        meta = self.block_store.load_block_meta(h)
        if block is None or meta is None:
            raise RPCError(-32603, f"block at height {h} not found")
        return {"block_id": ser.block_id_json(meta.block_id),
                "block": ser.block_json(block)}

    def block_by_hash(self, hash=None) -> dict:  # noqa: A002
        raw = base64.b64decode(hash) if hash else b""
        block = self.block_store.load_block_by_hash(raw)
        if block is None:
            return {"block_id": None, "block": None}
        return self.block(block.header.height)

    def header(self, height=None) -> dict:
        h = self._normalize_height(height)
        meta = self.block_store.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"header at height {h} not found")
        return {"header": ser.header_json(meta.header)}

    def commit(self, height=None) -> dict:
        """rpc/core/blocks.go Commit: the canonical commit for a
        height — what light clients verify."""
        h = self._normalize_height(height)
        meta = self.block_store.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"no commit for height {h}")
        if h == self._latest_height():
            commit = self.block_store.load_seen_commit(h)
            canonical = False
        else:
            commit = self.block_store.load_block_commit(h)
            canonical = True
        if commit is None:
            raise RPCError(-32603, f"no commit for height {h}")
        return {
            "signed_header": {
                "header": ser.header_json(meta.header),
                "commit": ser.commit_json(commit),
            },
            "canonical": canonical,
        }

    # -- light-client serving plane (cometbft_tpu/lightserve/) -------------
    def _lightserve(self):
        with self._lightserve_mtx:
            if self.lightserve is None:
                from ..lightserve import LightServeSession

                if self.genesis is not None:
                    chain_id = self.genesis.chain_id
                else:
                    st = self.state_store.load()
                    if st is None:
                        raise RPCError(-32603,
                                       "no state to serve light sync from")
                    chain_id = st.chain_id
                self.lightserve = LightServeSession(
                    self.block_store, self.state_store, chain_id)
            return self.lightserve

    def light_sync(self, trusted_height=None, target_height=None) -> dict:
        """Serve one skipping-sync request: the verified pivot path
        from trusted_height (exclusive) to target_height (inclusive,
        default latest) with each height's light block.  Concurrent
        requests coalesce onto shared verify futures server-side
        (docs/LIGHTSERVE.md)."""
        from ..lightserve import LightServeError

        try:
            return self._lightserve().sync(trusted_height, target_height)
        except LightServeError as e:
            raise RPCError(-32603, str(e))

    def light_status(self) -> dict:
        """Serving-plane counters: coalescing state, verify windows
        and signatures dispatched, planner/payload-cache stats."""
        return self._lightserve().status()

    def blockchain(self, minHeight=None, maxHeight=None) -> dict:
        """rpc/core/blocks.go BlockchainInfo: metas in [min, max]."""
        latest = self._latest_height()
        base = self.block_store.base()
        max_h = int(maxHeight) if maxHeight else latest
        max_h = min(max_h, latest)
        min_h = int(minHeight) if minHeight else max(base, max_h - 19)
        min_h = max(min_h, base)
        if min_h > max_h:
            raise RPCError(-32603,
                           f"min height {min_h} > max height {max_h}")
        metas = []
        for h in range(max_h, min_h - 1, -1):
            m = self.block_store.load_block_meta(h)
            if m is not None:
                metas.append(ser.block_meta_json(m))
        return {"last_height": str(latest), "block_metas": metas}

    def block_results(self, height=None) -> dict:
        h = self._normalize_height(height)
        raw = self.state_store.load_finalize_block_response(h)
        if raw is None:
            raise RPCError(-32603, f"no results for height {h}")
        resp = at.FinalizeBlockResponse.from_proto(raw)
        return {
            "height": str(h),
            "txs_results": [ser.exec_tx_result_json(r)
                            for r in resp.tx_results],
            "finalize_block_events": [ser.event_json(e)
                                      for e in resp.events],
            "validator_updates": [
                {"pub_key_type": v.pub_key_type,
                 "pub_key_bytes": ser.b64(v.pub_key_bytes),
                 "power": str(v.power)}
                for v in resp.validator_updates],
            "app_hash": ser.hex_upper(resp.app_hash),
        }

    def validators(self, height=None, page=None, per_page=None) -> dict:
        h = self._normalize_height(height)
        vals = self.state_store.load_validators(h)
        items = vals.validators
        page_i = int(page) if page else 1
        per = min(int(per_page) if per_page else 30, 100)
        start = (page_i - 1) * per
        sel = items[start:start + per]
        return {
            "block_height": str(h),
            "validators": [ser.validator_json(v) for v in sel],
            "count": str(len(sel)),
            "total": str(len(items)),
        }

    def consensus_params(self, height=None) -> dict:
        h = self._normalize_height(height)
        params = self.state_store.load_consensus_params(h)
        return {
            "block_height": str(h),
            "consensus_params": {
                "block": {"max_bytes": str(params.block.max_bytes),
                          "max_gas": str(params.block.max_gas)},
                "evidence": {
                    "max_age_num_blocks":
                        str(params.evidence.max_age_num_blocks),
                    "max_age_duration":
                        str(params.evidence.max_age_duration_ns),
                    "max_bytes": str(params.evidence.max_bytes)},
                "validator": {
                    "pub_key_types": params.validator.pub_key_types},
            },
        }

    # NOTE: handler names end in _handler because the Environment
    # dataclass FIELD consensus_state shadows any method of that name
    def consensus_state_handler(self) -> dict:
        cs = self.consensus_state
        if cs is None:
            raise RPCError(-32603, "consensus state unavailable")
        with cs._mtx:
            return {"round_state": {
                "height": str(cs.height), "round": cs.round,
                "step": cs.step,
                "proposal": cs.proposal is not None,
                "locked_round": cs.locked_round,
                "valid_round": cs.valid_round,
            }}

    def dump_consensus_state_handler(self) -> dict:
        out = self.consensus_state_handler()
        out["peers"] = [
            {"node_address": p.node_info.node_id}
            for p in (self.p2p_switch.peers.list()
                      if self.p2p_switch else [])]
        rec = getattr(self.consensus_state, "recorder", None)
        if rec is not None:
            out["flight_recorder"] = rec.summary()
        return out

    def flightrec_handler(self, limit=None) -> dict:
        """Dump the consensus flight recorder (libs/flightrec.py): the
        event timeline the round-state snapshot above cannot show.
        `limit` keeps only the newest N events."""
        rec = getattr(self.consensus_state, "recorder", None)
        if rec is None:
            from ..libs import flightrec as _fr
            rec = _fr.recorder()
        if rec is None:
            raise RPCError(-32603, "flight recorder unavailable")
        out = rec.dump()
        if limit:
            n = int(limit)
            if n >= 0:
                out["events"] = out["events"][-n:] if n else []
        return out

    def tracetl_handler(self, limit=None) -> dict:
        """Dump the node's event timeline (libs/tracetl.py): stage
        spans, instants, and the cross-node send/recv context edges.
        `limit` keeps only the newest N events."""
        tl = getattr(self.consensus_state, "timeline", None)
        if tl is None:
            from ..libs import tracetl as _tl
            tl = _tl.timeline()
        if tl is None:
            raise RPCError(-32603, "timeline unavailable")
        out = tl.dump()
        if limit:
            n = int(limit)
            if n >= 0:
                out["events"] = out["events"][-n:] if n else []
        return out

    def devprof_handler(self) -> dict:
        """Dump the device-time accounting plane (libs/devprof.py):
        per-device busy/idle partition with idle-cause attribution,
        occupancy fractions, and the XLA cold-compile ledger."""
        rec = getattr(self.consensus_state, "devprof", None)
        if rec is None:
            from ..libs import devprof as _dp
            rec = _dp.recorder()
        if rec is None:
            raise RPCError(-32603, "devprof recorder unavailable")
        return rec.dump()

    def latency_handler(self, limit=None) -> dict:
        """Dump the per-consumer verify-latency ledger
        (libs/latledger.py): request rows with their exact
        submit->resolve decomposition, per-consumer histograms, and
        the SLO burn state.  `limit` keeps only the newest N rows."""
        rec = getattr(self.consensus_state, "latledger", None)
        if rec is None:
            from ..libs import latledger as _ll
            rec = _ll.recorder()
        if rec is None:
            raise RPCError(-32603, "latency ledger unavailable")
        out = rec.dump()
        if limit:
            n = int(limit)
            if n >= 0:
                out["rows"] = out["rows"][-n:] if n else []
        return out

    def fleetobs_handler(self) -> dict:
        """Combined live telemetry snapshot for the fleet collector
        (cometbft_tpu/fleetobs/collect.py): every observability layer
        this node carries, in one read, plus the clock anchor and
        incarnation id the cross-process merge rebases by.  Layers the
        node did not enable come back null — the collector treats a
        partial snapshot exactly like a partial spool."""
        import os as _os
        import time as _time

        from ..libs import devprof as _dp
        from ..libs import flightrec as _fr
        from ..libs import latledger as _ll
        from ..libs import tracetl as _tl
        cs = self.consensus_state
        rec = getattr(cs, "recorder", None) or _fr.recorder()
        tl = getattr(cs, "timeline", None) or _tl.timeline()
        dp = getattr(cs, "devprof", None) or _dp.recorder()
        ll = getattr(cs, "latledger", None) or _ll.recorder()
        sw = getattr(cs, "telspool", None)
        reg = getattr(self, "metrics_registry", None)
        if rec is None and tl is None and dp is None and ll is None:
            raise RPCError(-32603, "no telemetry layers installed")
        incarnation = sw.incarnation if sw is not None \
            else "%d-live" % _os.getpid()
        return {
            "node": tl.node if tl is not None else "",
            "incarnation": incarnation,
            "clock": {"wall": _time.time(),
                      "perf": _time.perf_counter(),
                      "mono": _time.monotonic()},
            "flightrec": rec.dump() if rec is not None else None,
            "tracetl": tl.dump() if tl is not None else None,
            "devprof": {"snapshot": dp.snapshot(),
                        "counters": [list(s)
                                     for s in dp.counter_samples()]}
            if dp is not None else None,
            "latledger": {"dump": ll.dump(),
                          "counters": [list(s)
                                       for s in ll.counter_samples()]}
            if ll is not None else None,
            "metrics": reg.expose() if reg is not None else None,
            "telspool": sw.stats() if sw is not None else None,
        }

    # -- abci --------------------------------------------------------------
    def abci_info(self) -> dict:
        res = self.app_conns.query.info(at.InfoRequest())
        return {"response": {
            "data": res.data, "version": res.version,
            "app_version": str(res.app_version),
            "last_block_height": str(res.last_block_height),
            "last_block_app_hash": ser.b64(res.last_block_app_hash),
        }}

    def abci_query(self, path="", data="", height=None,
                   prove=False) -> dict:
        raw = bytes.fromhex(data) if data else b""
        res = self.app_conns.query.query(at.QueryRequest(
            data=raw, path=path or "",
            height=int(height) if height else 0,
            prove=bool(prove)))
        return {"response": {
            "code": res.code, "log": res.log, "info": res.info,
            "index": str(res.index),
            "key": ser.b64(res.key) if res.key else None,
            "value": ser.b64(res.value) if res.value else None,
            "height": str(res.height), "codespace": res.codespace,
        }}

    # -- txs ---------------------------------------------------------------
    def _decode_tx_param(self, tx) -> bytes:
        if isinstance(tx, bytes):
            return tx
        return base64.b64decode(tx)

    def broadcast_tx_async(self, tx=None) -> dict:
        raw = self._decode_tx_param(tx)
        threading.Thread(target=self._check_tx_ignore_errors,
                         args=(raw,), daemon=True).start()
        return {"code": 0, "data": "", "log": "",
                "hash": ser.hex_upper(tx_hash(raw))}

    def _check_tx_ignore_errors(self, raw: bytes) -> None:
        try:
            self.mempool.check_tx(raw)
        except Exception:
            pass

    def broadcast_tx_sync(self, tx=None) -> dict:
        """CheckTx result returned (rpc/core/mempool.go:38)."""
        raw = self._decode_tx_param(tx)
        from ..mempool.clist_mempool import ErrAppCheckTx, MempoolError
        try:
            res = self.mempool.check_tx(raw)
            code, log = res.code, res.log
        except ErrAppCheckTx as e:
            code, log = e.code, e.log
        except MempoolError as e:
            raise RPCError(-32603, str(e)) from e
        return {"code": code, "data": "", "log": log,
                "hash": ser.hex_upper(tx_hash(raw))}

    def broadcast_tx_commit(self, tx=None) -> dict:
        """Subscribe to the tx event, submit, wait for commit
        (rpc/core/mempool.go:76)."""
        raw = self._decode_tx_param(tx)
        h = tx_hash(raw)
        query = ev.pubsub.Query.parse(
            f"{ev.TX_HASH_KEY} = '{h.hex().upper()}'")
        subscriber = f"tx-commit-{h.hex()[:16]}"
        sub = self.event_bus.subscribe(subscriber, query)
        try:
            check = self.broadcast_tx_sync(tx=raw)
            if check["code"] != 0:
                return {"check_tx": check, "tx_result": None,
                        "hash": check["hash"], "height": "0"}
            timeout = self.config.rpc.timeout_broadcast_tx_commit \
                if self.config else 10.0
            msg = sub.next(timeout=timeout)
            if msg is None:
                raise RPCError(-32603,
                               "timed out waiting for tx to commit")
            data = msg.data  # EventDataTx
            return {
                "check_tx": check,
                "tx_result": ser.exec_tx_result_json(data.result),
                "hash": ser.hex_upper(h),
                "height": str(data.height),
            }
        finally:
            try:
                self.event_bus.unsubscribe_all(subscriber)
            except KeyError:
                pass

    # -- tx / event queries (rpc/core/tx.go, blocks.go BlockSearch) --------
    @staticmethod
    def _decode_hash_param(hash) -> bytes:  # noqa: A002
        """Accept hex (URI style, optional 0x) or base64 (JSON style)."""
        if not hash:
            raise RPCError(-32602, "hash is required")
        s = str(hash)
        if s.startswith("0x") or s.startswith("0X"):
            s = s[2:]
        try:
            return bytes.fromhex(s)
        except ValueError:
            try:
                return base64.b64decode(s, validate=True)
            except Exception:
                raise RPCError(-32602, f"invalid hash {hash!r}")

    @staticmethod
    def _paginate(total: int, page, per_page) -> tuple[int, int]:
        """Clamp like the reference's validatePage/validatePerPage."""
        per = max(1, min(int(per_page) if per_page else 30, 100))
        pages = max(1, (total + per - 1) // per)
        p = int(page) if page else 1
        if not 1 <= p <= pages:
            raise RPCError(-32603,
                           f"page must be in [1, {pages}], got {p}")
        return (p - 1) * per, per

    def _tx_result_json(self, rec: dict, prove=False) -> dict:
        tx = base64.b64decode(rec["tx"])
        out = {
            "hash": ser.hex_upper(tx_hash(tx)),
            "height": str(rec["height"]),
            "index": rec["index"],
            "tx_result": rec["result"],
            "tx": ser.b64(tx),
        }
        if prove:
            block = self.block_store.load_block(rec["height"])
            if block is not None and rec["index"] < len(block.data.txs):
                from ..crypto.merkle import proofs_from_byte_slices
                root, proofs = proofs_from_byte_slices(
                    [bytes(t) for t in block.data.txs])
                pf = proofs[rec["index"]]
                out["proof"] = {
                    "root_hash": ser.hex_upper(root),
                    "data": ser.b64(tx),
                    "proof": {
                        "total": str(pf.total),
                        "index": str(pf.index),
                        "leaf_hash": ser.b64(pf.leaf_hash),
                        "aunts": [ser.b64(a) for a in pf.aunts],
                    },
                }
        return out

    def tx(self, hash=None, prove=None) -> dict:  # noqa: A002
        """rpc/core/tx.go Tx: look a transaction up by hash."""
        if self.tx_indexer is None:
            raise RPCError(-32603, "transaction indexing is disabled")
        rec = self.tx_indexer.get(self._decode_hash_param(hash))
        if rec is None:
            raise RPCError(-32603, f"tx {hash} not found")
        return self._tx_result_json(rec, prove in (True, "true", "1"))

    def tx_search(self, query=None, prove=None, page=None, per_page=None,
                  order_by=None) -> dict:
        """rpc/core/tx.go TxSearch: event-query over indexed txs."""
        if self.tx_indexer is None:
            raise RPCError(-32603, "transaction indexing is disabled")
        if not query:
            raise RPCError(-32602, "query is required")
        from ..libs import pubsub
        try:
            q = pubsub.Query.parse(str(query))
        except pubsub.QueryError as e:
            raise RPCError(-32602, f"invalid query: {e}")
        recs = self.tx_indexer.search(q)
        recs.sort(key=lambda r: (r["height"], r["index"]),
                  reverse=(order_by == "desc"))
        start, per = self._paginate(len(recs), page, per_page)
        prove_b = prove in (True, "true", "1")
        return {
            "txs": [self._tx_result_json(r, prove_b)
                    for r in recs[start:start + per]],
            "total_count": str(len(recs)),
        }

    def block_search(self, query=None, page=None, per_page=None,
                     order_by=None) -> dict:
        """rpc/core/blocks.go BlockSearch: block-event query."""
        if self.block_indexer is None:
            raise RPCError(-32603, "block indexing is disabled")
        if not query:
            raise RPCError(-32602, "query is required")
        from ..libs import pubsub
        try:
            q = pubsub.Query.parse(str(query))
        except pubsub.QueryError as e:
            raise RPCError(-32602, f"invalid query: {e}")
        heights = self.block_indexer.search(q)
        heights.sort(reverse=(order_by == "desc"))
        start, per = self._paginate(len(heights), page, per_page)
        blocks = []
        for h in heights[start:start + per]:
            meta = self.block_store.load_block_meta(h)
            block = self.block_store.load_block(h)
            if meta is not None and block is not None:
                blocks.append({"block_id": ser.block_id_json(meta.block_id),
                               "block": ser.block_json(block)})
        return {"blocks": blocks, "total_count": str(len(heights))}

    def check_tx(self, tx=None) -> dict:
        """rpc/core/mempool.go CheckTx: run the app's CheckTx WITHOUT
        adding to the mempool."""
        raw = self._decode_tx_param(tx)
        res = self.app_conns.mempool.check_tx(
            at.CheckTxRequest(tx=raw, type=at.CHECK_TX_TYPE_CHECK))
        return {"code": res.code, "data": ser.b64(res.data)
                if res.data else None, "log": res.log,
                "codespace": res.codespace,
                "gas_wanted": str(res.gas_wanted),
                "gas_used": str(res.gas_used)}

    def genesis_chunked(self, chunk=None) -> dict:
        """rpc/core/env.go InitGenesisChunks: the genesis doc JSON
        itself (no result envelope) in 16MB chunks, computed once."""
        chunks = getattr(self, "_gen_chunks", None)
        if chunks is None:
            data = self.genesis.to_json().encode()
            size = 16 * 1024 * 1024
            chunks = [data[i:i + size]
                      for i in range(0, len(data), size)] or [b""]
            self._gen_chunks = chunks
        idx = int(chunk or 0)
        if not 0 <= idx < len(chunks):
            raise RPCError(
                -32603, f"chunk {idx} out of range [0, {len(chunks)})")
        return {"chunk": str(idx), "total": str(len(chunks)),
                "data": ser.b64(chunks[idx])}

    def header_by_hash(self, hash=None) -> dict:  # noqa: A002
        raw = self._decode_hash_param(hash)
        meta = self.block_store.load_block_meta_by_hash(raw)
        if meta is None:
            return {"header": None}
        return {"header": ser.header_json(meta.header)}

    def unconfirmed_txs(self, limit=None) -> dict:
        txs = self.mempool.reap_max_txs(int(limit) if limit else 30)
        return {
            "n_txs": str(len(txs)),
            "total": str(self.mempool.size()),
            "total_bytes": str(self.mempool.size_bytes()),
            "txs": [ser.b64(tx) for tx in txs],
        }

    def num_unconfirmed_txs(self) -> dict:
        return {
            "n_txs": str(self.mempool.size()),
            "total": str(self.mempool.size()),
            "total_bytes": str(self.mempool.size_bytes()),
        }

    # -- evidence ----------------------------------------------------------
    def broadcast_evidence(self, evidence=None) -> dict:
        from ..types.evidence import evidence_from_proto_wrapped
        ev_obj = evidence_from_proto_wrapped(
            base64.b64decode(evidence))
        self.evidence_pool.add_evidence(ev_obj)
        return {"hash": ser.hex_upper(ev_obj.hash())}


    # -- privileged pruning service (data companion) -----------------------
    # reference rpc/grpc/server/services/pruningservice; JSON-RPC here
    def _require_pruner(self):
        if self.pruner is None:
            raise RPCError(-32603, "pruning service unavailable")
        return self.pruner

    def set_block_retain_height(self, height=None) -> dict:
        p = self._require_pruner()
        h = int(height or 0)
        if h <= 0 or h > self._latest_height() + 1:
            raise RPCError(
                -32602, f"height must be in [1, chain height], got {h}")
        if not p.set_companion_block_retain_height(h):
            raise RPCError(
                -32603, "cannot lower the companion retain height "
                f"(currently {p.companion_block_retain_height()})")
        return {}

    def get_block_retain_height(self) -> dict:
        p = self._require_pruner()
        return {
            "app_retain_height": str(p.application_block_retain_height()),
            "pruning_service_retain_height":
                str(p.companion_block_retain_height()),
        }

    def set_block_results_retain_height(self, height=None) -> dict:
        p = self._require_pruner()
        h = int(height or 0)
        if h <= 0 or h > self._latest_height() + 1:
            raise RPCError(
                -32602, f"height must be in [1, chain height], got {h}")
        if not p.set_abci_res_retain_height(h):
            raise RPCError(
                -32603, "cannot lower the block-results retain height "
                f"(currently {p.abci_res_retain_height()})")
        return {}

    def get_block_results_retain_height(self) -> dict:
        p = self._require_pruner()
        return {"pruning_service_retain_height":
                str(p.abci_res_retain_height())}

    def set_tx_indexer_retain_height(self, height=None) -> dict:
        p = self._require_pruner()
        h = int(height or 0)
        if h <= 0:
            raise RPCError(-32602, f"height must be positive, got {h}")
        if not p.set_tx_indexer_retain_height(h):
            raise RPCError(
                -32603, "cannot lower the tx-indexer retain height "
                f"(currently {p.tx_indexer_retain_height()})")
        return {}

    def get_tx_indexer_retain_height(self) -> dict:
        p = self._require_pruner()
        return {"height": str(p.tx_indexer_retain_height())}

    def set_block_indexer_retain_height(self, height=None) -> dict:
        p = self._require_pruner()
        h = int(height or 0)
        if h <= 0:
            raise RPCError(-32602, f"height must be positive, got {h}")
        if not p.set_block_indexer_retain_height(h):
            raise RPCError(
                -32603, "cannot lower the block-indexer retain height "
                f"(currently {p.block_indexer_retain_height()})")
        return {}

    def get_block_indexer_retain_height(self) -> dict:
        p = self._require_pruner()
        return {"height": str(p.block_indexer_retain_height())}


# routes.go: method name -> handler attribute
ROUTES = {
    "health": "health",
    "status": "status",
    "net_info": "net_info",
    "genesis": "genesis_",
    "block": "block",
    "block_by_hash": "block_by_hash",
    "header": "header",
    "commit": "commit",
    "blockchain": "blockchain",
    "block_results": "block_results",
    "validators": "validators",
    "consensus_params": "consensus_params",
    "consensus_state": "consensus_state_handler",
    "dump_consensus_state": "dump_consensus_state_handler",
    "flightrec": "flightrec_handler",
    "tracetl": "tracetl_handler",
    "devprof": "devprof_handler",
    "latency": "latency_handler",
    "fleetobs": "fleetobs_handler",
    "abci_info": "abci_info",
    "abci_query": "abci_query",
    "broadcast_tx_async": "broadcast_tx_async",
    "broadcast_tx_sync": "broadcast_tx_sync",
    "broadcast_tx_commit": "broadcast_tx_commit",
    "unconfirmed_txs": "unconfirmed_txs",
    "num_unconfirmed_txs": "num_unconfirmed_txs",
    "broadcast_evidence": "broadcast_evidence",
    "tx": "tx",
    "tx_search": "tx_search",
    "block_search": "block_search",
    "check_tx": "check_tx",
    "genesis_chunked": "genesis_chunked",
    "header_by_hash": "header_by_hash",
    "light_sync": "light_sync",
    "light_status": "light_status",
}

# privileged routes: served only on the separate privileged listener
PRIVILEGED_ROUTES = {
    "set_block_retain_height": "set_block_retain_height",
    "get_block_retain_height": "get_block_retain_height",
    "set_block_results_retain_height": "set_block_results_retain_height",
    "get_block_results_retain_height": "get_block_results_retain_height",
    "set_tx_indexer_retain_height": "set_tx_indexer_retain_height",
    "get_tx_indexer_retain_height": "get_tx_indexer_retain_height",
    "set_block_indexer_retain_height": "set_block_indexer_retain_height",
    "get_block_indexer_retain_height": "get_block_indexer_retain_height",
}
