"""Reactor-level end-to-end benchmarks over the simnet.

Where bench.py's kernel metrics time the device dispatch loop over
pre-packed arrays, these drive the REAL protocol stack:

- blocksync e2e: blocks flow source-switch -> conditioned link ->
  syncing node's BlocksyncReactor -> BlockPool -> windowed
  DeferredSigBatch device verify -> BlockExecutor (ABCI finalize +
  commit) -> BlockStore.  The rate is blocks actually landed in the
  store per wall second, and the libs/trace.py stage spans
  (decode / verify_dispatch / device / apply / store) are reported
  alongside so the host-residual around the device dispatch is visible
  in the same record.

- light e2e: headers pulled through light/client.py's windowed
  sequential sync against a simnet node's REAL JSON-RPC server
  (HttpProvider -> HTTP -> rpc/core Environment -> stores), signatures
  batch-verified on the device per window.

Module-level `last_blocksync` / `last_light` keep the full result dict
of the most recent run (bench.py attaches the stage breakdown to its
extras from there, mirroring bench_rlc.last_pass_rates).
"""

from __future__ import annotations

import os
import time

from ..crypto import sigcache
from ..libs import devprof as libdevprof
from ..libs import trace as libtrace
from ..ops import compile_hook
from .node import SimNode, clone_chain, grow_chain, make_sim_genesis
from .transport import SimNetwork

last_blocksync: dict | None = None
last_light: dict | None = None
last_consensus: dict | None = None
last_cache_ab: dict | None = None
last_lightserve: dict | None = None
last_contention: dict | None = None


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, str(default)))


def bench_blocksync_e2e(n_blocks: int | None = None,
                        n_vals: int | None = None,
                        txs_per_block: int = 2,
                        seed: int = 7,
                        timeout: float = 480.0,
                        pipeline_depth: int | None = None,
                        mesh_devices: int | None = None) -> dict:
    """Sync n_blocks through the real blocksync reactor; returns the
    result dict (blocks_per_sec + stage breakdown + pipeline overlap
    report) and stores it in `last_blocksync`.

    pipeline_depth drives the reactor's overlapped verify pipeline
    (blocksync/reactor.PIPELINE_DEPTH default): 1 = the serial loop,
    >= 2 collects/packs window N+1 while window N is on device — the
    A/B knob for serial-vs-pipelined on the same seed.

    mesh_devices round-robins the pipeline's windows over that many
    mesh devices (blocksync/reactor.MESH_DEVICES default; see
    ops/sharding.mesh_device_list — 0 defers to the
    COMETBFT_TPU_MESH_DEVICES knob, off unless set)."""
    global last_blocksync
    n_blocks = n_blocks if n_blocks is not None else _env_int(
        "SIMNET_BENCH_BLOCKS", 96)
    n_vals = n_vals if n_vals is not None else _env_int(
        "SIMNET_BENCH_VALS", 64)
    pipeline_depth = pipeline_depth if pipeline_depth is not None \
        else _env_int("SIMNET_BENCH_PIPELINE_DEPTH", 0) or None
    mesh_devices = mesh_devices if mesh_devices is not None \
        else _env_int("SIMNET_BENCH_MESH_DEVICES", 0)

    net = SimNetwork(seed=seed)
    genesis, privs = make_sim_genesis(n_vals=n_vals, seed=seed)
    src = SimNode("bsrc", genesis, net, seed=seed)
    # +1: the tip block's LastCommit verifies height-1; blocksync
    # converges one block behind the serving tip (sync_target)
    grow_chain(src, privs, n_blocks + 1, txs_per_block=txs_per_block)
    syncer = SimNode("bsync", genesis, net, block_sync=True, seed=seed)
    if pipeline_depth is not None:
        syncer.blocksync_reactor.pipeline_depth = pipeline_depth
    if mesh_devices:
        syncer.blocksync_reactor.mesh_devices = mesh_devices

    prev_tracer = libtrace.tracer()
    tr = libtrace.StageTracer(
        metrics=prev_tracer.metrics if prev_tracer else None)
    libtrace.set_tracer(tr)
    # a fresh device-time account for exactly this run's traffic
    prev_devprof = libdevprof.recorder()
    prev_ledger = compile_hook.ledger()
    devprof_rec = libdevprof.DevprofRecorder()
    libdevprof.set_recorder(devprof_rec)
    compile_hook.install(devprof_rec)
    target = src.sync_target()
    try:
        src.start()
        syncer.start()
        t0 = time.perf_counter()
        syncer.dial(src)
        ok = syncer.wait_for_height(target, timeout=timeout)
        dt = time.perf_counter() - t0
    finally:
        libtrace.set_tracer(prev_tracer)
        libdevprof.set_recorder(prev_devprof)
        if prev_ledger is not None:
            compile_hook.install(prev_ledger)
        else:
            compile_hook.uninstall()
        syncer.stop()
        src.stop()
    if not ok:
        raise RuntimeError(
            f"blocksync e2e stalled at {syncer.height()}/{target} "
            f"after {timeout:.0f}s")
    # the source's header ABOVE the target carries the app hash the
    # syncer must have reached after applying the target block
    want = src.block_store.load_block(target + 1).header.app_hash
    if syncer.app_hash() != want:
        raise RuntimeError("blocksync e2e app hash diverged")

    stages = {k: v for k, v in tr.snapshot().items()
              if k.startswith("blocksync.")}
    # overlap report: sum-of-stages vs wall-clock (>1.0 = stages ran
    # concurrently), plus the DIRECT proof — wall-clock during which a
    # device span overlapped a collect or host_pack span of the next
    # window (libs/trace.py interval records)
    stage_sum = sum(v["seconds"] for v in stages.values())
    device_overlap_s = round(
        tr.overlap_seconds("blocksync", "device", "collect")
        + tr.overlap_seconds("blocksync", "device", "host_pack"), 6)
    last_blocksync = {
        "blocks_per_sec": round(n_blocks / dt, 2),
        "blocks": n_blocks,
        "validators": n_vals,
        "seconds": round(dt, 3),
        "pipeline_depth": (pipeline_depth if pipeline_depth is not None
                           else syncer.blocksync_reactor.pipeline_depth),
        "overlap_efficiency": round(stage_sum / dt, 4) if dt else 0.0,
        "device_overlap_seconds": device_overlap_s,
        "stages": stages,
    }
    devprof_snap = devprof_rec.snapshot()
    occ = libdevprof.occupancy_summary(devprof_snap)
    last_blocksync["device_occupancy_fraction"] = \
        occ["device_occupancy_fraction"]
    last_blocksync["host_bound_fraction"] = occ["host_bound_fraction"]
    last_blocksync["compile_seconds_total"] = \
        devprof_snap["compile"]["seconds_total"]
    last_blocksync["devprof"] = {
        "idle_cause_seconds": occ["idle_cause_seconds"],
        "devices": devprof_snap["devices"],
        "compile": devprof_snap["compile"],
    }
    return last_blocksync


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def bench_consensus_e2e(n_blocks: int | None = None,
                        n_vals: int | None = None,
                        seed: int = 13,
                        timeout: float = 300.0,
                        attach_timeline: bool | None = None,
                        trace_export: str | None = None,
                        cache: bool | None = None) -> dict:
    """Live multi-validator consensus over conditioned links: real
    rounds (propose -> prevote -> precommit -> commit) through the
    real reactors, votes pre-verified through the streaming-verifier
    device seam.  Reports blocks/sec, the per-stage consensus span
    breakdown (propose/prevote/precommit/commit/verify_dispatch/
    device), a round-latency histogram, and per-node flight-recorder
    summaries — the round-level observability record next to the
    blocksync/light e2e extras.  Stores the result in
    `last_consensus`.

    attach_timeline (SIMNET_TRACE_TIMELINE=1) installs a
    simnet/tracing.TraceSession over the cluster and adds the
    proposal->commit critical-path decomposition
    (`critical_path_device_share` + per-segment summary) to the
    result; trace_export (SIMNET_TRACE_EXPORT=path) additionally
    writes the merged Perfetto trace_event JSON there.

    cache forces the signature-verdict cache on (True) or off (False)
    for the run — the A/B knob bench_consensus_cache_ab drives; None
    leaves the process default (env COMETBFT_TPU_SIGCACHE).  The cache
    starts EMPTY either way, so the reported `verdict_cache` stats
    are entirely this run's traffic."""
    global last_consensus
    n_blocks = n_blocks if n_blocks is not None else _env_int(
        "SIMNET_CONSENSUS_BLOCKS", 12)
    n_vals = n_vals if n_vals is not None else _env_int(
        "SIMNET_CONSENSUS_VALS", 4)
    if attach_timeline is None:
        attach_timeline = os.environ.get(
            "SIMNET_TRACE_TIMELINE", "0") == "1"
    if trace_export is None:
        trace_export = os.environ.get("SIMNET_TRACE_EXPORT") or None
    attach_timeline = attach_timeline or trace_export is not None

    net = SimNetwork(seed=seed)
    net.set_default_link(latency=0.001)
    genesis, privs = make_sim_genesis(n_vals=n_vals, seed=seed)
    nodes = [SimNode(f"cval{i}", genesis, net, priv_validator=p,
                     consensus_active=True, seed=seed)
             for i, p in enumerate(privs)]

    prev_cache_enabled = sigcache._enabled_override
    sigcache.set_enabled(cache)
    sigcache.reset()

    # a fresh device-time account for exactly this run's traffic,
    # installed BEFORE the TraceSession so the session reuses it (its
    # counter samples land in the exported trace)
    prev_devprof = libdevprof.recorder()
    prev_ledger = compile_hook.ledger()
    devprof_rec = libdevprof.DevprofRecorder()
    libdevprof.set_recorder(devprof_rec)
    compile_hook.install(devprof_rec)
    session = None
    if attach_timeline:
        from .tracing import TraceSession
        session = TraceSession().install(nodes)
    prev_tracer = libtrace.tracer()
    tr = libtrace.StageTracer(
        metrics=prev_tracer.metrics if prev_tracer else None)
    libtrace.set_tracer(tr)
    trace = None
    try:
        for n in nodes:
            n.start()
        t0 = time.perf_counter()
        for i, a in enumerate(nodes):
            for b in nodes[i + 1:]:
                b.dial(a)
        deadline = t0 + timeout
        while time.perf_counter() < deadline:
            if all(n.height() >= n_blocks for n in nodes):
                break
            time.sleep(0.01)
        dt = time.perf_counter() - t0
    finally:
        libtrace.set_tracer(prev_tracer)
        summaries = {n.name: n.recorder_summary() for n in nodes}
        lats = sorted(lat for n in nodes for lat in n.round_latencies())
        for n in nodes:
            n.stop()
        cache_stats = sigcache.cache().stats()
        sigcache.set_enabled(prev_cache_enabled)
        if session is not None:
            trace = session.export()
            session.uninstall()
        libdevprof.set_recorder(prev_devprof)
        if prev_ledger is not None:
            compile_hook.install(prev_ledger)
        else:
            compile_hook.uninstall()
    if not all(n.height() >= n_blocks for n in nodes):
        raise RuntimeError(
            "consensus e2e stalled at "
            f"{[n.height() for n in nodes]}/{n_blocks}")

    stages = {k: v for k, v in tr.snapshot().items()
              if k.startswith("consensus.")}
    last_consensus = {
        "blocks_per_sec": round(n_blocks / dt, 2),
        "blocks": n_blocks,
        "validators": n_vals,
        "seconds": round(dt, 3),
        "stages": stages,
        "round_latency_seconds": {
            "p50": round(_percentile(lats, 0.50), 4),
            "p90": round(_percentile(lats, 0.90), 4),
            "max": round(lats[-1], 4) if lats else 0.0,
            "samples": len(lats),
        },
        "recorders": summaries,
        "cache_enabled": (bool(cache) if cache is not None
                          else sigcache.enabled()),
        "verdict_cache": cache_stats,
        "verdict_cache_hit_rate": cache_stats["hit_rate"],
        # byte-determinism probe: the cache must not change WHAT
        # commits, only how often signatures re-verify.  Sampled at
        # the FIXED height n_blocks (nodes race slightly past it), so
        # two same-seed runs must agree byte-for-byte.
        "heights": [n.height() for n in nodes],
        "app_hashes": [
            n.block_store.load_block_meta(n_blocks).header.app_hash.hex()
            for n in nodes],
    }
    devprof_snap = devprof_rec.snapshot()
    occ = libdevprof.occupancy_summary(devprof_snap)
    last_consensus["device_occupancy_fraction"] = \
        occ["device_occupancy_fraction"]
    last_consensus["host_bound_fraction"] = occ["host_bound_fraction"]
    last_consensus["compile_seconds_total"] = \
        devprof_snap["compile"]["seconds_total"]
    last_consensus["devprof"] = {
        "idle_cause_seconds": occ["idle_cause_seconds"],
        "devices": devprof_snap["devices"],
        "compile": devprof_snap["compile"],
    }
    if trace is not None:
        from ..libs import tracetl
        if trace_export:
            tracetl.write_trace(trace_export, trace)
        cp = tracetl.critical_path(trace)
        last_consensus["critical_path"] = cp["summary"]
        last_consensus["critical_path_device_share"] = \
            cp["summary"]["device_share"]
    return last_consensus


def bench_consensus_cache_ab(n_blocks: int | None = None,
                             n_vals: int | None = None,
                             seed: int = 13,
                             timeout: float = 300.0,
                             attach_timeline: bool | None = None) -> dict:
    """A/B the signature-verdict cache over the SAME seeded consensus
    run: arm A with the cache disabled, arm B with it force-enabled.

    The contract the cache must hold: identical heights and app hashes
    in both arms (verdicts are facts — caching them may not change
    what commits), while arm B shows a non-zero hit rate (the H+1
    LastCommit re-validation and duplicate vote gossip resolve from
    cache) and, when the timeline is attached, a LOWER share of the
    proposal->commit critical path spent in device verify dispatches.
    Stores the combined record in `last_cache_ab`."""
    global last_cache_ab
    off = bench_consensus_e2e(n_blocks=n_blocks, n_vals=n_vals,
                              seed=seed, timeout=timeout,
                              attach_timeline=attach_timeline,
                              cache=False)
    on = bench_consensus_e2e(n_blocks=n_blocks, n_vals=n_vals,
                             seed=seed, timeout=timeout,
                             attach_timeline=attach_timeline,
                             cache=True)
    if off["app_hashes"] != on["app_hashes"]:
        raise RuntimeError(
            "verdict cache changed app hashes: "
            f"off={off['app_hashes']} on={on['app_hashes']}")
    if min(off["heights"]) < off["blocks"] or \
            min(on["heights"]) < on["blocks"]:
        raise RuntimeError("cache A/B arm stalled below target height")
    last_cache_ab = {
        "blocks": on["blocks"],
        "validators": on["validators"],
        "seed": seed,
        "app_hash_parity": True,
        "hit_rate_off": off["verdict_cache_hit_rate"],
        "hit_rate_on": on["verdict_cache_hit_rate"],
        "verdict_cache_on": on["verdict_cache"],
        "blocks_per_sec_off": off["blocks_per_sec"],
        "blocks_per_sec_on": on["blocks_per_sec"],
    }
    for arm, rec in (("off", off), ("on", on)):
        if "critical_path_device_share" in rec:
            last_cache_ab[f"critical_path_device_share_{arm}"] = \
                rec["critical_path_device_share"]
    return last_cache_ab


def bench_light_e2e(n_headers: int | None = None,
                    n_vals: int | None = None,
                    seed: int = 11,
                    sequential_batch_size: int | None = None) -> dict:
    """Sequential light-client sync over the real RPC wire; returns the
    result dict (headers_per_sec + stage breakdown) and stores it in
    `last_light`."""
    global last_light
    n_headers = n_headers if n_headers is not None else _env_int(
        "SIMNET_LIGHT_HEADERS", 128)
    n_vals = n_vals if n_vals is not None else _env_int(
        "SIMNET_LIGHT_VALS", 32)

    from ..light.client import SEQUENTIAL, Client, TrustOptions
    from ..light.provider import HttpProvider

    net = SimNetwork(seed=seed)
    genesis, privs = make_sim_genesis(n_vals=n_vals, seed=seed)
    src = SimNode("lsrc", genesis, net, seed=seed)
    grow_chain(src, privs, n_headers + 1, txs_per_block=1)

    prev_tracer = libtrace.tracer()
    tr = libtrace.StageTracer(
        metrics=prev_tracer.metrics if prev_tracer else None)
    libtrace.set_tracer(tr)
    try:
        rpc_addr = src.start_rpc()
        provider = HttpProvider(genesis.chain_id, f"http://{rpc_addr}")
        root_meta = src.block_store.load_block_meta(1)
        opts = TrustOptions(
            period_ns=100 * 365 * 24 * 3600 * 1_000_000_000,
            height=1, hash=root_meta.header.hash())
        target = src.height()
        t0 = time.perf_counter()
        client = Client(
            genesis.chain_id, opts, provider,
            verification_mode=SEQUENTIAL,
            sequential_batch_size=(sequential_batch_size
                                   or min(384, n_headers)))
        lb = client.verify_light_block_at_height(target)
        dt = time.perf_counter() - t0
    finally:
        libtrace.set_tracer(prev_tracer)
        src.stop()
    if lb.height != target:
        raise RuntimeError(f"light e2e stopped at {lb.height}/{target}")

    stages = {k: v for k, v in tr.snapshot().items()
              if k.startswith("light.")}
    # headers verified = trust root (fetch+verify in _initialize) plus
    # every height from 2..target
    last_light = {
        "headers_per_sec": round(target / dt, 2),
        "headers": target,
        "validators": n_vals,
        "seconds": round(dt, 3),
        "stages": stages,
    }
    return last_light


def bench_lightserve_fleet(n_clients: int | None = None,
                           n_blocks: int | None = None,
                           n_vals: int | None = None,
                           seed: int = 23,
                           workers: int | None = None,
                           sample_verify: float = 0.0) -> dict:
    """A/B the lightserve coalescer over the SAME seeded client fleet:
    arm OFF serves every request through its own verify window, arm ON
    merges overlapping in-flight paths into shared flushes.

    The contract coalescing must hold: the fleet payload digest is
    bit-identical across arms (merging windows may not change a single
    served byte), every client is served, and the ON arm dispatches
    strictly fewer verify windows AND fewer signature verifies for the
    same traffic — that dispatch reduction is WHERE the throughput
    comes from.  The signature-verdict cache is forced off in both
    arms so the reduction is attributable to the coalescer alone.
    Stores the combined record in `last_lightserve`."""
    global last_lightserve
    n_clients = n_clients if n_clients is not None else _env_int(
        "SIMNET_LIGHT_FLEET_CLIENTS", 10_000)
    n_blocks = n_blocks if n_blocks is not None else _env_int(
        "SIMNET_LIGHT_FLEET_BLOCKS", 48)
    n_vals = n_vals if n_vals is not None else _env_int(
        "SIMNET_LIGHT_FLEET_VALS", 4)
    workers = workers if workers is not None else _env_int(
        "SIMNET_LIGHT_FLEET_WORKERS", 32)

    from ..crypto import dispatch
    from ..lightserve import LightServeSession
    from .lightfleet import run_fleet

    net = SimNetwork(seed=seed)
    genesis, privs = make_sim_genesis(n_vals=n_vals, seed=seed)
    src = SimNode("lfsrc", genesis, net, seed=seed)
    # +1: the block above the tip carries the commit that seals the
    # tip, so heights 1..n_blocks are all servable with a commit
    grow_chain(src, privs, n_blocks + 1, txs_per_block=1)

    pipe = dispatch.default_pipeline()
    prev_cache_enabled = sigcache._enabled_override
    arms: dict[str, dict] = {}
    try:
        for arm, coalesce in (("off", False), ("on", True)):
            # cache off + reset per arm: the dispatch reduction must
            # come from the coalescer, not verdict-cache hits
            sigcache.set_enabled(False)
            sigcache.reset()
            session = LightServeSession(
                src.block_store, src.state_store, genesis.chain_id,
                coalesce=coalesce)
            submitted0 = pipe.submitted
            try:
                rec = run_fleet(session, n_clients, seed,
                                workers=workers,
                                sample_verify=sample_verify,
                                chain_id=genesis.chain_id)
            finally:
                session.close()
            rec["verify_windows"] = session.verify_windows
            rec["verify_sigs"] = session.verify_sigs
            rec["pipeline_windows"] = pipe.submitted - submitted0
            arms[arm] = rec
    finally:
        sigcache.set_enabled(prev_cache_enabled)
        sigcache.reset()
        src.stop()

    off, on = arms["off"], arms["on"]
    if off["failures"] or on["failures"]:
        raise RuntimeError(
            "lightserve fleet arm had failures: "
            f"off={off['failures'][:3]} on={on['failures'][:3]}")
    if off["clients"] != n_clients or on["clients"] != n_clients:
        raise RuntimeError(
            f"lightserve fleet under-served: off={off['clients']} "
            f"on={on['clients']} of {n_clients}")
    if off["digest"] != on["digest"]:
        raise RuntimeError(
            "coalescing changed served bytes: "
            f"off={off['digest']} on={on['digest']}")
    if not (on["verify_windows"] < off["verify_windows"]
            and on["verify_sigs"] < off["verify_sigs"]):
        raise RuntimeError(
            "coalescing did not reduce verify dispatch: windows "
            f"{off['verify_windows']}->{on['verify_windows']}, sigs "
            f"{off['verify_sigs']}->{on['verify_sigs']}")

    ratio = (round(on["clients_per_sec"] / off["clients_per_sec"], 2)
             if off["clients_per_sec"] else 0.0)
    last_lightserve = {
        "light_clients_served_per_sec": on["clients_per_sec"],
        "light_serve_p99_ms": on["p99_ms"],
        "coalesce_ratio": ratio,
        "digest_parity": True,
        "clients": n_clients,
        "blocks": n_blocks,
        "validators": n_vals,
        "workers": workers,
        "seed": seed,
        "clients_per_sec_off": off["clients_per_sec"],
        "clients_per_sec_on": on["clients_per_sec"],
        "p99_ms_off": off["p99_ms"],
        "p99_ms_on": on["p99_ms"],
        "p50_ms_on": on["p50_ms"],
        "verify_windows_off": off["verify_windows"],
        "verify_windows_on": on["verify_windows"],
        "verify_sigs_off": off["verify_sigs"],
        "verify_sigs_on": on["verify_sigs"],
        "pipeline_windows_off": off["pipeline_windows"],
        "pipeline_windows_on": on["pipeline_windows"],
        "wall_s_off": off["wall_s"],
        "wall_s_on": on["wall_s"],
    }
    return last_lightserve


def _contention_feed(tag: str, seed: int, windows: int,
                     window_size: int) -> list:
    """Seeded signed windows for one contention-bench consumer: same
    (tag, seed) -> byte-identical feed, so both arms verify exactly
    the same triples."""
    import hashlib as _hashlib

    from ..crypto.ed25519 import PrivKey

    feed = []
    for w in range(windows):
        items = []
        for i in range(window_size):
            sd = _hashlib.sha256(
                b"contend-%s-%d-%d-%d"
                % (tag.encode(), seed, w, i)).digest()
            priv = PrivKey.generate(sd)
            msg = b"contention-%s-%d-%d" % (tag.encode(), w, i)
            items.append((priv.pub_key(), msg, priv.sign(msg)))
        feed.append(items)
    return feed


def bench_verify_contention(n_votes: int | None = None,
                            bulk_windows: int | None = None,
                            bulk_window_size: int | None = None,
                            light_requests: int | None = None,
                            light_window_size: int = 8,
                            seed: int = 29,
                            depth: int = 4,
                            timeout: float = 240.0,
                            device_threshold: int | None = None)\
        -> dict:
    """A/B the per-request verify latency under multi-tenant
    contention, over the SAME seeded request feeds: arm SOLO runs the
    vote stream alone through a fresh VerifyPipeline; arm CONTENDED
    runs the vote stream while a blocksync-shaped bulk feed and a
    lightserve-shaped burst share the SAME pipeline from their own
    threads (>= 3 concurrent consumers, one dispatch queue).

    What the latency ledger (libs/latledger.py) must show: every
    sampled request's segment decomposition sums EXACTLY to its wall
    (enforced here — a violation raises), per-consumer p50/p99 for
    each tenant, and the vote-p99 contention cost as the single
    number `vote_verify_p99_ms` (gated lower-is-better next to
    `bulk_verify_p99_ms`).  The signature-verdict cache is forced off
    so the queueing is real verify work, not cache hits.  Stores the
    combined record in `last_contention`.

    QoS A/B (crypto/sched.py): the contended arm runs twice over the
    SAME seeded feeds — scheduler ON and scheduler OFF (plain FIFO).
    Both arms must produce IDENTICAL verdict digests (the scheduler
    may only reorder, never change answers — enforced here), the OFF
    arm's vote p99 lands as the diagnostic
    `vote_verify_p99_ms_sched_off`, and the bulk tenant's sigs/s
    ON-vs-OFF lands as `bulk_verify_throughput_ratio` (gated
    higher-is-better: priority lanes must not tax bulk throughput
    beyond the tolerated margin)."""
    global last_contention
    n_votes = n_votes if n_votes is not None else _env_int(
        "SIMNET_CONTENTION_VOTES", 192)
    bulk_windows = bulk_windows if bulk_windows is not None \
        else _env_int("SIMNET_CONTENTION_BULK_WINDOWS", 12)
    bulk_window_size = bulk_window_size if bulk_window_size is not None \
        else _env_int("SIMNET_CONTENTION_BULK_WINDOW", 64)
    light_requests = light_requests if light_requests is not None \
        else _env_int("SIMNET_CONTENTION_LIGHT", 32)

    import hashlib as _hashlib
    import threading

    from ..crypto import dispatch
    from ..libs import latledger

    # one vote per window: the ledger row IS the per-vote latency
    vote_feed = _contention_feed("votes", seed, n_votes, 1)
    bulk_feed = _contention_feed("bulk", seed, bulk_windows,
                                 bulk_window_size)
    light_feed = _contention_feed("light", seed, light_requests,
                                  light_window_size)

    def run_arm(contended: bool, qos: bool = True) -> dict:
        rec = latledger.LatLedgerRecorder()
        prev_rec = latledger.recorder()
        latledger.set_recorder(rec)
        pipe = dispatch.VerifyPipeline(depth=depth,
                                       name="ContentionPipe",
                                       qos=qos)
        errors: list = []
        verdict_runs: dict[str, tuple] = {}
        feed_walls: dict[str, float] = {}

        def feed(label: str, windows: list) -> None:
            # device_threshold pass-through: tier-1 runs pin the host
            # verify path (no cold device compile inside the timing)
            try:
                t0 = time.monotonic()
                handles = [pipe.submit(
                    w, subsystem=label,
                    device_threshold=device_threshold)
                    for w in windows]
                out = []
                for h in handles:
                    ok, verdicts = h.result(timeout=timeout)
                    if not ok:
                        raise RuntimeError(
                            f"{label} window failed verification")
                    out.append(tuple(bool(v) for v in verdicts))
                # per-tenant wall (first submit -> last resolve) and
                # the verdict transcript for the A/B digest
                feed_walls[label] = time.monotonic() - t0
                verdict_runs[label] = tuple(out)
            except Exception as e:     # surfaced after the join
                errors.append((label, e))

        pipe.start()
        try:
            others = []
            if contended:
                others = [
                    threading.Thread(target=feed,
                                     args=("blocksync", bulk_feed),
                                     name="contend-bulk", daemon=True),
                    threading.Thread(target=feed,
                                     args=("lightserve", light_feed),
                                     name="contend-light", daemon=True),
                ]
            for t in others:
                t.start()
            feed("consensus", vote_feed)
            for t in others:
                t.join(timeout=timeout)
            if any(t.is_alive() for t in others):
                raise RuntimeError("contention feed thread stalled")
            sched = pipe.scheduler_snapshot()
        finally:
            pipe.stop()
            latledger.set_recorder(prev_rec)
        if errors:
            raise RuntimeError(f"contention arm failed: {errors}")
        # the ledger's core contract, enforced on every sampled row:
        # the decomposition is an EXACT partition of the wall
        for row in rec.rows():
            if row["wall"] != sum(row["segs"].values()):
                raise RuntimeError(
                    "latency decomposition does not sum to wall: "
                    f"{row}")
        digest = _hashlib.sha256(repr(sorted(
            verdict_runs.items())).encode()).hexdigest()
        return {"consumers": rec.consumers(),
                "slo": rec.slo.snapshot(),
                "requests": rec.recorded,
                "qos": qos,
                "digest": digest,
                "feed_walls_s": {k: round(v, 6)
                                 for k, v in feed_walls.items()},
                "sched": sched}

    prev_cache_enabled = sigcache._enabled_override
    sigcache.set_enabled(False)
    try:
        solo = run_arm(contended=False)
        contended = run_arm(contended=True, qos=True)
        contended_off = run_arm(contended=True, qos=False)
    finally:
        sigcache.set_enabled(prev_cache_enabled)

    # the scheduler may only REORDER work, never change answers: the
    # same seeded feeds must verify to the same transcript both arms
    if contended["digest"] != contended_off["digest"]:
        raise RuntimeError(
            "QoS A/B arms disagree on verdicts: "
            f"on={contended['digest'][:16]} "
            f"off={contended_off['digest'][:16]}")

    vote_solo = solo["consumers"].get("consensus", {})
    vote_load = contended["consumers"].get("consensus", {})
    bulk_load = contended["consumers"].get("blocksync", {})
    vote_off = contended_off["consumers"].get("consensus", {})
    if len(contended["consumers"]) < 3:
        raise RuntimeError(
            "contended arm saw fewer than 3 consumers: "
            f"{sorted(contended['consumers'])}")
    # bulk tenant throughput, sigs/s over its own feed wall: the cost
    # the priority lanes charge the bulk path
    bulk_sigs = bulk_windows * bulk_window_size
    bulk_wall_on = contended["feed_walls_s"].get("blocksync", 0.0)
    bulk_wall_off = contended_off["feed_walls_s"].get("blocksync", 0.0)
    thr_on = bulk_sigs / bulk_wall_on if bulk_wall_on else 0.0
    thr_off = bulk_sigs / bulk_wall_off if bulk_wall_off else 0.0
    last_contention = {
        "vote_verify_p99_ms": vote_load.get("p99_ms", 0.0),
        "bulk_verify_p99_ms": bulk_load.get("p99_ms", 0.0),
        "vote_verify_p99_ms_solo": vote_solo.get("p99_ms", 0.0),
        "vote_verify_p99_ms_sched_off": vote_off.get("p99_ms", 0.0),
        "vote_verify_p50_ms": vote_load.get("p50_ms", 0.0),
        "vote_p99_contention_ratio": round(
            vote_load.get("p99_ms", 0.0)
            / vote_solo.get("p99_ms", 1.0), 2)
        if vote_solo.get("p99_ms") else 0.0,
        "bulk_verify_sigs_per_s": round(thr_on, 1),
        "bulk_verify_throughput_ratio": round(thr_on / thr_off, 3)
        if thr_off else 0.0,
        "votes": n_votes,
        "bulk_windows": bulk_windows,
        "bulk_window_size": bulk_window_size,
        "light_requests": light_requests,
        "seed": seed,
        "depth": depth,
        "solo": solo,
        "contended": contended,
        "contended_sched_off": contended_off,
    }
    return last_contention
