"""simnet: deterministic in-process multi-node simulation harness.

Real reactors, real stores, real device-verification seam — in-memory
transport with seeded latency / jitter / drops / partitions.  See
docs/SIMNET.md.
"""

from .node import (  # noqa: F401
    SimNode, clone_chain, grow_chain, make_sim_genesis,
)
from .transport import LinkSpec, SimNetwork, SimTransport  # noqa: F401
