"""Synthetic light-client fleet driver: 10k+ clients against one
LightServeSession.

Clients arrive with a seeded mix of trust heights (most track near the
tip, a long tail starts from deep history — the profile a real serving
node sees) in a seeded arrival order, fan out over a bounded worker
pool, and each records its serve latency plus a digest of the exact
payload bytes it received.  The combined fleet digest is the parity
oracle for the coalescing A/B: two same-seed runs serving the same
chain must produce IDENTICAL digests whether coalescing is on or off.

``sample_verify`` additionally runs the full client-side
``codec.verify_payload`` (reconstruct commit + valset from the wire
bytes, ``verify_commit``) on a seeded fraction of clients — the chaos
``lightserve_partition`` checker runs it at 1.0.
"""

from __future__ import annotations

import hashlib
import random
import threading
import time

from ..libs import lockrank


def fleet_mix(n_clients: int, tip: int, seed: int) -> list[int]:
    """Seeded trust heights for n clients: ~85% within 3 blocks of the
    tip (clients that stay synced), the rest uniform over history
    (fresh installs, long-offline wallets)."""
    rng = random.Random(seed)
    out = []
    lo = max(1, tip - 3)
    for _ in range(n_clients):
        if tip > 2 and rng.random() < 0.85:
            out.append(rng.randint(lo, tip - 1))
        else:
            out.append(rng.randint(1, max(1, tip - 1)))
    return out


def run_fleet(session, n_clients: int, seed: int,
              target: int | None = None, workers: int = 16,
              sample_verify: float = 0.0,
              chain_id: str | None = None,
              deadline_s: float | None = None,
              retry_s: float = 0.05) -> dict:
    """Drive n_clients synthetic sync requests through ``session``.

    Returns clients served, wall seconds, clients/s, latency
    percentiles, the order-independent fleet payload digest, and any
    verification failures.  With ``deadline_s`` set, failed requests
    retry until the deadline (the chaos partition arm); without it a
    failure raises."""
    tip = session.block_store.height() if target is None else target
    trusts = fleet_mix(n_clients, tip, seed)
    order = list(range(n_clients))
    random.Random(seed + 1).shuffle(order)     # seeded arrival process
    verify_rng = random.Random(seed + 2)
    verify_mask = [verify_rng.random() < sample_verify
                   for _ in range(n_clients)]

    digests: list = [b""] * n_clients
    latencies: list = [0.0] * n_clients
    failures: list = []
    served = [0]
    cursor = [0]
    mtx = lockrank.RankedLock("simnet.lightfleet")
    t_start = time.perf_counter()

    def next_index():
        with mtx:
            if cursor[0] >= len(order):
                return None
            i = order[cursor[0]]
            cursor[0] += 1
            return i

    def client(i: int) -> None:
        t0 = time.perf_counter()
        deadline = None if deadline_s is None else t_start + deadline_s
        while True:
            try:
                _, blobs = session.serve(trusts[i], tip)
                break
            except Exception as e:
                if deadline is None or time.perf_counter() >= deadline:
                    raise e
                time.sleep(retry_s)
        latencies[i] = time.perf_counter() - t0
        h = hashlib.sha256()
        for blob in blobs:
            h.update(blob)
        digests[i] = h.digest()
        if verify_mask[i] and chain_id is not None:
            from ..lightserve import verify_payload

            for blob in blobs:
                verify_payload(chain_id, blob)
        with mtx:
            served[0] += 1

    def worker() -> None:
        while True:
            i = next_index()
            if i is None:
                return
            try:
                client(i)
            except Exception as e:
                with mtx:
                    failures.append(f"client {i} (trust {trusts[i]}): "
                                    f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker,
                                name=f"lightfleet-{w}", daemon=True)
               for w in range(max(1, workers))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start

    lats = sorted(x for x in latencies if x > 0.0)

    def pct(q: float) -> float:
        if not lats:
            return 0.0
        return lats[min(len(lats) - 1, int(q * (len(lats) - 1)))]

    fleet = hashlib.sha256()
    for d in sorted(digests):
        fleet.update(d)
    return {
        "clients": served[0],
        "requested": n_clients,
        "wall_s": round(wall, 3),
        "clients_per_sec": round(served[0] / wall, 2) if wall else 0.0,
        "p50_ms": round(pct(0.50) * 1000, 3),
        "p99_ms": round(pct(0.99) * 1000, 3),
        "digest": fleet.hexdigest(),
        "failures": failures,
        "verified_clients": sum(1 for i, m in enumerate(verify_mask)
                                if m and digests[i]),
    }
