"""SimNode: a full node's internals over the in-memory transport.

The assembly mirrors node/node.go in miniature — real BlockStore /
StateStore / mempool / evidence pool / BlockExecutor, real consensus +
mempool + evidence + blocksync REACTORS on a real p2p.Switch — with
only the transport swapped for simnet's conditioned in-memory links.
Everything between a peer's send queue and the block store (packet
framing, reactor dispatch, pool scheduling, DeferredSigBatch device
verification, ABCI execution) is the production code path.

grow_chain() extends a node's chain with REAL blocks: proposals built
by its own BlockExecutor (PrepareProposal consulted, mempool reaped),
commits signed by the genesis validators' real Ed25519 keys, every
block applied through apply_block so state/app/store agree — the
deterministic substitute for running multi-round consensus when a
bench or test needs a serving node with history.
"""

from __future__ import annotations

import hashlib
import time

from ..abci import types as at
from ..abci.client import LocalClient
from ..apps.kvstore import KVStoreApplication
from ..blocksync.reactor import BlocksyncReactor
from ..consensus.reactor import ConsensusReactor
from ..consensus.state import ConsensusState, test_consensus_config
from ..crypto import ed25519
from ..evidence import EvidencePool, EvidenceReactor
from ..mempool import CListMempool
from ..mempool.reactor import MempoolReactor
from ..node.node import NODE_CHANNELS
from ..p2p.key import NodeKey
from ..p2p.node_info import NodeInfo, ProtocolVersion
from ..p2p.switch import Switch
from ..privval import FilePV
from ..state.execution import BlockExecutor
from ..state.state import make_genesis_state
from ..state.store import StateStore
from ..store.blockstore import BlockStore
from ..store.kv import MemDB
from ..types import canonical
from ..types import events as ev
from ..types.block import (
    BLOCK_ID_FLAG_COMMIT, BlockID, ExtendedCommit, ExtendedCommitSig,
)
from ..types.genesis import GenesisDoc, GenesisValidator
from ..types.part_set import PartSet
from ..types.timestamp import Timestamp
from .transport import SimNetwork, SimTransport

GENESIS_TIME = Timestamp(1_700_000_000, 0)
PRECOMMIT_TYPE = 2


def _seed_bytes(tag: str, seed: int) -> bytes:
    return hashlib.sha256(f"simnet/{seed}/{tag}".encode()).digest()


def make_sim_genesis(n_vals: int = 4, chain_id: str = "simnet-chain",
                     power: int = 10, seed: int = 0,
                     key_module=ed25519):
    """Deterministic genesis + the validators' private keys.
    key_module picks the validator key type (crypto/ed25519 default;
    crypto/secp256k1 builds an ECDSA validator set — the simnet arm
    for the unified-MSM engine A/B)."""
    privs = [key_module.PrivKey.generate(_seed_bytes(f"val-{i}", seed))
             for i in range(n_vals)]
    genesis = GenesisDoc(
        chain_id=chain_id, genesis_time=GENESIS_TIME,
        validators=[GenesisValidator(pub_key=p.pub_key(), power=power)
                    for p in privs])
    return genesis, privs


class _LocalAppConns:
    """proxy.AppConns stand-in over one LocalClient: every connection
    is the same in-proc client (one mutex already serializes access),
    which is all the Handshaker needs (.query.info / .consensus)."""

    def __init__(self, client):
        self.consensus = client
        self.mempool = client
        self.query = client
        self.snapshot = client


class SimNode:
    """One in-process node on a SimNetwork.

    name        — unique within the network; doubles as the transport
                  host ('name:0' is the listen key).
    block_sync  — start the blocksync pool routine (a syncing node).
    consensus_active — run the consensus state machine (a live
                  validator); off by default so serving nodes with
                  pre-built chains don't churn rounds against stale
                  state.  Blocksync hands off to consensus on catch-up
                  only when active.
    dbs         — optional (state_db, block_db, evidence_db) MemDBs.
                  Passing the same triple to a SECOND construction is
                  the crash-restart path (cometbft_tpu/chaos): the
                  stores resume where they were and the production
                  Handshaker replays committed blocks into the fresh
                  app until app and store agree — the same recovery a
                  real node runs at startup (consensus/replay.py).
    wal         — optional consensus WAL (consensus/wal.WAL); the
                  chaos cluster gives validators one so crash-restart
                  can catchup_replay the in-flight height.
    priv_validator — an ed25519 PrivKey, or a prepared FilePV (the
                  restart path reuses the SAME FilePV so last-sign
                  state survives the crash, as the state file would).
    """

    def __init__(self, name: str, genesis: GenesisDoc,
                 network: SimNetwork, *, priv_validator=None,
                 block_sync: bool = False,
                 consensus_active: bool = False,
                 seed: int = 0, app=None, dbs=None, wal=None,
                 peer_timeout: float | None = None):
        self.name = name
        self.genesis = genesis
        self.network = network

        if dbs is None:
            dbs = (MemDB(), MemDB(), MemDB())
        self.dbs = dbs
        state_db, block_db, evidence_db = dbs
        self.state_store = StateStore(state_db)
        resumed = self.state_store.load()
        if resumed is None:
            state = make_genesis_state(genesis)
            self.state_store.bootstrap(state)
        else:
            state = resumed
        self.block_store = BlockStore(block_db)

        self.app = app if app is not None else KVStoreApplication()
        self.client = LocalClient(self.app)
        if resumed is None:
            self.client.init_chain(at.InitChainRequest(
                chain_id=genesis.chain_id,
                initial_height=state.initial_height))
        else:
            # crash-restart: the in-memory app came back empty while
            # the stores kept their history — run the REAL recovery
            # (ABCI handshake replays committed blocks until the app
            # hash agrees with the state store, replay.go semantics)
            from ..consensus.replay import Handshaker
            Handshaker(self.state_store, state, self.block_store,
                       genesis).handshake(_LocalAppConns(self.client))
            state = self.state_store.load() or state
        self.mempool = CListMempool(self.client)
        self.event_bus = ev.EventBus()
        self.evidence_pool = EvidencePool(evidence_db, self.state_store,
                                          self.block_store)
        self.block_exec = BlockExecutor(
            self.state_store, self.client, self.mempool,
            evidence_pool=self.evidence_pool,
            block_store=self.block_store, event_bus=self.event_bus)

        if priv_validator is None:
            pv = None
        elif isinstance(priv_validator, FilePV):
            pv = priv_validator      # restart: keep last-sign state
        else:
            pv = FilePV(priv_validator)
        self.priv_validator = pv
        self.wal = wal
        self.consensus_state = ConsensusState(
            test_consensus_config(), state, self.block_exec,
            self.block_store, wal=wal, priv_validator=pv,
            event_bus=self.event_bus, evidence_pool=self.evidence_pool,
            mempool=self.mempool)
        # per-node flight recorder (libs/flightrec.py): many nodes share
        # this process, so each consensus state records into its own
        # ring; benches/tests read recorder_summary() per node
        from ..libs.flightrec import FlightRecorder
        self.flight_recorder = FlightRecorder()
        self.consensus_state.recorder = self.flight_recorder
        # per-node event timeline (libs/tracetl.py), installed by
        # simnet/tracing.TraceSession; None = uninstrumented
        self.timeline = None
        # an inactive consensus reactor still gossips/receives (real
        # wiring) but never starts the state machine
        self.consensus_reactor = ConsensusReactor(
            self.consensus_state,
            wait_sync=block_sync or not consensus_active)
        self.blocksync_reactor = BlocksyncReactor(
            state, self.block_exec, self.block_store, block_sync,
            consensus_reactor=(self.consensus_reactor
                               if consensus_active else None),
            peer_timeout=peer_timeout)

        self.node_key = NodeKey(ed25519.PrivKey.generate(
            _seed_bytes(f"node-key-{name}", seed)))
        self.node_info = NodeInfo(
            protocol_version=ProtocolVersion(),
            node_id=self.node_key.id,
            listen_addr=f"{name}:0",
            network=genesis.chain_id,
            version="0.1.0-tpu",
            channels=NODE_CHANNELS,
            moniker=name)
        self.transport = SimTransport(network, self.node_key,
                                      self.node_info)
        self.switch = Switch(self.transport, listen_addr=f"{name}:0")
        self.switch.add_reactor("CONSENSUS", self.consensus_reactor)
        self.switch.add_reactor("MEMPOOL", MempoolReactor(self.mempool))
        self.switch.add_reactor("EVIDENCE",
                                EvidenceReactor(self.evidence_pool))
        self.switch.add_reactor("BLOCKSYNC", self.blocksync_reactor)

        self.rpc_server = None
        self.lightserve = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.event_bus.start()
        self.switch.start()

    def stop(self) -> None:
        if self.rpc_server is not None:
            self.rpc_server.stop()
            self.rpc_server = None
        if self.lightserve is not None:
            self.lightserve.close()
            self.lightserve = None
        self.switch.stop()
        self.event_bus.stop()

    def start_rpc(self) -> str:
        """Serve the real JSON-RPC stack over this node's stores on a
        loopback port; returns 'host:port'.  The light-client e2e bench
        points an HttpProvider here — the same wire a reference light
        client would use."""
        from ..rpc.core import Environment
        from ..rpc.server import RPCServer
        env = Environment(
            state_store=self.state_store,
            block_store=self.block_store,
            consensus_state=self.consensus_state,
            mempool=self.mempool,
            evidence_pool=self.evidence_pool,
            p2p_switch=self.switch,
            event_bus=self.event_bus,
            genesis=self.genesis,
            app_conns=None,
            node_info=self.node_info,
            config=None)
        # serving plane wired eagerly (the lazy rpc/core.py seam would
        # also work) so fleet benches can reach node.lightserve
        # counters directly; RPCServer.stop() closes it
        from ..lightserve import LightServeSession
        self.lightserve = LightServeSession(
            self.block_store, self.state_store, self.genesis.chain_id)
        env.lightserve = self.lightserve
        self.rpc_server = RPCServer(env, "127.0.0.1:0",
                                    with_websocket=False)
        self.rpc_server.start()
        return self.rpc_server.bound_addr

    # -- convenience -------------------------------------------------------
    @property
    def addr(self) -> str:
        return f"{self.node_key.id}@{self.name}:0"

    def height(self) -> int:
        return self.block_store.height()

    def sync_target(self) -> int:
        """Highest height blocksync can COMPLETE from this node: the
        tip block's LastCommit verifies height-1, the tip itself waits
        for consensus catch-up (reference pool.IsCaughtUp semantics —
        a syncer converges one block behind the serving tip)."""
        return max(0, self.height() - 1)

    def app_hash(self) -> bytes:
        st = self.state_store.load()
        return st.app_hash if st is not None else b""

    def recorder_summary(self) -> dict:
        """Per-kind flight-recorder counts for this node (the shape
        bench.py reports per node next to its e2e rates)."""
        return self.flight_recorder.summary()

    def round_latencies(self) -> list[float]:
        """Seconds between consecutive new_height recorder events —
        the commit-to-commit round latency series for this node."""
        heights = [e["t"] for e in self.flight_recorder.events()
                   if e["kind"] == "new_height"]
        return [t1 - t0 for t0, t1 in zip(heights, heights[1:])]

    def dial(self, other: "SimNode", persistent: bool = False) -> None:
        self.switch.dial_peer(other.addr, persistent=persistent)

    def wait_for_height(self, height: int, timeout: float = 60.0) -> bool:
        """True once the block at `height` is stored AND applied.  The
        blocksync reactor saves a block before executing it, so the
        store height alone can run one block ahead of the state (and
        of app_hash())."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.block_store.height() >= height:
                st = self.state_store.load()
                if st is not None and st.last_block_height >= height:
                    return True
            time.sleep(0.005)
        return False


def _ext_commit_from(commit) -> ExtendedCommit:
    """Vote-extension-free ExtendedCommit over an existing commit's
    signatures (extensions are disabled in simnet genesis params)."""
    return ExtendedCommit(
        height=commit.height, round=commit.round,
        block_id=commit.block_id,
        extended_signatures=[
            ExtendedCommitSig(s.block_id_flag, s.validator_address,
                              s.timestamp, s.signature)
            for s in commit.signatures])


def grow_chain(node: SimNode, privs, n_blocks: int,
               txs_per_block: int = 1,
               time_step_ns: int = 1_000_000_000) -> list:
    """Extend node's chain by n_blocks through its own executor.

    Every commit signature is a real Ed25519 signature over the
    reference canonical vote sign-bytes; all signers share one
    timestamp per height so the next block's BFT-median time is
    deterministic.  Returns the new blocks."""
    state = node.state_store.load()
    by_addr = {p.pub_key().address(): p for p in privs}

    last_ext = ExtendedCommit()
    h0 = state.last_block_height
    if h0 >= state.initial_height:
        seen = node.block_store.load_seen_commit(h0)
        if seen is None:
            raise ValueError(f"no seen commit at height {h0}")
        last_ext = _ext_commit_from(seen)

    blocks = []
    for h in range(h0 + 1, h0 + n_blocks + 1):
        for t in range(txs_per_block):
            node.mempool.check_tx(f"sim{h}x{t}=v{h}".encode())
        proposer = state.validators.get_proposer().address
        block = node.block_exec.create_proposal_block(
            h, state, last_ext, proposer)
        parts = PartSet.from_data(block.to_proto())
        bid = BlockID(block.hash(), parts.header)

        ts = block.header.time.add_ns(time_step_ns)
        ext_sigs = []
        for v in state.validators.validators:
            sb = canonical.vote_sign_bytes(
                state.chain_id, PRECOMMIT_TYPE, h, 0, bid, ts)
            ext_sigs.append(ExtendedCommitSig(
                BLOCK_ID_FLAG_COMMIT, v.address, ts,
                by_addr[v.address].sign(sb)))
        last_ext = ExtendedCommit(height=h, round=0, block_id=bid,
                                  extended_signatures=ext_sigs)

        node.block_store.save_block(block, parts, last_ext.to_commit())
        state = node.block_exec.apply_block(state, bid, block)
        blocks.append(block)
    return blocks


def clone_chain(src: SimNode, dst: SimNode) -> None:
    """Seed a second serving node with src's chain: validate + apply
    every block through DST'S OWN executor and stores (the same path
    blocksync ingestion takes, minus the network)."""
    state = dst.state_store.load()
    for h in range(state.last_block_height + 1, src.height() + 1):
        block = src.block_store.load_block(h)
        commit = src.block_store.load_seen_commit(h)
        parts = PartSet.from_data(block.to_proto())
        bid = BlockID(block.hash(), parts.header)
        dst.block_store.save_block(block, parts, commit)
        state = dst.block_exec.apply_block(state, bid, block)
