"""TraceSession: per-node event timelines over a simnet cluster.

Many SimNodes share one process, so the tracetl process seam alone
cannot attribute events to nodes.  The session gives every node its own
Timeline and hangs it on the node-owned objects that carry a `timeline`
attribute override (consensus state, consensus reactor, blocksync
reactor), plus one shared "crypto" timeline installed as the process
seam for the layers below node wiring (crypto/dispatch staging/device
threads, votestream flushes) — those are process-global engines, so
their spans land in a cluster-wide pseudo-node rather than being
misattributed to whichever node installed last.

export() merges everything into one Chrome/Perfetto trace_event JSON
(tracetl.perfetto_trace): one "process" per node, flow events for every
cross-node trace-context edge the simnet wire carried.  Flight-recorder
events are folded in per node at export time (clock-compatible — see
tracetl's module docstring), incrementally by seq so repeated exports
never double-ingest.

Usage::

    with TraceSession().install(nodes) as ts:
        ... run the cluster ...
        trace = ts.export()
    tracetl.write_trace("run.trace.json", trace)
    cp = tracetl.critical_path(trace)
"""

from __future__ import annotations

from ..libs import devprof
from ..libs import tracetl

# node-owned objects that honor a per-object `timeline` override
_NODE_SLOTS = ("consensus_state", "consensus_reactor",
               "blocksync_reactor")


class TraceSession:
    """Attach/detach timelines on a set of SimNodes; export merged."""

    def __init__(self, capacity: int = tracetl.DEFAULT_CAPACITY):
        self.capacity = capacity
        self.timelines: dict[str, tracetl.Timeline] = {}
        self.crypto_timeline: tracetl.Timeline | None = None
        self._nodes: list = []
        self._saved: list[tuple] = []       # (obj, prev timeline attr)
        self._prev_seam: tracetl.Timeline | None = None
        self.devprof_recorder: devprof.DevprofRecorder | None = None
        self._prev_devprof = None
        self._owns_devprof = False
        self._installed = False
        self._flightrec_seq: dict[str, int] = {}

    # -- lifecycle ---------------------------------------------------------
    def install(self, nodes) -> "TraceSession":
        if self._installed:
            raise RuntimeError("TraceSession already installed")
        self._nodes = list(nodes)
        for node in self._nodes:
            tl = tracetl.Timeline(node=node.name, capacity=self.capacity)
            self.timelines[node.name] = tl
            node.timeline = tl
            for slot in _NODE_SLOTS:
                obj = getattr(node, slot, None)
                if obj is None:
                    continue
                self._saved.append((obj, getattr(obj, "timeline", None)))
                obj.timeline = tl
        self.crypto_timeline = tracetl.Timeline(
            node="crypto", capacity=self.capacity)
        self._prev_seam = tracetl.timeline()
        tracetl.set_timeline(self.crypto_timeline)
        # device-time accounting rides along: reuse an already-installed
        # recorder (a node's, a bench's) or install a session-owned one
        # so export() always has occupancy counter tracks to merge
        self._prev_devprof = devprof.recorder()
        if self._prev_devprof is None:
            self.devprof_recorder = devprof.DevprofRecorder()
            devprof.set_recorder(self.devprof_recorder)
            self._owns_devprof = True
        else:
            self.devprof_recorder = self._prev_devprof
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for obj, prev in self._saved:
            obj.timeline = prev
        self._saved = []
        for node in self._nodes:
            if getattr(node, "timeline", None) in self.timelines.values():
                node.timeline = None
        tracetl.set_timeline(self._prev_seam)
        self._prev_seam = None
        if self._owns_devprof:
            devprof.set_recorder(self._prev_devprof)
            self._owns_devprof = False
        self._prev_devprof = None
        self._installed = False

    def __enter__(self) -> "TraceSession":
        if not self._installed:
            raise RuntimeError("call install(nodes) before entering")
        return self

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False

    # -- export ------------------------------------------------------------
    def _fold_flightrec(self) -> None:
        """Merge each node's flight-recorder events into its timeline,
        incrementally by seq (safe to call per export)."""
        for node in self._nodes:
            rec = getattr(node, "flight_recorder", None)
            if rec is None:
                continue
            tl = self.timelines[node.name]
            last = self._flightrec_seq.get(node.name, -1)
            new = [e for e in rec.events() if e["seq"] > last]
            if new:
                tl.ingest_flightrec(new)
                self._flightrec_seq[node.name] = new[-1]["seq"]

    def export(self, include_flightrec: bool = True) -> dict:
        """The merged multi-node Perfetto trace (tracetl.perfetto_trace
        shape).  Works during and after the run."""
        if include_flightrec:
            self._fold_flightrec()
        merged = dict(self.timelines)
        if self.crypto_timeline is not None \
                and len(self.crypto_timeline):
            merged["crypto"] = self.crypto_timeline
        rec = self.devprof_recorder
        counters = rec.counter_samples() if rec is not None else None
        # per-consumer verify-p99 counter tracks (libs/latledger.py)
        # render beside the devprof occupancy counters; concatenation
        # is enough — perfetto_trace normalizes over the union
        from ..libs import latledger as _ll

        ll = _ll.recorder()
        if ll is not None:
            lat = ll.counter_samples()
            if lat:
                counters = list(counters or ()) + list(lat)
        return tracetl.perfetto_trace(merged, counters=counters)

    def critical_path(self, include_flightrec: bool = True) -> dict:
        """Convenience: export + proposal->commit decomposition."""
        return tracetl.critical_path(self.export(include_flightrec))
