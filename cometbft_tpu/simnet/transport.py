"""In-memory p2p transport for the simnet subsystem.

SimTransport implements the MultiplexTransport surface (listen / dial /
close) that p2p.Switch drives, but over queues instead of TCP +
SecretConnection: N real nodes in one process, connected through links
with injectable one-way latency, jitter, probabilistic frame drops, and
named partitions — all from ONE seeded RNG per link, so a faulted run
is reproducible bit-for-bit at the fault schedule level.

Fault semantics match what the real stack would see:

- latency/jitter delay whole write() payloads without throttling the
  sender (the LatencyConnection shape: a burst stays a burst, shifted);
- drops swallow whole write() payloads.  MConnection's send routine
  emits write()s that are concatenations of complete length-prefixed
  packets, so a dropped frame loses messages without desyncing the
  receiver's framing — the protocol must recover via its own retry
  machinery (pool redo/timeout), never via transport magic;
- a partition silently drops frames BETWEEN groups and fails dials
  across the cut, like a blackholed route; heal() restores delivery
  for everything sent afterwards.

Everything the Switch/MConnection layer touches is real: channel
descriptors, packetization, flow control, peer lifecycle.  Only the
wire and the crypto handshake are elided (nodes in one process have
nothing to prove to each other; NodeInfo compatibility checks still
run, matching transport.upgrade's gate order).
"""

from __future__ import annotations

import collections
import hashlib
import queue
import random
import threading
import time

from ..libs import lockrank

from ..p2p.transport import ErrRejected, TransportError, parse_addr

_CLOSED = object()          # inbox sentinel: EOF


class LinkSpec:
    """Per-link conditioning: one-way latency (s), uniform jitter (s),
    drop probability per frame, duplicate probability per frame, and
    pairwise-reorder probability per frame.

    dup re-delivers a whole write() payload; reorder holds a frame in a
    one-slot buffer and releases it AFTER the next frame from the same
    sender (a bounded, seeded pairwise swap).  Both operate on whole
    write() payloads: when a payload is a batch of complete packets of
    complete messages, the receiver sees duplicate/reordered MESSAGES
    and the protocol layers dedup (vote sets, block pool) — when a
    large message spans several payloads, a dup/reorder corrupts its
    reassembly, the MConnection errors out, and the peer is evicted,
    which is exactly the byzantine-wire recovery path the chaos
    scenarios exist to exercise."""

    __slots__ = ("latency", "jitter", "drop", "dup", "reorder")

    def __init__(self, latency: float = 0.0, jitter: float = 0.0,
                 drop: float = 0.0, dup: float = 0.0,
                 reorder: float = 0.0):
        self.latency = latency
        self.jitter = jitter
        self.drop = drop
        self.dup = dup
        self.reorder = reorder

    @property
    def conditioned(self) -> bool:
        return self.latency > 0 or self.jitter > 0 or self.drop > 0 \
            or self.dup > 0 or self.reorder > 0


class SimNetwork:
    """Registry of listening SimTransports + link/partition state.

    Node endpoints register under a "host:port" key (the host part of
    the node's listen address names the node).  Link specs are keyed by
    the unordered endpoint pair; partitions are lists of key groups —
    endpoints in different groups cannot exchange frames until heal().
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._mtx = lockrank.RankedLock("simnet.network")
        self._transports: dict[str, "SimTransport"] = {}
        self._default = LinkSpec()
        self._links: dict[frozenset, LinkSpec] = {}
        self._groups: list[set[str]] | None = None

    # -- registry ----------------------------------------------------------
    def _register(self, key: str, transport: "SimTransport") -> None:
        with self._mtx:
            if key in self._transports:
                raise TransportError(f"simnet address {key!r} taken")
            self._transports[key] = transport

    def _unregister(self, key: str) -> None:
        with self._mtx:
            self._transports.pop(key, None)

    # -- link conditioning -------------------------------------------------
    def set_default_link(self, latency: float = 0.0, jitter: float = 0.0,
                         drop: float = 0.0, dup: float = 0.0,
                         reorder: float = 0.0) -> None:
        with self._mtx:
            self._default = LinkSpec(latency, jitter, drop, dup, reorder)

    def set_link(self, a: str, b: str, latency: float = 0.0,
                 jitter: float = 0.0, drop: float = 0.0,
                 dup: float = 0.0, reorder: float = 0.0) -> None:
        """Condition the (a, b) link; names may be bare hosts or
        'host:port' keys."""
        with self._mtx:
            self._links[self._pair(a, b)] = LinkSpec(latency, jitter,
                                                     drop, dup, reorder)

    @staticmethod
    def _norm(name: str) -> str:
        return name.split(":")[0]

    def _pair(self, a: str, b: str) -> frozenset:
        return frozenset((self._norm(a), self._norm(b)))

    def link_spec(self, a: str, b: str) -> LinkSpec:
        with self._mtx:
            return self._links.get(self._pair(a, b), self._default)

    def link_rng(self, a: str, b: str) -> random.Random:
        """Seeded per unordered link: stable across runs and process
        restarts (never Python's randomized str hash)."""
        lo, hi = sorted((self._norm(a), self._norm(b)))
        digest = hashlib.sha256(
            f"simnet/{self.seed}/{lo}/{hi}".encode()).digest()
        return random.Random(int.from_bytes(digest[:8], "big"))

    # -- partitions --------------------------------------------------------
    def partition(self, *groups) -> None:
        """Split the network: endpoints in different groups stop
        exchanging frames.  Endpoints named in no group are unaffected
        (they still reach everyone)."""
        self._groups_set([set(self._norm(n) for n in g) for g in groups])

    def _groups_set(self, groups: list[set[str]] | None) -> None:
        with self._mtx:
            self._groups = groups

    def heal(self) -> None:
        self._groups_set(None)

    def blocked(self, a: str, b: str) -> bool:
        a, b = self._norm(a), self._norm(b)
        with self._mtx:
            groups = self._groups
        if not groups:
            return False
        ga = next((i for i, g in enumerate(groups) if a in g), None)
        gb = next((i for i, g in enumerate(groups) if b in g), None)
        if ga is None or gb is None:
            return False
        return ga != gb

    # -- connection establishment -------------------------------------------
    def connect(self, from_key: str, to_key: str):
        """Pair two endpoints across a conditioned link.  Returns
        (local_conn, remote_conn, remote_transport)."""
        with self._mtx:
            target = self._transports.get(to_key)
        if target is None or target._accept_cb is None:
            raise TransportError(f"no simnet listener at {to_key!r}")
        if self.blocked(from_key, to_key):
            raise TransportError(
                f"simnet partition blocks {from_key!r} -> {to_key!r}")
        link = _Link(self, from_key, to_key)
        return link.end_a, link.end_b, target


class _Link:
    """One bidirectional connection: two endpoints, two delivery pumps.

    Each direction is a FIFO of (due_time, frame); the pump sleeps
    until due and moves frames into the receiving endpoint's inbox.
    Conditioning (drop decision, delay draw) happens at SEND time from
    the link's seeded RNG, so the fault schedule depends only on the
    seed and the sequence of sends, not on receiver timing."""

    def __init__(self, network: SimNetwork, key_a: str, key_b: str):
        self.network = network
        self.key_a = key_a
        self.key_b = key_b
        self._rng = network.link_rng(key_a, key_b)
        self._rng_mtx = lockrank.RankedLock("simnet.rng")
        self._closed = threading.Event()
        self.end_a = _SimConn(self, key_a, key_b)
        self.end_b = _SimConn(self, key_b, key_a)
        self.end_a._peer = self.end_b
        self.end_b._peer = self.end_a

    def send(self, src: "_SimConn", data: bytes) -> None:
        if self._closed.is_set():
            raise OSError("simnet connection closed")
        if self.network.blocked(src.local_key, src.remote_key):
            return                       # partitioned: blackholed
        spec = self.network.link_spec(src.local_key, src.remote_key)
        delay = 0.0
        dup = reorder = False
        if spec.conditioned:
            # every RNG draw is conditional only on the spec and on
            # earlier outcomes of THIS send sequence, so the fault
            # schedule stays a pure function of (seed, sends)
            with self._rng_mtx:
                if spec.drop > 0 and self._rng.random() < spec.drop:
                    return               # dropped whole frame
                if spec.jitter > 0:
                    delay = spec.latency + self._rng.random() * spec.jitter
                else:
                    delay = spec.latency
                if spec.dup > 0:
                    dup = self._rng.random() < spec.dup
                if spec.reorder > 0:
                    reorder = self._rng.random() < spec.reorder
        # pairwise reorder: hold this frame, release it right after the
        # NEXT frame from the same sender (one-slot buffer — bounded
        # disorder; frames for one direction come from MConnection's
        # single send routine, so the slot cannot race)
        held = src._reorder_hold
        src._reorder_hold = None
        if reorder and held is None:
            src._reorder_hold = (data, delay)
            return
        peer = src._peer
        peer._deliver(data, delay)
        if dup:
            peer._deliver(data, delay)   # duplicated whole frame
        if held is not None:
            peer._deliver(held[0], held[1])

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        for end in (self.end_a, self.end_b):
            # flush a held reordered frame ahead of EOF so close
            # never silently converts a reorder into a drop
            held = end._reorder_hold
            end._reorder_hold = None
            if held is not None and end._peer is not None:
                end._peer._deliver(held[0], held[1])
            end._deliver(_CLOSED, 0.0)


class _SimConn:
    """One endpoint: the conn interface MConnection drives
    (write / read / close) plus the remote_addr attribute the Switch
    reads for inbound peers."""

    def __init__(self, link: _Link, local_key: str, remote_key: str):
        self._link = link
        self.local_key = local_key
        self.remote_key = remote_key
        self.remote_addr = remote_key
        self._peer: _SimConn | None = None
        self._inbox: queue.Queue = queue.Queue()
        self._sched: queue.Queue = queue.Queue()
        self._pump_started = False
        self._pump_mtx = lockrank.RankedLock("simnet.pump")
        # one-slot (frame, delay) buffer for the link's pairwise
        # reorder fault; written only from this endpoint's sender thread
        self._reorder_hold: tuple | None = None
        # trace contexts (libs/tracetl.py) delivered with frames but
        # not yet claimed by a completed message.  Touched only by the
        # reader thread (read() stashes, pop_recv_ctx() claims), so no
        # lock; bounded so a non-popping consumer cannot leak
        self._recv_ctxs: collections.deque = collections.deque(
            maxlen=4096)

    # -- receiving side plumbing (called by the OTHER endpoint) -----------
    def _deliver(self, frame, delay: float) -> None:
        # once any frame has been delayed, EVERY later frame routes
        # through the pump — mixing direct puts with an active pump
        # would reorder frames and corrupt message reassembly.  Frames
        # for one endpoint come from a single sender thread
        # (MConnection's send routine), so the started flag cannot race.
        if delay > 0:
            self._ensure_pump()
        if self._pump_started:
            self._sched.put((time.monotonic() + delay, frame))
        else:
            self._inbox.put(frame)

    def _ensure_pump(self) -> None:
        with self._pump_mtx:
            if self._pump_started:
                return
            self._pump_started = True
            threading.Thread(target=self._pump, daemon=True,
                             name=f"simnet-pump-{self.local_key}").start()

    def _pump(self) -> None:
        while True:
            due, frame = self._sched.get()
            wait = due - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            self._inbox.put(frame)
            if frame is _CLOSED:
                return

    # -- conn interface ----------------------------------------------------
    def write(self, data: bytes) -> int:
        self._link.send(self, data)
        return len(data)

    def write_with_ctx(self, data: bytes, ctxs: list) -> int:
        """Ship the frame together with its per-message trace-context
        list: one _Link.send, so drops/dups/reorders condition frame
        and contexts as a unit and the receiver's per-message FIFO
        stays aligned under every fault the link can inject."""
        self._link.send(self, (data, tuple(ctxs)))
        return len(data)

    def pop_recv_ctx(self):
        """Claim the next delivered trace context (None when the frame
        carried none for this message or ctxs are not flowing)."""
        try:
            return self._recv_ctxs.popleft()
        except IndexError:
            return None

    def read(self) -> bytes:
        item = self._inbox.get()
        if item is _CLOSED:
            self._inbox.put(_CLOSED)     # every later read also EOFs
            return b""
        if type(item) is tuple:          # (frame, trace-context list)
            data, ctxs = item
            if ctxs:
                self._recv_ctxs.extend(ctxs)
            return data
        return item

    def close(self) -> None:
        self._link.close()


class SimTransport:
    """Drop-in for p2p.transport.MultiplexTransport over a SimNetwork.

    Addresses look like the real thing ('id@host:port') so
    Switch.dial_peer's parsing, peer-ID pinning, and dedup all run
    unchanged; the 'host' names the node inside the network.
    """

    def __init__(self, network: SimNetwork, node_key, node_info):
        self.network = network
        self.node_key = node_key
        self.node_info = node_info
        self._accept_cb = None
        self.key: str | None = None
        self._closed = False

    # -- MultiplexTransport surface ----------------------------------------
    def listen(self, addr: str, accept_cb) -> str:
        _, host, port = parse_addr(addr)
        self.key = f"{host}:{port}"
        self._accept_cb = accept_cb
        self.network._register(self.key, self)
        return self.key

    def dial(self, addr: str):
        """-> (conn, their NodeInfo); same gate order as
        transport.upgrade: identity pin, self-connect, compatibility."""
        if self._closed:
            raise TransportError("transport closed")
        peer_id, host, port = parse_addr(addr)
        if self.key is None:
            raise TransportError("dial before listen")
        local, remote, target = self.network.connect(
            self.key, f"{host}:{port}")
        their_info = target.node_info
        if peer_id and their_info.node_id != peer_id:
            local.close()
            raise ErrRejected(
                f"peer ID mismatch: dialed {peer_id}, got "
                f"{their_info.node_id}")
        if their_info.node_id == self.node_info.node_id:
            local.close()
            raise ErrRejected("connected to self")
        try:
            self.node_info.compatible_with(their_info)
            their_info.compatible_with(self.node_info)
        except Exception as e:
            local.close()
            raise ErrRejected(str(e)) from e
        # hand the remote end to the target's accept loop off-thread,
        # like the real transport's per-connection handler
        my_info = self.node_info
        threading.Thread(
            target=target._handle_inbound, args=(remote, my_info),
            daemon=True, name=f"simnet-accept-{target.key}").start()
        return local, their_info

    def _handle_inbound(self, conn, their_info) -> None:
        cb = self._accept_cb
        if cb is None or self._closed:
            conn.close()
            return
        try:
            cb(conn, their_info)
        except Exception:
            conn.close()

    def close(self) -> None:
        self._closed = True
        if self.key is not None:
            self.network._unregister(self.key)
