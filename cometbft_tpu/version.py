"""Version constants (reference version/version.go)."""

CMT_SEM_VER = "0.1.0-tpu"       # node software version
ABCI_SEM_VER = "2.1.0"          # ABCI protocol version (reference ABCISemVer)
P2P_PROTOCOL = 9                # reference P2PProtocol
BLOCK_PROTOCOL = 11             # reference BlockProtocol
