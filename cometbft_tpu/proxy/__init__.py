"""AppConns: the 4 logical ABCI connections (reference proxy/)."""

from .multi_app_conn import AppConns, ClientCreator, local_client_creator, \
    socket_client_creator, default_client_creator  # noqa: F401
