"""The AppConns multiplexer (reference proxy/multi_app_conn.go:42-54).

One application, four independent logical connections so slow queries
never block consensus:

  consensus - InitChain, PrepareProposal, ProcessProposal,
              FinalizeBlock, ExtendVote, VerifyVoteExtension, Commit
  mempool   - CheckTx
  query     - Info, Query
  snapshot  - ListSnapshots, OfferSnapshot, Load/ApplySnapshotChunk

For a local app all four share one mutex (the app is one object); for a
socket app they are four pipelined connections to the app process.
"""

from __future__ import annotations

import threading
from typing import Callable

from ..abci.application import Application
from ..abci.client import ABCIClient, LocalClient, SocketClient

ClientCreator = Callable[[], ABCIClient]


def local_client_creator(app: Application) -> ClientCreator:
    """All connections share one mutex (proxy/client.go NewLocalClientCreator)."""
    lock = threading.Lock()
    return lambda: LocalClient(app, shared_lock=lock)


def unsync_local_client_creator(app: Application) -> ClientCreator:
    """Per-connection mutex — for apps that handle their own locking
    (proxy/client.go NewUnsyncLocalClientCreator)."""
    return lambda: LocalClient(app)


def socket_client_creator(addr: str) -> ClientCreator:
    return lambda: SocketClient(addr)


def default_client_creator(addr: str, app: Application | None = None
                           ) -> ClientCreator:
    """Address dispatch (proxy/client.go:265 DefaultClientCreator):
    'kvstore' -> in-proc example app; 'local' -> provided app;
    otherwise a socket address."""
    if addr == "kvstore":
        from ..apps.kvstore import KVStoreApplication
        return local_client_creator(KVStoreApplication())
    if addr == "local":
        if app is None:
            raise ValueError("local client creator requires an app")
        return local_client_creator(app)
    return socket_client_creator(addr)


class AppConns:
    """proxy.AppConns: start/stop the 4 clients as one service."""

    def __init__(self, creator: ClientCreator):
        self.consensus = creator()
        self.mempool = creator()
        self.query = creator()
        self.snapshot = creator()

    def start(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            c.start()

    def stop(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            c.stop()
