"""The AppConns multiplexer (reference proxy/multi_app_conn.go:42-54).

One application, four independent logical connections so slow queries
never block consensus:

  consensus - InitChain, PrepareProposal, ProcessProposal,
              FinalizeBlock, ExtendVote, VerifyVoteExtension, Commit
  mempool   - CheckTx
  query     - Info, Query
  snapshot  - ListSnapshots, OfferSnapshot, Load/ApplySnapshotChunk

For a local app all four share one mutex (the app is one object); for a
socket app they are four pipelined connections to the app process.
"""

from __future__ import annotations

from ..libs import lockrank
from typing import Callable

from ..abci.application import Application
from ..abci.client import ABCIClient, LocalClient, SocketClient

ClientCreator = Callable[[], ABCIClient]


def local_client_creator(app: Application) -> ClientCreator:
    """All connections share one mutex (proxy/client.go NewLocalClientCreator)."""
    lock = lockrank.RankedLock("abci.client")
    return lambda: LocalClient(app, shared_lock=lock)


def unsync_local_client_creator(app: Application) -> ClientCreator:
    """Per-connection mutex — for apps that handle their own locking
    (proxy/client.go NewUnsyncLocalClientCreator)."""
    return lambda: LocalClient(app)


def socket_client_creator(addr: str) -> ClientCreator:
    return lambda: SocketClient(addr)


def default_client_creator(addr: str, app: Application | None = None
                           ) -> ClientCreator:
    """Address dispatch (proxy/client.go:265 DefaultClientCreator):
    'kvstore' -> in-proc example app; 'local' -> provided app;
    otherwise a socket address."""
    if addr == "kvstore":
        from ..apps.kvstore import KVStoreApplication
        return local_client_creator(KVStoreApplication())
    if addr == "local":
        if app is None:
            raise ValueError("local client creator requires an app")
        return local_client_creator(app)
    return socket_client_creator(addr)


class MeteredAppConn:
    """Per-connection ABCI method timing (the reference wraps each
    AppConn method and observes proxy/metrics.go
    MethodTimingSeconds{method, type}).  Metering is off until a
    ProxyMetrics is installed; the wrapper always exists so references
    taken at node build time stay metered once the node wires
    metrics."""

    def __init__(self, client, conn_name: str):
        self._client = client
        self._conn_name = conn_name
        self.metrics = None          # ProxyMetrics when the node meters

    def start(self) -> None:
        self._client.start()

    def stop(self) -> None:
        self._client.stop()

    def __getattr__(self, name):
        attr = getattr(self._client, name)
        if not callable(attr) or name.startswith("_"):
            return attr
        import time

        def timed(*args, **kwargs):
            m = self.metrics       # read dynamically: set_metrics may
            if m is None:          # install metrics after first use
                return attr(*args, **kwargs)
            t0 = time.monotonic()
            try:
                return attr(*args, **kwargs)
            finally:
                m.method_timing_seconds.labels(
                    name, self._conn_name).observe(time.monotonic() - t0)

        # cache on the instance: __getattr__ only fires on misses, so
        # the per-call closure allocation happens once per method
        self.__dict__[name] = timed
        return timed


class AppConns:
    """proxy.AppConns: start/stop the 4 clients as one service."""

    def __init__(self, creator: ClientCreator):
        self.consensus = MeteredAppConn(creator(), "consensus")
        self.mempool = MeteredAppConn(creator(), "mempool")
        self.query = MeteredAppConn(creator(), "query")
        self.snapshot = MeteredAppConn(creator(), "snapshot")

    def set_metrics(self, pm) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            c.metrics = pm

    def start(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            c.start()

    def stop(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            c.stop()
