"""Coalescing light-client serving plane: one TPU-owning node
amortizing shared verify windows across thousands of concurrent
light-client sync requests (docs/LIGHTSERVE.md).

The package splits along the serve path:

- ``planner``: the trust-path planner — the deterministic
  skipping-bisection plan (the 9/16 pivot chain light/client.py
  walks), a hot-trust-height profile, and the serialized payload
  cache (types/part_set.SerializedBlockCache) hot paths serve from
  without re-joining header + commit + valset;
- ``coalesce``: the request coalescer — per-height shared verify
  futures deduping identical header-verify work across concurrent
  requests (the StreamingVerifier in-flight dedupe, generalized
  across RPC requests), drained round-robin for fairness and flushed
  as merged windows;
- ``session``: LightServeSession — the facade rpc/core.py's
  ``light_sync``/``light_status`` routes and the simnet fleet driver
  call; owns the verify flush (one DeferredSigBatch window per flush
  through the VerifyPipeline under ``sigcache.consumer("lightserve")``);
- ``codec``: payload decode + client-side ``verify_commit`` over the
  served wire bytes — what the chaos checker and the fleet driver's
  sampled verification use to prove no client was handed a header
  that does not verify.
"""

from .coalesce import RequestCoalescer, RequestTicket
from .codec import decode_payload, verify_payload
from .planner import TrustPathPlanner, skip_path
from .session import LightServeError, LightServeSession

__all__ = [
    "LightServeError",
    "LightServeSession",
    "RequestCoalescer",
    "RequestTicket",
    "TrustPathPlanner",
    "decode_payload",
    "skip_path",
    "verify_payload",
]
