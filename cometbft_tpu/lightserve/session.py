"""LightServeSession: the serving-plane facade.

One session serves skipping-sync requests over one node's stores:

    plan (planner) -> verify once per height (coalescer -> one merged
    DeferredSigBatch window through the VerifyPipeline, labeled
    ``sigcache.consumer("lightserve")``) -> serve cached payload bytes.

The session is what rpc/core.py's ``light_sync``/``light_status``
handlers, light/proxy.py, the simnet fleet driver, and the chaos
``lightserve_partition`` scenario all talk to; metrics land in
libs.metrics.LightServeMetrics when a node installed one, and plain
int counters mirror them for bench assertions without a registry.
"""

from __future__ import annotations

import json
import os
import time

from ..crypto import sigcache
from ..libs import flightrec, lockrank
from ..libs import metrics as libmetrics
from ..libs.trace import span as trace_span
from ..types import validation
from . import codec
from .coalesce import RequestCoalescer
from .planner import TrustPathPlanner

# every PREFETCH_EVERY requests the planner re-encodes the hot paths
# against the current tip — cheap (cache-guarded) and keeps the hot
# frontier tracking a moving chain without a dedicated thread
PREFETCH_EVERY = 64


class LightServeError(Exception):
    pass


def _coalesce_default() -> bool:
    return os.environ.get("COMETBFT_TPU_LIGHTSERVE_COALESCE", "1") != "0"


class LightServeSession:
    def __init__(self, block_store, state_store, chain_id: str, *,
                 coalesce: bool | None = None,
                 window_ms: float | None = None,
                 max_batch: int | None = None,
                 pipeline=None, planner: TrustPathPlanner | None = None,
                 start: bool = True):
        self.block_store = block_store
        self.state_store = state_store
        self.chain_id = chain_id
        self.coalesce = _coalesce_default() if coalesce is None \
            else bool(coalesce)
        self._pipe = pipeline
        self.planner = planner if planner is not None \
            else TrustPathPlanner()
        self._mtx = lockrank.RankedLock("lightserve.session")
        self._closed = False
        self.requests = 0
        self.headers_served = 0
        self.verify_windows = 0
        self.verify_sigs = 0
        self.failed_heights = 0
        self.coalescer: RequestCoalescer | None = None
        if self.coalesce:
            self.coalescer = RequestCoalescer(
                self._verify_heights, window_ms=window_ms,
                max_batch=max_batch, start=start)

    # -- verify plane ------------------------------------------------------

    def _pipeline(self):
        if self._pipe is None:
            from ..crypto import dispatch

            self._pipe = dispatch.default_pipeline()
        return self._pipe

    def _commit_for(self, height: int):
        commit = self.block_store.load_block_commit(height)
        if commit is None:
            commit = self.block_store.load_seen_commit(height)
        return commit

    def _verify_heights(self, heights, lane: str | None = None) -> dict:
        """Verify one merged batch of heights: host-side structure +
        voting-power tallies per commit, then ONE deferred window
        through the pipeline.  Returns {height: Exception | None} —
        per-height blame, so one forged commit in a merged flush fails
        only the requests that needed that height.  `lane` (from the
        coalescer: the most urgent claimant's consumer) re-lanes the
        window's QoS priority; attribution stays lightserve."""
        out: dict = {h: None for h in heights}
        db = validation.DeferredSigBatch()
        with trace_span("lightserve", "collect", heights=len(out)):
            for h in heights:
                try:
                    commit = self._commit_for(h)
                    vals = self.state_store.load_validators(h)
                    if commit is None or vals is None:
                        raise LightServeError(
                            f"height {h} not in store")
                    validation.verify_commit_light(
                        self.chain_id, vals, commit.block_id, h,
                        commit, defer_to=db)
                except Exception as exc:
                    out[h] = exc
        nsigs = db.count()
        lm = libmetrics.lightserve_metrics()
        with trace_span("lightserve", "verify_dispatch", sigs=nsigs), \
                sigcache.consumer("lightserve"):
            verdict = db.verify_async(self._pipeline(),
                                      subsystem="lightserve",
                                      lane=lane)
            bad = verdict.failed_contexts()
        if nsigs:
            self.verify_windows += 1
            self.verify_sigs += nsigs
            if lm is not None:
                lm.verify_windows_total.inc()
                lm.verify_sigs_total.inc(nsigs)
        for h in bad:
            self.failed_heights += 1
            out[h] = validation.ErrInvalidSignature(
                f"invalid signature in commit at height {h}")
            flightrec.record(flightrec.EV_LIGHTSERVE_REJECT, height=h)
        return out

    # -- payload plane -----------------------------------------------------

    def _encode_payload(self, height: int) -> bytes | None:
        meta = self.block_store.load_block_meta(height)
        commit = self._commit_for(height)
        vals = self.state_store.load_validators(height)
        if meta is None or commit is None or vals is None:
            return None
        return codec.encode_payload(height, meta.header, commit, vals)

    def payload_bytes(self, height: int) -> bytes:
        blob = self.planner.payload(height)
        if blob is None:
            blob = self._encode_payload(height)
            if blob is None:
                raise LightServeError(
                    f"height {height} not in store")
            self.planner.put_payload(height, blob)
        return blob

    def prefetch_hot(self, target: int | None = None,
                     top_n: int = 8) -> int:
        """Planner-driven prefetch: encode the hot trust paths'
        payloads ahead of demand."""
        tip = self.block_store.height() if target is None else target
        n = self.planner.prefetch(tip, self._encode_payload, top_n)
        if n:
            lm = libmetrics.lightserve_metrics()
            if lm is not None:
                lm.prefetched_headers_total.inc(n)
        return n

    # -- serve path --------------------------------------------------------

    def _resolve_heights(self, trusted_height, target_height):
        tip = self.block_store.height()
        base = self.block_store.base()
        target = tip if target_height in (None, "", 0) \
            else int(target_height)
        trusted = base if trusted_height in (None, "") \
            else int(trusted_height)
        if trusted < 1:
            raise LightServeError(
                f"trusted_height must be positive, got {trusted}")
        if target > tip or target < base:
            raise LightServeError(
                f"target height {target} outside [{base}, {tip}]")
        if trusted >= target:
            raise LightServeError(
                f"trusted height {trusted} must be below target "
                f"{target}")
        return trusted, target

    def serve(self, trusted_height, target_height=None):
        """Verify + serve one request's path; returns
        (path, [payload bytes per path height]).  Raises on any
        verification failure — nothing is served past a bad height."""
        t0 = time.perf_counter()
        if self._closed:
            raise LightServeError("session is closed")
        trusted, target = self._resolve_heights(trusted_height,
                                                target_height)
        path = self.planner.plan(trusted, target)
        self.requests += 1
        lm = libmetrics.lightserve_metrics()
        if lm is not None:
            lm.requests_total.inc()
        if self.coalescer is not None:
            self.coalescer.acquire(path).wait()
        else:
            results = self._verify_heights(path)
            for h in path:
                if results[h] is not None:
                    raise results[h]
        blobs = [self.payload_bytes(h) for h in path]
        self.headers_served += len(path)
        if lm is not None:
            lm.headers_served_total.inc(len(path))
            lm.serve_seconds.observe(time.perf_counter() - t0)
        if self.requests % PREFETCH_EVERY == 0:
            self.prefetch_hot(target)
        return path, blobs

    def sync(self, trusted_height=None, target_height=None) -> dict:
        """The light_sync RPC result: the verified path and its light
        blocks, decoded from the same canonical bytes ``serve`` hands
        the wire."""
        trusted, target = self._resolve_heights(trusted_height,
                                                target_height)
        path, blobs = self.serve(trusted, target)
        return {
            "trusted_height": str(trusted),
            "target_height": str(target),
            "path": [str(h) for h in path],
            "light_blocks": [codec.decode_payload(b) for b in blobs],
            "coalesced": self.coalesce,
        }

    def status(self) -> dict:
        cstats = self.coalescer.stats() if self.coalescer is not None \
            else {}
        return {
            "coalescing": self.coalesce,
            "chain_id": self.chain_id,
            "latest_height": str(self.block_store.height()),
            "base_height": str(self.block_store.base()),
            "requests": str(self.requests),
            "headers_served": str(self.headers_served),
            "verify_windows": str(self.verify_windows),
            "verify_sigs": str(self.verify_sigs),
            "failed_heights": str(self.failed_heights),
            "coalesced_heights": str(cstats.get("coalesced", 0)),
            "inflight_heights": str(cstats.get("inflight_heights", 0)),
            "planner": {k: str(v)
                        for k, v in self.planner.stats().items()},
        }

    def close(self) -> None:
        with self._mtx:
            if self._closed:
                return
            self._closed = True
        if self.coalescer is not None:
            self.coalescer.close()
