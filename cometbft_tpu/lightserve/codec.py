"""Serve-payload wire format + the client-side decode/verify pair.

A lightserve payload is one height's LightBlock as canonical JSON
(sorted keys, no whitespace): the signed header and the validator set
in exactly the shapes rpc/serialize.py emits, so the bytes double as
the ``light_sync`` RPC result.  Canonical encoding is what makes the
coalescing A/B meaningful: two arms serving the same chain MUST
produce bit-identical blobs.

``verify_payload`` is the fleet/chaos checker's client: it
reconstructs the Commit and ValidatorSet from the received wire bytes
(not from the server's objects) and runs the full ``verify_commit`` —
the strongest "no client received an unverifiable header" assertion
available without a second chain.
"""

from __future__ import annotations

import base64
import json

from ..libs import tmjson
from ..rpc import serialize as ser
from ..types import validation
from ..types.block import BlockID, Commit, CommitSig, PartSetHeader
from ..types.timestamp import Timestamp
from ..types.validator_set import Validator, ValidatorSet

_FLAGS = {"BLOCK_ID_FLAG_ABSENT": 1, "BLOCK_ID_FLAG_COMMIT": 2,
          "BLOCK_ID_FLAG_NIL": 3}


def encode_payload(height: int, header, commit, vals) -> bytes:
    doc = {
        "height": str(height),
        "signed_header": {
            "header": ser.header_json(header),
            "commit": ser.commit_json(commit),
        },
        "validator_set": {
            "validators": [ser.validator_json(v)
                           for v in vals.validators],
            "total_voting_power": str(vals.total_voting_power()),
        },
    }
    return json.dumps(doc, sort_keys=True,
                      separators=(",", ":")).encode()


def decode_payload(blob: bytes) -> dict:
    return json.loads(blob)


def _block_id_from_json(d: dict) -> BlockID:
    return BlockID(
        bytes.fromhex(d["hash"]),
        PartSetHeader(int(d["parts"]["total"]),
                      bytes.fromhex(d["parts"]["hash"])))


def commit_from_json(d: dict) -> Commit:
    sigs = []
    for s in d["signatures"]:
        flag = _FLAGS.get(s["block_id_flag"])
        if flag is None:
            flag = int(s["block_id_flag"])
        sig = base64.b64decode(s["signature"]) if s["signature"] else b""
        sigs.append(CommitSig(
            block_id_flag=flag,
            validator_address=bytes.fromhex(s["validator_address"]),
            timestamp=Timestamp.from_rfc3339(s["timestamp"]),
            signature=sig))
    return Commit(int(d["height"]), int(d["round"]),
                  _block_id_from_json(d["block_id"]), sigs)


def validator_set_from_json(d: dict) -> ValidatorSet:
    vals = []
    for v in d["validators"]:
        pub = tmjson.from_obj(v["pub_key"])
        vals.append(Validator(
            pub_key=pub,
            voting_power=int(v["voting_power"]),
            proposer_priority=int(v["proposer_priority"]),
            address=bytes.fromhex(v["address"])))
    return ValidatorSet.from_validated(vals)


def verify_payload(chain_id: str, blob: bytes) -> dict:
    """Decode one served payload and verify it the way a receiving
    light client would: structural consistency, then the full
    ``verify_commit`` (+2/3 power, every signature checked) over the
    RECONSTRUCTED commit and validator set.  Raises on any failure;
    returns the decoded document."""
    doc = decode_payload(blob)
    height = int(doc["height"])
    header = doc["signed_header"]["header"]
    commit = commit_from_json(doc["signed_header"]["commit"])
    vals = validator_set_from_json(doc["validator_set"])
    if int(header["height"]) != height or commit.height != height:
        raise validation.CommitVerificationError(
            f"payload height mismatch: payload {height}, header "
            f"{header['height']}, commit {commit.height}")
    if header["chain_id"] != chain_id:
        raise validation.CommitVerificationError(
            f"payload chain {header['chain_id']!r} != {chain_id!r}")
    validation.verify_commit(chain_id, vals, commit.block_id, height,
                             commit)
    return doc
