"""Request coalescer: many concurrent sync requests, one verify per
height.

Concurrent ``/light_sync`` requests overlap heavily — every client
walking to the same tip shares the tail of its pivot chain, and
popular trust heights share whole paths.  The coalescer holds ONE
shared future per in-flight height (the StreamingVerifier in-flight
dedupe of PR 9, generalized across RPC requests): the first request to
ask for a height enqueues it, every later request attaches to the same
future, and a flusher drains queued heights into merged verify windows
(the session's ``verify_fn``).

Fairness: queued heights are drained ROUND-ROBIN across requests, so
a one-height request rides the next flush beside a 60-height request's
head instead of behind its tail.

Locking: everything is guarded by one RankedCondition
("lightserve.cv", rank above — i.e. outside — the stores and the
verify plane); the lock is held only around queue/counter mutation,
never across store reads or pipeline submits.  ``verify_fn`` runs with
no coalescer lock held.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import OrderedDict, deque

from ..libs import lockrank
from ..libs import metrics as libmetrics

DEFAULT_WINDOW_MS = float(os.environ.get(
    "COMETBFT_TPU_LIGHTSERVE_WINDOW_MS", "2"))
DEFAULT_MAX_BATCH = int(os.environ.get(
    "COMETBFT_TPU_LIGHTSERVE_MAX_BATCH", "512"))


class _Entry:
    __slots__ = ("future", "refs", "queued", "consumer")

    def __init__(self, future, consumer: str = "lightserve"):
        self.future = future
        self.refs = 1
        self.queued = True
        # the OLDEST claimant's consumer label: a merged flush
        # schedules under the most urgent claimant lane in the batch
        # (crypto/sched.py) while ledger/cache attribution stays the
        # session's own subsystem
        self.consumer = consumer


class RequestTicket:
    """One request's claim on its path heights: a mapping from height
    to the (possibly shared) verify future."""

    __slots__ = ("_co", "tid", "futures", "owned", "cancelled")

    def __init__(self, co, tid, futures, owned):
        self._co = co
        self.tid = tid
        self.futures = futures          # OrderedDict[height -> future]
        self.owned = owned              # heights this ticket enqueued
        self.cancelled = False

    def wait(self, timeout: float | None = None) -> None:
        """Block until every height verified; raises the first
        failure (in path order).  On failure the remaining resolved
        futures' exceptions are retrieved so nothing trips the
        future-leak sanitizer, and still-queued exclusive heights are
        released via cancel()."""
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            for fut in self.futures.values():
                left = None if deadline is None \
                    else max(0.0, deadline - time.monotonic())
                fut.result(left)
        except BaseException:
            for fut in self.futures.values():
                if fut.done():
                    try:
                        fut.exception(timeout=0)
                    except BaseException:
                        pass
            self.cancel()
            raise

    def cancel(self) -> None:
        if not self.cancelled:
            self.cancelled = True
            self._co._cancel(self)


class RequestCoalescer:
    def __init__(self, verify_fn, *, window_ms: float | None = None,
                 max_batch: int | None = None, start: bool = True):
        # verify_fn(heights) -> dict[height -> Exception | None];
        # when it accepts a `lane` kwarg the flusher passes the most
        # urgent claimant lane of each merged batch (QoS scheduling
        # only — attribution is the session's)
        self._verify = verify_fn
        try:
            import inspect

            self._verify_takes_lane = "lane" in \
                inspect.signature(verify_fn).parameters
        except (TypeError, ValueError):   # builtins, odd callables
            self._verify_takes_lane = False
        self.window_s = (DEFAULT_WINDOW_MS if window_ms is None
                         else float(window_ms)) / 1000.0
        self.max_batch = max(1, DEFAULT_MAX_BATCH if max_batch is None
                             else int(max_batch))
        self._cv = lockrank.RankedCondition(name="lightserve.cv")
        self._entries: dict[int, _Entry] = {}
        # per-ticket pending queues + the round-robin rotation order
        self._queues: OrderedDict[int, deque] = OrderedDict()
        self._rr: deque = deque()
        self._ids = itertools.count(1)
        self._stop = False
        self._thread: threading.Thread | None = None
        self.flushes = 0
        self.coalesced = 0
        self.verified_heights = 0
        self.cancelled_heights = 0
        if start:
            self._thread = threading.Thread(
                target=self._run, name="lightserve-flush", daemon=True)
            self._thread.start()

    # -- request side ------------------------------------------------------

    def acquire(self, heights) -> RequestTicket:
        from ..libs import latledger

        tid = next(self._ids)
        futures: OrderedDict = OrderedDict()
        owned: set[int] = set()
        attached = 0
        with self._cv:
            if self._stop:
                raise RuntimeError("coalescer is closed")
            q = None
            for h in heights:
                if h in futures:
                    continue        # duplicate within one request
                e = self._entries.get(h)
                if e is not None:
                    e.refs += 1
                    attached += 1
                    # every claimant on a shared height gets its OWN
                    # latency-ledger row: the attached request's wait
                    # is real even though the verify is shared (the
                    # owner's decomposition rides the merged pipeline
                    # window; this row lands as coalesce_wait)
                    req = latledger.submit(1, consumer="lightserve")
                    if req is not None:
                        e.future.add_done_callback(
                            lambda f, r=req: r.resolve_coalesced())
                else:
                    from ..crypto import sigcache

                    # record who FIRST asked for this height; the
                    # ambient default ("crypto" = nobody declared)
                    # means a plain serving request -> lightserve
                    label = sigcache.current_consumer()
                    e = _Entry(lockrank.TrackedFuture(),
                               consumer=label if label in sigcache.LANES
                               and label != "crypto" else "lightserve")
                    self._entries[h] = e
                    if q is None:
                        q = deque()
                        self._queues[tid] = q
                        self._rr.append(tid)
                    q.append(h)
                    owned.add(h)
                futures[h] = e.future
            self.coalesced += attached
            self._gauge_locked(attached)
            self._cv.notify_all()
        return RequestTicket(self, tid, futures, owned)

    def _cancel(self, ticket: RequestTicket) -> None:
        """Drop the ticket's claims: shared heights just lose a ref;
        exclusively-held heights still queued are removed entirely
        (their futures cancelled), so an abandoned request costs the
        flusher nothing."""
        with self._cv:
            q = self._queues.get(ticket.tid)
            for h, fut in ticket.futures.items():
                e = self._entries.get(h)
                if e is None or e.future is not fut:
                    continue
                e.refs -= 1
                if e.refs <= 0 and e.queued:
                    del self._entries[h]
                    self.cancelled_heights += 1
                    if q is not None:
                        try:
                            q.remove(h)
                        except ValueError:
                            pass
                    fut.cancel()
            if q is not None and not q:
                self._queues.pop(ticket.tid, None)
            self._gauge_locked(0)

    # -- flush side --------------------------------------------------------

    def _gauge_locked(self, attached: int) -> None:
        lm = libmetrics.lightserve_metrics()
        if lm is not None:
            if attached:
                lm.coalesced_heights_total.inc(attached)
            lm.inflight_heights.set(len(self._entries))

    def _drain_locked(self) -> list[int]:
        """Round-robin across ticket queues, one height per turn, up
        to max_batch."""
        batch: list[int] = []
        spins = len(self._rr)
        while self._rr and len(batch) < self.max_batch and spins >= 0:
            tid = self._rr.popleft()
            q = self._queues.get(tid)
            if not q:
                self._queues.pop(tid, None)
                spins -= 1
                continue
            h = q.popleft()
            if q:
                self._rr.append(tid)
            else:
                self._queues.pop(tid, None)
            e = self._entries.get(h)
            if e is not None and e.queued:
                e.queued = False
                batch.append(h)
        return batch

    def _flush_once(self) -> int:
        """Drain one merged batch and verify it; resolves the heights'
        shared futures.  Returns the batch size (0 = nothing queued)."""
        with self._cv:
            batch = self._drain_locked()
            lanes = [self._entries[h].consumer for h in batch
                     if h in self._entries]
        if not batch:
            return 0
        try:
            if self._verify_takes_lane:
                from ..crypto import sigcache

                # the merged window rides the MOST URGENT claimant's
                # lane: one consensus-priority claimant lifts the
                # whole shared flush
                lane = (min(lanes, key=sigcache.lane_priority)
                        if lanes else None)
                results = self._verify(batch, lane=lane)
            else:
                results = self._verify(batch)
        except Exception as exc:        # verify_fn itself failed
            results = {h: exc for h in batch}
        with self._cv:
            self.flushes += 1
            self.verified_heights += len(batch)
            resolved = [(h, self._entries.pop(h, None)) for h in batch]
            self._gauge_locked(0)
        for h, e in resolved:
            if e is None:
                continue
            exc = results.get(h)
            if exc is None:
                e.future.set_result(True)
            else:
                e.future.set_exception(exc)
                if e.refs <= 0:
                    # every claimant cancelled while the flush was in
                    # flight: retrieve the exception ourselves so the
                    # dropped future is not a sanitizer leak
                    try:
                        e.future.exception(timeout=0)
                    except BaseException:
                        pass
        return len(batch)

    def flush_now(self) -> int:
        """Synchronously drain everything queued (tests, close)."""
        total = 0
        while True:
            n = self._flush_once()
            if n == 0:
                return total
            total += n

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._stop and not self._queues:
                    self._cv.wait(timeout=0.1)
                if self._stop:
                    return
            # accumulation window: let concurrent arrivals merge into
            # this flush before draining
            if self.window_s > 0:
                time.sleep(self.window_s)
            self._flush_once()

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)
        # serve whatever was still queued so no future hangs forever
        self.flush_now()

    def stats(self) -> dict:
        with self._cv:
            return {
                "flushes": self.flushes,
                "coalesced": self.coalesced,
                "verified_heights": self.verified_heights,
                "cancelled_heights": self.cancelled_heights,
                "inflight_heights": len(self._entries),
                "pending_tickets": len(self._queues),
            }
