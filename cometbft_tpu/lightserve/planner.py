"""Trust-path planner: precompute the skipping-bisection paths common
client trust heights walk, and keep their encoded payloads hot.

A skipping light client (light/client.py _verify_skipping) that cannot
trust the target directly pivots at 9/16 of the remaining span and
retries; under a stable validator-set overlap profile the heights it
will request are a deterministic function of (trusted, target).  The
serving node exploits that: ``skip_path`` reproduces the pivot chain,
the planner counts which trust heights clients actually arrive with,
and ``prefetch`` encodes the union of the hot paths' LightBlock
payloads into a SerializedBlockCache so the serve path hands out
cached wire bytes without re-joining header + commit + valset per
request.
"""

from __future__ import annotations

import os
from collections import Counter

from ..libs import lockrank
from ..types.part_set import SerializedBlockCache

# pivot ratio, identical to light/client.py (client.go:31-32) so the
# server-side plan is the path a real skipping client would walk
_SKIP_NUM = 9
_SKIP_DEN = 16

DEFAULT_PLAN_DEPTH = int(os.environ.get(
    "COMETBFT_TPU_LIGHTSERVE_PLAN_DEPTH", "64"))
DEFAULT_PAYLOAD_CAPACITY = int(os.environ.get(
    "COMETBFT_TPU_LIGHTSERVE_PAYLOAD_CACHE", "1024"))


def skip_path(trusted: int, target: int,
              max_pivots: int = DEFAULT_PLAN_DEPTH) -> list[int]:
    """Heights a skipping client verifies between ``trusted``
    (exclusive) and ``target`` (inclusive): the geometric 9/16 pivot
    chain, worst case for trust propagation (every direct try fails,
    every pivot verifies).  Serving the full chain gives the client a
    proof path where each hop is verifiable from the previous one;
    ``max_pivots`` bounds pathological spans (the tail collapses to
    adjacent steps near the target anyway)."""
    if target <= trusted:
        return []
    path: list[int] = []
    v = trusted
    while len(path) < max_pivots:
        span = target - v
        if span <= 1:
            break
        p = v + span * _SKIP_NUM // _SKIP_DEN
        if p <= v:
            p = v + 1
        if p >= target:
            break
        path.append(p)
        v = p
    path.append(target)
    return path


class TrustPathPlanner:
    """Hot-path profile + payload cache for one serving session.

    The lock guards only the trust-height counter; the payload cache
    (part_set.block_cache, rank far below lightserve.planner) has its
    own lock and is never touched while the planner lock is held."""

    def __init__(self, max_pivots: int | None = None,
                 payload_capacity: int | None = None):
        self.max_pivots = (DEFAULT_PLAN_DEPTH if max_pivots is None
                           else max(1, int(max_pivots)))
        self.cache = SerializedBlockCache(
            capacity=DEFAULT_PAYLOAD_CAPACITY
            if payload_capacity is None else payload_capacity)
        self._mtx = lockrank.RankedLock("lightserve.planner")
        self._trust_counts: Counter = Counter()
        self.plans = 0
        self.prefetched = 0

    def plan(self, trusted: int, target: int) -> list[int]:
        """The serve path for one request; notes the trust height in
        the hot profile as a side effect."""
        with self._mtx:
            self._trust_counts[trusted] += 1
            self.plans += 1
        return skip_path(trusted, target, self.max_pivots)

    def hot_trust_heights(self, top_n: int = 8) -> list[int]:
        with self._mtx:
            return [h for h, _ in self._trust_counts.most_common(top_n)]

    def hot_heights(self, target: int, top_n: int = 8) -> list[int]:
        """Union of the skip paths the most common trust heights walk
        to ``target`` — the prefetch frontier."""
        out: set[int] = set()
        for trusted in self.hot_trust_heights(top_n):
            out.update(skip_path(trusted, target, self.max_pivots))
        return sorted(out)

    def prefetch(self, target: int, encode_fn, top_n: int = 8) -> int:
        """Encode not-yet-cached payloads on the hot paths;
        ``encode_fn(height) -> bytes | None`` joins and serializes one
        LightBlock.  Returns how many payloads were newly encoded."""
        fresh = 0
        for h in self.hot_heights(target, top_n):
            if self.cache.get_block_bytes(h) is not None:
                continue
            blob = encode_fn(h)
            if blob is None:
                continue
            self.cache.put(h, blob, ())
            fresh += 1
        if fresh:
            with self._mtx:
                self.prefetched += fresh
        return fresh

    def payload(self, height: int) -> bytes | None:
        return self.cache.get_block_bytes(height)

    def put_payload(self, height: int, blob: bytes) -> None:
        self.cache.put(height, blob, ())

    def stats(self) -> dict:
        with self._mtx:
            distinct = len(self._trust_counts)
            plans = self.plans
            prefetched = self.prefetched
        return {
            "plans": plans,
            "distinct_trust_heights": distinct,
            "prefetched": prefetched,
            "payload_cache_hits": self.cache.hits,
            "payload_cache_misses": self.cache.misses,
            "payload_cache_evictions": self.cache.evictions,
            "payload_cache_entries": len(self.cache),
        }
