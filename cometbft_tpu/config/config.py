"""The full node configuration tree (reference config/config.go:93-108)
with TOML persistence (config/toml.go).

Layout on disk mirrors the reference:
    <root>/config/config.toml
    <root>/config/genesis.json
    <root>/config/node_key.json
    <root>/config/priv_validator_key.json
    <root>/data/priv_validator_state.json
    <root>/data/*.db, <root>/data/cs.wal/
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields


@dataclass
class BaseConfig:
    """config.go BaseConfig."""
    root_dir: str = ""
    moniker: str = "tpu-node"
    db_backend: str = "sqlite"        # memdb | sqlite
    db_dir: str = "data"
    log_level: str = "info"
    genesis_file: str = "config/genesis.json"
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    priv_validator_laddr: str = ""
    node_key_file: str = "config/node_key.json"
    abci: str = "kvstore"             # app address or 'kvstore' builtin
    filter_peers: bool = False


@dataclass
class RPCConfig:
    laddr: str = "tcp://127.0.0.1:26657"
    cors_allowed_origins: list = field(default_factory=list)
    grpc_laddr: str = ""
    max_open_connections: int = 900
    unsafe: bool = False
    max_subscription_clients: int = 100
    max_subscriptions_per_client: int = 5
    timeout_broadcast_tx_commit: float = 10.0
    max_body_bytes: int = 1000000
    max_header_bytes: int = 1 << 20
    pprof_laddr: str = ""
    # privileged JSON-RPC listener for the data-companion pruning service
    # (reference: rpc/grpc/server privileged services, node.go:819-861)
    privileged_laddr: str = ""
    # native gRPC listeners (reference [grpc] config section): public
    # Version/Block/BlockResults services and the privileged pruning
    # service (rpc/grpc_services.py)
    grpc_services_laddr: str = ""
    grpc_privileged_laddr: str = ""


@dataclass
class P2PConfig:
    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    seeds: str = ""
    persistent_peers: str = ""
    addr_book_file: str = "config/addrbook.json"
    addr_book_strict: bool = True
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    flush_throttle_timeout: float = 0.1
    max_packet_msg_payload_size: int = 1024
    send_rate: int = 5120000
    recv_rate: int = 5120000
    pex: bool = True
    seed_mode: bool = False
    private_peer_ids: str = ""
    allow_duplicate_ip: bool = False
    handshake_timeout: float = 20.0
    dial_timeout: float = 3.0
    # testnet WAN emulation: one-way delivery delay added to every
    # peer frame this node sends (the reference's e2e runner injects
    # per-zone latency with tc netem, test/e2e/pkg/latency/; a
    # subprocess testnet has no containers, so the transport does it)
    emulate_latency_ms: float = 0.0


@dataclass
class MempoolConfig:
    recheck: bool = True
    broadcast: bool = True
    size: int = 5000
    max_txs_bytes: int = 1 << 30
    cache_size: int = 10000
    keep_invalid_txs_in_cache: bool = False
    max_tx_bytes: int = 1048576


@dataclass
class StateSyncConfig:
    enable: bool = False
    rpc_servers: list = field(default_factory=list)
    trust_height: int = 0
    trust_hash: str = ""
    trust_period: float = 168 * 3600.0   # 1 week
    discovery_time: float = 15.0
    chunk_request_timeout: float = 10.0
    chunk_fetchers: int = 4
    temp_dir: str = ""


@dataclass
class BlockSyncConfig:
    version: str = "v0"
    # per-request peer timeout (blocksync/pool.py peerTimeout); 0 (or
    # negative) defers to the module default, keeping old configs valid
    peer_timeout: float = 0.0


@dataclass
class ConsensusTimeoutConfig:
    """config.go:1163-1207 defaults."""
    wal_file: str = "data/cs.wal/wal"
    timeout_propose: float = 3.0
    timeout_propose_delta: float = 0.5
    timeout_prevote: float = 1.0
    timeout_prevote_delta: float = 0.5
    timeout_precommit: float = 1.0
    timeout_precommit_delta: float = 0.5
    timeout_commit: float = 1.0
    double_sign_check_height: int = 0
    create_empty_blocks: bool = True
    create_empty_blocks_interval: float = 0.0
    peer_gossip_sleep_duration: float = 0.1
    peer_query_maj23_sleep_duration: float = 2.0


@dataclass
class StorageConfig:
    discard_abci_responses: bool = False


@dataclass
class TxIndexConfig:
    """reference config.go TxIndexConfig: "kv" or "null"."""
    indexer: str = "kv"


@dataclass
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    max_open_connections: int = 3
    namespace: str = "cometbft_tpu"


@dataclass
class Config:
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    blocksync: BlockSyncConfig = field(default_factory=BlockSyncConfig)
    consensus: ConsensusTimeoutConfig = field(
        default_factory=ConsensusTimeoutConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    instrumentation: InstrumentationConfig = field(
        default_factory=InstrumentationConfig)

    # -- path helpers ------------------------------------------------------
    def _abs(self, rel: str) -> str:
        if os.path.isabs(rel):
            return rel
        return os.path.join(self.base.root_dir, rel)

    def genesis_file(self) -> str:
        return self._abs(self.base.genesis_file)

    def priv_validator_key_file(self) -> str:
        return self._abs(self.base.priv_validator_key_file)

    def priv_validator_state_file(self) -> str:
        return self._abs(self.base.priv_validator_state_file)

    def node_key_file(self) -> str:
        return self._abs(self.base.node_key_file)

    def addr_book_file(self) -> str:
        return self._abs(self.p2p.addr_book_file)

    def wal_file(self) -> str:
        return self._abs(self.consensus.wal_file)

    def db_dir(self) -> str:
        return self._abs(self.base.db_dir)

    def ensure_dirs(self) -> None:
        for d in ("config", "data"):
            os.makedirs(os.path.join(self.base.root_dir, d),
                        exist_ok=True)
        os.makedirs(os.path.dirname(self.wal_file()), exist_ok=True)

    def validate_basic(self) -> None:
        if self.base.db_backend not in ("memdb", "sqlite"):
            raise ValueError(
                f"unknown db_backend {self.base.db_backend!r}")
        for name in ("timeout_propose", "timeout_prevote",
                     "timeout_precommit", "timeout_commit"):
            if getattr(self.consensus, name) < 0:
                raise ValueError(f"negative consensus.{name}")
        if self.mempool.size < 0 or self.mempool.max_tx_bytes < 0:
            raise ValueError("negative mempool limits")


def default_config(root_dir: str = "") -> Config:
    cfg = Config()
    cfg.base.root_dir = root_dir
    return cfg


def test_config(root_dir: str = "") -> Config:
    """config.TestConfig: tight timeouts, memdb, random ports."""
    cfg = default_config(root_dir)
    cfg.base.db_backend = "memdb"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    c = cfg.consensus
    c.timeout_propose = 0.08
    c.timeout_propose_delta = 0.002
    c.timeout_prevote = 0.02
    c.timeout_prevote_delta = 0.002
    c.timeout_precommit = 0.02
    c.timeout_precommit_delta = 0.002
    c.timeout_commit = 0.02
    return cfg


# -- TOML ------------------------------------------------------------------

_SECTIONS = [
    ("", "base"), ("rpc", "rpc"), ("p2p", "p2p"),
    ("mempool", "mempool"), ("statesync", "statesync"),
    ("blocksync", "blocksync"), ("consensus", "consensus"),
    ("storage", "storage"), ("tx_index", "tx_index"),
    ("instrumentation", "instrumentation"),
]


def _toml_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, list):
        return "[" + ", ".join(_toml_value(x) for x in v) + "]"
    return '"' + str(v).replace("\\", "\\\\").replace('"', '\\"') + '"'


def write_config_file(path: str, cfg: Config) -> None:
    """config/toml.go WriteConfigFile analog."""
    lines = ["# cometbft_tpu configuration", ""]
    for section, attr in _SECTIONS:
        sub = getattr(cfg, attr)
        if section:
            lines.append(f"[{section}]")
        for f in fields(sub):
            if f.name == "root_dir":
                continue
            lines.append(f"{f.name} = {_toml_value(getattr(sub, f.name))}")
        lines.append("")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as fobj:
        fobj.write("\n".join(lines))


def load_config(root_dir: str) -> Config:
    """Read <root>/config/config.toml into a Config (missing file =
    defaults)."""
    try:
        import tomllib
    except ModuleNotFoundError:  # Python < 3.11: tomllib is vendored tomli
        import tomli as tomllib
    cfg = default_config(root_dir)
    path = os.path.join(root_dir, "config", "config.toml")
    if not os.path.exists(path):
        return cfg
    with open(path, "rb") as f:
        data = tomllib.load(f)
    for section, attr in _SECTIONS:
        sub = getattr(cfg, attr)
        src = data if section == "" else data.get(section, {})
        for fdef in fields(sub):
            if fdef.name in src and fdef.name != "root_dir":
                setattr(sub, fdef.name, src[fdef.name])
    return cfg
