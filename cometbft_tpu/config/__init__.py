"""Configuration tree (reference config/)."""

from .config import (  # noqa: F401
    BaseConfig, Config, ConsensusTimeoutConfig, MempoolConfig, P2PConfig,
    RPCConfig, StateSyncConfig, BlockSyncConfig, StorageConfig,
    InstrumentationConfig, default_config, test_config, load_config,
    write_config_file,
)
