"""Fleet observability plane: cross-process telemetry collection.

The seventh observability layer.  flightrec / tracetl / devprof /
latledger / Prometheus each describe ONE interpreter; the e2e runner's
real node subprocesses need their telemetry harvested (live RPC dumps
plus the crash-safe spools libs/telspool.py persists), clock-aligned
onto one fleet time axis (clocksync.py), and merged into the single
Perfetto trace / critical-path / histogram readings the in-process
layers already provide (merge.py, report.py).

    capture = collect.collect_testnet(testnet)   # or load from JSON
    fleet = report.fleet_report(capture)         # trace + readings
"""

from . import clocksync, collect, merge, report

__all__ = ["clocksync", "collect", "merge", "report"]
