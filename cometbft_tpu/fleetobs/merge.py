"""Rebase per-process telemetry onto one fleet axis and merge it into
the single-trace shapes the in-process layers already export.

Input is a CAPTURE (fleetobs/collect.py): per logical node, the
recovered spool records plus an optional live RPC dump.  Each record
belongs to a clock domain — (node, incarnation) — and the merge:

1. deduplicates ring events per domain by their ring seq (the spool
   writes increments, the live dump overlaps the newest of them);
2. solves one fleet-axis offset per domain (fleetobs/clocksync.py:
   edge pairs where the p2p mesh provides them, spooled wall-clock
   anchors where it does not);
3. rebases every timeline/flightrec event and devprof/latledger
   counter sample onto the fleet axis;
4. folds all incarnations of one node into ONE replay timeline per
   node — `tracetl.perfetto_trace` assigns pids by sorted node name,
   so a node keeps its pid across restarts BY CONSTRUCTION — and
   prefixes counter tracks per node ("node00:occupancy_pct/dev0").

The result dict feeds fleetobs/report.py (critical path, histogram
merge, occupancy, coverage) and scripts/fleet_report.py.
"""

from __future__ import annotations

from ..libs import tracetl
from . import clocksync


def domain_str(key: tuple) -> str:
    return "%s@%s" % key


class ReplayTimeline:
    """Duck-typed stand-in for tracetl.Timeline carrying already
    rebased events — exactly the surface `perfetto_trace` reads."""

    def __init__(self, node: str, events: list[dict],
                 recorded: int | None = None, dropped: int = 0):
        self.node = node
        self._events = events
        self.recorded = len(events) if recorded is None else recorded
        self.dropped = dropped

    def dump(self) -> dict:
        return {"node": self.node, "recorded": self.recorded,
                "dropped": self.dropped,
                "capacity": max(len(self._events), 1),
                "events": self._events}


def _merge_ring_events(slot: dict, events: list[dict]) -> None:
    """Dedup ring events by their per-incarnation ring seq; the spool
    spools increments and the live dump overlaps the tail of them, so
    last-write-wins on equal seq is a no-op."""
    for e in events or ():
        if isinstance(e, dict) and isinstance(e.get("seq"), int):
            slot[e["seq"]] = e


def domains_from_capture(capture: dict) -> dict:
    """(node, incarnation) -> the domain's deduplicated telemetry:
    ``tracetl`` / ``flightrec`` event lists, latest ``devprof`` /
    ``latledger`` / ``metrics`` cumulative snapshots, ``anchors``
    (spooled clock records, oldest first), and ``dropped`` tallies."""
    domains: dict = {}

    def slot(node: str, incarnation: str) -> dict:
        return domains.setdefault((node, str(incarnation)), {
            "tracetl": {}, "flightrec": {}, "anchors": [],
            "devprof": None, "latledger": None, "metrics": None,
            "tracetl_recorded": 0, "flightrec_recorded": 0,
        })

    for node, nd in sorted((capture.get("nodes") or {}).items()):
        for rec in nd.get("spool") or ():
            if not isinstance(rec, dict) or "incarnation" not in rec:
                continue
            d = slot(node, rec["incarnation"])
            kind = rec.get("kind")
            if kind == "clock":
                d["anchors"].append({k: rec[k] for k in
                                     ("wall", "perf", "mono")
                                     if k in rec})
            elif kind == "tracetl":
                _merge_ring_events(d["tracetl"], rec.get("events"))
                d["tracetl_recorded"] = max(d["tracetl_recorded"],
                                            rec.get("recorded", 0))
            elif kind == "flightrec":
                _merge_ring_events(d["flightrec"], rec.get("events"))
                d["flightrec_recorded"] = max(d["flightrec_recorded"],
                                              rec.get("recorded", 0))
            elif kind == "devprof":
                d["devprof"] = {"snapshot": rec.get("snapshot"),
                                "counters": rec.get("counters") or []}
            elif kind == "latledger":
                d["latledger"] = {"dump": rec.get("dump"),
                                  "counters": rec.get("counters") or []}
            elif kind == "metrics":
                d["metrics"] = rec.get("exposition")
        live = nd.get("live")
        if isinstance(live, dict) and live.get("incarnation"):
            d = slot(node, live["incarnation"])
            clk = live.get("clock")
            if isinstance(clk, dict):
                d["anchors"].append({k: clk[k] for k in
                                     ("wall", "perf", "mono")
                                     if k in clk})
            tl = live.get("tracetl")
            if isinstance(tl, dict):
                _merge_ring_events(d["tracetl"], tl.get("events"))
                d["tracetl_recorded"] = max(d["tracetl_recorded"],
                                            tl.get("recorded", 0))
            fr = live.get("flightrec")
            if isinstance(fr, dict):
                _merge_ring_events(d["flightrec"], fr.get("events"))
                d["flightrec_recorded"] = max(d["flightrec_recorded"],
                                              fr.get("recorded", 0))
            # the live dump is strictly newer than any spooled
            # cumulative snapshot of the same incarnation
            if isinstance(live.get("devprof"), dict):
                d["devprof"] = live["devprof"]
            if isinstance(live.get("latledger"), dict):
                d["latledger"] = live["latledger"]
            if live.get("metrics"):
                d["metrics"] = live["metrics"]
    return domains


def _mono_to_perf(domain: dict) -> float:
    """Shift mapping this domain's monotonic stamps (flightrec,
    latledger counters) onto its perf_counter axis — zero without an
    anchor (both clocks are CLOCK_MONOTONIC on the platforms this runs
    on, so the residual is ns-scale)."""
    for a in reversed(domain["anchors"]):
        if "perf" in a and "mono" in a:
            return a["perf"] - a["mono"]
    return 0.0


def _latest_anchor(domain: dict) -> dict | None:
    for a in reversed(domain["anchors"]):
        if "wall" in a and "perf" in a:
            return a
    return None


def merge_capture(capture: dict, reference=None) -> dict:
    """The full merge: offsets solved, events rebased, one replay
    timeline per node, node-prefixed counter tracks, and the latest
    cumulative snapshots carried through per node.

    Returns ``{"trace", "offsets", "domains", "clock_offset_spread_ms",
    "latledger", "devprof", "metrics"}`` — ``trace`` is the single
    Perfetto trace; per-node dicts are keyed by node name with the
    NEWEST incarnation's cumulative snapshot winning (pre-kill
    incarnations contribute their ring events to the trace, while
    counters/accounts restart with the process that owns them)."""
    domains = domains_from_capture(capture)
    events_by_domain = {k: sorted(d["tracetl"].values(),
                                  key=lambda e: e["seq"])
                        for k, d in domains.items()}
    edges = clocksync.pair_edges(events_by_domain)
    anchors = {k: a for k, d in domains.items()
               if (a := _latest_anchor(d)) is not None}
    offsets = clocksync.solve_offsets(domains.keys(), edges, anchors,
                                      reference=reference)

    per_node_events: dict[str, list] = {}
    per_node_dropped: dict[str, int] = {}
    counters: list[tuple] = []
    latledger_by_node: dict = {}
    devprof_by_node: dict = {}
    metrics_by_node: dict = {}
    # newest incarnation per node = the one with the latest wall anchor
    newest: dict[str, tuple] = {}
    for key, d in domains.items():
        node = key[0]
        a = _latest_anchor(d)
        wall = a["wall"] if a else 0.0
        if node not in newest or wall > newest[node][0]:
            newest[node] = (wall, key)

    for key, d in sorted(domains.items()):
        node = key[0]
        off = offsets[key]["offset"]
        mono_shift = _mono_to_perf(d)
        evs = per_node_events.setdefault(node, [])
        for e in sorted(d["tracetl"].values(), key=lambda x: x["seq"]):
            e2 = dict(e)
            e2["t"] = e["t"] + off
            evs.append(e2)
        for e in sorted(d["flightrec"].values(),
                        key=lambda x: x["seq"]):
            # flightrec events join as instants, the ingest_flightrec
            # convention, on the fleet axis
            fields = {k: v for k, v in e.items()
                      if k not in ("seq", "t", "kind")}
            evs.append({"seq": e["seq"], "t": e["t"] + mono_shift + off,
                        "ph": tracetl.PH_INSTANT, "sub": "flightrec",
                        "name": e["kind"], **fields})
        per_node_dropped[node] = per_node_dropped.get(node, 0) + max(
            0, d["tracetl_recorded"] - len(d["tracetl"])) + max(
            0, d["flightrec_recorded"] - len(d["flightrec"]))
        if d["devprof"] is not None:
            for s in d["devprof"].get("counters") or ():
                if len(s) == 3:
                    counters.append((s[0] + off,
                                     "%s:%s" % (node, s[1]), s[2]))
        if d["latledger"] is not None:
            for s in d["latledger"].get("counters") or ():
                if len(s) == 3:
                    counters.append((s[0] + mono_shift + off,
                                     "%s:%s" % (node, s[1]), s[2]))
        if key == newest[node][1]:
            if d["latledger"] is not None:
                latledger_by_node[node] = d["latledger"].get("dump")
            if d["devprof"] is not None:
                devprof_by_node[node] = d["devprof"].get("snapshot")
            if d["metrics"] is not None:
                metrics_by_node[node] = d["metrics"]

    replays = []
    for node, evs in sorted(per_node_events.items()):
        evs.sort(key=lambda e: e["t"])
        # renumber: merged incarnations would repeat ring seqs
        evs = [{**e, "seq": i} for i, e in enumerate(evs)]
        replays.append(ReplayTimeline(
            node, evs, recorded=len(evs) + per_node_dropped.get(node, 0),
            dropped=per_node_dropped.get(node, 0)))
    counters.sort(key=lambda s: s[0])
    trace = tracetl.perfetto_trace(replays, counters=counters or None)
    return {
        "trace": trace,
        "offsets": {domain_str(k): v for k, v in offsets.items()},
        "domains": sorted(domain_str(k) for k in domains),
        "clock_offset_spread_ms": round(
            clocksync.offset_spread_ms(offsets, anchors), 3),
        "latledger": latledger_by_node,
        "devprof": devprof_by_node,
        "metrics": metrics_by_node,
    }
