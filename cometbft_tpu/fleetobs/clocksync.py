"""NTP-style clock-offset estimation from p2p send/recv edge pairs.

Every process records timeline events against its own
``time.perf_counter()``, which resets per process — so per-process
telemetry lives in disjoint CLOCK DOMAINS, one per (node, incarnation).
Merging them onto one fleet axis needs one offset per domain, and the
p2p layer already emits the measurements: every gossip message carries
a trace context, and the sender's ``send`` event plus the receiver's
``recv`` event for the same context are a one-way delay sample
contaminated by exactly the offset difference we want.

The estimator is the classic NTP midpoint argument.  For domains A and
B let ``m_AB = min over edges A->B of (t_recv_B - t_send_A)`` (local
clocks).  Writing ``O_X`` for the offset mapping X's clock onto the
fleet axis and assuming the MINIMUM one-way delay is symmetric (same
wire both ways — true by construction in the e2e runner's loopback
mesh),

    m_AB = d_min - (O_B - O_A)
    m_BA = d_min + (O_B - O_A)
    =>  O_B - O_A = (m_BA - m_AB) / 2,    d_min = (m_AB + m_BA) / 2

Asymmetric ACTUAL latency only widens the residual: the recovered
offset is always within the minimum one-way delay of the truth, which
is the bound tests/test_fleetobs.py pins.  Relative offsets propagate
by BFS from a reference domain, so any domain connected to the
reference through bidirected edge pairs gets an edge-solved offset.
Degenerate domains — no edges at all, or edges in only one direction —
fall back to their spooled wall-clock anchor (``clock`` records, see
libs/telspool.py): fleet time = wall time as that process saw it,
accurate to NTP-on-the-host rather than to the wire.
"""

from __future__ import annotations

METHOD_REFERENCE = "reference"
METHOD_EDGES = "edges"
METHOD_ANCHOR = "anchor"
METHOD_NONE = "none"


def pair_edges(events_by_domain: dict) -> list[tuple]:
    """Pair cross-domain send/recv timeline events into
    ``(src_domain, dst_domain, t_send, t_recv)`` edges.

    ``events_by_domain`` maps a domain key to its tracetl event dicts
    (the ``events()`` shape).  Pairing is by trace-context identity;
    a context claimed by sends in MORE than one domain (a post-restart
    ctx-seq collision) is ambiguous and dropped.
    """
    sends: dict[tuple, list] = {}
    recvs: list[tuple] = []
    for dom, evs in events_by_domain.items():
        for e in evs:
            ctx = e.get("ctx")
            if not ctx or len(ctx) != 4:
                continue
            fid = tuple(ctx)
            if e.get("ph") == "send":
                sends.setdefault(fid, []).append((dom, e["t"]))
            elif e.get("ph") == "recv":
                recvs.append((fid, dom, e["t"]))
    edges = []
    for fid, dom, t_recv in recvs:
        cands = sends.get(fid)
        if not cands:
            continue
        src_doms = {d for d, _ in cands}
        if len(src_doms) != 1:
            continue                    # ambiguous across incarnations
        src, t_send = cands[0]
        if src == dom:
            continue                    # self-delivery carries no info
        edges.append((src, dom, t_send, t_recv))
    return edges


def min_deltas(edges: list[tuple]) -> dict:
    """Per ordered domain pair, the minimum local-clock delta
    ``t_recv - t_send`` over its edges."""
    out: dict[tuple, float] = {}
    for src, dst, t_send, t_recv in edges:
        d = t_recv - t_send
        k = (src, dst)
        if k not in out or d < out[k]:
            out[k] = d
    return out


def solve_offsets(domains, edges: list[tuple], anchors: dict,
                  reference=None) -> dict:
    """Solve one fleet-axis offset per domain.

    ``domains``: iterable of domain keys.  ``edges``: `pair_edges`
    output.  ``anchors``: domain -> {"wall": .., "perf": ..} — the
    latest spooled clock anchor (absent entries allowed).  The fleet
    axis is the REFERENCE domain's wall clock: its offset comes from
    its own anchor, every edge-connected domain chains off it by the
    midpoint estimate, and disconnected domains use their own anchor.

    Returns domain -> {"offset": float, "method": str,
    "delay_bound": float | None} where ``offset`` maps that domain's
    perf_counter times onto the fleet axis and ``delay_bound`` is the
    estimated minimum one-way delay to its BFS parent (the error bound
    of the edge-solved offset).
    """
    domains = sorted(set(domains) | {d for e in edges for d in e[:2]})
    if not domains:
        return {}
    mind = min_deltas(edges)
    # undirected adjacency over pairs measured in BOTH directions
    rel: dict[tuple, tuple] = {}
    for (a, b), m_ab in mind.items():
        if (b, a) not in mind or (a, b) in rel or (b, a) in rel:
            continue
        m_ba = mind[(b, a)]
        rel[(a, b)] = ((m_ba - m_ab) / 2.0, (m_ab + m_ba) / 2.0)
    adj: dict = {}
    for (a, b), (off_b_minus_a, delay) in rel.items():
        adj.setdefault(a, []).append((b, off_b_minus_a, delay))
        adj.setdefault(b, []).append((a, -off_b_minus_a, delay))

    def anchor_offset(dom):
        a = anchors.get(dom)
        if a and "wall" in a and "perf" in a:
            return a["wall"] - a["perf"]
        return None

    if reference is None:
        # the best-connected anchored domain keeps the BFS tree shallow
        anchored = [d for d in domains if anchor_offset(d) is not None]
        pool = anchored or domains
        reference = max(pool, key=lambda d: (len(adj.get(d, ())), d))

    out: dict = {}
    ref_off = anchor_offset(reference)
    out[reference] = {
        "offset": ref_off if ref_off is not None else 0.0,
        "method": METHOD_REFERENCE, "delay_bound": None}
    frontier = [reference]
    while frontier:
        cur = frontier.pop(0)
        for nxt, rel_off, delay in adj.get(cur, ()):
            if nxt in out:
                continue
            out[nxt] = {"offset": out[cur]["offset"] + rel_off,
                        "method": METHOD_EDGES, "delay_bound": delay}
            frontier.append(nxt)
    for dom in domains:
        if dom in out:
            continue
        a_off = anchor_offset(dom)
        if a_off is not None:
            out[dom] = {"offset": a_off, "method": METHOD_ANCHOR,
                        "delay_bound": None}
        else:
            # no edges AND no anchor: leave the domain on its local
            # axis rather than inventing an alignment
            out[dom] = {"offset": 0.0, "method": METHOD_NONE,
                        "delay_bound": None}
    return out


def offset_spread_ms(offsets: dict, anchors: dict) -> float:
    """Spread of the edge-solved corrections against the wall-clock
    anchors, in ms — how far apart the processes' wall clocks were
    from the wire's view.  0.0 with fewer than two comparable domains.
    """
    corrections = []
    for dom, sol in offsets.items():
        a = anchors.get(dom)
        if sol["method"] not in (METHOD_EDGES, METHOD_REFERENCE) \
                or not a or "wall" not in a or "perf" not in a:
            continue
        corrections.append(sol["offset"] - (a["wall"] - a["perf"]))
    if len(corrections) < 2:
        return 0.0
    return (max(corrections) - min(corrections)) * 1000.0
