"""Harvest fleet telemetry: crash-safe spools + live RPC dumps.

A CAPTURE is a plain JSON-serializable dict:

    {"nodes": {name: {"spool": [records...], "live": {...} | None}},
     "collected_at": wall-clock seconds}

Spool records come from libs/telspool.read_spool over each node's
``<home>/data/telspool`` directory — they survive SIGKILL, so a killed
node still contributes every flush it completed.  The live half comes
from the ``fleetobs`` RPC route (rpc/core.py), which snapshots the
CURRENT incarnation's full rings plus a fresh clock anchor; a node
that is down (or mid-restart) simply contributes spool-only, which is
the whole point.

The collector is duck-typed over the e2e runner's `Testnet` (nodes
with ``name`` / ``home`` / ``rpc()`` / ``running()``) so simnet or ad
hoc topologies can reuse it; `Testnet.collect_telemetry()` is the
wired entry point.
"""

from __future__ import annotations

import json
import os
import time

from ..libs import telspool

SPOOL_SUBDIR = os.path.join("data", "telspool")


def spool_dir_for(home: str) -> str:
    return os.path.join(home, SPOOL_SUBDIR)


def harvest_spool(home: str) -> list[dict]:
    """Every recovered spool record under a node home; [] when the
    node never spooled (knob off, or no flush completed)."""
    return telspool.read_spool(spool_dir_for(home))


def collect_node(name: str, home: str, rpc=None,
                 rpc_timeout: float = 5.0) -> dict:
    """One node's capture entry.  ``rpc`` is a callable
    ``rpc(method, timeout=..) -> result`` (TestnetNode.rpc); live
    collection failures degrade to spool-only, never raise."""
    live = None
    if rpc is not None:
        try:
            live = rpc("fleetobs", timeout=rpc_timeout)
        except Exception:
            live = None
    return {"spool": harvest_spool(home), "live": live}


def collect_testnet(testnet) -> dict:
    """Capture across a Testnet: spools always, live dumps from the
    nodes that answer RPC right now."""
    nodes = {}
    for node in testnet.nodes:
        rpc = node.rpc if node.running() else None
        nodes[node.name] = collect_node(node.name, node.home, rpc=rpc)
    return {"nodes": nodes, "collected_at": time.time()}


def save_capture(path: str, capture: dict) -> None:
    with open(path, "w") as f:
        json.dump(capture, f)


def load_capture(path: str) -> dict:
    with open(path) as f:
        capture = json.load(f)
    if not isinstance(capture, dict) or "nodes" not in capture:
        raise ValueError(f"{path} is not a fleetobs capture")
    return capture
