"""Fleet-level readings over a merged capture: the cross-process
equivalents of the single-process trace_report surfaces.

- `critical_path` over the merged trace (tracetl.critical_path — the
  exact segment-sum invariant holds on the rebased axis because the
  sweep is a pure function of the trace, axis offsets included);
- merged per-consumer latledger histograms (element-wise histogram
  merge is associative/commutative by design, so per-node snapshots
  fold into fleet-true quantile upper bounds);
- fleet occupancy (busy/wall summed across every node's chips) and a
  per-node SLO passthrough;
- height coverage + cross-process flow-edge accounting — the honesty
  metrics: how much of the chain the capture actually observed, and
  whether causal edges really crossed process boundaries.
"""

from __future__ import annotations

from ..libs import devprof as libdevprof
from ..libs import tracetl
from ..libs.latledger import LatHistogram
from . import merge as libmerge


def _hist_from_snapshot(snap: dict) -> LatHistogram | None:
    try:
        h = LatHistogram(tuple(snap["bounds"]))
        counts = list(snap["counts"])
        if len(counts) != len(h.counts):
            return None
        h.counts = counts
        h.count = int(snap["count"])
        h.sum = float(snap["sum"])
        return h
    except (KeyError, TypeError, ValueError):
        return None


def merge_latledgers(latledger_by_node: dict) -> dict:
    """Fold per-node ledger dumps into fleet per-consumer histograms
    plus an SLO passthrough keyed by node."""
    hists: dict[str, LatHistogram] = {}
    requests: dict[str, int] = {}
    nodes_seen: dict[str, int] = {}
    slo = {}
    for node, dump in sorted(latledger_by_node.items()):
        if not isinstance(dump, dict):
            continue
        if dump.get("slo"):
            slo[node] = dump["slo"]
        for label, c in (dump.get("consumers") or {}).items():
            snap = (c or {}).get("hist")
            h = _hist_from_snapshot(snap) if snap else None
            if h is None:
                continue
            if label not in hists:
                hists[label] = h
            else:
                try:
                    hists[label] = hists[label].merge(h)
                except ValueError:
                    # a mixed-build fleet may disagree on bucket
                    # layouts; skip the odd one out, never raise
                    continue
            requests[label] = requests.get(label, 0) \
                + int(c.get("requests", 0))
            nodes_seen[label] = nodes_seen.get(label, 0) + 1
    consumers = {}
    for label, h in sorted(hists.items()):
        consumers[label] = {
            "count": h.count,
            "sum_seconds": round(h.sum, 6),
            "requests": requests.get(label, 0),
            "nodes": nodes_seen.get(label, 0),
            "p50_ms": round(h.quantile(0.50) * 1000.0, 3),
            "p99_ms": round(h.quantile(0.99) * 1000.0, 3),
        }
    return {"consumers": consumers, "slo": slo}


def fleet_occupancy(devprof_by_node: dict) -> dict:
    """Per-node occupancy summaries plus the fleet aggregate (busy and
    wall summed over every chip of every node)."""
    per_node = {}
    busy = wall = 0.0
    for node, snap in sorted(devprof_by_node.items()):
        if not isinstance(snap, dict):
            continue
        s = libdevprof.occupancy_summary(snap)
        per_node[node] = s
        busy += s.get("busy_seconds", 0.0)
        wall += s.get("wall_seconds", 0.0)
    return {"per_node": per_node,
            "fleet": {"busy_seconds": round(busy, 6),
                      "wall_seconds": round(wall, 6),
                      "device_occupancy_fraction":
                          round(busy / wall, 6) if wall else 0.0}}


def _height_of_flow_id(fid: str) -> int | None:
    parts = fid.rsplit("/", 3)
    if len(parts) != 4:
        return None
    try:
        return int(parts[1])
    except ValueError:
        return None


def trace_coverage(trace: dict) -> dict:
    """Commit coverage + cross-process flow-edge accounting straight
    off the merged trace.  ``height_coverage`` is the share of
    union-observed committed heights that EVERY node's telemetry
    covers — 1.0 means no node lost a height's worth of rings to a
    perturbation."""
    pid_names = {}
    commits: dict[int, set] = {}
    flow_s: dict[str, set] = {}
    flow_f: dict[str, set] = {}
    for e in trace.get("traceEvents", []):
        if not isinstance(e, dict):
            continue
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e.get("pid")] = (e.get("args") or {}).get("name")
        elif e.get("ph") == "i" and e.get("name") == "commit":
            h = (e.get("args") or {}).get("height")
            if isinstance(h, int):
                commits.setdefault(h, set()).add(e.get("pid"))
        elif e.get("ph") in ("s", "f") and isinstance(e.get("id"), str):
            (flow_s if e["ph"] == "s" else flow_f).setdefault(
                e["id"], set()).add(e.get("pid"))
    node_pids = {pid for pid, name in pid_names.items()
                 if name != "devprof"}
    union = set(commits)
    common = {h for h, pids in commits.items()
              if node_pids and node_pids <= pids}
    # a flow edge is CROSS-process when its send pid and recv pid differ
    cross_by_height: dict[int, int] = {}
    for fid in set(flow_s) & set(flow_f):
        if flow_f[fid] - flow_s[fid]:
            h = _height_of_flow_id(fid)
            if h is not None:
                cross_by_height[h] = cross_by_height.get(h, 0) + 1
    common_with_edge = sum(1 for h in common
                           if cross_by_height.get(h, 0) > 0)
    return {
        "nodes": sorted(n for n in pid_names.values()
                        if n and n != "devprof"),
        "union_heights": len(union),
        "common_heights": len(common),
        "height_coverage": round(len(common) / len(union), 6)
        if union else 0.0,
        "cross_flow_edges": sum(cross_by_height.values()),
        "common_heights_with_cross_edge": common_with_edge,
        "cross_edges_by_height": {
            str(h): n for h, n in sorted(cross_by_height.items())},
    }


def fleet_report(capture: dict, reference=None) -> dict:
    """The whole pipeline: merge, decompose, fold, count.  Returns the
    merged artifacts under ``"merged"`` (trace included) plus the
    fleet readings bench.py and scripts/fleet_report.py consume."""
    merged = libmerge.merge_capture(capture, reference=reference)
    cp = tracetl.critical_path(merged["trace"])
    cov = trace_coverage(merged["trace"])
    return {
        "merged": merged,
        "critical_path": cp,
        "coverage": cov,
        "latledger": merge_latledgers(merged["latledger"]),
        "occupancy": fleet_occupancy(merged["devprof"]),
        "clock_offset_spread_ms": merged["clock_offset_spread_ms"],
    }
