"""CRC-32C (Castagnoli, poly 0x1EDC6F41 reflected = 0x82F63B78).

The WAL record checksum (reference internal/consensus/wal.go:317 uses
crc32.MakeTable(crc32.Castagnoli)). Table-driven; records are small
(votes ~200 B) so pure Python is fine on the host path.
"""

from __future__ import annotations

_POLY = 0x82F63B78


def _make_table() -> list[int]:
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        table.append(c)
    return table


_TABLE = _make_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF
