"""Rolling file groups for the consensus WAL
(reference internal/autofile/group.go:82-188).

A Group is a head file `path` plus rolled chunks `path.000`, `path.001`,
... Writes land in the head; when the head exceeds `head_size_limit` it
is rotated to the next index. Total size is bounded by dropping the
oldest chunks. Readers iterate chunks oldest -> head.
"""

from __future__ import annotations

import os
import re

from . import lockrank


class Group:
    def __init__(self, head_path: str,
                 head_size_limit: int = 10 * 1024 * 1024,
                 total_size_limit: int = 1024 * 1024 * 1024):
        self.head_path = head_path
        self.head_size_limit = head_size_limit
        self.total_size_limit = total_size_limit
        self._mtx = lockrank.RankedRLock("autofile")
        os.makedirs(os.path.dirname(head_path) or ".", exist_ok=True)
        self._head = open(head_path, "ab")
        self._min_index, self._max_index = self._scan_indexes()

    # -- index bookkeeping -------------------------------------------------

    def _scan_indexes(self) -> tuple[int, int]:
        """min/max rolled-chunk indexes on disk; head is max_index+0."""
        d = os.path.dirname(self.head_path) or "."
        base = os.path.basename(self.head_path)
        pat = re.compile(re.escape(base) + r"\.(\d{3,})$")
        indexes = sorted(int(m.group(1)) for f in os.listdir(d)
                         if (m := pat.match(f)))
        if not indexes:
            return 0, 0
        return indexes[0], indexes[-1] + 1

    def _chunk_path(self, index: int) -> str:
        return f"{self.head_path}.{index:03d}"

    def min_index(self) -> int:
        with self._mtx:
            return self._min_index

    def max_index(self) -> int:
        """Index of the head chunk (rolled chunks are < max_index)."""
        with self._mtx:
            return self._max_index

    # -- writing -----------------------------------------------------------

    def write(self, data: bytes) -> None:
        with self._mtx:
            self._head.write(data)

    def flush(self) -> None:
        with self._mtx:
            self._head.flush()

    def flush_and_sync(self) -> None:
        with self._mtx:
            self._head.flush()
            os.fsync(self._head.fileno())

    def maybe_rotate(self) -> None:
        """Roll the head if over the size limit (group.go checkHeadSizeLimit)
        and enforce the total size bound by dropping oldest chunks."""
        with self._mtx:
            self._head.flush()
            if self._head.tell() < self.head_size_limit:
                return
            self.rotate_file()
            self._enforce_total_size()

    def rotate_file(self) -> None:
        with self._mtx:
            self._head.flush()
            os.fsync(self._head.fileno())
            self._head.close()
            os.rename(self.head_path, self._chunk_path(self._max_index))
            self._max_index += 1
            self._head = open(self.head_path, "ab")

    def _enforce_total_size(self) -> None:
        while True:
            total = self._head.tell()
            chunks = list(range(self._min_index, self._max_index))
            for i in chunks:
                try:
                    total += os.path.getsize(self._chunk_path(i))
                except OSError:
                    pass
            if total <= self.total_size_limit or not chunks:
                return
            try:
                os.remove(self._chunk_path(chunks[0]))
            except OSError:
                pass
            self._min_index = chunks[0] + 1

    def reopen(self) -> None:
        """Re-open the head and rescan indexes after external surgery on
        the group's files (WAL corruption repair)."""
        with self._mtx:
            try:
                self._head.close()
            except OSError:
                pass
            self._head = open(self.head_path, "ab")
            self._min_index, self._max_index = self._scan_indexes()

    # -- reading -----------------------------------------------------------

    def chunk_paths(self) -> list[str]:
        """All chunk paths oldest->newest, head last."""
        with self._mtx:
            paths = [self._chunk_path(i)
                     for i in range(self._min_index, self._max_index)]
            paths.append(self.head_path)
            return paths

    def read_all(self) -> bytes:
        self.flush()
        out = []
        for p in self.chunk_paths():
            try:
                with open(p, "rb") as f:
                    out.append(f.read())
            except FileNotFoundError:
                pass
        return b"".join(out)

    def close(self) -> None:
        with self._mtx:
            self._head.flush()
            os.fsync(self._head.fileno())
            self._head.close()
