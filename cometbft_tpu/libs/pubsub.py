"""Query-subscription pubsub server (reference libs/pubsub/).

Events are published with a map of composite-keyed attributes
(`tm.event`, `tx.height`, ...), each key holding a list of string
values; subscribers register a compiled Query and receive messages on a
bounded queue. The query language mirrors libs/pubsub/query/syntax:

    tm.event = 'NewBlock' AND tx.height > 5 AND tx.hash CONTAINS 'ab'
    account.owner EXISTS

Operators: = < <= > >= CONTAINS EXISTS, joined by AND.
"""

from __future__ import annotations

import queue
import re
import threading
from . import lockrank
from dataclasses import dataclass, field


class QueryError(ValueError):
    pass


_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<op><=|>=|=|<|>)
      | (?P<contains>CONTAINS\b)
      | (?P<exists>EXISTS\b)
      | (?P<and>AND\b)
      | (?P<str>'[^']*')
      | (?P<num>-?\d+(?:\.\d+)?)
      | (?P<date>(?:DATE|TIME)\s+\S+)
      | (?P<key>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""", re.VERBOSE)


def _tokenize(s: str) -> list[tuple[str, str]]:
    toks, pos = [], 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m or m.end() == pos:
            if s[pos:].strip() == "":
                break
            raise QueryError(f"cannot tokenize query at: {s[pos:]!r}")
        pos = m.end()
        kind = m.lastgroup
        toks.append((kind, m.group(kind)))
    return toks


@dataclass(frozen=True)
class Condition:
    key: str
    op: str  # '=', '<', '<=', '>', '>=', 'CONTAINS', 'EXISTS'
    value: str | float | None = None

    def matches(self, values: list[str]) -> bool:
        if self.op == "EXISTS":
            return True  # key presence is checked by the caller
        for v in values:
            if self.op == "=":
                if isinstance(self.value, float):
                    try:
                        if float(v) == self.value:
                            return True
                    except ValueError:
                        pass
                elif v == self.value:
                    return True
            elif self.op == "CONTAINS":
                if str(self.value) in v:
                    return True
            else:  # ordered comparison
                try:
                    t: float | str = float(self.value)
                    numeric = True
                except (TypeError, ValueError):
                    numeric = False
                if numeric:
                    # numeric operand: non-numeric values never match
                    try:
                        x: float | str = float(v)
                    except ValueError:
                        continue
                else:
                    # DATE/TIME operand: ISO-8601 sorts correctly as text
                    x, t = str(v), str(self.value)
                if ((self.op == "<" and x < t)
                        or (self.op == "<=" and x <= t)
                        or (self.op == ">" and x > t)
                        or (self.op == ">=" and x >= t)):
                    return True
        return False


class Query:
    """Compiled conjunctive query (libs/pubsub/query/query.go Compile)."""

    def __init__(self, conditions: list[Condition], source: str = ""):
        self.conditions = conditions
        self.source = source

    @staticmethod
    def parse(s: str) -> "Query":
        toks = _tokenize(s)
        conds: list[Condition] = []
        i = 0
        while i < len(toks):
            kind, val = toks[i]
            if kind != "key":
                raise QueryError(f"expected key, got {val!r}")
            key = val
            i += 1
            if i >= len(toks):
                raise QueryError(f"dangling key {key!r}")
            kind, val = toks[i]
            if kind == "exists":
                conds.append(Condition(key, "EXISTS"))
                i += 1
            elif kind == "contains":
                i += 1
                if i >= len(toks) or toks[i][0] != "str":
                    raise QueryError("CONTAINS requires a string operand")
                conds.append(Condition(key, "CONTAINS", toks[i][1][1:-1]))
                i += 1
            elif kind == "op":
                op = val
                i += 1
                if i >= len(toks):
                    raise QueryError(f"dangling operator {op!r}")
                okind, oval = toks[i]
                if okind == "str":
                    operand: str | float = oval[1:-1]
                elif okind == "num":
                    operand = float(oval)
                elif okind == "date":
                    operand = oval.split(None, 1)[1]
                else:
                    raise QueryError(f"bad operand {oval!r}")
                conds.append(Condition(key, op, operand))
                i += 1
            else:
                raise QueryError(f"expected operator after {key!r}")
            if i < len(toks):
                if toks[i][0] != "and":
                    raise QueryError(f"expected AND, got {toks[i][1]!r}")
                i += 1
                if i >= len(toks):
                    raise QueryError("dangling AND")
        return Query(conds, s)

    def matches(self, events: dict[str, list[str]]) -> bool:
        """All conditions satisfied by the event attribute map
        (query.go Matches)."""
        for c in self.conditions:
            vals = events.get(c.key)
            if vals is None:
                return False
            if not c.matches(vals):
                return False
        return True

    def __str__(self) -> str:
        return self.source

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and \
            self.conditions == other.conditions

    def __hash__(self) -> int:
        return hash(tuple(self.conditions))


ALL = Query([], "empty")  # matches everything (query.All)


@dataclass
class Message:
    data: object
    events: dict[str, list[str]] = field(default_factory=dict)


class Subscription:
    """A subscriber's bounded delivery queue. `canceled` is set with a
    reason when the server terminates the subscription (unsubscribed or
    overflow)."""

    def __init__(self, capacity: int = 100):
        self.out: queue.Queue[Message] = queue.Queue(capacity)
        self.canceled = threading.Event()
        self.cancel_reason: str | None = None

    def next(self, timeout: float | None = None) -> Message | None:
        try:
            return self.out.get(timeout=timeout)
        except queue.Empty:
            return None

    def _cancel(self, reason: str) -> None:
        self.cancel_reason = reason
        self.canceled.set()


class Server:
    """Pubsub hub (libs/pubsub/pubsub.go Server). Publishing is
    synchronous fan-out; a full subscriber queue cancels that subscriber
    (the reference's non-buffered semantics with client timeout)."""

    def __init__(self):
        self._mtx = lockrank.RankedRLock("pubsub")
        # subscriber -> {query -> Subscription}
        self._subs: dict[str, dict[Query, Subscription]] = {}

    def subscribe(self, subscriber: str, query: Query,
                  capacity: int = 100) -> Subscription:
        with self._mtx:
            by_query = self._subs.setdefault(subscriber, {})
            if query in by_query:
                raise ValueError(
                    f"{subscriber!r} already subscribed to {query}")
            sub = Subscription(capacity)
            by_query[query] = sub
            return sub

    def unsubscribe(self, subscriber: str, query: Query) -> None:
        with self._mtx:
            by_query = self._subs.get(subscriber, {})
            sub = by_query.pop(query, None)
            if sub is None:
                raise KeyError(f"{subscriber!r} not subscribed to {query}")
            if not by_query:
                self._subs.pop(subscriber, None)
        sub._cancel("unsubscribed")

    def unsubscribe_all(self, subscriber: str) -> None:
        with self._mtx:
            by_query = self._subs.pop(subscriber, None)
        if not by_query:
            raise KeyError(f"{subscriber!r} has no subscriptions")
        for sub in by_query.values():
            sub._cancel("unsubscribed")

    def num_clients(self) -> int:
        with self._mtx:
            return len(self._subs)

    def num_client_subscriptions(self, subscriber: str) -> int:
        with self._mtx:
            return len(self._subs.get(subscriber, {}))

    def publish(self, data: object,
                events: dict[str, list[str]] | None = None) -> None:
        events = events or {}
        msg = Message(data, events)
        with self._mtx:
            targets = [
                (name, q, sub)
                for name, by_query in self._subs.items()
                for q, sub in by_query.items()
                if q.matches(events)
            ]
        dead = []
        for name, q, sub in targets:
            try:
                sub.out.put_nowait(msg)
            except queue.Full:
                dead.append((name, q, sub))
        for name, q, sub in dead:
            with self._mtx:
                by_query = self._subs.get(name, {})
                if by_query.get(q) is sub:
                    by_query.pop(q, None)
                    if not by_query:
                        self._subs.pop(name, None)
            sub._cancel("client is not pulling messages fast enough")
