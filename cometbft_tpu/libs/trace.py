"""Stage-span tracing: per-stage wall-clock timers for the protocol
hot paths (decode -> verify-dispatch -> device -> apply -> store).

The host-residual breakdown that blocksync_profile_r5.jsonl measured
with a one-off script becomes a first-class observable: reactors and
the light client open spans around each stage, a process-wide
StageTracer accumulates (count, seconds) per (subsystem, stage), and —
when the node runs with instrumentation — every span also lands in the
libs/metrics.py registry as a histogram observation
(cometbft_trace_stage_duration_seconds{subsystem, stage}).

No reference analog: the reference profiles with pprof; here the
interesting question is how much of a block's wall time is host work
around the single device dispatch, so the stages are first-class.

The seam mirrors libs/metrics.set_device_metrics: a module-level
tracer the crypto/reactor layers reach without any node wiring.  With
no tracer installed a span is a shared no-op object — the hot paths
pay one global read and an `is None` test.
"""

from __future__ import annotations

import time

from . import lockrank

# canonical stage names for the blocksync ingest pipeline; other
# subsystems (light) reuse the subset that applies to them
BLOCKSYNC_STAGES = ("decode", "verify_dispatch", "device", "apply",
                    "store")
# extra stages emitted by the overlapped verify pipeline
# (crypto/dispatch.py): collect runs in the submitter, host_pack in
# the staging thread — concurrent with the previous window's device
PIPELINE_STAGES = ("collect", "host_pack")
LIGHT_STAGES = ("fetch", "verify_dispatch", "device", "store")

# interval ring size per tracer: enough to prove overlap across a
# bench run without unbounded growth on long-lived nodes
MAX_INTERVALS = 1024


class StageTracer:
    """Accumulates span durations per (subsystem, stage); optionally
    mirrors every observation into a metrics.TraceMetrics bundle.
    Also keeps a bounded ring of (start, end) INTERVALS per span so
    concurrency between stages — the overlapped pipeline's whole
    claim — is provable from the record, not asserted."""

    def __init__(self, metrics=None):
        self._mtx = lockrank.RankedLock("trace.stage")
        self._totals: dict[tuple[str, str], list] = {}
        self._intervals: list = []      # (sub, stage, t0, t1, fields)
        self.dropped_intervals = 0      # ring overflow, no longer silent
        self.metrics = metrics

    def record(self, subsystem: str, stage: str, seconds: float,
               end: float | None = None, fields=None) -> None:
        t1 = end if end is not None else time.perf_counter()
        overflow = 0
        with self._mtx:
            t = self._totals.setdefault((subsystem, stage), [0, 0.0])
            t[0] += 1
            t[1] += seconds
            self._intervals.append(
                (subsystem, stage, t1 - seconds, t1, fields))
            if len(self._intervals) > MAX_INTERVALS:
                overflow = len(self._intervals) - MAX_INTERVALS
                del self._intervals[:overflow]
                self.dropped_intervals += overflow
        if self.metrics is not None:
            self.metrics.stage_duration_seconds.labels(
                subsystem, stage).observe(seconds)
            if overflow:
                self.metrics.intervals_dropped.add(overflow)

    def intervals(self, subsystem: str | None = None,
                  stage: str | None = None) -> list[dict]:
        """Retained span intervals, oldest first."""
        with self._mtx:
            raw = list(self._intervals)
        return [{"subsystem": sub, "stage": st, "start": t0, "end": t1,
                 **(dict(f) if f else {})}
                for (sub, st, t0, t1, f) in raw
                if (subsystem is None or sub == subsystem)
                and (stage is None or st == stage)]

    def overlap_seconds(self, subsystem: str, stage_a: str,
                        stage_b: str) -> float:
        """Total wall-clock during which a stage_a span and a stage_b
        span of `subsystem` ran CONCURRENTLY — the proof that a device
        span overlapped the next window's collect/pack span."""
        a = self.intervals(subsystem, stage_a)
        b = self.intervals(subsystem, stage_b)
        total = 0.0
        for ia in a:
            for ib in b:
                lo = max(ia["start"], ib["start"])
                hi = min(ia["end"], ib["end"])
                if hi > lo:
                    total += hi - lo
        return total

    def snapshot(self) -> dict:
        """{"subsystem.stage": {"count": n, "seconds": s}} — the shape
        the simnet benches report alongside their e2e rates."""
        with self._mtx:
            return {
                f"{sub}.{stage}": {"count": c, "seconds": round(s, 6)}
                for (sub, stage), (c, s) in sorted(self._totals.items())}

    def reset(self) -> None:
        with self._mtx:
            self._totals.clear()


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _TimedSpan:
    __slots__ = ("_tracer", "_subsystem", "_stage", "_t0", "_fields")

    def __init__(self, tracer: StageTracer, subsystem: str, stage: str,
                 fields=None):
        self._tracer = tracer
        self._subsystem = subsystem
        self._stage = stage
        self._fields = fields

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tracer.record(self._subsystem, self._stage,
                            t1 - self._t0, end=t1, fields=self._fields)
        return False


# process-wide tracer seam (same pattern as metrics.set_device_metrics)
_tracer: StageTracer | None = None


def set_tracer(t: StageTracer | None) -> None:
    global _tracer
    _tracer = t


def tracer() -> StageTracer | None:
    return _tracer


def span(subsystem: str, stage: str, **fields):
    """Context manager timing one stage; free when no tracer is set.
    Keyword fields (e.g. inflight=, depth=) land on the interval
    record so pipeline depth is visible next to the timing."""
    t = _tracer
    if t is None:
        return _NULL_SPAN
    return _TimedSpan(t, subsystem, stage, fields or None)
