"""Stage-span tracing: per-stage wall-clock timers for the protocol
hot paths (decode -> verify-dispatch -> device -> apply -> store).

The host-residual breakdown that blocksync_profile_r5.jsonl measured
with a one-off script becomes a first-class observable: reactors and
the light client open spans around each stage, a process-wide
StageTracer accumulates (count, seconds) per (subsystem, stage), and —
when the node runs with instrumentation — every span also lands in the
libs/metrics.py registry as a histogram observation
(cometbft_trace_stage_duration_seconds{subsystem, stage}).

No reference analog: the reference profiles with pprof; here the
interesting question is how much of a block's wall time is host work
around the single device dispatch, so the stages are first-class.

The seam mirrors libs/metrics.set_device_metrics: a module-level
tracer the crypto/reactor layers reach without any node wiring.  With
no tracer installed a span is a shared no-op object — the hot paths
pay one global read and an `is None` test.
"""

from __future__ import annotations

import threading
import time

# canonical stage names for the blocksync ingest pipeline; other
# subsystems (light) reuse the subset that applies to them
BLOCKSYNC_STAGES = ("decode", "verify_dispatch", "device", "apply",
                    "store")
LIGHT_STAGES = ("fetch", "verify_dispatch", "device", "store")


class StageTracer:
    """Accumulates span durations per (subsystem, stage); optionally
    mirrors every observation into a metrics.TraceMetrics bundle."""

    def __init__(self, metrics=None):
        self._mtx = threading.Lock()
        self._totals: dict[tuple[str, str], list] = {}
        self.metrics = metrics

    def record(self, subsystem: str, stage: str, seconds: float) -> None:
        with self._mtx:
            t = self._totals.setdefault((subsystem, stage), [0, 0.0])
            t[0] += 1
            t[1] += seconds
        if self.metrics is not None:
            self.metrics.stage_duration_seconds.labels(
                subsystem, stage).observe(seconds)

    def snapshot(self) -> dict:
        """{"subsystem.stage": {"count": n, "seconds": s}} — the shape
        the simnet benches report alongside their e2e rates."""
        with self._mtx:
            return {
                f"{sub}.{stage}": {"count": c, "seconds": round(s, 6)}
                for (sub, stage), (c, s) in sorted(self._totals.items())}

    def reset(self) -> None:
        with self._mtx:
            self._totals.clear()


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _TimedSpan:
    __slots__ = ("_tracer", "_subsystem", "_stage", "_t0")

    def __init__(self, tracer: StageTracer, subsystem: str, stage: str):
        self._tracer = tracer
        self._subsystem = subsystem
        self._stage = stage

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tracer.record(self._subsystem, self._stage,
                            time.perf_counter() - self._t0)
        return False


# process-wide tracer seam (same pattern as metrics.set_device_metrics)
_tracer: StageTracer | None = None


def set_tracer(t: StageTracer | None) -> None:
    global _tracer
    _tracer = t


def tracer() -> StageTracer | None:
    return _tracer


def span(subsystem: str, stage: str):
    """Context manager timing one stage; free when no tracer is set."""
    t = _tracer
    if t is None:
        return _NULL_SPAN
    return _TimedSpan(t, subsystem, stage)
