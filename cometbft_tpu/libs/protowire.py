"""Minimal protobuf wire codec (the libs/protoio analog).

Hand-rolled writer/reader for the protobuf wire format, matching the
byte-for-byte behavior of the reference's gogoproto-generated marshallers
(/root/reference/api/cometbft/**/*.pb.go) that produce consensus-critical
bytes: canonical sign-bytes, header field hashes, validator-set hashes.

Gogoproto conventions reproduced here:
- proto3 scalar/enum/bytes/string fields with zero values are omitted;
- `nullable=false` embedded messages are ALWAYS emitted, even when empty
  (e.g. CanonicalVote.timestamp, canonical.pb.go:610-617);
- fields are emitted in ascending tag order;
- negative int32/int64 varints sign-extend to 10 bytes;
- sfixed64 is 8-byte little-endian two's complement.

Also provides the length-delimited framing used by SignBytes / the WAL /
socket ABCI (reference libs/protoio/writer.go).
"""

from __future__ import annotations

import struct

_U64 = (1 << 64) - 1

MASK64 = (1 << 64) - 1


def delimited_field_size(n: int) -> int:
    """Wire size of an n-byte length-delimited field with a 1-byte tag
    (types/tx.go ComputeProtoSizeForTxs)."""
    return 1 + len(encode_uvarint(n)) + n

# wire types
VARINT = 0
FIXED64 = 1
BYTES = 2
FIXED32 = 5


def encode_uvarint(v: int) -> bytes:
    if v < 0:
        raise ValueError("uvarint must be non-negative")
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_uvarint(buf: bytes, pos: int = 0) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            if result > _U64:
                raise ValueError("varint overflows uint64")
            return result, pos
        shift += 7
        if shift >= 70:
            raise ValueError("varint too long")


class Writer:
    """Appends proto fields in tag order; caller keeps tags ascending."""

    __slots__ = ("_parts",)

    def __init__(self):
        self._parts: list[bytes] = []

    # -- raw --------------------------------------------------------------
    def raw(self, b: bytes) -> "Writer":
        self._parts.append(b)
        return self

    def tag(self, field: int, wire: int) -> "Writer":
        self._parts.append(encode_uvarint((field << 3) | wire))
        return self

    # -- scalars (proto3: zero omitted) ------------------------------------
    def uvarint_field(self, field: int, v: int) -> "Writer":
        if v != 0:
            self.tag(field, VARINT).raw(encode_uvarint(v))
        return self

    def int_field(self, field: int, v: int) -> "Writer":
        """int32/int64/enum: negative encodes as 10-byte two's complement."""
        if v != 0:
            self.tag(field, VARINT).raw(encode_uvarint(v & _U64))
        return self

    def bool_field(self, field: int, v: bool) -> "Writer":
        if v:
            self.tag(field, VARINT).raw(b"\x01")
        return self

    def sfixed64_field(self, field: int, v: int) -> "Writer":
        if v != 0:
            self.tag(field, FIXED64).raw(struct.pack("<q", v))
        return self

    def bytes_field(self, field: int, v: bytes) -> "Writer":
        if v:
            self.tag(field, BYTES).raw(encode_uvarint(len(v))).raw(v)
        return self

    def string_field(self, field: int, v: str) -> "Writer":
        return self.bytes_field(field, v.encode("utf-8"))

    def packed_uint64_field(self, field: int, vals) -> "Writer":
        payload = b"".join(encode_uvarint(v & MASK64) for v in vals)
        return self.bytes_field(field, payload)

    # -- messages ----------------------------------------------------------
    def message_field(self, field: int, payload: bytes) -> "Writer":
        """Embedded message, gogo nullable=false: always emitted."""
        self.tag(field, BYTES).raw(encode_uvarint(len(payload))).raw(payload)
        return self

    def optional_message_field(self, field: int,
                               payload: bytes | None) -> "Writer":
        """Embedded message behind a pointer: omitted when None."""
        if payload is not None:
            self.message_field(field, payload)
        return self

    def bytes(self) -> bytes:  # noqa: A003 - mirrors bytes() of buffers
        return b"".join(self._parts)


def sint_from_uvarint(v: int) -> int:
    """Interpret a uint64 varint as two's-complement int64."""
    return v - (1 << 64) if v >= (1 << 63) else v


class Reader:
    """Field-by-field reader over one message's payload."""

    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf: bytes, pos: int = 0, end: int | None = None):
        self.buf = buf
        self.pos = pos
        self.end = len(buf) if end is None else end

    def at_end(self) -> bool:
        return self.pos >= self.end

    def read_tag(self) -> tuple[int, int]:
        key = self.read_uvarint()
        return key >> 3, key & 0x7

    def read_uvarint(self) -> int:
        v, pos = decode_uvarint(self.buf[:self.end], self.pos)
        self.pos = pos
        return v

    def read_int(self) -> int:
        return sint_from_uvarint(self.read_uvarint())

    def read_sfixed64(self) -> int:
        if self.pos + 8 > self.end:
            raise ValueError("truncated sfixed64 field")
        v = struct.unpack_from("<q", self.buf, self.pos)[0]
        self.pos += 8
        return v

    def read_fixed32(self) -> int:
        if self.pos + 4 > self.end:
            raise ValueError("truncated fixed32 field")
        v = struct.unpack_from("<I", self.buf, self.pos)[0]
        self.pos += 4
        return v

    def read_bytes(self) -> bytes:
        n = self.read_uvarint()
        if self.pos + n > self.end:
            raise ValueError("truncated bytes field")
        v = self.buf[self.pos:self.pos + n]
        self.pos += n
        return v

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")

    def read_packed_uint64(self) -> list[int]:
        payload = self.read_bytes()
        vals, pos = [], 0
        while pos < len(payload):
            v, pos = decode_uvarint(payload, pos)
            vals.append(v)
        return vals

    def sub_reader(self) -> "Reader":
        n = self.read_uvarint()
        if self.pos + n > self.end:
            raise ValueError("truncated message field")
        r = Reader(self.buf, self.pos, self.pos + n)
        self.pos += n
        return r

    def skip(self, wire: int) -> None:
        if wire == VARINT:
            self.read_uvarint()
        elif wire == FIXED64:
            self.read_sfixed64()
        elif wire == BYTES:
            self.read_bytes()
        elif wire == FIXED32:
            self.read_fixed32()
        else:
            raise ValueError(f"unknown wire type {wire}")


# -- length-delimited framing (libs/protoio) --------------------------------

def marshal_delimited(payload: bytes) -> bytes:
    """varint(len) || payload — the framing of SignBytes and the WAL
    (reference types/vote.go:150-158, libs/protoio/writer.go:31)."""
    return encode_uvarint(len(payload)) + payload


def unmarshal_delimited(buf: bytes, pos: int = 0) -> tuple[bytes, int]:
    n, pos = decode_uvarint(buf, pos)
    if pos + n > len(buf):
        raise ValueError("truncated delimited message")
    return buf[pos:pos + n], pos + n


def try_unmarshal_delimited(buf: bytes, pos: int = 0,
                            max_frame: int = 256 * 1024 * 1024):
    """Streaming-friendly framing: returns (payload, end_pos) for a whole
    frame, None when more bytes are needed, and raises ValueError for a
    genuinely corrupt stream (invalid/oversized length varint) — the
    distinction socket read loops need to tell 'wait' from 'tear down'."""
    try:
        n, body = decode_uvarint(buf, pos)
    except ValueError as e:
        if "truncated" in str(e) and len(buf) - pos < 10:
            return None  # varint may still be arriving
        raise
    if n > max_frame:
        raise ValueError(f"frame length {n} exceeds cap {max_frame}")
    if body + n > len(buf):
        return None
    return buf[body:body + n], body + n


# -- google.protobuf.Timestamp ----------------------------------------------

def encode_timestamp(seconds: int, nanos: int) -> bytes:
    """Timestamp payload: int64 seconds = 1, int32 nanos = 2."""
    return Writer().int_field(1, seconds).int_field(2, nanos).bytes()


def decode_timestamp(payload: bytes) -> tuple[int, int]:
    r = Reader(payload)
    seconds = nanos = 0
    while not r.at_end():
        field, wire = r.read_tag()
        if field == 1 and wire == VARINT:
            seconds = r.read_int()
        elif field == 2 and wire == VARINT:
            nanos = r.read_int()
        else:
            r.skip(wire)
    return seconds, nanos
