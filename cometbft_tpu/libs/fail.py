"""Deterministic crash-point injection (reference internal/fail/fail.go).

Named fail points are sprinkled through the commit sequence
(state/execution.py, consensus finalize); setting FAIL_TEST_INDEX to the
ordinal of a call makes the process exit there, so tests can replay a
crash at every window of the save->WAL->apply->save ordering.
"""

from __future__ import annotations

import os

_call_index = -1
_callback = None


def reset() -> None:
    global _call_index, _callback
    _call_index = -1
    _callback = None


def set_callback(cb) -> None:
    """Tests can install a callback instead of killing the process."""
    global _callback
    _callback = cb


def fail_point(name: str = "") -> None:
    """fail.Fail(): exit (or invoke the test callback) when this is the
    FAIL_TEST_INDEX-th fail point hit since process start."""
    env = os.environ.get("FAIL_TEST_INDEX")
    if env is None and _callback is None:
        return
    global _call_index
    _call_index += 1
    if _callback is not None:
        _callback(_call_index, name)
        return
    if _call_index == int(env):
        os._exit(1)
