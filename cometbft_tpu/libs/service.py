"""Uniform Start/Stop/Quit lifecycle for long-lived objects
(reference libs/service/service.go).

Every engine component (reactors, switch, WAL, event bus, node) shares
this contract: start once, stop once, wait for quit. Thread-based —
the runtime around the JAX compute path is ordinary host concurrency.
"""

from __future__ import annotations

import threading

from . import lockrank


class AlreadyStartedError(RuntimeError):
    pass


class AlreadyStoppedError(RuntimeError):
    pass


class BaseService:
    """Template-method lifecycle: subclasses override on_start/on_stop."""

    def __init__(self, name: str = ""):
        self._name = name or type(self).__name__
        self._started = False
        self._stopped = False
        self._quit = threading.Event()
        self._lifecycle_mtx = lockrank.RankedLock("service.lifecycle")

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        with self._lifecycle_mtx:
            if self._started:
                raise AlreadyStartedError(self._name)
            if self._stopped:
                raise AlreadyStoppedError(self._name)
            self._started = True
        self.on_start()

    def stop(self) -> None:
        with self._lifecycle_mtx:
            if self._stopped:
                return
            self._stopped = True
        self.on_stop()
        self._quit.set()

    def is_running(self) -> bool:
        return self._started and not self._stopped

    def wait(self, timeout: float | None = None) -> bool:
        return self._quit.wait(timeout)

    def quit_event(self) -> threading.Event:
        return self._quit

    # -- overridables ------------------------------------------------------
    def on_start(self) -> None:  # pragma: no cover - trivial default
        pass

    def on_stop(self) -> None:  # pragma: no cover - trivial default
        pass

    def __str__(self) -> str:
        return self._name
