"""ctypes binding for the native commit codec (native/protowire/).

The repeated-CommitSig section dominates commit serialization (~33 ms
per 6668-sig commit in pure Python); the C encoder produces identical
bytes in well under a millisecond, leaving only the columnar gather
(~2-3 ms) on the Python side.  Commit.to_proto routes here when the
library is present and the commit is large enough to amortize the
gather; byte parity with the pure path is pinned by tests.

Mirrors the crypto/bls12381 native pattern: build() compiles with g++
on demand, load is lazy + self-tested, absence degrades silently to
the pure-Python encoder.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

from . import lockrank

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native", "protowire")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libcommitcodec.so")

# below this many signatures the columnar gather costs more than the
# pure encoder saves
MIN_SIGS = int(os.environ.get("COMETBFT_TPU_NATIVE_CODEC_MIN", "64"))

_lib = None
_failed = False          # sticky: one bad load/build attempt ends it
_lib_lock = lockrank.RankedLock("native_codec.lib")


def build() -> bool:
    """Compile the native library (g++, <1 s).  Returns True when the
    .so exists afterwards — same contract as crypto/bls12381.build()
    (tests skip on False instead of erroring on toolchain-less
    hosts)."""
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True)
    except (OSError, subprocess.CalledProcessError):
        pass
    return os.path.exists(_LIB_PATH)


def _load():
    global _lib, _failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _failed:
            return None
        if not os.path.exists(_LIB_PATH) and not build():
            # no .so and no toolchain: don't retry per call — the
            # caller sits on the serialization hot path
            _failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _failed = True
            return None
        fn = lib.pw_encode_commit_sigs
        fn.argtypes = [
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_int), ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_long,
        ]
        fn.restype = ctypes.c_long
        lib.pw_codec_selftest.restype = ctypes.c_int
        try:
            bad = lib.pw_codec_selftest() != 0
        except Exception:
            bad = True
        if bad:
            # stale/corrupt .so: cache the failure (a dlopen +
            # self-test per large commit would sit on the very hot
            # path this module exists to speed up) and fall back pure
            _failed = True
            raise RuntimeError("commit codec native self-test failed")
        _lib = lib
        return _lib


def enabled() -> bool:
    try:
        return _load() is not None
    except Exception:
        return False


def encode_commit_sigs(sigs) -> bytes | None:
    """The concatenated field-4-wrapped CommitSig messages for a
    signature list, or None when the native path doesn't apply."""
    if len(sigs) < MIN_SIGS:
        return None
    try:
        lib = _load()
    except Exception:
        return None
    if lib is None:
        return None
    n = len(sigs)
    flags = (ctypes.c_longlong * n)()
    ts_sec = (ctypes.c_longlong * n)()
    ts_nano = (ctypes.c_int * n)()
    addr_off = (ctypes.c_int * (n + 1))()
    sig_off = (ctypes.c_int * (n + 1))()
    addrs = []
    sblobs = []
    a_pos = s_pos = 0
    for i, s in enumerate(sigs):
        # negative decoded flags pass through as-is: the C side casts
        # to unsigned 64-bit, which IS Writer.int_field's (v & _U64)
        # 10-byte two's-complement encoding
        flags[i] = s.block_id_flag
        t = s.timestamp
        ts_sec[i] = t.seconds
        ts_nano[i] = t.nanos
        a = s.validator_address
        addrs.append(a)
        a_pos += len(a)
        addr_off[i + 1] = a_pos
        sg = s.signature
        sblobs.append(sg)
        s_pos += len(sg)
        sig_off[i + 1] = s_pos
    addr_blob = b"".join(addrs)
    sig_blob = b"".join(sblobs)
    # worst case per sig: 1+5 wrap + flag 11 + addr 6+len + ts 2+24 +
    # sig 6+len — 64 fixed bytes of headroom is generous
    cap = 64 * n + a_pos + s_pos
    out = ctypes.create_string_buffer(cap)
    w = lib.pw_encode_commit_sigs(
        n, flags, addr_off, addr_blob, ts_sec, ts_nano, sig_off,
        sig_blob, ctypes.cast(out, ctypes.c_char_p), cap)
    if w < 0:
        return None
    return out.raw[:w]
