"""Per-device time accounting: what fraction of each chip's wall-clock
the verify plane actually used, and — when a chip sat idle — WHY.

The critical-path sweep (libs/tracetl.py) decomposes one height's
latency; it cannot say whether the mesh is device-bound or host-bound
across a run.  This plane answers that: every pipeline dispatch thread
(crypto/dispatch.py) drives a per-device account through `advance`,
attributing every instant since the device attached to exactly one
state — BUSY (a window was dispatching) or one of four idle causes:

  staging       the next window's host work (host_pack / host_splice)
                had not finished when the device went looking
  backpressure  windows exist but none are dispatchable for this
                device (depth-K slots held by other devices' windows,
                or computed windows waiting on in-order publication)
  no_work       the submit queue was empty — including cache-starved:
                fully-cached windows resolve at submit and bypass the
                device BY DESIGN (crypto/sigcache.py)
  drain         fault recovery: the pipeline (or this mesh device) is
                draining to the host after a device error

The accounting is mark-advance: each account keeps one `mark`
timestamp, and `advance(state, now)` assigns [mark, now) to a single
bucket then moves the mark — so busy + idle seconds sum to the
accounted wall-clock EXACTLY, by construction (pinned in
tests/test_devprof.py).

A second ledger counts XLA compilation: ops/compile_hook.py forwards
jax.monitoring compile-duration events here, labeled by the dispatch
wrapper that triggered them (kind + input shape), classified
first-vs-recompile per (kind, shape) — so a run's cold-compile seconds
read separately from warm occupancy.

Surfaces: DevprofMetrics (libs/metrics.py) series driven incrementally
from `advance`, bounded counter-track samples merged into the Perfetto
export (tracetl.perfetto_trace counters=), the `devprof` RPC route,
/debug/pprof/devprof, and the bench extras device_occupancy_fraction /
host_bound_fraction / compile_seconds_total.

Cost contract — the flightrec discipline: with no recorder installed
the hot paths pay one module-global read and an `is None` test; one
advance is a lock, a few float adds, and (when the occupancy level
changed) one ring store.  Bounded everywhere: counter samples and
compile-ledger entries ring-overwrite, totals keep counting.

Clocks: accounts and samples use ``time.perf_counter`` — the tracetl
timeline clock — so occupancy counter tracks land on the same axis as
the exported spans.
"""

from __future__ import annotations

import time

from . import lockrank

BUSY = "busy"
IDLE_STAGING = "staging"
IDLE_BACKPRESSURE = "backpressure"
IDLE_NO_WORK = "no_work"
IDLE_DRAIN = "drain"
IDLE_QUARANTINE = "quarantine"
# the QoS scheduler (crypto/sched.py) is deliberately keeping this
# chip idle: an urgent lane's window is mid-staging and dispatching
# the staged bulk candidate now would make the urgent window wait a
# whole indivisible bulk dispatch — a bounded hold
# (COMETBFT_TPU_SCHED_HOLD_MS), distinct from backpressure because the
# operator should read it as policy, not as a starved feed path
IDLE_SCHED_HOLD = "sched_hold"
IDLE_CAUSES = (IDLE_STAGING, IDLE_BACKPRESSURE, IDLE_NO_WORK,
               IDLE_DRAIN, IDLE_QUARANTINE, IDLE_SCHED_HOLD)
STATES = (BUSY,) + IDLE_CAUSES

COMPILE_FIRST = "first"
COMPILE_RECOMPILE = "recompile"

# Label registries — the closed vocabularies for kernel-time
# attribution.  Every compile_hook.dispatch_scope kind and every
# devprof busy-path / flush-path label used anywhere in the tree must
# appear here; scripts/check_metrics.py lints the call sites against
# these sets so new kernels cannot ship unlabeled (their device time
# would silently pool under "other" on the occupancy dashboards).
DISPATCH_KINDS = frozenset({
    "ed25519_persig", "ed25519_persig_hash", "ed25519_persig_sharded",
    "ed25519_rlc", "ed25519_rlc_cached", "ed25519_rlc_hash",
    "ed25519_a_tables",
    "secp256k1_persig", "secp256k1_msm", "secp256k1_q_tables",
    "other",
})
BUSY_PATHS = frozenset({"device", "host", "cache", "drain", "error",
                        "probe"})

DEFAULT_SAMPLE_CAPACITY = 16384
DEFAULT_LEDGER_CAPACITY = 512


class DeviceAccount:
    """One device's mark-advance time partition.  Not locked — the
    owning DevprofRecorder serializes access."""

    __slots__ = ("device", "attached_at", "mark", "busy_seconds",
                 "busy_by_path", "idle_seconds", "dispatches")

    def __init__(self, device: str, now: float):
        self.device = device
        self.attached_at = now
        self.mark = now
        self.busy_seconds = 0.0
        # path -> seconds within busy: "device" is chip time, "host"
        # is the dispatch thread running a below-threshold window on
        # the CPU (the chip itself is free; consumers that want chip
        # occupancy alone read busy_by_path["device"])
        self.busy_by_path: dict[str, float] = {}
        self.idle_seconds = {c: 0.0 for c in IDLE_CAUSES}
        self.dispatches = 0

    def advance(self, state: str, now: float,
                path: str | None = None) -> float:
        """Assign [mark, now) to `state` and move the mark; returns the
        slice length.  The partition invariant lives here: every
        accounted instant lands in exactly one bucket."""
        dt = now - self.mark
        if dt < 0.0:                 # clock went backwards: re-anchor
            self.mark = now
            return 0.0
        if state == BUSY:
            self.busy_seconds += dt
            key = path or "device"
            self.busy_by_path[key] = self.busy_by_path.get(key, 0.0) + dt
            self.dispatches += 1
        else:
            self.idle_seconds[state] = \
                self.idle_seconds.get(state, 0.0) + dt
        self.mark = now
        return dt

    def wall_seconds(self) -> float:
        return self.mark - self.attached_at

    def snapshot(self) -> dict:
        wall = self.wall_seconds()
        return {
            "busy_seconds": self.busy_seconds,
            "busy_by_path": dict(self.busy_by_path),
            "idle_seconds": dict(self.idle_seconds),
            "wall_seconds": wall,
            "occupancy": (self.busy_seconds / wall) if wall > 0 else 0.0,
            "dispatches": self.dispatches,
        }


class DevprofRecorder:
    """Thread-safe per-device accounts + occupancy/queue counter-track
    samples (bounded ring) + the XLA compile-cost ledger."""

    def __init__(self, sample_capacity: int = DEFAULT_SAMPLE_CAPACITY,
                 ledger_capacity: int = DEFAULT_LEDGER_CAPACITY,
                 clock=time.perf_counter):
        if sample_capacity <= 0 or ledger_capacity <= 0:
            raise ValueError("capacity must be positive")
        self.sample_capacity = sample_capacity
        self.ledger_capacity = ledger_capacity
        self._clock = clock
        self._mtx = lockrank.RankedLock("devprof.ring")
        self._accounts: dict[str, DeviceAccount] = {}
        # counter-track samples: (t, track, value) ring, same
        # recorded/dropped discipline as flightrec
        self._samples: list = [None] * sample_capacity
        self._sampled = 0
        self._last_value: dict[str, float] = {}
        # compile ledger
        self._ledger: list = [None] * ledger_capacity
        self._compiled = 0
        self._compile_seen: set = set()
        self._compile_seconds = 0.0
        self._compile_first_seconds = 0.0
        self._compile_count = 0
        self._compile_by_kind: dict[str, dict] = {}

    # -- device accounts ---------------------------------------------------

    def attach(self, device: str, t: float | None = None) -> None:
        """Open an account for `device` (idempotent): accounting — and
        the exact-partition window — starts at the attach instant."""
        now = t if t is not None else self._clock()
        with self._mtx:
            if device not in self._accounts:
                self._accounts[device] = DeviceAccount(device, now)
                self._sample_locked(now, "occupancy_pct/dev%s" % device,
                                    0.0)

    def advance(self, device: str, state: str,
                path: str | None = None,
                t: float | None = None) -> float:
        """Attribute everything since this device's mark to `state`
        (BUSY or an idle cause) and move the mark.  Auto-attaches on
        first sight.  Drives the DevprofMetrics seam and the occupancy
        counter track incrementally; returns the slice length."""
        now = t if t is not None else self._clock()
        with self._mtx:
            acct = self._accounts.get(device)
            if acct is None:
                acct = self._accounts[device] = DeviceAccount(device,
                                                              now)
            start = acct.mark
            dt = acct.advance(state, now, path=path)
            if dt > 0.0:
                # the counter track is a step function: the level over
                # [start, now) was 100 iff busy; only level CHANGES
                # store a sample, so a long all-busy run costs two
                self._sample_locked(
                    start, "occupancy_pct/dev%s" % device,
                    100.0 if state == BUSY else 0.0)
            busy = acct.busy_seconds
            wall = acct.wall_seconds()
        if dt > 0.0:
            from . import metrics as libmetrics
            dm = libmetrics.devprof_metrics()
            if dm is not None:
                if state == BUSY:
                    dm.busy_seconds.labels(device).add(dt)
                else:
                    dm.idle_seconds.labels(device, state).add(dt)
                if wall > 0:
                    dm.occupancy.labels(device).set(busy / wall)
        return dt

    # -- counter tracks ----------------------------------------------------

    def _sample_locked(self, t: float, track: str, value: float) -> None:
        if self._last_value.get(track) == value:
            return
        self._last_value[track] = value
        seq = self._sampled
        self._samples[seq % self.sample_capacity] = (t, track, value)
        self._sampled = seq + 1

    def counter(self, track: str, value: float,
                t: float | None = None) -> None:
        """Record one counter-track sample (queue depth, in-flight
        windows, ...) for the Perfetto export; deduplicates repeats of
        the same level."""
        now = t if t is not None else self._clock()
        with self._mtx:
            self._sample_locked(now, track, float(value))

    def counter_samples(self) -> list[tuple]:
        """Retained (t, track, value) samples, oldest first — the
        `counters=` input of tracetl.perfetto_trace."""
        with self._mtx:
            n = self._sampled
            kept = min(n, self.sample_capacity)
            return [self._samples[(n - kept + i) % self.sample_capacity]
                    for i in range(kept)]

    # -- compile ledger ----------------------------------------------------

    def compile_event(self, kind: str, shape, seconds: float,
                      backend: bool = True) -> None:
        """One jax.monitoring compile-duration event.  All phases
        (trace / lower / backend-compile) accumulate seconds; only the
        backend compile counts and classifies first-vs-recompile per
        (kind, shape) — the cold-compile ledger entry."""
        try:
            shape = tuple(shape) if shape is not None else None
        except TypeError:
            shape = (repr(shape),)
        with self._mtx:
            self._compile_seconds += seconds
            if backend:
                key = (kind, shape)
                first = key not in self._compile_seen
                self._compile_seen.add(key)
                phase = COMPILE_FIRST if first else COMPILE_RECOMPILE
                if first:
                    self._compile_first_seconds += seconds
                self._compile_count += 1
                per = self._compile_by_kind.setdefault(
                    kind, {"count": 0, "seconds": 0.0,
                           COMPILE_FIRST: 0, COMPILE_RECOMPILE: 0})
                per["count"] += 1
                per["seconds"] += seconds
                per[phase] += 1
                seq = self._compiled
                self._ledger[seq % self.ledger_capacity] = {
                    "kind": kind,
                    "shape": list(shape) if shape is not None else None,
                    "seconds": round(seconds, 6),
                    "phase": phase,
                }
                self._compiled = seq + 1
        from . import metrics as libmetrics
        dm = libmetrics.devprof_metrics()
        if dm is not None:
            dm.compile_seconds.add(seconds)
            if backend:
                dm.compile_count.labels(kind).inc()

    # -- reading -----------------------------------------------------------

    def snapshot(self) -> dict:
        """Per-device partitions + the compile ledger totals — the
        shape the bench extras and the RPC dump read from."""
        with self._mtx:
            devices = {d: a.snapshot()
                       for d, a in sorted(self._accounts.items())}
            n = self._compiled
            kept = min(n, self.ledger_capacity)
            entries = [self._ledger[(n - kept + i)
                                    % self.ledger_capacity]
                       for i in range(kept)]
            compile_ = {
                "seconds_total": round(self._compile_seconds, 6),
                "first_seconds": round(self._compile_first_seconds, 6),
                "count": self._compile_count,
                "by_kind": {k: {**v, "seconds": round(v["seconds"], 6)}
                            for k, v in
                            sorted(self._compile_by_kind.items())},
                "entries": entries,
            }
            samples = {"recorded": self._sampled,
                       "dropped": self._sampled
                       - min(self._sampled, self.sample_capacity)}
        for d in devices.values():
            for k in ("busy_seconds", "wall_seconds", "occupancy"):
                d[k] = round(d[k], 6)
            d["busy_by_path"] = {k: round(v, 6)
                                 for k, v in d["busy_by_path"].items()}
            d["idle_seconds"] = {k: round(v, 6)
                                 for k, v in d["idle_seconds"].items()}
        return {"devices": devices, "compile": compile_,
                "samples": samples}

    def dump(self) -> dict:
        return self.snapshot()

    def dump_text(self) -> str:
        s = self.snapshot()
        lines = ["devprof: %d device(s), %d compile(s) %.3fs "
                 "(%d samples, %d dropped)"
                 % (len(s["devices"]), s["compile"]["count"],
                    s["compile"]["seconds_total"],
                    s["samples"]["recorded"], s["samples"]["dropped"])]
        for dev, d in s["devices"].items():
            idle = " ".join("%s=%.3fs" % (c, d["idle_seconds"].get(c, 0.0))
                            for c in IDLE_CAUSES)
            lines.append(
                "  dev%s: occupancy %.1f%% busy=%.3fs wall=%.3fs "
                "dispatches=%d idle[%s]"
                % (dev, 100.0 * d["occupancy"], d["busy_seconds"],
                   d["wall_seconds"], d["dispatches"], idle))
        for kind, v in s["compile"]["by_kind"].items():
            lines.append("  compile %s: %d (%d first) %.3fs"
                         % (kind, v["count"], v[COMPILE_FIRST],
                            v["seconds"]))
        return "\n".join(lines)

    def clear(self) -> None:
        with self._mtx:
            self._accounts = {}
            self._samples = [None] * self.sample_capacity
            self._sampled = 0
            self._last_value = {}
            self._ledger = [None] * self.ledger_capacity
            self._compiled = 0
            self._compile_seen = set()
            self._compile_seconds = 0.0
            self._compile_first_seconds = 0.0
            self._compile_count = 0
            self._compile_by_kind = {}


def occupancy_summary(snapshot: dict) -> dict:
    """Aggregate one recorder snapshot into the bench extras:
    device_occupancy_fraction (busy / wall over every device) and
    host_bound_fraction (the staging idle share — wall the chips spent
    waiting on host pack/splice)."""
    busy = wall = staging = 0.0
    causes = {c: 0.0 for c in IDLE_CAUSES}
    for d in (snapshot.get("devices") or {}).values():
        busy += d["busy_seconds"]
        wall += d["wall_seconds"]
        for c in IDLE_CAUSES:
            causes[c] += d["idle_seconds"].get(c, 0.0)
    staging = causes[IDLE_STAGING]
    return {
        "device_occupancy_fraction": round(busy / wall, 6)
        if wall > 0 else 0.0,
        "host_bound_fraction": round(staging / wall, 6)
        if wall > 0 else 0.0,
        "idle_cause_seconds": {c: round(v, 6)
                               for c, v in causes.items()},
        "busy_seconds": round(busy, 6),
        "wall_seconds": round(wall, 6),
    }


# -- process-wide seam -------------------------------------------------------
# The pipeline's dispatch threads sit below node wiring and report
# through this, exactly like flightrec.record / metrics.device_metrics.
_recorder: DevprofRecorder | None = None


def set_recorder(r: DevprofRecorder | None) -> None:
    global _recorder
    _recorder = r


def recorder() -> DevprofRecorder | None:
    return _recorder
