"""Consensus flight recorder: a bounded ring buffer of structured
events covering one node's round lifecycle — step/round transitions,
timeout fires, vote arrivals (with lateness), proposal receipts,
verify flushes (batch size + execution path), and RLC fallbacks.

The reference answers "why did this round go long?" with the
DumpConsensusState RPC — a snapshot of the CURRENT round state.  A
snapshot cannot show the timeline that led there, and the question this
framework exists for (where between vote arrival and device flush did
the time go?) is inherently a timeline question.  So the recorder is
event-sourced: recording is always-on once installed, the buffer is
bounded (old events overwrite, totals keep counting), and dumps are
reachable three ways — the `flightrec` RPC route (rpc/core.py), the
`/debug/pprof/flightrec` handler (libs/pprof.py), and an automatic
dump-to-log when a height escalates past round 0 or a device verify
flush fails.

Cost contract (the acceptance bar for the kernel benches): with no
recorder installed, the hot paths pay ONE module-global read and an
`is None` test — the same seam discipline as metrics.device_metrics()
and trace.tracer().  With a recorder installed, one event is a lock,
two integer ops, and a list store; there is no serialization, no I/O,
and no allocation beyond the caller's field dict.
"""

from __future__ import annotations

import logging
import time

from . import lockrank

_log = logging.getLogger(__name__)

DEFAULT_CAPACITY = 4096

# canonical event kinds (callers may record others; these are the ones
# the consensus/crypto layers emit)
EV_STEP = "step"                     # round-step transition
EV_NEW_HEIGHT = "new_height"         # height advanced (commit applied)
EV_TIMEOUT = "timeout"               # a scheduled timeout fired
EV_VOTE = "vote"                     # vote arrival (with lateness)
EV_PROPOSAL = "proposal"             # proposal receipt
EV_ESCALATION = "round_escalation"   # height moved past round 0
EV_VERIFY_FLUSH = "verify_flush"     # streaming-verifier flush
EV_DEVICE_FALLBACK = "device_fallback"  # device flush failed -> host
EV_RLC_FALLBACK = "rlc_fallback"     # RLC whole-batch check failed
EV_CACHE_LOOKUP = "cache_lookup"     # sigcache batch consult with hits
EV_CACHE_INSERT = "cache_insert"     # sigcache batch verdict insertion
EV_PIPELINE_DRAIN = "pipeline_drain"  # verify pipeline drained after a
#                                       mid-flight device failure
#                                       (crypto/dispatch.py); carries
#                                       inflight + staged depths
EV_DEVICE_HASH_FALLBACK = "device_hash_fallback"  # a window left the
#                                       fused device-hash path (message
#                                       exceeded the static SHA-512
#                                       block bucket) and re-staged
#                                       through host hashing
EV_DEVICE_QUARANTINE = "device_quarantine"  # devhealth circuit breaker
#                                       opened: the device left the
#                                       dispatch rotation (fault rate,
#                                       hang, or a failed probe)
EV_DEVICE_PROBE = "device_probe"     # known-answer probe batch verdict
#                                       on a quarantined device (result
#                                       ok -> back in rotation, fail ->
#                                       backoff doubles)
EV_WATCHDOG_TIMEOUT = "watchdog_timeout"  # a device dispatch outlived
#                                       its deadline: window resolved
#                                       on the host, wedged thread
#                                       abandoned + replaced, device
#                                       quarantined
EV_BROWNOUT = "brownout"             # every device quarantined
#                                       (entered=True): pure host
#                                       fallback with bounded depth and
#                                       shrunken windows; entered=False
#                                       when a probe returns a chip
EV_LIGHTSERVE_REJECT = "lightserve_reject"  # the serving plane caught
#                                       an invalid commit signature in
#                                       a merged flush: that height's
#                                       requests fail, nothing is
#                                       served past it
EV_SLO_BURN = "slo_burn"             # latency-ledger SLO burn
#                                       (libs/latledger.py): a
#                                       consumer's short-window burn
#                                       rate tripped its declared p99
#                                       target budget; sustained=True
#                                       after consecutive trips (auto
#                                       dump-to-log)
EV_SCHED_PREEMPT = "sched_preempt"   # QoS scheduler (crypto/sched.py)
#                                       dispatched a higher-lane window
#                                       ahead of earlier-submitted
#                                       lower-lane ones; carries the
#                                       winning lane, its batch size,
#                                       and how many staged windows it
#                                       overtook (their wait books as
#                                       held time in SchedulerMetrics
#                                       and queue_wait in the ledger)


class FlightRecorder:
    """Bounded ring of (seq, monotonic, kind, fields) event tuples.

    `recorded` counts every event ever seen; the ring keeps the last
    `capacity` of them, so `dropped = recorded - len(ring)`.  Thread
    safe: consensus state thread, reactor gossip threads, and the
    votestream worker all record into one instance.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 clock=time.monotonic):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._clock = clock
        self._mtx = lockrank.RankedLock("flightrec.ring")
        self._ring: list = [None] * capacity
        self._recorded = 0

    # -- recording ---------------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        t = self._clock()
        with self._mtx:
            seq = self._recorded
            self._ring[seq % self.capacity] = (seq, t, kind, fields)
            self._recorded = seq + 1

    # -- reading -----------------------------------------------------------
    def __len__(self) -> int:
        with self._mtx:
            return min(self._recorded, self.capacity)

    @property
    def recorded(self) -> int:
        with self._mtx:
            return self._recorded

    def events(self) -> list[dict]:
        """Oldest-to-newest snapshot of the retained events."""
        with self._mtx:
            n = self._recorded
            kept = min(n, self.capacity)
            raw = [self._ring[(n - kept + i) % self.capacity]
                   for i in range(kept)]
        return [{"seq": seq, "t": t, "kind": kind, **dict(fields)}
                for (seq, t, kind, fields) in raw]

    def dump(self) -> dict:
        evs = self.events()
        return {
            "recorded": self.recorded,
            "dropped": self.recorded - len(evs),
            "capacity": self.capacity,
            "events": evs,
        }

    def summary(self) -> dict:
        """Per-kind counts over the retained window plus totals — the
        shape simnet reports per node next to its e2e rates."""
        counts: dict[str, int] = {}
        max_round = 0
        for e in self.events():
            counts[e["kind"]] = counts.get(e["kind"], 0) + 1
            if e["kind"] in (EV_STEP, EV_ESCALATION):
                max_round = max(max_round, int(e.get("round", 0)))
        return {"recorded": self.recorded,
                "dropped": self.recorded - len(self),
                "by_kind": counts,
                "max_round_seen": max_round}

    def dump_text(self) -> str:
        d = self.dump()
        lines = [f"flight recorder: {d['recorded']} recorded, "
                 f"{d['dropped']} dropped (capacity {d['capacity']})"]
        for e in d["events"]:
            extra = " ".join(f"{k}={v}" for k, v in e.items()
                             if k not in ("seq", "t", "kind"))
            lines.append(f"  #{e['seq']:<6} t={e['t']:.6f} "
                         f"{e['kind']:<16} {extra}")
        return "\n".join(lines)

    def dump_to_log(self, reason: str, logger=None) -> None:
        (logger or _log).warning("flight recorder dump (%s):\n%s",
                                 reason, self.dump_text())

    def clear(self) -> None:
        with self._mtx:
            self._ring = [None] * self.capacity
            self._recorded = 0


# -- process-wide seam -------------------------------------------------------
# Layers below any node wiring (crypto/votestream, crypto/batch) report
# through this, exactly like metrics.set_device_metrics / trace.set_tracer.
_recorder: FlightRecorder | None = None


def set_recorder(r: FlightRecorder | None) -> None:
    global _recorder
    _recorder = r


def recorder() -> FlightRecorder | None:
    return _recorder


def record(kind: str, **fields) -> None:
    """Record into the process-wide recorder; free when none is set."""
    r = _recorder
    if r is None:
        return
    r.record(kind, **fields)
