"""BitArray: vote/part presence tracking for gossip
(reference internal/bits/bit_array.go).

Backed by a numpy bool array — `sub`, `or`, `not` and pick-random are
vector ops, matching how the gossip routines use BitArrays to compute
"parts the peer is missing" set differences.
"""

from __future__ import annotations

import random

import numpy as np

from . import protowire as pw

# Upper bound for wire-decoded sizes: generous for both vote sets
# (MaxVotesCount=10000) and block part sets (100MiB / 64KiB parts)
MAX_PROTO_BITS = 1 << 22


class BitArray:
    __slots__ = ("bits",)

    def __init__(self, n: int = 0):
        self.bits = np.zeros(max(n, 0), dtype=bool)

    @staticmethod
    def from_bools(vals) -> "BitArray":
        ba = BitArray(0)
        ba.bits = np.asarray(list(vals), dtype=bool)
        return ba

    def size(self) -> int:
        return int(self.bits.shape[0])

    def __len__(self) -> int:
        return self.size()

    def get_index(self, i: int) -> bool:
        if i < 0 or i >= self.size():
            return False
        return bool(self.bits[i])

    def set_index(self, i: int, v: bool) -> bool:
        if i < 0 or i >= self.size():
            return False
        self.bits[i] = v
        return True

    def copy(self) -> "BitArray":
        ba = BitArray(0)
        ba.bits = self.bits.copy()
        return ba

    def or_(self, other: "BitArray") -> "BitArray":
        """Union, sized to the larger operand (bit_array.go Or)."""
        n = max(self.size(), other.size())
        ba = BitArray(n)
        ba.bits[:self.size()] = self.bits
        ba.bits[:other.size()] |= other.bits
        return ba

    def and_(self, other: "BitArray") -> "BitArray":
        n = min(self.size(), other.size())
        ba = BitArray(0)
        ba.bits = self.bits[:n] & other.bits[:n]
        return ba

    def not_(self) -> "BitArray":
        ba = BitArray(0)
        ba.bits = ~self.bits
        return ba

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other; result sized as self
        (bit_array.go Sub)."""
        ba = self.copy()
        n = min(self.size(), other.size())
        ba.bits[:n] &= ~other.bits[:n]
        return ba

    def is_empty(self) -> bool:
        return not bool(self.bits.any())

    def is_full(self) -> bool:
        return bool(self.bits.all()) if self.size() else True

    def pick_random(self) -> tuple[int, bool]:
        """A uniformly random set index (bit_array.go PickRandom)."""
        idxs = np.flatnonzero(self.bits)
        if idxs.size == 0:
            return 0, False
        return int(random.choice(idxs)), True

    def true_indices(self) -> list[int]:
        return [int(i) for i in np.flatnonzero(self.bits)]

    def num_true(self) -> int:
        return int(self.bits.sum())

    def update(self, other: "BitArray") -> None:
        """Copy other's bits into self (bit_array.go Update)."""
        n = min(self.size(), other.size())
        self.bits[:n] = other.bits[:n]

    def __eq__(self, other) -> bool:
        if not isinstance(other, BitArray):
            return NotImplemented
        return self.size() == other.size() and bool(
            (self.bits == other.bits).all())

    def __str__(self) -> str:
        return "BA{%d:%s}" % (
            self.size(),
            "".join("x" if b else "_" for b in self.bits))

    # proto: message BitArray { int64 bits = 1; repeated uint64 elems = 2; }
    def to_proto(self) -> bytes:
        n = self.size()
        elems = []
        for w in range((n + 63) // 64):
            word = 0
            for b in range(64):
                i = w * 64 + b
                if i < n and self.bits[i]:
                    word |= 1 << b
            elems.append(word)
        wtr = pw.Writer().int_field(1, n)
        if elems:
            wtr.packed_uint64_field(2, elems)
        return wtr.bytes()

    @staticmethod
    def from_proto(payload: bytes) -> "BitArray":
        r = pw.Reader(payload)
        n, elems = 0, []
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1 and w == pw.VARINT:
                n = r.read_int()
            elif f == 2 and w == pw.BYTES:
                elems = r.read_packed_uint64()
            elif f == 2 and w == pw.VARINT:
                elems.append(r.read_uvarint() & pw.MASK64)
            else:
                r.skip(w)
        # DoS bound: the declared size is attacker-controlled gossip input
        if n < 0 or n > MAX_PROTO_BITS:
            raise ValueError(f"BitArray size {n} out of range")
        words = np.array(elems, dtype=np.uint64)
        unpacked = np.unpackbits(
            words.view(np.uint8), bitorder="little")
        ba = BitArray(n)
        m = min(n, unpacked.shape[0])
        ba.bits[:m] = unpacked[:m].astype(bool)
        return ba
