"""Lock-rank runtime checker + concurrency sanitizer seams: the
verify plane's tsan-lite.

The thread mesh grew dense — per-device dispatch loops, the staging
thread, the hung-dispatch watchdog with generation-bumped thread
abandonment, the lock-striped sigcache, the process-wide devhealth
registry — and until this module the only thing preventing deadlock
was reviewer discipline (PR 9 and PR 13 each patched a latent shutdown
race found by accident).  CometBFT's reference codebase leans on Go's
race detector and deadlock-ordered mutexes; this is the Python-side
equivalent:

- a drop-in ``RankedLock`` / ``RankedRLock`` / ``RankedCondition``
  family replacing every raw ``threading.Lock/RLock/Condition`` in
  cometbft_tpu/ (scripts/check_concurrency.py rule C1 rejects raw
  constructions);
- a declared global lock-rank table (``LOCK_RANKS``): one rank per
  named lock, lower rank = acquired FIRST (outermost).  Acquiring a
  lock whose rank is <= the highest rank already held by the thread is
  a rank inversion and raises (or records, in warn mode) immediately —
  BEFORE blocking, so the checker reports the would-be deadlock
  instead of deadlocking;
- a cross-thread acquisition-order edge table: the first time thread T1
  acquires B while holding A, the edge A->B is recorded with its stack;
  if any thread later acquires A while holding B, the violation report
  carries BOTH stacks (the classic two-thread cycle, caught on the
  second edge, not in a post-mortem);
- thread-leak and future-leak registries backing the autouse pytest
  sanitizer fixtures in tests/conftest.py (``TrackedFuture`` is the
  Future-subclass seam crypto/dispatch.py mints its window futures
  from: a future garbage-collected with an exception nobody retrieved
  is a swallowed failure).

Cost contract (flightrec discipline): with the checker disabled the
hot path is ONE module-global read and an ``is None`` branch ahead of
the raw lock op — tests/test_lockrank.py pins the disabled-mode
overhead.  Enable with ``COMETBFT_TPU_LOCKRANK=1`` (raise) or ``=warn``
(record to ``violations()``, keep going — the bring-up mode that maps
an unknown codebase's real acquisition order); tests/conftest.py turns
it on for the whole tier-1 suite.

Adding a new lock: pick a name (``subsystem.lock``), add it to
LOCK_RANKS at a rank consistent with every path that nests it (see
docs/ANALYSIS.md for the maintained ordering rationale), and construct
``RankedLock("your.name")``.  A name not in the table raises at
construction — the table is the closed registry, same discipline as
devprof.DISPATCH_KINDS.  ``multi=True`` marks a lock with many peer
instances under one name (per-stripe, per-node, per-metric): peer
instances may nest at equal rank, and same-name pairs are excluded
from the cycle-edge table (documented tradeoff: symmetric per-instance
deadlocks among peers are not modeled; every CROSS-name order still
is).
"""

from __future__ import annotations

import os
import threading
import traceback
import weakref
from concurrent.futures import Future

# ---------------------------------------------------------------------------
# The global lock-rank table.  Lower rank = acquired first (outermost).
# scripts/check_concurrency.py parses this dict via AST (no import) and
# lints every RankedLock("<name>") call site against it; docs/ANALYSIS.md
# documents the ordering rationale layer by layer.
# ---------------------------------------------------------------------------

LOCK_RANKS: dict[str, int] = {
    # orchestration above the node engines
    "chaos.cluster": 10,
    # synthetic light-client fleet driver (simnet/lightfleet.py):
    # guards the fleet's cursor/latency/failure tallies only — never
    # held across a session.serve call, so it sits at the very top
    "simnet.lightfleet": 11,
    # light-client serving plane (lightserve/): outermost product locks
    # — the coalescer cv and planner are held only around queue/counter
    # mutation, never across store reads or pipeline submits, but the
    # request path REACHES stores (140+), the payload cache (470) and
    # the verify plane (370+) after release, so the serving tier ranks
    # above (i.e. outside) all of them
    "lightserve.session": 12,
    "lightserve.cv": 14,
    "lightserve.planner": 16,
    # consensus core: the state mutex is the outermost product lock —
    # nearly every subsystem below is reachable while it is held
    "consensus.state": 20,
    "consensus.peerstate": 30,
    "consensus.ticker": 40,
    "evidence.pool": 50,
    # per-request ABCI callback guard: fires mempool/proxy callbacks
    # while held, so it sits OUTSIDE the mempool mutex
    "abci.reqres": 55,
    "mempool.clist": 60,
    "mempool.cache": 70,
    "blocksync.pool": 80,
    "statesync.syncer": 90,
    "statesync.chunks": 100,
    "statesync.snapshots": 110,
    "state.sink": 120,
    "state.indexer": 130,
    # storage plane (held while touching kv + the encode-once cache)
    "store.blockstore": 140,
    "state.store": 150,
    "store.kv": 160,
    "pubsub": 170,
    # p2p / rpc edge
    "p2p.switch": 180,
    "rpc.websocket": 190,
    "privval.signer": 200,
    "p2p.peer": 210,
    "p2p.peer_data": 220,
    "p2p.addrbook": 230,
    "p2p.fuzz": 240,
    "p2p.conn.send": 250,
    "p2p.conn.recv": 260,
    # abci / app
    "proxy.app": 270,
    "abci.grpc": 280,
    "abci.client": 290,
    "abci.client_write": 300,
    "abci.client_pending": 310,
    "abci.server_app": 320,
    "apps.kvstore": 330,
    # simnet transport
    "simnet.network": 340,
    "simnet.pump": 350,
    "simnet.rng": 360,
    # verify plane: default-instance guards, then the pipeline state
    # lock (one condition variable shared by submitters, the staging
    # thread, the per-device dispatch loops and the watchdog), then the
    # layers the pipeline consults while holding it
    "dispatch.default": 370,
    "votestream.default": 380,
    "votestream.cv": 390,
    "dispatch.cv": 400,
    "autofile": 410,
    "devhealth.registry": 420,
    "ed25519.atable": 430,
    "secp256k1.qtable": 440,
    "sigcache.global": 450,
    "sigcache.stripe": 460,
    "part_set.block_cache": 470,
    "flowrate": 480,
    # telemetry spool (libs/telspool.py): a flush HOLDS the spool lock
    # across every observability ring's dump call below, so it ranks
    # outside all of them
    "telspool.spool": 485,
    # observability rings (leaf-most product locks: recordable from
    # under any of the above)
    "devprof.ring": 490,
    # latledger sits OUTSIDE flightrec: committing a row under the
    # ring lock may record an EV_SLO_BURN event (latledger.py _commit
    # -> SLOTracker.on_burn -> flightrec.record)
    "latledger.ring": 495,
    "flightrec.ring": 500,
    "tracetl.ring": 510,
    "trace.stage": 520,
    "metrics.registry": 530,
    "metrics.series": 540,
    # pure leaves
    "service.lifecycle": 550,
    "native_codec.lib": 560,
    "bls12381.lib": 570,
    "msm.coeff": 580,
    "compile_hook": 590,
}

# locks with many peer instances under one name (per-node, per-stripe,
# per-metric, ...): equal-rank nesting among peers is allowed and
# same-name pairs are excluded from the cycle-edge table
MULTI_OK = frozenset({
    "lightserve.session", "lightserve.cv", "lightserve.planner",
    "consensus.state", "consensus.peerstate", "consensus.ticker",
    "evidence.pool", "mempool.clist", "mempool.cache",
    "blocksync.pool", "state.sink", "state.indexer",
    "store.blockstore", "state.store", "store.kv", "pubsub",
    "p2p.switch", "rpc.websocket", "p2p.peer", "p2p.peer_data",
    "p2p.addrbook", "p2p.fuzz", "p2p.conn.send", "p2p.conn.recv",
    "proxy.app", "abci.grpc", "abci.client", "abci.client_write",
    "abci.client_pending", "abci.server_app", "apps.kvstore",
    "abci.reqres", "simnet.pump", "simnet.rng",
    "votestream.cv", "dispatch.cv",
    "autofile", "devhealth.registry", "sigcache.stripe",
    "part_set.block_cache", "flowrate", "devprof.ring",
    "flightrec.ring", "tracetl.ring", "trace.stage",
    "metrics.registry", "metrics.series", "service.lifecycle",
    "statesync.chunks", "statesync.syncer", "statesync.snapshots",
})


class LockRankError(RuntimeError):
    """A rank inversion or cross-thread acquisition cycle.  Raised
    BEFORE the offending acquire blocks, with the held-lock context
    (and the other thread's recorded stack when the reverse edge is
    known)."""


_STACK_LIMIT = 16


def _stack() -> str:
    return "".join(traceback.format_stack(limit=_STACK_LIMIT)[:-2])


class Checker:
    """Per-thread held-lock accounting + the cross-thread edge table.

    One instance is installed process-wide (``enable``); every
    Ranked* op funnels through it when installed.  ``mode``:

    - "raise": violations raise LockRankError at the acquire site;
    - "warn":  violations append to ``violations`` (deduplicated by
      lock pair + code location) and execution continues — the
      bring-up mode that maps real acquisition order in one run.
    """

    def __init__(self, mode: str = "raise"):
        if mode not in ("raise", "warn"):
            raise ValueError("mode must be 'raise' or 'warn'")
        self.mode = mode
        self.violations: list[str] = []
        self._seen: set[tuple] = set()
        self._tls = threading.local()
        # (held_name, acquired_name) -> formatted stack of first sight.
        # Guarded by a RAW lock: the checker cannot check itself.
        self._edges: dict[tuple[str, str], str] = {}
        self._emtx = threading.Lock()

    # -- held-lock bookkeeping (all called from the owning thread) -----

    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def held_names(self) -> list[str]:
        return [e[0].name for e in self._held()]

    def before_acquire(self, lock, blocking: bool) -> None:
        """Rank + cycle check, BEFORE the raw acquire (so a would-be
        deadlock reports instead of deadlocking).  Non-blocking
        attempts skip the rank check (a trylock cannot wait, hence
        cannot deadlock at this site) but their success still lands in
        the held list via after_acquire."""
        held = self._held()
        if not held:
            return
        for entry in held:
            if entry[0] is lock:
                if lock.reentrant:
                    return
                self._violate(
                    "self-deadlock: thread re-acquiring non-reentrant "
                    f"lock '{lock.name}' it already holds", lock)
                return
        if not blocking:
            return
        top = max(held, key=lambda e: e[0].rank)[0]
        if lock.rank > top.rank:
            self._note_edges(held, lock)
            return
        if (lock.rank == top.rank and lock.multi
                and lock.name == top.name):
            return  # peer instances of a multi lock
        other = self._edges.get((lock.name, top.name))
        msg = (f"rank inversion: acquiring '{lock.name}' "
               f"(rank {lock.rank}) while holding '{top.name}' "
               f"(rank {top.rank}); declared order requires "
               f"'{lock.name}' first.  held={self.held_names()}")
        if other is not None:
            msg += ("\n--- stack that established the opposite order "
                    f"('{lock.name}' -> '{top.name}') ---\n" + other)
        self._violate(msg, lock)

    def _note_edges(self, held, lock) -> None:
        for entry in held:
            a = entry[0]
            if a.name == lock.name:
                continue
            key = (a.name, lock.name)
            if key in self._edges:
                continue
            st = _stack()
            with self._emtx:
                self._edges.setdefault(key, st)

    def after_acquire(self, lock) -> None:
        held = self._held()
        for entry in held:
            if entry[0] is lock:
                entry[1] += 1
                return
        held.append([lock, 1])

    def on_release(self, lock) -> None:
        held = self._held()
        for i, entry in enumerate(held):
            if entry[0] is lock:
                entry[1] -= 1
                if entry[1] <= 0:
                    del held[i]
                return

    # condition-variable wait: the cv's lock leaves the held set for
    # the duration (wait releases it), everything ELSE the thread holds
    # stays — and holding anything else across a wait is itself a
    # blocking-under-lock hazard worth reporting
    def on_wait_release(self, lock):
        held = self._held()
        others = [e[0].name for e in held if e[0] is not lock]
        if others:
            self._violate(
                f"cv wait on '{lock.name}' while holding {others}: "
                "a condition wait must not park other held locks",
                lock)
        for i, entry in enumerate(held):
            if entry[0] is lock:
                del held[i]
                return entry
        return None

    def on_wait_reacquire(self, lock, token) -> None:
        if token is not None:
            self._held().append(token)

    # -- violation sink ------------------------------------------------

    def _violate(self, msg: str, lock) -> None:
        if self.mode == "raise":
            raise LockRankError(msg + "\n--- acquiring stack ---\n"
                                + _stack())
        site = traceback.extract_stack(limit=8)
        loc = next((f"{f.filename}:{f.lineno}"
                    for f in reversed(site)
                    if "lockrank" not in f.filename), "?")
        key = (msg.split("\n", 1)[0], loc)
        if key not in self._seen:
            self._seen.add(key)
            self.violations.append(f"{msg.splitlines()[0]} at {loc}")


# -- process-wide checker seam (flightrec discipline) -----------------------

_checker: Checker | None = None


def enable(mode: str = "raise") -> Checker:
    global _checker
    _checker = Checker(mode)
    return _checker


def disable() -> None:
    global _checker
    _checker = None


def checker() -> Checker | None:
    return _checker


def enabled() -> bool:
    return _checker is not None


def violations() -> list[str]:
    c = _checker
    return list(c.violations) if c is not None else []


def enable_from_env() -> Checker | None:
    """Install a checker according to COMETBFT_TPU_LOCKRANK: "1"/
    "raise" -> raise mode, "warn" -> warn mode, anything else -> off.
    tests/conftest.py calls this once per session."""
    v = os.environ.get("COMETBFT_TPU_LOCKRANK", "0")
    if v in ("1", "raise"):
        return enable("raise")
    if v == "warn":
        return enable("warn")
    disable()
    return None


# ---------------------------------------------------------------------------
# The ranked lock family
# ---------------------------------------------------------------------------


class RankedLock:
    """threading.Lock with a declared rank.  Disabled-checker cost:
    one global read + one branch per op, then the raw C lock."""

    reentrant = False
    __slots__ = ("name", "rank", "multi", "_lock")

    def __init__(self, name: str):
        rank = LOCK_RANKS.get(name)
        if rank is None:
            raise ValueError(
                f"lock name {name!r} is not in lockrank.LOCK_RANKS — "
                "add it to the table (see docs/ANALYSIS.md)")
        self.name = name
        self.rank = rank
        self.multi = name in MULTI_OK
        self._lock = self._make_lock()

    def _make_lock(self):
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        c = _checker
        if c is None:
            return self._lock.acquire(blocking, timeout)
        c.before_acquire(self, blocking)
        got = self._lock.acquire(blocking, timeout)
        if got:
            c.after_acquire(self)
        return got

    def release(self) -> None:
        self._lock.release()
        c = _checker
        if c is not None:
            c.on_release(self)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        c = _checker
        if c is None:
            self._lock.acquire()
            return self
        c.before_acquire(self, True)
        self._lock.acquire()
        c.after_acquire(self)
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"<{type(self).__name__} {self.name!r} "
                f"rank={self.rank}>")


class RankedRLock(RankedLock):
    """threading.RLock with a declared rank (reentrant: re-acquiring
    the SAME instance never violates)."""

    reentrant = True
    __slots__ = ()

    def _make_lock(self):
        return threading.RLock()

    def locked(self):  # pragma: no cover - parity with RLock
        raise AttributeError("RLock has no locked()")

    # threading.Condition(raw) support
    def _is_owned(self) -> bool:
        return self._lock._is_owned()


class RankedCondition:
    """threading.Condition over a ranked lock.

    Construct with a name (fresh RankedRLock underneath, matching
    threading.Condition()'s default RLock) or with an existing
    RankedLock/RankedRLock (the ``Condition(self._mtx)`` sharing
    pattern).  wait/wait_for temporarily drop the cv's lock from the
    checker's held set — and report if the thread parks while holding
    any OTHER ranked lock."""

    __slots__ = ("_rlock", "_cond")

    def __init__(self, lock: RankedLock | None = None,
                 name: str | None = None):
        if lock is None:
            if name is None:
                raise ValueError("RankedCondition needs a lock or name")
            lock = RankedRLock(name)
        elif not isinstance(lock, RankedLock):
            raise TypeError("RankedCondition requires a ranked lock")
        self._rlock = lock
        self._cond = threading.Condition(lock._lock)

    @property
    def name(self) -> str:
        return self._rlock.name

    @property
    def rank(self) -> int:
        return self._rlock.rank

    def acquire(self, *a, **kw):
        return self._rlock.acquire(*a, **kw)

    def release(self) -> None:
        self._rlock.release()

    def __enter__(self):
        self._rlock.__enter__()
        return self

    def __exit__(self, *exc) -> bool:
        self._rlock.release()
        return False

    def wait(self, timeout: float | None = None) -> bool:
        c = _checker
        if c is None:
            return self._cond.wait(timeout)
        token = c.on_wait_release(self._rlock)
        try:
            return self._cond.wait(timeout)
        finally:
            c2 = _checker
            if c2 is not None:
                c2.on_wait_reacquire(self._rlock, token)

    def wait_for(self, predicate, timeout: float | None = None):
        c = _checker
        if c is None:
            return self._cond.wait_for(predicate, timeout)
        token = c.on_wait_release(self._rlock)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            c2 = _checker
            if c2 is not None:
                c2.on_wait_reacquire(self._rlock, token)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


# ---------------------------------------------------------------------------
# Future-leak seam (sanitizer): crypto/dispatch.py mints its window
# futures from TrackedFuture; a future collected with an exception
# nobody retrieved is a swallowed failure the tests must see.
# ---------------------------------------------------------------------------

_san_enabled = False
_leaked_futures: list[str] = []
_pending_exc: "weakref.WeakSet[TrackedFuture]" = weakref.WeakSet()


def sanitizer_enabled() -> bool:
    return _san_enabled


def set_sanitizer(on: bool) -> None:
    """Arm/disarm the future-leak registry (tests/conftest.py does,
    under COMETBFT_TPU_SANITIZERS)."""
    global _san_enabled
    _san_enabled = bool(on)


def leaked_futures() -> list[str]:
    """Descriptions of futures garbage-collected with an unretrieved
    exception since the last clear."""
    return list(_leaked_futures)


def clear_leaked_futures() -> None:
    del _leaked_futures[:]
    # drop pending markers too: a cleared slate must not blame earlier
    # tests' still-live futures on the next test
    for f in list(_pending_exc):
        f._lr_retrieved = True
    _pending_exc.clear()


class TrackedFuture(Future):
    """concurrent.futures.Future that reports exception-drop leaks.

    set_exception marks the future pending-retrieval; result()/
    exception() clear the mark; __del__ on a still-marked future
    records the leak (the sys.unraisablehook conftest wrapper catches
    anything this finalizer itself cannot say)."""

    def __init__(self):
        super().__init__()
        self._lr_retrieved = False
        self._lr_where: str | None = None

    def set_exception(self, exception) -> None:
        if _san_enabled:
            self._lr_where = _stack()
            _pending_exc.add(self)
        super().set_exception(exception)

    def _lr_mark(self):
        self._lr_retrieved = True

    def result(self, timeout=None):
        self._lr_retrieved = True
        return super().result(timeout)

    def exception(self, timeout=None):
        self._lr_retrieved = True
        return super().exception(timeout)

    def __del__(self):
        if not _san_enabled or self._lr_retrieved:
            return
        try:
            exc = super().exception(timeout=0)
        except Exception:
            return
        if exc is None:
            return
        where = self._lr_where or "(set_exception stack not captured)"
        _leaked_futures.append(
            "future dropped with unretrieved exception "
            f"{type(exc).__name__}: {exc!r}\n"
            "--- set_exception stack ---\n" + where)


# ---------------------------------------------------------------------------
# Thread-leak helper backing the conftest fixture
# ---------------------------------------------------------------------------


def sanctioned_threads() -> set:
    """Threads owned by the process-wide default engines (dispatch
    default pipeline, votestream default verifier): long-lived BY
    DESIGN, not leaks.  Resolved lazily so merely importing lockrank
    never constructs them."""
    import sys

    out: set = set()
    disp = sys.modules.get("cometbft_tpu.crypto.dispatch")
    vs = sys.modules.get("cometbft_tpu.crypto.votestream")
    for mod in (disp, vs):
        d = getattr(mod, "_default", None) if mod is not None else None
        if d is None:
            continue
        for attr in ("_staging", "_device", "_watchdog", "_thread"):
            th = getattr(d, attr, None)
            if th is not None:
                out.add(th)
        out.update(getattr(d, "_dev_threads", ()) or ())
        pool = getattr(d, "_pool", None)
        if pool is not None:
            out.update(getattr(pool, "_threads", ()) or ())
    return out


def leaked_threads(baseline: set, grace_s: float = 1.0) -> list:
    """Non-daemon threads alive now that were not in ``baseline`` and
    are not sanctioned default-engine threads; each gets up to
    ``grace_s`` (total) to finish before being reported."""
    import time

    deadline = time.monotonic() + grace_s
    leaked = []
    for th in threading.enumerate():
        if th in baseline or th.daemon or not th.is_alive():
            continue
        if th is threading.current_thread():
            continue
        th.join(timeout=max(0.0, deadline - time.monotonic()))
        if th.is_alive():
            leaked.append(th)
    return [t for t in leaked if t not in sanctioned_threads()]
