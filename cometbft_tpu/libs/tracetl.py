"""Cross-node causal timeline: one bounded event record per node that
stitches the existing observability layers — stage spans (libs/trace.py
interval records), flight-recorder events (libs/flightrec.py), and the
verify-pipeline's window lifecycle — into a single trace, and carries a
compact TRACE CONTEXT across the simnet wire so cross-node edges
(proposal gossip, block-part delivery, blocksync responses) are
reconstructable after the run.

A trace context is a plain tuple ``(origin, height, round, seq)``:
origin node name, consensus height/round the message belongs to, and a
per-node sequence number that makes every send unique.  Senders attach
it at the reactor layer (peer.send(..., tctx=...)); MConnection keeps
one context slot per message-EOF packet so packetization/batching never
misaligns it; the simnet transport ships the per-frame context list
WITH the frame (drops/dups/reorders condition frame+contexts together),
and the receiving reactor sees it on ``Envelope.tctx``.  Real TCP conns
do not implement the carry (getattr probe -> plain write), so the
context simply does not travel outside the simnet — same graceful
degradation as every other seam here.

Exports are Chrome/Perfetto ``trace_event`` JSON (open in
https://ui.perfetto.dev or chrome://tracing): one "process" per node,
one "thread" per subsystem, "X" complete events for spans, "i" instants
for point events, and "s"/"f" flow events binding each cross-node
send/recv pair into a causal edge.  `critical_path()` then decomposes
each committed height's proposal->commit window into
gossip/collect/host_pack/device/apply segments by a prioritized sweep
over the merged spans — a PARTITION of the window, so the segment sum
equals the measured wall time by construction.

Cost contract: identical to flightrec/trace — with no timeline
installed the hot paths pay one attribute/module-global read and an
``is None`` test.  Recording one event is a lock, an integer bump, and
a list store.

Clocks: timelines record ``time.perf_counter()``; flightrec records
``time.monotonic()``.  On the platforms this runs on both are
CLOCK_MONOTONIC, so `ingest_flightrec` merges them on one axis; all
simnet nodes share one process clock, which is what makes the
multi-node merge meaningful at all.
"""

from __future__ import annotations

import json
import time

from . import lockrank

DEFAULT_CAPACITY = 65536

# event phases (internal record shape, pre-Perfetto)
PH_SPAN = "span"
PH_INSTANT = "instant"
PH_SEND = "send"
PH_RECV = "recv"

# stage-name -> critical-path segment; anything unmapped (and all
# uncovered wall time) falls into the "gossip" residual
STAGE_SEGMENTS = {
    "device": "device", "device_wait": "device",
    "host_pack": "host_pack", "verify_dispatch": "host_pack",
    "apply": "apply", "store": "apply", "commit": "apply",
    "collect": "collect", "decode": "collect", "fetch": "collect",
    "propose": "collect", "prevote": "collect", "precommit": "collect",
    # device-hash verify mode (crypto/dispatch.py): host_pack's
    # successors — staging shrinks to splice+pack, hashing joins the
    # device dispatch.  Mapping INTO the existing segments keeps the
    # critical-path sweep's exact-sum property and every downstream
    # consumer (perf gate, PERF.md tables) comparable across modes.
    "host_splice": "host_pack", "device_hash": "device",
}
# highest-priority segment wins when spans overlap in the sweep
SEGMENT_PRIORITY = ("device", "host_pack", "apply", "collect")
SEGMENTS = SEGMENT_PRIORITY + ("gossip",)


def make_ctx(origin: str, height: int, round_: int, seq: int) -> tuple:
    return (origin, int(height), int(round_), int(seq))


def ctx_fields(ctx) -> dict:
    """Flatten a trace context into the origin/height/round keys the
    flight recorder and timeline dumps cross-reference by."""
    if not isinstance(ctx, tuple) or len(ctx) != 4:
        return {}
    return {"origin": ctx[0], "height": ctx[1], "round": ctx[2]}


class Timeline:
    """Bounded ring of per-node timeline events.

    Same ring discipline as FlightRecorder: `recorded` counts every
    event ever seen, the ring keeps the last `capacity`, `dropped` is
    the difference.  Thread safe — consensus state thread, gossip
    threads, and the pipeline's staging/device threads all record into
    one node's instance.
    """

    def __init__(self, node: str = "node",
                 capacity: int = DEFAULT_CAPACITY,
                 clock=time.perf_counter):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.node = node
        self.capacity = capacity
        self._clock = clock
        self._mtx = lockrank.RankedLock("tracetl.ring")
        self._ring: list = [None] * capacity
        self._recorded = 0
        self._ctx_seq = 0

    # -- recording ---------------------------------------------------------
    def _store(self, t, ph, subsystem, name, dur, ctx, fields) -> None:
        with self._mtx:
            seq = self._recorded
            self._ring[seq % self.capacity] = (
                seq, t, ph, subsystem, name, dur, ctx, fields)
            self._recorded = seq + 1

    def span(self, subsystem: str, stage: str, start: float,
             end: float, **fields) -> None:
        """A completed stage interval [start, end] on this node."""
        self._store(start, PH_SPAN, subsystem, stage,
                    end - start, None, fields or None)

    def instant(self, subsystem: str, name: str, t: float | None = None,
                **fields) -> None:
        """A point event (proposal receipt, commit, step change)."""
        self._store(t if t is not None else self._clock(),
                    PH_INSTANT, subsystem, name, None, None,
                    fields or None)

    def send(self, subsystem: str, name: str, ctx, **fields) -> None:
        self._store(self._clock(), PH_SEND, subsystem, name, None,
                    ctx, fields or None)

    def recv(self, subsystem: str, name: str, ctx, **fields) -> None:
        self._store(self._clock(), PH_RECV, subsystem, name, None,
                    ctx, fields or None)

    def ctx(self, height: int, round_: int) -> tuple:
        """Mint a trace context originating at this node."""
        with self._mtx:
            self._ctx_seq += 1
            seq = self._ctx_seq
        return make_ctx(self.node, height, round_, seq)

    # -- reading -----------------------------------------------------------
    def __len__(self) -> int:
        with self._mtx:
            return min(self._recorded, self.capacity)

    @property
    def recorded(self) -> int:
        with self._mtx:
            return self._recorded

    @property
    def dropped(self) -> int:
        with self._mtx:
            return self._recorded - min(self._recorded, self.capacity)

    def events(self) -> list[dict]:
        """Oldest-to-newest snapshot of the retained events."""
        with self._mtx:
            n = self._recorded
            kept = min(n, self.capacity)
            raw = [self._ring[(n - kept + i) % self.capacity]
                   for i in range(kept)]
        out = []
        for (seq, t, ph, sub, name, dur, ctx, fields) in raw:
            e = {"seq": seq, "t": t, "ph": ph, "sub": sub, "name": name}
            if dur is not None:
                e["dur"] = dur
            if ctx is not None:
                e["ctx"] = list(ctx)
            if fields:
                e.update(fields)
            out.append(e)
        return out

    def dump(self) -> dict:
        evs = self.events()
        return {
            "node": self.node,
            "recorded": self.recorded,
            "dropped": self.recorded - len(evs),
            "capacity": self.capacity,
            "events": evs,
        }

    def dump_text(self) -> str:
        d = self.dump()
        lines = [f"timeline {d['node']}: {d['recorded']} recorded, "
                 f"{d['dropped']} dropped (capacity {d['capacity']})"]
        for e in d["events"]:
            extra = " ".join(f"{k}={v}" for k, v in e.items()
                             if k not in ("seq", "t", "ph", "sub", "name"))
            lines.append(f"  #{e['seq']:<6} t={e['t']:.6f} "
                         f"{e['ph']:<7} {e['sub']}.{e['name']} {extra}")
        return "\n".join(lines)

    def clear(self) -> None:
        with self._mtx:
            self._ring = [None] * self.capacity
            self._recorded = 0

    # -- stitching ---------------------------------------------------------
    def ingest_intervals(self, intervals: list[dict]) -> None:
        """Copy StageTracer.intervals() records in as span events —
        the bridge for stages not directly timeline-instrumented."""
        for iv in intervals:
            fields = {k: v for k, v in iv.items()
                      if k not in ("subsystem", "stage", "start", "end")}
            self.span(iv["subsystem"], iv["stage"], iv["start"],
                      iv["end"], **fields)

    def ingest_flightrec(self, events: list[dict],
                         subsystem: str = "flightrec") -> None:
        """Copy FlightRecorder.events() in as instants so round
        lifecycle markers sit on the same axis as the spans."""
        for ev in events:
            fields = {k: v for k, v in ev.items()
                      if k not in ("seq", "t", "kind")}
            self.instant(subsystem, ev["kind"], t=ev["t"], **fields)


# -- span context manager ----------------------------------------------------

class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _TimedSpan:
    __slots__ = ("_tl", "_subsystem", "_stage", "_t0", "_fields")

    def __init__(self, tl: Timeline, subsystem: str, stage: str, fields):
        self._tl = tl
        self._subsystem = subsystem
        self._stage = stage
        self._fields = fields

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tl.span(self._subsystem, self._stage, self._t0,
                      time.perf_counter(), **(self._fields or {}))
        return False


# -- process-wide seam -------------------------------------------------------
# Layers below node wiring (crypto/dispatch, crypto/votestream) report
# through this, exactly like flightrec.record / trace.span.  Node-owned
# layers (consensus state, reactors) carry a per-object `timeline`
# attribute that overrides the seam, so N simnet nodes in one process
# stay attributable.
_timeline: Timeline | None = None


def set_timeline(tl: Timeline | None) -> None:
    global _timeline
    _timeline = tl


def timeline() -> Timeline | None:
    return _timeline


def active(owner=None) -> Timeline | None:
    """The timeline `owner` records to: its own attribute if assigned,
    else the process-wide seam, else None (record nothing)."""
    tl = getattr(owner, "timeline", None) if owner is not None else None
    return tl if tl is not None else _timeline


def span_for(owner, subsystem: str, stage: str, **fields):
    """Context manager emitting a timeline span; free when neither the
    owner nor the process seam has a timeline installed."""
    tl = active(owner)
    if tl is None:
        return _NULL_SPAN
    return _TimedSpan(tl, subsystem, stage, fields or None)


def instant(subsystem: str, name: str, **fields) -> None:
    """Record an instant into the process-wide timeline; free when
    none is set."""
    tl = _timeline
    if tl is None:
        return
    tl.instant(subsystem, name, **fields)


# -- Perfetto export ---------------------------------------------------------

def _flow_id(ctx) -> str:
    return "%s/%d/%d/%d" % tuple(ctx)


def perfetto_trace(timelines, counters=None) -> dict:
    """Merge per-node timelines into one Chrome/Perfetto trace_event
    JSON object: pid per node, tid per subsystem, X/i slices, and
    s->f flow events for every cross-node context edge.

    `timelines` is a {name: Timeline} dict or an iterable of Timeline
    (named by their .node).  `counters` is an optional iterable of
    (t, track, value) samples (DevprofRecorder.counter_samples());
    they render as "C" counter tracks under a dedicated "devprof"
    process so occupancy/queue-depth trajectories sit on the same time
    axis as the spans they explain."""
    if isinstance(timelines, dict):
        items = sorted(timelines.items())
    else:
        items = sorted((tl.node, tl) for tl in timelines)

    dumps = [(name, tl.dump()) for name, tl in items]
    counters = list(counters) if counters is not None else []
    t0 = min((e["t"] for _, d in dumps for e in d["events"]),
             default=None)
    if counters:
        ct0 = min(t for t, _, _ in counters)
        t0 = ct0 if t0 is None else min(t0, ct0)
    if t0 is None:
        t0 = 0.0

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 3)

    events = []
    tids: dict[tuple, int] = {}
    for pid, (name, d) in enumerate(dumps, start=1):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": name}})
        for e in d["events"]:
            key = (pid, e["sub"])
            tid = tids.get(key)
            if tid is None:
                tid = tids[key] = len(tids) + 1
                events.append({"ph": "M", "name": "thread_name",
                               "pid": pid, "tid": tid,
                               "args": {"name": e["sub"]}})
            args = {k: v for k, v in e.items()
                    if k not in ("seq", "t", "ph", "sub", "name",
                                 "dur", "ctx")}
            ctx = e.get("ctx")
            if ctx:
                args.update(ctx_fields(tuple(ctx)))
            base = {"name": e["name"], "cat": e["sub"], "pid": pid,
                    "tid": tid, "ts": us(e["t"]), "args": args}
            if e["ph"] == PH_SPAN:
                events.append({**base, "ph": "X",
                               "dur": round(e["dur"] * 1e6, 3)})
            elif e["ph"] == PH_INSTANT:
                events.append({**base, "ph": "i", "s": "t"})
            else:                       # send / recv: slice + flow event
                direction = e["ph"]
                events.append({**base, "ph": "X", "dur": 1.0,
                               "name": f"{direction}:{e['name']}"})
                if ctx:
                    flow = {"ph": "s" if direction == PH_SEND else "f",
                            "cat": "causal", "name": e["name"],
                            "id": _flow_id(tuple(ctx)), "pid": pid,
                            "tid": tid, "ts": base["ts"]}
                    if direction == PH_RECV:
                        flow["bp"] = "e"
                    events.append(flow)
    if counters:
        cpid = len(dumps) + 1
        events.append({"ph": "M", "name": "process_name", "pid": cpid,
                       "tid": 0, "args": {"name": "devprof"}})
        for t, track, value in counters:
            events.append({"ph": "C", "name": track, "pid": cpid,
                           "tid": 0, "ts": us(t),
                           "args": {"value": value}})
    return {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "metadata": {
            "nodes": [name for name, _ in dumps],
            "dropped": {name: d["dropped"] for name, d in dumps},
            "counters": len(counters),
        },
    }


def write_trace(path: str, trace: dict) -> None:
    with open(path, "w") as f:
        json.dump(trace, f)


# -- critical-path decomposition ---------------------------------------------

def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def _sweep(spans: list[tuple], lo: float, hi: float) -> dict:
    """Prioritized-sweep PARTITION of [lo, hi]: every instant belongs
    to the highest-priority segment with an active span there, or to
    the gossip residual — so the segment sum equals hi - lo exactly.
    `spans` is a list of (start, end, segment)."""
    rank = {seg: i for i, seg in enumerate(SEGMENT_PRIORITY)}
    clipped = [(max(s, lo), min(e, hi), seg) for s, e, seg in spans
               if min(e, hi) > max(s, lo)]
    bounds = sorted({lo, hi, *(s for s, _, _ in clipped),
                     *(e for _, e, _ in clipped)})
    out = {seg: 0.0 for seg in SEGMENTS}
    for a, b in zip(bounds, bounds[1:]):
        if b <= lo or a >= hi:
            continue
        active_segs = [seg for s, e, seg in clipped if s <= a and e >= b]
        best = min(active_segs, key=lambda s: rank[s], default=None)
        out[best if best is not None else "gossip"] += b - a
    return out


def critical_path(trace: dict) -> dict:
    """Decompose each committed height's proposal->commit window into
    gossip/collect/host_pack/device/apply segments from an exported
    Perfetto trace (the `perfetto_trace` shape).

    The window opens at the EARLIEST "proposal" instant for the height
    on any node and closes at the LATEST "commit" instant — i.e. the
    cluster-wide wall clock a client would observe.  Spans from every
    node compete in one sweep (device work anywhere counts as device
    time), which is the right reading for "is the device the
    bottleneck yet?".  Deterministic: a pure function of the trace."""
    proposals: dict[int, float] = {}
    commits: dict[int, float] = {}
    spans: list[tuple] = []
    for e in trace.get("traceEvents", []):
        # only "i" instants and "X" slices feed the sweep; any other
        # phase ("M" metadata, "s"/"f" flows, "C" counter tracks, or
        # phases a future exporter invents) passes through untouched,
        # as do malformed events missing ts/name
        if not isinstance(e, dict):
            continue
        ph = e.get("ph")
        if not isinstance(e.get("ts"), (int, float)) \
                or not isinstance(e.get("name"), str):
            continue
        if ph == "i":
            h = (e.get("args") or {}).get("height")
            if not isinstance(h, int):
                continue
            t = e["ts"] / 1e6
            if e["name"] == "proposal":
                if h not in proposals or t < proposals[h]:
                    proposals[h] = t
            elif e["name"] == "commit":
                if h not in commits or t > commits[h]:
                    commits[h] = t
        elif ph == "X":
            seg = STAGE_SEGMENTS.get(e["name"])
            if seg is not None:
                t = e["ts"] / 1e6
                spans.append((t, t + e.get("dur", 0.0) / 1e6, seg))

    per_height = []
    for h in sorted(commits):
        lo, hi = proposals.get(h), commits[h]
        if lo is None or hi <= lo:
            continue
        segs = _sweep(spans, lo, hi)
        wall = round(hi - lo, 6)
        rounded = {k: round(v, 6) for k, v in segs.items()}
        # the residual segment absorbs per-segment rounding error so
        # the partition sums EXACTLY to wall_seconds (the invariant
        # fleet reports assert); may dip a microsecond below zero
        rounded["gossip"] = round(
            wall - sum(v for k, v in rounded.items() if k != "gossip"),
            6)
        per_height.append({
            "height": h,
            "wall_seconds": wall,
            "segments": rounded,
        })

    by_seg = {seg: sorted(r["segments"][seg] for r in per_height)
              for seg in SEGMENTS}
    walls = [r["wall_seconds"] for r in per_height]
    total_wall = sum(walls)
    total_device = sum(by_seg["device"])
    summary = {
        "heights": len(per_height),
        "wall_seconds_total": round(total_wall, 6),
        "device_share": round(total_device / total_wall, 6)
        if total_wall else 0.0,
        "segments": {
            seg: {
                "total_seconds": round(sum(vals), 6),
                "p50": round(_percentile(vals, 0.50), 6),
                "p99": round(_percentile(vals, 0.99), 6),
            } for seg, vals in by_seg.items()},
    }
    return {"per_height": per_height, "summary": summary}
