"""Crash-safe telemetry spool: the persistence seam under the fleet
observability plane (cometbft_tpu/fleetobs/).

Every observability layer before this one — flightrec (ring), tracetl
(ring), devprof (accounts), latledger (histograms), Prometheus counters
— lives and dies inside one interpreter.  The e2e runner's REAL node
subprocesses get SIGKILLed mid-run by design (perturbations), and a
killed ring is an erased ring.  The spool closes that gap with the WAL
discipline consensus/wal.py already proved out: a background flusher
periodically snapshots every installed telemetry source into
length-framed, CRC-checked JSONL records appended to bounded, rotated
segment files under the node's home dir.  A SIGKILL loses at most one
flush interval of telemetry — never the file: replay tolerates a torn
tail (the crash-mid-write suffix) by stopping at the first incomplete
or corrupt frame of the NEWEST segment, exactly like WAL replay.

Frame format (consensus/wal.py idiom):

    crc32c(payload) u32 BE | len(payload) u32 BE | payload (JSON, utf-8)

Record kinds (closed registry, scripts/check_metrics.py rule 10):

    meta       once per segment: node, incarnation, pid, spool seq
    clock      per flush: wall/perf_counter/monotonic triple — the
               anchor that maps ring timestamps onto wall clock when a
               node has no p2p edges to offset-solve against
    flightrec  incremental flightrec events (cursor by seq)
    tracetl    incremental timeline events (cursor by seq)
    devprof    cumulative device-account snapshot (replay keeps latest)
    latledger  cumulative ledger dump incl. mergeable histogram
               snapshots (replay keeps latest)
    metrics    Prometheus text exposition (replay keeps latest)

Incremental vs cumulative: ring events are append-only facts, so the
writer keeps a seq cursor per ring and spools only what is new each
flush; account/histogram snapshots are already cumulative, so replay
takes the last complete one and rotation never loses history that the
latest snapshot still carries.  Rotation drops whole OLD segments
(oldest-first) once the directory exceeds its budget — the newest
segment, the only one a crash can tear, is never the one dropped.

Clock domains: ring timestamps are perf_counter/monotonic, which reset
per PROCESS.  Each writer mints an incarnation id (pid + start wall
clock); every record carries it, and fleetobs/clocksync.py solves for
one offset per (node, incarnation) domain, falling back to the spooled
clock anchors when a domain has no p2p edges.

Cost contract (flightrec discipline): with the spool off (default —
``COMETBFT_TPU_TELSPOOL=0``) nothing is constructed and the node pays
nothing.  With it on, the hot paths still pay nothing: flushing is a
background daemon thread touching only the rings' public snapshot
methods, at ``COMETBFT_TPU_TELSPOOL_INTERVAL_S`` cadence (default 2s).
``COMETBFT_TPU_TELSPOOL_SEGMENT_BYTES`` (default 1 MiB) bounds one
segment, ``COMETBFT_TPU_TELSPOOL_SEGMENTS`` (default 8) bounds the
directory.  The spool lock ranks at 485 — OUTSIDE every observability
ring (490-510) because a flush holds it across the rings' dump calls.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time

from . import lockrank
from .crc32c import crc32c

# the closed record-kind registry; scripts/check_metrics.py rule 10
# lints every literal kind written through SpoolWriter against it
RECORD_KINDS = (
    "meta",
    "clock",
    "flightrec",
    "tracetl",
    "devprof",
    "latledger",
    "metrics",
)

DEFAULT_INTERVAL_S = float(os.environ.get(
    "COMETBFT_TPU_TELSPOOL_INTERVAL_S", "2.0"))
DEFAULT_SEGMENT_BYTES = int(os.environ.get(
    "COMETBFT_TPU_TELSPOOL_SEGMENT_BYTES", str(1 << 20)))
DEFAULT_SEGMENTS = int(os.environ.get(
    "COMETBFT_TPU_TELSPOOL_SEGMENTS", "8"))

SEGMENT_PREFIX = "spool-"
SEGMENT_SUFFIX = ".tel"

_FRAME_HEADER = struct.Struct(">II")     # crc32c(payload), len(payload)
_MAX_RECORD_BYTES = 64 << 20             # sanity bound on one frame


def enabled() -> bool:
    """The master knob: spooling is opt-in (the e2e runner opts its
    node subprocesses in via the environment)."""
    return os.environ.get("COMETBFT_TPU_TELSPOOL", "0") not in ("0", "")


def incarnation_id(pid: int | None = None,
                   start_wall: float | None = None) -> str:
    """One clock domain = one process incarnation: perf_counter and
    monotonic reset across exec, so offsets are solved per-incarnation."""
    pid = os.getpid() if pid is None else pid
    start_wall = time.time() if start_wall is None else start_wall
    return "%d-%d" % (pid, int(start_wall * 1000))


# -- framing -----------------------------------------------------------------

def encode_frame(payload: bytes) -> bytes:
    return _FRAME_HEADER.pack(crc32c(payload), len(payload)) + payload


def iter_frames(data: bytes):
    """Yield complete, CRC-valid payloads from a segment's bytes,
    stopping silently at the first torn or corrupt frame — the WAL
    torn-tail contract.  Never raises on truncation."""
    off = 0
    n = len(data)
    while off + _FRAME_HEADER.size <= n:
        crc, length = _FRAME_HEADER.unpack_from(data, off)
        if length > _MAX_RECORD_BYTES:
            return
        end = off + _FRAME_HEADER.size + length
        if end > n:
            return                      # torn tail: header without body
        payload = data[off + _FRAME_HEADER.size:end]
        if crc32c(payload) != crc:
            return                      # corrupt (or torn inside header)
        yield payload
        off = end


# -- reading -----------------------------------------------------------------

def segment_paths(spool_dir: str) -> list[str]:
    """Spool segments oldest-to-newest (lexicographic == numeric for
    the zero-padded names)."""
    try:
        names = os.listdir(spool_dir)
    except OSError:
        return []
    return [os.path.join(spool_dir, n) for n in sorted(names)
            if n.startswith(SEGMENT_PREFIX) and n.endswith(SEGMENT_SUFFIX)]


def read_segment(path: str) -> list[dict]:
    """Every complete record of one segment; [] when unreadable.
    Records that frame intact but fail to parse as JSON objects are
    skipped (same contract as torn frames: recover what is whole)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return []
    out = []
    for payload in iter_frames(data):
        try:
            rec = json.loads(payload)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def read_spool(spool_dir: str) -> list[dict]:
    """All recovered records across a node's spool directory, segment
    order (oldest first).  Torn tails and missing dirs are normal
    operation, not errors."""
    out = []
    for path in segment_paths(spool_dir):
        out.extend(read_segment(path))
    return out


# -- writing -----------------------------------------------------------------

class SpoolWriter:
    """Periodic snapshotter of a node's telemetry sources into rotated,
    CRC-framed spool segments.

    Sources are optional attributes (assign after construction, the
    same per-object override pattern as consensus_state.recorder):
    ``flight_recorder``, ``timeline``, ``devprof``, ``latledger``,
    ``metrics_registry``.  Absent sources are simply skipped, so the
    writer needs no knowledge of which layers a node enabled.
    """

    def __init__(self, spool_dir: str, node: str = "node",
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 max_segments: int = DEFAULT_SEGMENTS,
                 interval_s: float = DEFAULT_INTERVAL_S):
        if segment_bytes <= 0 or max_segments <= 0:
            raise ValueError("segment_bytes and max_segments must be "
                             "positive")
        self.spool_dir = spool_dir
        self.node = node
        self.segment_bytes = segment_bytes
        self.max_segments = max_segments
        self.interval_s = interval_s
        self.incarnation = incarnation_id()
        # telemetry sources (assigned by the node after construction)
        self.flight_recorder = None
        self.timeline = None
        self.devprof = None
        self.latledger = None
        self.metrics_registry = None

        self._mtx = lockrank.RankedLock("telspool.spool")
        self._fh = None
        self._seg_written = 0
        self._flightrec_cursor = 0
        self._tracetl_cursor = 0
        self._flushes = 0
        self._records_written = 0
        self._closed = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        os.makedirs(spool_dir, exist_ok=True)
        # continue numbering past any previous incarnation's segments —
        # a restart must never overwrite the pre-crash evidence
        existing = segment_paths(spool_dir)
        self._seg_seq = 0
        if existing:
            last = os.path.basename(existing[-1])
            try:
                self._seg_seq = int(
                    last[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)])
            except ValueError:
                self._seg_seq = len(existing)

    # -- segment lifecycle (under self._mtx) --------------------------------

    def _open_segment(self) -> None:
        self._seg_seq += 1
        path = os.path.join(
            self.spool_dir,
            "%s%06d%s" % (SEGMENT_PREFIX, self._seg_seq, SEGMENT_SUFFIX))
        self._fh = open(path, "ab")
        self._seg_written = 0
        self._write_record("meta", node=self.node, pid=os.getpid(),
                           segment=self._seg_seq)
        self._prune()

    def _prune(self) -> None:
        paths = segment_paths(self.spool_dir)
        # never prune the newest (open) segment; drop oldest-first
        while len(paths) > self.max_segments:
            victim = paths.pop(0)
            try:
                os.unlink(victim)
            except OSError:
                break

    def _write_record(self, kind: str, **fields) -> None:
        if kind not in RECORD_KINDS:
            raise ValueError(f"unknown spool record kind {kind!r}")
        rec = {"kind": kind, "node": self.node,
               "incarnation": self.incarnation, "t_wall": time.time()}
        rec.update(fields)
        frame = encode_frame(
            json.dumps(rec, separators=(",", ":")).encode())
        self._fh.write(frame)
        self._seg_written += len(frame)
        self._records_written += 1

    # -- flushing -----------------------------------------------------------

    def flush(self) -> int:
        """Snapshot every installed source into the spool; returns the
        number of records written.  Durable on return (flush + fsync),
        so a SIGKILL after a flush loses nothing from it."""
        with self._mtx:
            if self._closed:
                return 0
            if self._fh is None:
                self._open_segment()
            wrote0 = self._records_written
            # the clock anchor first: every flush re-pins the ring
            # clocks to wall time, bounding anchor-fallback error to
            # one flush interval of drift
            self._write_record("clock", wall=time.time(),
                               perf=time.perf_counter(),
                               mono=time.monotonic())
            fr = self.flight_recorder
            if fr is not None:
                evs = [e for e in fr.events()
                       if e["seq"] >= self._flightrec_cursor]
                if evs:
                    self._flightrec_cursor = evs[-1]["seq"] + 1
                    self._write_record(
                        "flightrec", recorded=fr.recorded, events=evs)
            tl = self.timeline
            if tl is not None:
                evs = [e for e in tl.events()
                       if e["seq"] >= self._tracetl_cursor]
                if evs:
                    self._tracetl_cursor = evs[-1]["seq"] + 1
                    self._write_record(
                        "tracetl", timeline_node=tl.node,
                        recorded=tl.recorded, events=evs)
            dp = self.devprof
            if dp is not None:
                self._write_record(
                    "devprof", snapshot=dp.snapshot(),
                    counters=[list(s) for s in dp.counter_samples()])
            ll = self.latledger
            if ll is not None:
                self._write_record(
                    "latledger", dump=ll.dump(),
                    counters=[list(s) for s in ll.counter_samples()])
            reg = self.metrics_registry
            if reg is not None:
                self._write_record("metrics", exposition=reg.expose())
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._flushes += 1
            wrote = self._records_written - wrote0
            if self._seg_written >= self.segment_bytes:
                self._fh.close()
                self._fh = None         # next flush opens a fresh one
            return wrote

    def stats(self) -> dict:
        with self._mtx:
            return {"spool_dir": self.spool_dir,
                    "incarnation": self.incarnation,
                    "flushes": self._flushes,
                    "records_written": self._records_written,
                    "segment_seq": self._seg_seq,
                    "interval_s": self.interval_s}

    # -- background flusher -------------------------------------------------

    def start(self) -> None:
        """Launch the background flusher (daemon — it must never hold
        interpreter shutdown hostage; `stop` does the final durable
        flush on the graceful path)."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"telspool-{self.node}", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.flush()
            except OSError:
                # a full/areadonly disk must not kill the flusher;
                # the next interval retries
                continue

    def stop(self) -> None:
        """Final flush + thread join — the graceful-exit half of the
        durability contract (atexit / SIGTERM via Node.on_stop).
        Idempotent: the atexit hook and Node.on_stop may both fire."""
        with self._mtx:
            if self._closed:
                return
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        try:
            self.flush()
        except OSError:
            pass
        with self._mtx:
            self._closed = True
            if self._fh is not None:
                self._fh.close()
                self._fh = None
