"""Amino-compatible JSON with a type registry
(reference libs/json/: encoder.go, decoder.go, structs.go registry).

Registered types marshal as {"type": "<amino name>", "value": <payload>}
— the envelope CometBFT uses for keys in genesis docs, priv_validator
files, and RPC results.  The registry covers the key types (public and
private, all supported curves) and the evidence types; `marshal` falls
through to plain JSON for unregistered values the way the reference
does for types without a registered name.
"""

from __future__ import annotations

import base64
import json
from typing import Callable

_BY_NAME: dict[str, Callable[[object], object]] = {}
_BY_TYPE: dict[type, tuple[str, Callable[[object], object]]] = {}


def register(cls: type, name: str,
             encode: Callable[[object], object],
             decode: Callable[[object], object]) -> None:
    """libs/json RegisterType."""
    if name in _BY_NAME:
        raise ValueError(f"amino name {name!r} already registered")
    _BY_NAME[name] = decode
    _BY_TYPE[cls] = (name, encode)


def name_of(obj) -> str | None:
    ent = _BY_TYPE.get(type(obj))
    return ent[0] if ent else None


def to_obj(value):
    """Value -> JSON-able object, wrapping registered types."""
    ent = _BY_TYPE.get(type(value))
    if ent is not None:
        name, encode = ent
        return {"type": name, "value": encode(value)}
    if isinstance(value, (list, tuple)):
        return [to_obj(v) for v in value]
    if isinstance(value, dict):
        return {k: to_obj(v) for k, v in value.items()}
    if isinstance(value, bytes):
        return base64.b64encode(value).decode()
    return value


def from_obj(obj):
    """JSON object -> value, unwrapping registered type envelopes."""
    if isinstance(obj, dict):
        if set(obj) == {"type", "value"} and obj["type"] in _BY_NAME:
            return _BY_NAME[obj["type"]](obj["value"])
        return {k: from_obj(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [from_obj(v) for v in obj]
    return obj


def marshal(value, indent=None) -> str:
    return json.dumps(to_obj(value), indent=indent)


def unmarshal(text: str):
    return from_obj(json.loads(text))


# ---------------------------------------------------------------------------
# standard registrations (reference libs/json/structs.go + crypto pkgs)
# ---------------------------------------------------------------------------

def _key_codec(cls):
    return (lambda k: base64.b64encode(k.bytes()).decode(),
            lambda v: cls(base64.b64decode(v)))


def _register_defaults() -> None:
    from ..crypto import ed25519, secp256k1, sr25519

    for mod, pub_name, priv_name in (
            (ed25519, "tendermint/PubKeyEd25519",
             "tendermint/PrivKeyEd25519"),
            (secp256k1, "tendermint/PubKeySecp256k1",
             "tendermint/PrivKeySecp256k1"),
            (sr25519, "tendermint/PubKeySr25519",
             "tendermint/PrivKeySr25519")):
        enc, dec = _key_codec(mod.PubKey)
        register(mod.PubKey, pub_name, enc, dec)
        enc, dec = _key_codec(mod.PrivKey)
        register(mod.PrivKey, priv_name, enc, dec)

    from ..types.evidence import (DuplicateVoteEvidence,
                                  LightClientAttackEvidence,
                                  evidence_from_proto_wrapped,
                                  evidence_to_proto_wrapped)

    def _ev_codec(cls, name):
        register(
            cls, name,
            lambda e: base64.b64encode(
                evidence_to_proto_wrapped(e)).decode(),
            lambda v: evidence_from_proto_wrapped(base64.b64decode(v)))

    _ev_codec(DuplicateVoteEvidence, "tendermint/DuplicateVoteEvidence")
    _ev_codec(LightClientAttackEvidence,
              "tendermint/LightClientAttackEvidence")


_register_defaults()
