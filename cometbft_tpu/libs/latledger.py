"""Per-request verify-latency ledger: submit→resolve decomposition
per consumer.

Every layer above measures something adjacent to — but not — the
question ROADMAP item 4 is judged by: `DeviceMetrics.
flush_latency_seconds` times whole flushes, devprof times chip
seconds, tracetl times per-height critical paths.  Once requests from
different consumers merge into one verify window, the individual
request is invisible: nobody can say "votes waited 3 ms behind a
blocksync window" because nothing stamps the vote.

The ledger stamps every signature-verify request at submit and
decomposes its submit→resolve wall time into an EXACT partition
(devprof discipline — segments sum to the wall by construction):

| segment | meaning |
|---|---|
| ``queue_wait``   | backpressure before staging + the staged-but-undispatched wait |
| ``coalesce_wait``| the whole life of a deduped duplicate (votestream in-flight dedupe, lightserve shared futures) |
| ``host_pack``    | staging: parse + columnar pack/splice |
| ``device``       | device dispatch compute (device-path windows) |
| ``host_verify``  | host/drain/error-path compute |
| ``cache``        | resolved from the signature-verdict cache |
| ``publish``      | compute done → caller's future resolved (in-order publication, callbacks) |

Rows are keyed by the existing ``sigcache.consumer(...)`` label, so
votes (consensus), blocksync, light, lightserve, and evidence each get
their own mergeable log-bucketed histogram.  Ring discipline matches
flightrec: bounded, thread-safe, ``recorded``/``dropped`` totals, and
with no recorder installed the hot paths pay one module-global read +
an ``is None`` test.

``SLOTracker`` adds declared per-consumer p99 targets with
multi-window burn-rate accounting (short window catches a spike, long
window proves it sustained); a trip records an ``EV_SLO_BURN``
flightrec event and a SUSTAINED burn auto-dumps the flight recorder
to the log.  Surfaces: the ``latency`` RPC route,
``/debug/pprof/latency``, per-consumer p99 counter tracks merged into
the Perfetto export (`simnet/tracing.py`), and the
``bench_verify_contention`` A/B behind the ``vote_verify_p99_ms`` /
``bulk_verify_p99_ms`` bench extras.

Knobs: ``COMETBFT_TPU_LATLEDGER=0`` forces the ledger off even with a
recorder installed; ``COMETBFT_TPU_LATLEDGER_CAPACITY`` sizes the row
ring (default 4096); ``COMETBFT_TPU_LATLEDGER_SLO_BURN`` sets the
short-window burn-rate trip threshold (default 14.0).
"""

from __future__ import annotations

import bisect
import os
import time

from . import lockrank
from . import metrics as libmetrics

DEFAULT_CAPACITY = int(os.environ.get(
    "COMETBFT_TPU_LATLEDGER_CAPACITY", "4096"))
BURN_THRESHOLD = float(os.environ.get(
    "COMETBFT_TPU_LATLEDGER_SLO_BURN", "14.0"))

# resolution paths: the pipeline's closed set plus "coalesced" — a
# duplicate attributed to the in-flight original it attached to
PATHS = ("device", "host", "cache", "drain", "error", "coalesced")

# the closed segment vocabulary (module docstring table)
SEGMENTS = ("queue_wait", "coalesce_wait", "host_pack", "device",
            "host_verify", "cache", "publish")

# which segment the compute interval books under, per resolution path
_COMPUTE_SEG = {"device": "device", "host": "host_verify",
                "drain": "host_verify", "error": "host_verify",
                "cache": "cache"}

# wall-seconds bucket bounds shared with the metrics registry's
# closed scheme table — one layout, mergeable across processes
BUCKETS = libmetrics.BUCKET_SCHEMES["verify_latency"]

# declared per-consumer p99 targets (seconds).  Keys come from the
# closed consumer registry (crypto/sigcache.CONSUMERS — linted both
# ways by scripts/check_metrics.py rule 8).  Votes are the
# latency-critical tenant; bulk feeds tolerate an order more.
DEFAULT_SLO_TARGETS = {
    "consensus": 0.050,
    "blocksync": 0.500,
    "light": 0.250,
    "lightserve": 0.250,
    "evidence": 0.250,
}

# consumers with no declared target (the "crypto"/"bench" default
# class) schedule against this bound — the QoS scheduler
# (crypto/sched.py) uses it as the starvation guard for lanes the SLO
# table does not name, so even the lowest class gets dispatched within
# a bounded wait under a sustained higher-priority flood
DEFAULT_TARGET_S = 1.0


def target_for(consumer: str) -> float:
    """Declared p99 target in seconds for a consumer label; labels
    outside DEFAULT_SLO_TARGETS get the DEFAULT_TARGET_S bound."""
    return DEFAULT_SLO_TARGETS.get(consumer, DEFAULT_TARGET_S)

_ENV_ON = os.environ.get("COMETBFT_TPU_LATLEDGER", "1") != "0"


class LatHistogram:
    """Fixed-boundary log-bucket histogram of wall seconds.

    Merge is element-wise addition over identical boundaries, so it is
    associative and commutative by construction — per-consumer
    histograms from different rings (or processes) fold into one.
    """

    __slots__ = ("bounds", "counts", "count", "sum")

    def __init__(self, bounds=BUCKETS):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.sum += v

    def merge(self, other: "LatHistogram") -> "LatHistogram":
        if self.bounds != other.bounds:
            raise ValueError("cannot merge histograms with different "
                             "bucket layouts")
        out = LatHistogram(self.bounds)
        out.counts = [a + b for a, b in zip(self.counts, other.counts)]
        out.count = self.count + other.count
        out.sum = self.sum + other.sum
        return out

    def quantile(self, q: float) -> float:
        """Upper-bound estimate of the q-quantile (the bucket's upper
        edge; the overflow bucket reports the top boundary)."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank and c:
                return self.bounds[min(i, len(self.bounds) - 1)]
        return self.bounds[-1]

    def snapshot(self) -> dict:
        return {"bounds": list(self.bounds), "counts": list(self.counts),
                "count": self.count, "sum": self.sum}


class Request:
    """One in-flight verify request's stamps.  Created by submit(),
    stamped by the pipeline seams, committed exactly once by
    resolve()/resolve_coalesced().  The recorder reference is captured
    at submit so a recorder swap mid-flight cannot split a row."""

    __slots__ = ("rec", "consumer", "n", "t0", "stamps", "done")

    def __init__(self, rec, consumer: str, n: int, t0: float):
        self.rec = rec
        self.consumer = consumer
        self.n = n
        self.t0 = t0
        self.stamps: dict = {}
        self.done = False

    def stamp(self, name: str) -> None:
        self.stamps[name] = self.rec._clock()

    def _partition(self, t_res: float, path: str) -> dict:
        """Fold the stamps into segment seconds.  Each cut clamps into
        [previous cut, t_res], so missing or out-of-order stamps can
        only shrink a segment, never break the partition; the row's
        wall is DEFINED as the sum of its segments (telescoping to
        t_res - t0), which is what makes sum(segs) == wall exact."""
        segs: dict = {}
        upto = self.t0

        def cut(seg: str, t: float) -> None:
            nonlocal upto
            t = min(max(t, upto), t_res)
            if t > upto:
                segs[seg] = segs.get(seg, 0.0) + (t - upto)
                upto = t

        ss = self.stamps.get("stage_start")
        if ss is not None:
            cut("queue_wait", ss)
        se = self.stamps.get("stage_end")
        if se is not None:
            cut("host_pack", se)
        d = self.stamps.get("dispatch")
        if d is not None:
            # staged but not yet dispatched: the head-of-line wait
            # behind other windows is backpressure, same as pre-staging
            cut("queue_wait", d)
        ce = self.stamps.get("compute_end")
        comp = _COMPUTE_SEG.get(path, "host_verify")
        if ce is not None:
            cut(comp, ce)
            cut("publish", t_res)
        else:
            # no compute stamp (cache-at-submit, stopped-path host
            # loop): the remainder IS the compute
            cut(comp, t_res)
        return segs

    def resolve(self, path: str) -> None:
        """Commit this request's row; idempotent (first resolution
        wins — the drain path and a racing device resolve cannot
        double-count)."""
        rec = self.rec
        t_res = rec._clock()
        rec._commit(self, path, self._partition(t_res, path))

    def resolve_coalesced(self) -> None:
        """Commit a duplicate's row: its whole life was spent waiting
        on the original's shared future."""
        rec = self.rec
        t_res = rec._clock()
        wall = max(0.0, t_res - self.t0)
        segs = {"coalesce_wait": wall} if wall > 0.0 else {}
        rec._commit(self, "coalesced", segs)


class _ConsumerStats:
    __slots__ = ("hist", "seg_seconds", "requests", "sigs", "coalesced")

    def __init__(self):
        self.hist = LatHistogram()
        self.seg_seconds = {}
        self.requests = 0
        self.sigs = 0
        self.coalesced = 0


class SLOTracker:
    """Per-consumer p99 targets with multi-window burn-rate accounting.

    An observation is "bad" when its wall exceeds the consumer's
    target; the error budget of a p99 target is 1%.  Burn rate =
    bad-fraction / budget over a window; the tracker trips when the
    SHORT window burns past ``threshold`` while the LONG window burns
    past 1.0 (a spike that is also eating real budget), and calls
    ``on_burn(consumer, info, sustained)`` — sustained=True after
    ``sustain`` consecutive tripping observations, the auto-dump
    signal.  Windows are 1-second buckets in bounded deques; not
    thread-safe on its own (the recorder serializes under its ring
    lock)."""

    ERROR_BUDGET = 0.01

    def __init__(self, targets=None, *, short_s: float = 60.0,
                 long_s: float = 600.0,
                 threshold: float | None = None, sustain: int = 3,
                 clock=time.monotonic, on_burn=None):
        self.targets = dict(DEFAULT_SLO_TARGETS if targets is None
                            else targets)
        self.short_s = float(short_s)
        self.long_s = float(long_s)
        self.threshold = BURN_THRESHOLD if threshold is None \
            else float(threshold)
        self.sustain = max(1, int(sustain))
        self._clock = clock
        self.on_burn = on_burn
        # consumer -> list of [bucket_second, good, bad] (long window)
        self._buckets: dict[str, list] = {}
        self._trips: dict[str, int] = {}
        self.burn_events = 0

    def _rates(self, rows, now: float) -> tuple[float, float]:
        sg = sb = lg = lb = 0
        for sec, good, bad in rows:
            age = now - sec
            if age <= self.long_s:
                lg += good
                lb += bad
                if age <= self.short_s:
                    sg += good
                    sb += bad

        def burn(good: int, bad: int) -> float:
            total = good + bad
            if not total:
                return 0.0
            return (bad / total) / self.ERROR_BUDGET

        return burn(sg, sb), burn(lg, lb)

    def observe(self, consumer: str, wall: float) -> None:
        target = self.targets.get(consumer)
        if target is None:
            return
        now = self._clock()
        rows = self._buckets.setdefault(consumer, [])
        sec = int(now)
        if rows and rows[-1][0] == sec:
            row = rows[-1]
        else:
            row = [sec, 0, 0]
            rows.append(row)
            while rows and now - rows[0][0] > self.long_s:
                rows.pop(0)
        if wall > target:
            row[2] += 1
        else:
            row[1] += 1
        short, long_ = self._rates(rows, now)
        if short >= self.threshold and long_ >= 1.0:
            self._trips[consumer] = self._trips.get(consumer, 0) + 1
            self.burn_events += 1
            if self.on_burn is not None:
                self.on_burn(consumer,
                             {"target_ms": target * 1000.0,
                              "burn_short": round(short, 2),
                              "burn_long": round(long_, 2)},
                             self._trips[consumer] >= self.sustain)
        else:
            self._trips[consumer] = 0

    def snapshot(self) -> dict:
        now = self._clock()
        out = {}
        for consumer, target in sorted(self.targets.items()):
            short, long_ = self._rates(self._buckets.get(consumer, ()),
                                       now)
            out[consumer] = {"target_ms": target * 1000.0,
                             "burn_short": round(short, 2),
                             "burn_long": round(long_, 2),
                             "tripping": self._trips.get(consumer,
                                                         0) > 0}
        return {"consumers": out, "burn_events": self.burn_events,
                "threshold": self.threshold}


class LatLedgerRecorder:
    """Bounded ring of per-request rows + per-consumer aggregates.

    Thread-safe (one ranked lock, leaf-most like the other
    observability rings); every aggregate is recomputable from rows
    alone modulo ring overflow, so ``recorded``/``dropped`` keep the
    overflow honest.  ``counter_samples()`` exposes per-consumer p99
    trajectories in the (t, track, value) shape tracetl's Perfetto
    export merges as counter tracks — level-deduped like devprof's."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 clock=time.monotonic, slo: SLOTracker | None = None):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._clock = clock
        self._mtx = lockrank.RankedLock("latledger.ring")
        self._ring: list = [None] * capacity
        self._recorded = 0
        self._stats: dict[str, _ConsumerStats] = {}
        self.slo = SLOTracker(clock=clock, on_burn=self._on_burn) \
            if slo is None else slo
        if slo is not None and slo.on_burn is None:
            slo.on_burn = self._on_burn
        self._samples: list = []
        self._samples_dropped = 0
        self._levels: dict[str, float] = {}

    # -- recording ---------------------------------------------------------
    def submit(self, n: int = 1, consumer: str | None = None) -> Request:
        if consumer is None:
            from ..crypto import sigcache

            consumer = sigcache.current_consumer()
        return Request(self, consumer, max(1, int(n)), self._clock())

    def _on_burn(self, consumer: str, info: dict,
                 sustained: bool) -> None:
        from . import flightrec

        flightrec.record(flightrec.EV_SLO_BURN, consumer=consumer,
                         sustained=sustained, **info)
        if sustained:
            rec = flightrec.recorder()
            if rec is not None:
                rec.dump_to_log(
                    f"sustained SLO burn: {consumer} "
                    f"(burn_short={info['burn_short']}, "
                    f"target={info['target_ms']}ms)")

    def _commit(self, req: Request, path: str, segs: dict) -> None:
        wall = sum(segs.values())
        with self._mtx:
            if req.done:
                return
            req.done = True
            seq = self._recorded
            self._ring[seq % self.capacity] = (
                seq, req.t0, req.consumer, path, req.n, wall, segs)
            self._recorded = seq + 1
            st = self._stats.get(req.consumer)
            if st is None:
                st = self._stats[req.consumer] = _ConsumerStats()
            st.hist.observe(wall)
            st.requests += 1
            st.sigs += req.n
            if path == "coalesced":
                st.coalesced += 1
            for k, v in segs.items():
                st.seg_seconds[k] = st.seg_seconds.get(k, 0.0) + v
            p99 = st.hist.quantile(0.99) * 1000.0
            track = f"verify_p99_ms/{req.consumer}"
            if self._levels.get(track) != p99:
                self._levels[track] = p99
                if len(self._samples) >= self.capacity:
                    self._samples.pop(0)
                    self._samples_dropped += 1
                self._samples.append((self._clock(), track, p99))
            self.slo.observe(req.consumer, wall)

    # -- reading -----------------------------------------------------------
    def __len__(self) -> int:
        with self._mtx:
            return min(self._recorded, self.capacity)

    @property
    def recorded(self) -> int:
        with self._mtx:
            return self._recorded

    def rows(self) -> list[dict]:
        """Oldest-to-newest snapshot of the retained rows."""
        with self._mtx:
            n = self._recorded
            kept = min(n, self.capacity)
            raw = [self._ring[(n - kept + i) % self.capacity]
                   for i in range(kept)]
        return [{"seq": seq, "t": t, "consumer": c, "path": p, "n": n_,
                 "wall": wall, "segs": dict(segs)}
                for (seq, t, c, p, n_, wall, segs) in raw]

    def counter_samples(self) -> list[tuple]:
        """(t, track, value) per-consumer p99 trajectory, oldest
        first — the counters= input of tracetl.perfetto_trace."""
        with self._mtx:
            return list(self._samples)

    def consumers(self) -> dict:
        """Per-consumer aggregate snapshot (the dump's core)."""
        with self._mtx:
            out = {}
            for label, st in sorted(self._stats.items()):
                out[label] = {
                    "requests": st.requests,
                    "sigs": st.sigs,
                    "coalesced": st.coalesced,
                    "p50_ms": round(st.hist.quantile(0.50) * 1000, 3),
                    "p99_ms": round(st.hist.quantile(0.99) * 1000, 3),
                    "mean_ms": round(
                        st.hist.sum / st.hist.count * 1000, 3)
                    if st.hist.count else 0.0,
                    "seg_seconds": {k: round(v, 6) for k, v in
                                    sorted(st.seg_seconds.items())},
                    "hist": st.hist.snapshot(),
                }
            return out

    def dump(self) -> dict:
        rows = self.rows()
        return {
            "recorded": self.recorded,
            "dropped": self.recorded - len(rows),
            "capacity": self.capacity,
            "consumers": self.consumers(),
            "slo": self.slo.snapshot(),
            "rows": rows,
        }

    def dump_text(self) -> str:
        d = self.dump()
        lines = [f"latency ledger: {d['recorded']} rows recorded, "
                 f"{d['dropped']} dropped (capacity {d['capacity']})"]
        for label, c in d["consumers"].items():
            total = sum(c["seg_seconds"].values()) or 1.0
            shares = " ".join(
                f"{k}={v / total:.0%}" for k, v in
                c["seg_seconds"].items())
            lines.append(
                f"  {label:<12} n={c['requests']:<7} "
                f"sigs={c['sigs']:<8} p50={c['p50_ms']:.3f}ms "
                f"p99={c['p99_ms']:.3f}ms coalesced={c['coalesced']}")
            lines.append(f"    {shares}")
        slo = d["slo"]
        for label, s in slo["consumers"].items():
            lines.append(
                f"  slo {label:<8} target={s['target_ms']:.0f}ms "
                f"burn_short={s['burn_short']} "
                f"burn_long={s['burn_long']}"
                f"{'  TRIPPING' if s['tripping'] else ''}")
        return "\n".join(lines)

    def clear(self) -> None:
        with self._mtx:
            self._ring = [None] * self.capacity
            self._recorded = 0
            self._stats = {}
            self._samples = []
            self._samples_dropped = 0
            self._levels = {}


# -- process-wide seam -------------------------------------------------------
# same discipline as flightrec/devprof: layers below node wiring stamp
# through this; with nothing installed a submit is one global read.
_recorder: LatLedgerRecorder | None = None


def set_recorder(r: LatLedgerRecorder | None) -> None:
    global _recorder
    _recorder = r


def recorder() -> LatLedgerRecorder | None:
    return _recorder


def submit(n: int = 1, consumer: str | None = None) -> Request | None:
    """Stamp one request at submit time; None when the ledger is off
    (no recorder, or COMETBFT_TPU_LATLEDGER=0) — every wiring seam
    guards on that None, so the disabled cost is this call."""
    r = _recorder
    if r is None or not _ENV_ON:
        return None
    return r.submit(n, consumer)
