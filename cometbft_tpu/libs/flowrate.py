"""Flow-rate monitoring and limiting (reference internal/flowrate/).

Token-bucket style: a Monitor tracks transfer rate over a sliding
window; Limit() tells the caller how many bytes it may move now to stay
under a target rate, used by MConnection's send/recv routines.
"""

from __future__ import annotations

import time

from . import lockrank


class Monitor:
    """flowrate.Monitor: EMA transfer-rate sampling."""

    def __init__(self, sample_period: float = 0.1,
                 window: float = 1.0):
        self._mtx = lockrank.RankedLock("flowrate")
        self._sample_period = sample_period
        self._window = window
        self._start = time.monotonic()
        self._bytes = 0            # total transferred
        self._rate_ema = 0.0       # bytes/sec
        self._sample_bytes = 0
        self._sample_start = self._start
        self._active = True
        # token bucket for limit(): refilled at the caller's rate,
        # burst-capped to one window
        self._tokens = 0.0
        self._bucket_rate = 0
        self._last_refill = self._start

    def update(self, n: int) -> int:
        """Record n transferred bytes; returns n."""
        with self._mtx:
            now = time.monotonic()
            self._bytes += n
            self._sample_bytes += n
            self._tokens = max(self._tokens - n, 0.0)
            elapsed = now - self._sample_start
            if elapsed >= self._sample_period:
                rate = self._sample_bytes / elapsed
                w = min(elapsed / self._window, 1.0)
                self._rate_ema = self._rate_ema * (1 - w) + rate * w
                self._sample_bytes = 0
                self._sample_start = now
        return n

    def status(self) -> dict:
        with self._mtx:
            now = time.monotonic()
            duration = now - self._start
            avg = self._bytes / duration if duration > 0 else 0.0
            return {
                "bytes": self._bytes,
                "duration": duration,
                "avg_rate": avg,
                "cur_rate": self._rate_ema,
            }

    def limit(self, want: int, rate: int, block: bool = False) -> int:
        """How many of `want` bytes may be transferred now to keep the
        rate <= rate bytes/sec (0 = unlimited). Token bucket with burst
        capped to one window — idle time does NOT accrue unbounded
        credit. Callers report actual transfer via update(), which
        drains the bucket. If block, sleep until at least one byte is
        allowed (flowrate Limit)."""
        if rate <= 0:
            return want
        while True:
            with self._mtx:
                now = time.monotonic()
                if self._bucket_rate != rate:
                    # rate changed (or first use): start with one window
                    self._bucket_rate = rate
                    self._tokens = float(rate) * self._window
                    self._last_refill = now
                self._tokens = min(
                    self._tokens + rate * (now - self._last_refill),
                    float(rate) * self._window)
                self._last_refill = now
                allowed = int(min(want, self._tokens))
            if allowed > 0:
                return allowed
            if not block:
                return 0
            time.sleep(max(1.0 / rate, 0.001))
