"""Profiling/introspection HTTP server — the Python analog of the
reference's pprof listener (node/node.go:889-902, config RPC
pprof_laddr).

Endpoints (GET):
  /debug/pprof/           - index
  /debug/pprof/goroutine  - live thread stack dump (goroutine analog)
  /debug/pprof/heap       - gc + allocation counters, top object types
  /debug/pprof/profile?seconds=N - statistical CPU profile (cProfile)
  /debug/pprof/cmdline    - process command line
  /debug/pprof/flightrec  - consensus flight recorder dump
  /debug/pprof/devprof    - device-time accounting dump (occupancy,
                            idle causes, compile ledger)
  /debug/pprof/devhealth  - device health states (quarantines, probe
                            history, circuit-breaker backoffs)
  /debug/pprof/latency    - per-consumer verify-latency ledger (request
                            decomposition rows, histograms, SLO burn)
"""

from __future__ import annotations

import gc
import sys
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse

_ENDPOINTS = ("goroutine", "heap", "profile", "cmdline", "flightrec",
              "tracetl", "devprof", "devhealth", "latency")


def _dump_threads() -> str:
    out = []
    frames = sys._current_frames()
    for t in threading.enumerate():
        out.append(f"goroutine: {t.name} (ident={t.ident} "
                   f"daemon={t.daemon} alive={t.is_alive()})")
        frame = frames.get(t.ident)
        if frame is not None:
            out.extend("  " + ln.rstrip()
                       for ln in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


def _dump_heap() -> str:
    from collections import Counter
    counts = Counter(type(o).__name__ for o in gc.get_objects())
    lines = [f"gc: counts={gc.get_count()} thresholds={gc.get_threshold()}",
             f"tracked objects: {len(gc.get_objects())}", "", "top types:"]
    for name, n in counts.most_common(30):
        lines.append(f"  {n:>9}  {name}")
    return "\n".join(lines)


def _cpu_profile(seconds: float) -> str:  # noqa: C901
    """Statistical whole-process profile: sample every thread's stack
    via sys._current_frames() (a per-thread cProfile would only see the
    handler thread sleeping)."""
    import time
    from collections import Counter

    interval = 0.005
    seconds = min(seconds, 30.0)         # hard cap, reported honestly
    samples: Counter[tuple] = Counter()
    own = threading.get_ident()
    deadline = time.monotonic() + seconds
    n = 0
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == own:
                continue
            stack = []
            f = frame
            while f is not None and len(stack) < 12:
                code = f.f_code
                stack.append(f"{code.co_filename}:{f.f_lineno} "
                             f"({code.co_name})")
                f = f.f_back
            if stack:
                samples[tuple(stack[:3])] += 1
        n += 1
        time.sleep(interval)
    lines = [f"samples: {n} over {seconds:g}s at {interval*1e3:g} ms", ""]
    for stack, count in samples.most_common(40):
        lines.append(f"{count:>6}  {stack[0]}")
        for fr in stack[1:]:
            lines.append(f"        <- {fr}")
    return "\n".join(lines)


class PprofServer:
    def __init__(self, addr: str):
        host, _, port = addr.replace("tcp://", "").rpartition(":")

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: N802
                pass

            def _text(self, body: str, status: int = 200) -> None:
                data = body.encode()
                self.send_response(status)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                parsed = urlparse(self.path)
                params = dict(parse_qsl(parsed.query))
                name = parsed.path.rstrip("/").rsplit("/", 1)[-1]
                if parsed.path.rstrip("/").endswith("/debug/pprof") or \
                        name == "pprof":
                    self._text("profiles:\n" + "\n".join(
                        f"  /debug/pprof/{e}" for e in _ENDPOINTS))
                elif name == "goroutine":
                    self._text(_dump_threads())
                elif name == "heap":
                    self._text(_dump_heap())
                elif name == "profile":
                    try:
                        secs = float(params.get("seconds", "5"))
                    except ValueError:
                        self._text("seconds must be a number", 400)
                        return
                    self._text(_cpu_profile(secs))
                elif name == "cmdline":
                    self._text("\x00".join(sys.argv))
                elif name == "flightrec":
                    from . import flightrec as _fr
                    rec = _fr.recorder()
                    if rec is None:
                        self._text("no flight recorder installed", 404)
                    else:
                        self._text(rec.dump_text())
                elif name == "tracetl":
                    from . import tracetl as _tl
                    tl = _tl.timeline()
                    if tl is None:
                        self._text("no timeline installed", 404)
                    else:
                        self._text(tl.dump_text())
                elif name == "devprof":
                    from . import devprof as _dp
                    rec = _dp.recorder()
                    if rec is None:
                        self._text("no devprof recorder installed", 404)
                    else:
                        self._text(rec.dump_text())
                elif name == "latency":
                    from . import latledger as _ll
                    rec = _ll.recorder()
                    if rec is None:
                        self._text("no latency ledger installed", 404)
                    else:
                        self._text(rec.dump_text())
                elif name == "devhealth":
                    from ..crypto import devhealth as _dh
                    reg = _dh.registry()
                    if reg is None:
                        self._text("no health registry installed", 404)
                    else:
                        self._text(reg.dump_text())
                else:
                    self._text("unknown profile", 404)

        self._httpd = ThreadingHTTPServer((host or "127.0.0.1",
                                           int(port)), Handler)
        self._httpd.daemon_threads = True
        self.bound_addr = "%s:%d" % self._httpd.server_address
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pprof-http",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()
