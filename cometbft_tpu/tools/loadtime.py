"""Load generation + latency reporting
(reference test/loadtime/: cmd/load, payload/, report/report.go).

The generator submits txs whose payload embeds the send time; the
reporter walks committed blocks, matches payloads, and derives per-tx
latency (block time - send time) plus block-interval statistics — the
reference's report.GenerateFromBlockStore over our stores or RPC.
"""

from __future__ import annotations

import json
import statistics
import time
import uuid
from dataclasses import dataclass, field

_PREFIX = b"loadtime:"


def make_payload(seq: int, run_id: str, size: int = 0,
                 now_ns: int | None = None) -> bytes:
    """payload/payload.go: id + sequence + send time (+ padding).

    Shaped as `loadtime:{...}=<pad>` so kv-style apps (which require a
    key=value form, like the reference's kvstore) admit it.  `size` is
    a MINIMUM total length: the natural payload (~74 bytes) is never
    truncated."""
    body = {
        "run": run_id,
        "seq": seq,
        "time_ns": time.time_ns() if now_ns is None else now_ns,
    }
    raw = _PREFIX + json.dumps(body).encode() + b"="
    if size > len(raw):
        raw += b"." * (size - len(raw))
    else:
        raw += b"1"
    return raw


def parse_payload(tx: bytes) -> dict | None:
    if not tx.startswith(_PREFIX):
        return None
    try:
        end = tx.find(b"}", len(_PREFIX))
        return json.loads(tx[len(_PREFIX):end + 1])
    except (ValueError, json.JSONDecodeError):
        return None


class LoadGenerator:
    """cmd/load: submit rate-limited payloads over an RPC client."""

    def __init__(self, client, rate: float = 20.0, size: int = 64):
        self.client = client
        self.rate = rate
        self.size = size
        self.run_id = uuid.uuid4().hex[:12]
        self.sent = 0

    def run(self, n_txs: int) -> int:
        for i in range(n_txs):
            tx = make_payload(i, self.run_id, self.size)
            try:
                self.client.broadcast_tx_sync(tx)
                self.sent += 1
            except Exception:
                pass
            time.sleep(1.0 / self.rate)
        return self.sent


@dataclass
class Report:
    """report.go Report: latency quantiles + block stats."""
    run_id: str = ""
    n_txs: int = 0
    latencies_s: list = field(default_factory=list)
    block_intervals_s: list = field(default_factory=list)
    first_height: int = 0
    last_height: int = 0

    def summary(self) -> dict:
        lat = sorted(self.latencies_s)

        def q(p):
            if not lat:
                return 0.0
            return lat[min(len(lat) - 1, int(p * len(lat)))]

        return {
            "run_id": self.run_id,
            "txs": self.n_txs,
            "heights": [self.first_height, self.last_height],
            "latency_s": {
                "min": round(min(lat), 4) if lat else 0,
                "p50": round(q(0.50), 4),
                "p90": round(q(0.90), 4),
                "p99": round(q(0.99), 4),
                "max": round(max(lat), 4) if lat else 0,
                "avg": round(statistics.fmean(lat), 4) if lat else 0,
            },
            "block_interval_s": {
                "avg": round(statistics.fmean(self.block_intervals_s), 4)
                if self.block_intervals_s else 0,
                "stddev": round(statistics.pstdev(self.block_intervals_s), 4)
                if len(self.block_intervals_s) > 1 else 0,
            },
        }


def report_from_block_store(block_store, run_id: str | None = None,
                            from_height: int = 1) -> Report:
    """report.go GenerateFromBlockStore."""
    rep = Report(run_id=run_id or "")
    prev_time_ns = None
    rep.first_height = max(from_height, block_store.base())
    rep.last_height = block_store.height()
    for h in range(rep.first_height, rep.last_height + 1):
        block = block_store.load_block(h)
        if block is None:
            continue
        t = block.header.time
        t_ns = t.seconds * 1_000_000_000 + t.nanos
        if prev_time_ns is not None:
            rep.block_intervals_s.append((t_ns - prev_time_ns) / 1e9)
        prev_time_ns = t_ns
        for tx in block.data.txs:
            body = parse_payload(bytes(tx))
            if body is None:
                continue
            if run_id is not None and body.get("run") != run_id:
                continue
            sent_ns = body.get("time_ns")
            if not isinstance(sent_ns, int):
                continue          # malformed payload: skip, don't abort
            rep.n_txs += 1
            rep.latencies_s.append((t_ns - sent_ns) / 1e9)
            if not rep.run_id:
                rep.run_id = body.get("run", "")
    return rep
