"""Dump a consensus WAL as JSON lines (reference scripts/wal2json):
every consensus decision is reconstructable from the WAL, and this is
the operator's window into it after an incident.

    python -m cometbft_tpu.tools.wal2json <data_dir>/cs.wal/wal
"""

from __future__ import annotations

import base64
import dataclasses
import json
import sys


def _jsonable(obj):
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, bytes):
        return base64.b64encode(obj).decode()
    if isinstance(obj, (list, tuple)):
        return [_jsonable(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if dataclasses.is_dataclass(obj):
        return {f.name: _jsonable(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if hasattr(obj, "__dict__"):
        return {k: _jsonable(v) for k, v in vars(obj).items()
                if not k.startswith("_")}
    return repr(obj)


def wal_to_json_lines(head_path: str):
    """Yield one JSON-ready dict per WAL record (time, type, body).

    STRICTLY read-only: constructing consensus.wal.WAL would repair
    (truncate) a torn head and open it for append — exactly what a
    forensic dump must never do.  The rotated-chunk naming is read
    directly (libs/autofile Group layout: head, head.000, head.001...).
    """
    import os
    import re

    from ..consensus.wal import decode_records

    if not os.path.exists(head_path):
        raise FileNotFoundError(head_path)
    d = os.path.dirname(head_path) or "."
    base = os.path.basename(head_path)
    pat = re.compile(re.escape(base) + r"\.(\d{3,})$")
    indexes = sorted(int(m.group(1)) for f in os.listdir(d)
                     if (m := pat.match(f)))
    paths = [os.path.join(d, f"{base}.{i:03d}") for i in indexes]
    paths.append(head_path)
    buf = b""
    for p in paths:
        try:
            with open(p, "rb") as f:
                buf += f.read()
        except FileNotFoundError:
            pass
    for timed in decode_records(buf):
        msg = timed.msg
        yield {
            "time": {"seconds": timed.time.seconds,
                     "nanos": timed.time.nanos},
            "type": type(msg).__name__,
            "msg": _jsonable(msg),
        }


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m cometbft_tpu.tools.wal2json <wal-head-path>",
              file=sys.stderr)
        return 2
    try:
        for rec in wal_to_json_lines(argv[0]):
            print(json.dumps(rec))
    except FileNotFoundError:
        print(f"no WAL at {argv[0]}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
