"""Light-block providers (light/provider analog).

Provider is the seam the client fetches LightBlocks through
(/root/reference/light/provider/provider.go:15-40). HttpProvider speaks
the CometBFT JSON-RPC /commit + /validators endpoints of a full node, so
this client can sync against real reference chains; tests use in-memory
providers.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Protocol

from .types import LightBlock


class ProviderError(Exception):
    pass


class ErrLightBlockNotFound(ProviderError):
    pass


class ErrNoResponse(ProviderError):
    pass


class ErrHeightTooHigh(ProviderError):
    pass


class ErrBadLightBlock(ProviderError):
    pass


class Provider(Protocol):
    def light_block(self, height: int) -> LightBlock:
        """Fetch the light block at height (0 = latest).

        Raises ProviderError subclasses on failure."""
        ...

    def chain_id(self) -> str: ...

    def report_evidence(self, ev) -> None:
        """Submit misbehavior evidence back to this provider's node
        (provider.Provider ReportEvidence)."""
        ...


class MemoryProvider:
    """In-memory provider for tests and local verification."""

    def __init__(self, chain_id: str,
                 blocks: dict[int, LightBlock] | None = None):
        self._chain_id = chain_id
        self._blocks: dict[int, LightBlock] = dict(blocks or {})
        self.reported_evidence: list = []

    def add(self, lb: LightBlock) -> None:
        self._blocks[lb.height] = lb

    def report_evidence(self, ev) -> None:
        self.reported_evidence.append(ev)

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        if height == 0:
            if not self._blocks:
                raise ErrLightBlockNotFound("no blocks")
            height = max(self._blocks)
        lb = self._blocks.get(height)
        if lb is None:
            if self._blocks and height > max(self._blocks):
                raise ErrHeightTooHigh(str(height))
            raise ErrLightBlockNotFound(str(height))
        return lb


class HttpProvider:
    """JSON-RPC provider over a CometBFT full node's RPC
    (light/provider/http/http.go analog: /commit + /validators with
    pagination)."""

    def __init__(self, chain_id: str, base_url: str, timeout: float = 10.0):
        self._chain_id = chain_id
        self._base = base_url.rstrip("/")
        self._timeout = timeout

    def chain_id(self) -> str:
        return self._chain_id

    def _rpc(self, path: str, params: dict) -> dict:
        qs = "&".join(f"{k}={v}" for k, v in params.items())
        url = f"{self._base}/{path}?{qs}" if qs else f"{self._base}/{path}"
        try:
            with urllib.request.urlopen(url, timeout=self._timeout) as resp:
                body = json.loads(resp.read())
        except Exception as e:  # noqa: BLE001 - network failures map to ErrNoResponse
            raise ErrNoResponse(str(e)) from e
        if "error" in body and body["error"]:
            msg = str(body["error"])
            if "height" in msg and "must be less" in msg:
                raise ErrHeightTooHigh(msg)
            raise ErrLightBlockNotFound(msg)
        return body["result"]

    def light_block(self, height: int) -> LightBlock:
        from .rpc_decode import signed_header_from_rpc, validators_from_rpc

        hparam = {} if height == 0 else {"height": height}
        commit_res = self._rpc("commit", hparam)
        sh = signed_header_from_rpc(commit_res["signed_header"])
        # pin the validators query to the commit's height: with height=0
        # ("latest") a new block could land between the two RPCs
        vparam = {"height": sh.height}
        vals = []
        page, per_page = 1, 100
        while True:
            res = self._rpc("validators", {**vparam, "page": page,
                                           "per_page": per_page})
            batch = validators_from_rpc(res["validators"])
            if not batch:
                raise ErrBadLightBlock(
                    f"validators page {page} empty with "
                    f"{len(vals)}/{res['total']} fetched")
            vals.extend(batch)
            if len(vals) >= int(res["total"]):
                break
            page += 1
        from ..types.validator_set import ValidatorSet
        vs = ValidatorSet.from_validated(vals)
        lb = LightBlock(sh, vs)
        try:
            lb.validate_basic(self._chain_id)
        except ValueError as e:
            raise ErrBadLightBlock(str(e)) from e
        return lb

    def report_evidence(self, ev) -> None:
        """POST the evidence to the node's /broadcast_evidence
        (light/provider/http ReportEvidence)."""
        import base64

        from ..types.evidence import evidence_to_proto_wrapped

        from urllib.parse import quote

        wrapped = base64.b64encode(
            evidence_to_proto_wrapped(ev)).decode()
        self._rpc("broadcast_evidence", {"evidence": quote(wrapped)})
