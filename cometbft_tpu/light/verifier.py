"""Pure light-client verification (light/verifier.go analog).

verify_adjacent / verify_non_adjacent / verify_backwards reproduce
/root/reference/light/verifier.go:30,91,129,196-230 exactly; the
signature checks route through the TPU batch verifier via
types/validation.py. Durations are nanoseconds (ints).
"""

from __future__ import annotations

from ..crypto import sigcache
from ..types.timestamp import Timestamp
from ..types.validation import (
    ErrNotEnoughVotingPowerSigned, Fraction, verify_commit_light,
    verify_commit_light_trusting,
)
from .types import LightBlock, SignedHeader

DEFAULT_TRUST_LEVEL = Fraction(1, 3)

SECOND = 1_000_000_000
DEFAULT_MAX_CLOCK_DRIFT = 10 * SECOND


class LightClientError(Exception):
    pass


class ErrOldHeaderExpired(LightClientError):
    pass


class ErrInvalidHeader(LightClientError):
    pass


class ErrNewValSetCantBeTrusted(LightClientError):
    pass


class ErrHeaderHeightAdjacent(LightClientError):
    pass


class ErrHeaderHeightNotAdjacent(LightClientError):
    pass


class ErrInvalidTrustLevel(LightClientError):
    pass


def validate_trust_level(lvl: Fraction) -> None:
    """[1/3, 1] (verifier.go:184-192)."""
    if (lvl.numerator * 3 < lvl.denominator
            or lvl.numerator > lvl.denominator
            or lvl.denominator == 0):
        raise ErrInvalidTrustLevel(f"trust level must be in [1/3, 1]: {lvl}")


def header_expired(h: SignedHeader, trusting_period_ns: int,
                   now: Timestamp) -> bool:
    expiration = h.header.time.add_ns(trusting_period_ns)
    return expiration <= now


def _verify_new_header_and_vals(untrusted: SignedHeader, untrusted_vals,
                                trusted: SignedHeader, now: Timestamp,
                                max_clock_drift_ns: int) -> None:
    try:
        untrusted.validate_basic(trusted.chain_id)
    except ValueError as e:
        raise ErrInvalidHeader(f"header validate basic: {e}") from e
    if untrusted.height <= trusted.height:
        raise ErrInvalidHeader(
            f"expected new header height {untrusted.height} > "
            f"{trusted.height}")
    if untrusted.header.time <= trusted.header.time:
        raise ErrInvalidHeader("non-monotonic header time")
    if untrusted.header.time >= now.add_ns(max_clock_drift_ns):
        raise ErrInvalidHeader("new header time exceeds max clock drift")
    if untrusted.header.validators_hash != untrusted_vals.hash():
        raise ErrInvalidHeader(
            f"validators hash mismatch at height {untrusted.height}")


def verify_adjacent(trusted: SignedHeader, untrusted: SignedHeader,
                    untrusted_vals, trusting_period_ns: int, now: Timestamp,
                    max_clock_drift_ns: int, defer_to=None) -> None:
    """verifier.go:91-127.  defer_to (validation.DeferredSigBatch)
    collects the commit's signature checks for a later cross-header
    device batch; every header/valset structural check still runs
    immediately."""
    if untrusted.height != trusted.height + 1:
        raise ErrHeaderHeightNotAdjacent()
    if header_expired(trusted, trusting_period_ns, now):
        raise ErrOldHeaderExpired()
    _verify_new_header_and_vals(untrusted, untrusted_vals, trusted, now,
                                max_clock_drift_ns)
    if untrusted.header.validators_hash != trusted.header.next_validators_hash:
        raise ErrInvalidHeader(
            f"expected old header next validators "
            f"({trusted.header.next_validators_hash.hex()}) to match those "
            f"from new header ({untrusted.header.validators_hash.hex()})")
    try:
        # commits the full node already verified (consensus/blocksync)
        # are verdict-cache hits here — attributed to the "light"
        # consumer in CacheMetrics
        with sigcache.consumer("light"):
            verify_commit_light(trusted.chain_id, untrusted_vals,
                                untrusted.commit.block_id,
                                untrusted.height, untrusted.commit,
                                defer_to=defer_to)
    except Exception as e:
        raise ErrInvalidHeader(str(e)) from e


def verify_non_adjacent(trusted: SignedHeader, trusted_vals,
                        untrusted: SignedHeader, untrusted_vals,
                        trusting_period_ns: int, now: Timestamp,
                        max_clock_drift_ns: int,
                        trust_level: Fraction) -> None:
    """verifier.go:30-89: 1/3 overlap with trusted vals, then +2/3 of
    the new set. The order matters: the trusting check runs first so an
    attacker can't DOS with a huge fake untrusted valset."""
    if untrusted.height == trusted.height + 1:
        raise ErrHeaderHeightAdjacent()
    if header_expired(trusted, trusting_period_ns, now):
        raise ErrOldHeaderExpired()
    _verify_new_header_and_vals(untrusted, untrusted_vals, trusted, now,
                                max_clock_drift_ns)
    try:
        with sigcache.consumer("light"):
            verify_commit_light_trusting(trusted.chain_id, trusted_vals,
                                         untrusted.commit, trust_level)
    except ErrNotEnoughVotingPowerSigned as e:
        raise ErrNewValSetCantBeTrusted(str(e)) from e
    try:
        with sigcache.consumer("light"):
            verify_commit_light(trusted.chain_id, untrusted_vals,
                                untrusted.commit.block_id,
                                untrusted.height, untrusted.commit)
    except Exception as e:
        raise ErrInvalidHeader(str(e)) from e


def verify(trusted: SignedHeader, trusted_vals, untrusted: SignedHeader,
           untrusted_vals, trusting_period_ns: int, now: Timestamp,
           max_clock_drift_ns: int, trust_level: Fraction) -> None:
    """verifier.go:131-148: adjacent or skipping."""
    if untrusted.height != trusted.height + 1:
        verify_non_adjacent(trusted, trusted_vals, untrusted,
                            untrusted_vals, trusting_period_ns, now,
                            max_clock_drift_ns, trust_level)
    else:
        verify_adjacent(trusted, untrusted, untrusted_vals,
                        trusting_period_ns, now, max_clock_drift_ns)


def verify_backwards(untrusted_header, trusted_header) -> None:
    """verifier.go:196-230: hash-chain one height backwards."""
    try:
        untrusted_header.validate_basic()
    except ValueError as e:
        raise ErrInvalidHeader(str(e)) from e
    if untrusted_header.chain_id != trusted_header.chain_id:
        raise ErrInvalidHeader("header belongs to another chain")
    if untrusted_header.time >= trusted_header.time:
        raise ErrInvalidHeader(
            "expected older header time to be before new header time")
    if untrusted_header.hash() != trusted_header.last_block_id.hash:
        raise ErrInvalidHeader(
            "older header hash does not match trusted header's last block")


def verify_light_block(trusted: LightBlock, untrusted: LightBlock,
                       trusting_period_ns: int, now: Timestamp,
                       max_clock_drift_ns: int,
                       trust_level: Fraction) -> None:
    verify(trusted.signed_header, trusted.validator_set,
           untrusted.signed_header, untrusted.validator_set,
           trusting_period_ns, now, max_clock_drift_ns, trust_level)
