"""Trusted light-block stores (light/store analog).

MemoryStore for tests; FileStore persists proto-encoded LightBlocks in a
directory (the reference uses pebble/leveldb, light/store/db/db.go; an
fsync'd file-per-height layout gives the same guarantees here without a
KV dependency).
"""

from __future__ import annotations

import os
from typing import Protocol

from .types import LightBlock


class Store(Protocol):
    def save_light_block(self, lb: LightBlock) -> None: ...
    def light_block(self, height: int) -> LightBlock | None: ...
    def light_block_before(self, height: int) -> LightBlock | None: ...
    def latest_light_block(self) -> LightBlock | None: ...
    def first_light_block(self) -> LightBlock | None: ...
    def delete_light_blocks_before(self, height: int) -> int: ...
    def prune(self, size: int) -> None: ...
    def size(self) -> int: ...


class MemoryStore:
    def __init__(self):
        self._blocks: dict[int, LightBlock] = {}

    def save_light_block(self, lb: LightBlock) -> None:
        self._blocks[lb.height] = lb

    def light_block(self, height: int) -> LightBlock | None:
        return self._blocks.get(height)

    def light_block_before(self, height: int) -> LightBlock | None:
        """Greatest stored block strictly below height (db.go
        LightBlockBefore)."""
        below = [h for h in self._blocks if h < height]
        return self._blocks[max(below)] if below else None

    def latest_light_block(self) -> LightBlock | None:
        return self._blocks[max(self._blocks)] if self._blocks else None

    def first_light_block(self) -> LightBlock | None:
        return self._blocks[min(self._blocks)] if self._blocks else None

    def delete_light_blocks_before(self, height: int) -> int:
        gone = [h for h in self._blocks if h < height]
        for h in gone:
            del self._blocks[h]
        return len(gone)

    def prune(self, size: int) -> None:
        """Drop oldest blocks until `size` remain (db.go Prune)."""
        while len(self._blocks) > size:
            del self._blocks[min(self._blocks)]

    def size(self) -> int:
        return len(self._blocks)


class FileStore:
    """One proto file per height: <dir>/lb_<height:020d>.bin."""

    def __init__(self, dir_path: str):
        self._dir = dir_path
        os.makedirs(dir_path, exist_ok=True)

    def _path(self, height: int) -> str:
        return os.path.join(self._dir, f"lb_{height:020d}.bin")

    def _heights(self) -> list[int]:
        out = []
        for name in os.listdir(self._dir):
            if name.startswith("lb_") and name.endswith(".bin"):
                out.append(int(name[3:-4]))
        return sorted(out)

    def save_light_block(self, lb: LightBlock) -> None:
        tmp = self._path(lb.height) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(lb.to_proto())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path(lb.height))

    def light_block(self, height: int) -> LightBlock | None:
        try:
            with open(self._path(height), "rb") as f:
                return LightBlock.from_proto(f.read())
        except FileNotFoundError:
            return None

    def light_block_before(self, height: int) -> LightBlock | None:
        below = [h for h in self._heights() if h < height]
        return self.light_block(max(below)) if below else None

    def latest_light_block(self) -> LightBlock | None:
        hs = self._heights()
        return self.light_block(hs[-1]) if hs else None

    def first_light_block(self) -> LightBlock | None:
        hs = self._heights()
        return self.light_block(hs[0]) if hs else None

    def delete_light_blocks_before(self, height: int) -> int:
        n = 0
        for h in self._heights():
            if h < height:
                os.remove(self._path(h))
                n += 1
        return n

    def prune(self, size: int) -> None:
        hs = self._heights()
        for h in hs[:max(0, len(hs) - size)]:
            os.remove(self._path(h))

    def size(self) -> int:
        return len(self._heights())
