"""Decode CometBFT JSON-RPC responses into our types.

The JSON shapes come from the reference RPC (rpc/core/blocks.go /commit,
rpc/core/consensus.go /validators), which serializes with amino-style
JSON (base64 bytes, decimal-string ints, RFC3339 times).
"""

from __future__ import annotations

import base64

from ..crypto.encoding import make_pubkey
from ..types.block import (
    BlockID, Commit, CommitSig, Consensus, Header, PartSetHeader,
)
from ..types.timestamp import Timestamp
from ..types.validator_set import Validator
from .types import SignedHeader

_FLAGS = {"BLOCK_ID_FLAG_ABSENT": 1, "BLOCK_ID_FLAG_COMMIT": 2,
          "BLOCK_ID_FLAG_NIL": 3}

_KEY_TYPES = {
    "tendermint/PubKeyEd25519": "ed25519",
    "tendermint/PubKeySecp256k1": "secp256k1",
    "cometbft/PubKeyEd25519": "ed25519",
    "cometbft/PubKeySecp256k1": "secp256k1",
}


def _b64(s: str | None) -> bytes:
    return base64.b64decode(s) if s else b""


def _hex(s: str | None) -> bytes:
    return bytes.fromhex(s) if s else b""


def _int(v) -> int:
    return int(v) if v is not None else 0


def block_id_from_rpc(d: dict | None) -> BlockID:
    if not d:
        return BlockID()
    psh = d.get("parts") or d.get("part_set_header") or {}
    return BlockID(
        hash=_hex(d.get("hash")),
        part_set_header=PartSetHeader(_int(psh.get("total")),
                                      _hex(psh.get("hash"))))


def header_from_rpc(d: dict) -> Header:
    ver = d.get("version") or {}
    return Header(
        version=Consensus(_int(ver.get("block")), _int(ver.get("app"))),
        chain_id=d["chain_id"],
        height=_int(d["height"]),
        time=Timestamp.from_rfc3339(d["time"]),
        last_block_id=block_id_from_rpc(d.get("last_block_id")),
        last_commit_hash=_hex(d.get("last_commit_hash")),
        data_hash=_hex(d.get("data_hash")),
        validators_hash=_hex(d.get("validators_hash")),
        next_validators_hash=_hex(d.get("next_validators_hash")),
        consensus_hash=_hex(d.get("consensus_hash")),
        app_hash=_hex(d.get("app_hash")),
        last_results_hash=_hex(d.get("last_results_hash")),
        evidence_hash=_hex(d.get("evidence_hash")),
        proposer_address=_hex(d.get("proposer_address")))


def commit_from_rpc(d: dict) -> Commit:
    sigs = []
    for s in d.get("signatures", []):
        flag = s.get("block_id_flag")
        if isinstance(flag, str):
            flag = _FLAGS[flag] if flag in _FLAGS else _int(flag)
        ts = s.get("timestamp")
        sigs.append(CommitSig(
            block_id_flag=_int(flag),
            validator_address=_hex(s.get("validator_address")),
            timestamp=Timestamp.from_rfc3339(ts)
            if ts and not ts.startswith("0001-01-01") else Timestamp.zero(),
            signature=_b64(s.get("signature"))))
    return Commit(
        height=_int(d["height"]),
        round=_int(d.get("round")),
        block_id=block_id_from_rpc(d.get("block_id")),
        signatures=sigs)


def signed_header_from_rpc(d: dict) -> SignedHeader:
    return SignedHeader(header_from_rpc(d["header"]),
                        commit_from_rpc(d["commit"]))


def validators_from_rpc(items: list[dict]) -> list[Validator]:
    out = []
    for v in items:
        pk = v["pub_key"]
        if "type" in pk:
            key_type = _KEY_TYPES.get(pk["type"], pk["type"])
            data = _b64(pk["value"])
        else:  # {"ed25519": "..."} shape from the newer RPC
            key_type, data = next(iter(pk.items()))
            data = _b64(data)
        out.append(Validator(
            pub_key=make_pubkey(key_type, data),
            voting_power=_int(v.get("voting_power")),
            proposer_priority=_int(v.get("proposer_priority")),
            address=_hex(v.get("address"))))
    return out
