"""Light client: trust-minimized header verification (light/ analog)."""

from .types import SignedHeader, LightBlock  # noqa: F401
from .verifier import (  # noqa: F401
    verify, verify_adjacent, verify_non_adjacent, verify_backwards,
    header_expired, validate_trust_level, DEFAULT_TRUST_LEVEL,
)
from .client import Client, TrustOptions  # noqa: F401
