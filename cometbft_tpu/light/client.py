"""Light client with bisection and witness cross-checking
(light/client.go analog).

Sync strategies (client.go:612,705,932):
- sequential: verify every header from trusted to target;
- skipping (default): trust-propagation bisection — try the target
  directly against the latest trusted block; on 1/3-overlap failure
  fetch a pivot at 9/16 of the span and recurse, caching fetched blocks;
- backwards: hash-chain walk for heights below the trusted root.

Every commit verification lands on the TPU batch verifier, so one
bisection hop = one or two device launches regardless of valset size.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass

from ..crypto import sigcache
from ..libs.trace import span as trace_span
from ..types.timestamp import Timestamp
from ..types.validation import Fraction
from . import verifier
from .provider import (
    ErrHeightTooHigh, ErrLightBlockNotFound, ErrNoResponse, Provider,
    ProviderError,
)
from .store import MemoryStore, Store
from .types import LightBlock
from .verifier import (
    DEFAULT_TRUST_LEVEL, ErrNewValSetCantBeTrusted, LightClientError, SECOND,
)

SEQUENTIAL = "sequential"
SKIPPING = "skipping"

# pivot ratio for bisection (client.go:31-32)
_SKIP_NUM = 9
_SKIP_DEN = 16

DEFAULT_PRUNING_SIZE = 1000

# QoS lane override for light-client verify windows (crypto/sched.py):
# empty = the light lane itself.  Re-laning changes dispatch priority
# only; trace/ledger/cache attribution stays "light".
SCHED_LANE = os.environ.get(
    "COMETBFT_TPU_SCHED_LIGHT_LANE", "") or None


@dataclass
class TrustOptions:
    """Trust root: (period, height, hash) (light/client.go TrustOptions)."""

    period_ns: int
    height: int
    hash: bytes

    def validate_basic(self) -> None:
        if self.period_ns <= 0:
            raise ValueError("trusting period must be > 0")
        if self.height <= 0:
            raise ValueError("trusted height must be > 0")
        if len(self.hash) != 32:
            raise ValueError("expected 32-byte trusted hash")


class ErrLightClientAttack(LightClientError):
    def __init__(self, evidence):
        super().__init__("light client attack detected")
        self.evidence = evidence


class _WindowPrefetcher:
    """Single-worker window prefetch for the sequential sync paths.

    Replaces the ThreadPoolExecutor(max_workers=1) both sequential
    strategies used: the executor's worker was invisible to the
    concurrency lints (check_concurrency.py C4 only sees
    threading.Thread constructions) and, being non-daemon, hung
    interpreter shutdown whenever a verify failure unwound the context
    manager while a fetch was still blocked on a dead provider —
    executor __exit__ is shutdown(wait=True).  The worker here is a
    daemon (a wedged provider can never wedge shutdown), close() still
    joins it on the orderly path, and the construction is registered
    in scripts/check_concurrency.JOINED_THREADS."""

    def __init__(self):
        import queue

        self._jobs: "queue.Queue" = queue.Queue()
        self._empty = queue.Empty
        self._inflight = None
        self._thread = threading.Thread(
            target=self._run, name="light-prefetch", daemon=True)
        self._thread.start()

    def submit(self, fn, *args):
        import concurrent.futures as cf

        fut = cf.Future()
        self._jobs.put((fut, fn, args))
        return fut

    def _run(self) -> None:
        while True:
            item = self._jobs.get()
            if item is None:
                return
            fut, fn, args = item
            if not fut.set_running_or_notify_cancel():
                continue
            self._inflight = fut
            try:
                fut.set_result(fn(*args))
            except BaseException as e:
                fut.set_exception(e)
            finally:
                self._inflight = None

    def close(self, timeout: float = 5.0) -> None:
        """Cancel queued fetches, stop the worker, join with a bound.
        A fetch already blocked inside a provider cannot be
        interrupted; its daemon thread is abandoned and its future's
        eventual exception consumed here so nothing leaks."""
        try:
            while True:
                item = self._jobs.get_nowait()
                if item is not None:
                    item[0].cancel()
        except self._empty:
            pass
        self._jobs.put(None)
        self._thread.join(timeout=timeout)
        fut = self._inflight
        if fut is not None and fut.done():
            try:
                fut.exception(timeout=0)
            except BaseException:
                pass

    def __enter__(self) -> "_WindowPrefetcher":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class Client:
    def __init__(self, chain_id: str, trust_options: TrustOptions,
                 primary: Provider, witnesses: list[Provider] | None = None,
                 trusted_store: Store | None = None,
                 verification_mode: str = SKIPPING,
                 trust_level: Fraction = DEFAULT_TRUST_LEVEL,
                 max_clock_drift_ns: int = 10 * SECOND,
                 pruning_size: int = DEFAULT_PRUNING_SIZE,
                 # 384 from the r4b on-TPU depth sweep (ab_round4b_
                 # results.jsonl prod3_light under the full kernel
                 # stack): 3708.7 headers/s at 192 vs 5338.6 at 384
                 # commits per RLC dispatch — the relay's fixed
                 # dispatch cost rewards depth, and the r4b kernels
                 # keep a 384-commit dispatch well under 100 ms
                 sequential_batch_size: int = 384,
                 # overlapped verify pipeline depth for sequential
                 # sync (crypto/dispatch.py): fetch + collect window
                 # w+1 while window w's dispatch is on device; 1 =
                 # the strictly serial loop
                 pipeline_depth: int = 2,
                 # mesh round-robin for the verify pipeline
                 # (ops/sharding.mesh_device_list semantics: 0 defers
                 # to COMETBFT_TPU_MESH_DEVICES, off unless set)
                 mesh_devices: int = 0,
                 now_fn=Timestamp.now):
        verifier.validate_trust_level(trust_level)
        trust_options.validate_basic()
        self.chain_id = chain_id
        self.trusting_period_ns = trust_options.period_ns
        self.trust_level = trust_level
        self.max_clock_drift_ns = max_clock_drift_ns
        self.verification_mode = verification_mode
        self.primary = primary
        self.witnesses = list(witnesses or [])
        self.store: Store = trusted_store or MemoryStore()
        self.pruning_size = pruning_size
        self.sequential_batch_size = max(1, sequential_batch_size)
        self.pipeline_depth = max(1, pipeline_depth)
        self.mesh_devices = mesh_devices
        self._now = now_fn
        self._initialize(trust_options)

    # -- initialization ----------------------------------------------------

    def _initialize(self, opts: TrustOptions) -> None:
        """client.go initializeWithTrustOptions: fetch the root block,
        check hash + self-consistency, persist."""
        existing = self.store.light_block(opts.height)
        if existing is not None:
            if existing.hash() != opts.hash:
                raise LightClientError(
                    "trusted store block hash does not match trust options")
            return
        lb = self._from_primary(opts.height)
        if lb.hash() != opts.hash:
            raise LightClientError(
                f"primary's header hash {lb.hash().hex()} does not match "
                f"trust options' {opts.hash.hex()}")
        lb.validate_basic(self.chain_id)
        # 2/3 of that height's valset must have signed (self-consistent root)
        from ..types.validation import verify_commit_light
        verify_commit_light(self.chain_id, lb.validator_set,
                            lb.signed_header.commit.block_id, lb.height,
                            lb.signed_header.commit)
        self.store.save_light_block(lb)

    # -- public API --------------------------------------------------------

    def trusted_light_block(self, height: int) -> LightBlock | None:
        return self.store.light_block(height)

    def latest_trusted(self) -> LightBlock | None:
        return self.store.latest_light_block()

    def update(self, now: Timestamp | None = None) -> LightBlock | None:
        """Fetch + verify the primary's latest block (client.go:447)."""
        now = now or self._now()
        latest = self._from_primary(0)
        trusted = self.store.latest_light_block()
        if trusted is not None and latest.height <= trusted.height:
            return None
        return self.verify_light_block_at_height(latest.height, now, latest)

    def verify_light_block_at_height(self, height: int,
                                     now: Timestamp | None = None,
                                     prefetched: LightBlock | None = None
                                     ) -> LightBlock:
        """client.go:473 VerifyLightBlockAtHeight."""
        if height <= 0:
            raise ValueError("height must be positive")
        now = now or self._now()
        existing = self.store.light_block(height)
        if existing is not None:
            return existing
        latest = self.store.latest_light_block()
        if latest is None:
            raise LightClientError("no trusted state: initialize first")
        target = prefetched if prefetched is not None and \
            prefetched.height == height else self._from_primary(height)
        if target.height != height:
            raise LightClientError(
                f"provider returned height {target.height}, wanted {height}")
        self.verify_header(target, now)
        return target

    def verify_header(self, new_block: LightBlock, now: Timestamp) -> None:
        """client.go:563 VerifyHeader (already-fetched block path).

        Verifies forward from the closest trusted block below the target
        (client.go:594-600); heights below the first trusted block go
        through backwards hash-chaining."""
        latest = self.store.latest_light_block()
        if latest is None:
            raise LightClientError("no trusted state")
        if new_block.height < self.store.first_light_block().height:
            self._backwards(new_block, now)
            return
        anchor = self.store.light_block_before(new_block.height + 1)
        if anchor is not None and anchor.height == new_block.height:
            return  # already trusted (caller checked, but be safe)
        new_block.validate_basic(self.chain_id)
        if self.verification_mode == SEQUENTIAL:
            trace = self._verify_sequential(anchor, new_block, now)
        else:
            trace = self._verify_skipping(self.primary, anchor, new_block,
                                          now)
        self._detect_divergence(trace, now)
        with trace_span("light", "store"):
            for lb in trace[1:]:
                self.store.save_light_block(lb)
            self.store.prune(self.pruning_size)

    # -- strategies --------------------------------------------------------

    def _verify_sequential(self, trusted: LightBlock, target: LightBlock,
                           now: Timestamp) -> list[LightBlock]:
        """client.go:612 verifySequential, WINDOWED for the device:
        headers are fetched and host-checked (chaining, valset hashes,
        timestamps) one by one, but their commits' signatures collect
        into a DeferredSigBatch verified once per window — one RLC
        dispatch covers sequential_batch_size commits over the (mostly
        repeated) validator set.  A bad signature fails the whole
        window before anything is returned or stored.

        With pipeline_depth >= 2 the overlapped path runs instead:
        window w+1 fetches AND collects while window w's dispatch is
        in flight (crypto/dispatch.py) — headers join the trace only
        after their window's verdict future resolved true."""
        if self.pipeline_depth >= 2:
            return self._verify_sequential_pipelined(trusted, target,
                                                     now)
        from ..types import validation

        def fetch_window(start: int, end: int) -> list[LightBlock]:
            with trace_span("light", "fetch"):
                return [target if hh == target.height else
                        self._from_primary(hh)
                        for hh in range(start, end + 1)]

        trace = [trusted]
        verified = trusted
        h = trusted.height + 1
        # overlap: while window w's signatures run on the device, a
        # single worker thread fetches window w+1 from the provider —
        # a syncing client's wall-clock is max(fetch, verify), not sum
        with _WindowPrefetcher() as ex:
            wend = min(h + self.sequential_batch_size - 1, target.height)
            pending = ex.submit(fetch_window, h, wend)
            while h <= target.height:
                window = pending.result()
                nxt = wend + 1
                if nxt <= target.height:
                    nxt_end = min(nxt + self.sequential_batch_size - 1,
                                  target.height)
                    pending = ex.submit(fetch_window, nxt, nxt_end)
                batch = validation.DeferredSigBatch()
                with trace_span("light", "verify_dispatch"):
                    for interim in window:
                        verifier.verify_adjacent(
                            verified.signed_header, interim.signed_header,
                            interim.validator_set, self.trusting_period_ns,
                            now, self.max_clock_drift_ns, defer_to=batch)
                        verified = interim
                with trace_span("light", "device"), \
                        sigcache.consumer("light"):
                    batch.verify()
                trace.extend(window)
                h = wend + 1
                wend = min(h + self.sequential_batch_size - 1,
                           target.height)
        return trace

    def _verify_sequential_pipelined(self, trusted: LightBlock,
                                     target: LightBlock,
                                     now: Timestamp) -> list[LightBlock]:
        """The overlapped sequential sync: header-range prefetch AND
        the next window's host-side checks run while the previous
        window's signatures are on device (VerifyPipeline, depth =
        pipeline_depth).  Verdicts resolve in submission order and a
        window's headers extend the trace only after its verdict
        future resolved true; any failure raises before the target —
        or anything past the failed window — is stored."""
        from collections import deque

        from ..crypto.dispatch import VerifyPipeline
        from ..types import validation

        def fetch_window(start: int, end: int) -> list[LightBlock]:
            with trace_span("light", "fetch"):
                return [target if hh == target.height else
                        self._from_primary(hh)
                        for hh in range(start, end + 1)]

        from ..ops import sharding

        trace = [trusted]
        verified = trusted
        h = trusted.height + 1
        bs = self.sequential_batch_size
        inflight: deque = deque()
        devices = sharding.mesh_device_list(self.mesh_devices or None)
        depth = self.pipeline_depth if devices is None else \
            max(self.pipeline_depth, 2 * len(devices))
        with _WindowPrefetcher() as ex, \
                VerifyPipeline(depth=depth,
                               name="light-pipeline",
                               devices=devices if devices is not None
                               else ()) as pipe:
            wend = min(h + bs - 1, target.height)
            pending = ex.submit(fetch_window, h, wend) \
                if h <= target.height else None
            while h <= target.height or inflight:
                if h <= target.height \
                        and len(inflight) < depth:
                    window = pending.result()
                    nxt = wend + 1
                    if nxt <= target.height:
                        nxt_end = min(nxt + bs - 1, target.height)
                        pending = ex.submit(fetch_window, nxt, nxt_end)
                    batch = validation.DeferredSigBatch()
                    with trace_span("light", "verify_dispatch",
                                    inflight=len(inflight)), \
                            trace_span("light", "collect"):
                        for interim in window:
                            verifier.verify_adjacent(
                                verified.signed_header,
                                interim.signed_header,
                                interim.validator_set,
                                self.trusting_period_ns,
                                now, self.max_clock_drift_ns,
                                defer_to=batch)
                            verified = interim
                    inflight.append(
                        (window,
                         batch.verify_async(pipe, subsystem="light",
                                            lane=SCHED_LANE)))
                    h = wend + 1
                    wend = min(h + bs - 1, target.height)
                else:
                    window, verdict = inflight.popleft()
                    verdict.wait()
                    trace.extend(window)
        return trace

    def _verify_skipping(self, source: Provider, trusted: LightBlock,
                         target: LightBlock, now: Timestamp
                         ) -> list[LightBlock]:
        """client.go:705 verifySkipping (bisection with block cache)."""
        block_cache = [target]
        depth = 0
        verified = trusted
        trace = [trusted]
        while True:
            try:
                verifier.verify_light_block(
                    verified, block_cache[depth], self.trusting_period_ns,
                    now, self.max_clock_drift_ns, self.trust_level)
            except ErrNewValSetCantBeTrusted:
                if depth == len(block_cache) - 1:
                    pivot = verified.height + (
                        block_cache[depth].height - verified.height
                    ) * _SKIP_NUM // _SKIP_DEN
                    try:
                        interim = source.light_block(pivot)
                    except (ErrLightBlockNotFound, ErrNoResponse,
                            ErrHeightTooHigh) as pe:
                        raise LightClientError(
                            f"cannot get pivot block {pivot}: {pe}") from pe
                    block_cache.append(interim)
                depth += 1
                continue
            if depth == 0:
                return trace + [target] if trace[-1] is not target else trace
            verified = block_cache[depth]
            block_cache = block_cache[:depth]
            depth = 0
            trace.append(verified)

    def _backwards(self, target: LightBlock, now: Timestamp) -> None:
        """client.go:932 backwards: hash-chain below the trusted root.

        Interim headers are NOT saved (client.go:507) — only the fully
        validated target, after the whole chain of hashes checks out."""
        target.validate_basic(self.chain_id)
        first = self.store.first_light_block()
        verified_header = first.signed_header.header
        while verified_header.height > target.height:
            h = verified_header.height - 1
            interim = target if h == target.height else self._from_primary(h)
            verifier.verify_backwards(interim.signed_header.header,
                                      verified_header)
            verified_header = interim.signed_header.header
        self.store.save_light_block(target)

    # -- witnesses ---------------------------------------------------------

    def _detect_divergence(self, trace: list[LightBlock],
                           now: Timestamp) -> None:
        """detector.go: compare the newly-verified header against every
        witness; a witness with a conflicting verified header means a
        light-client attack."""
        if not self.witnesses:
            return
        target = trace[-1]
        for w in list(self.witnesses):
            try:
                other = w.light_block(target.height)
            except ProviderError:
                continue
            if other.hash() != target.hash():
                evidence = self._examine_divergence(w, trace, other, now)
                if evidence is None:
                    # the witness could not back its header with a
                    # verifiable chain: it is faulty, not the primary —
                    # drop it and keep going (detector.go:121).  Running
                    # out of witnesses fails CLOSED like the reference's
                    # ErrNoWitnesses: without cross-checking, a forking
                    # primary would go undetected.
                    self.witnesses.remove(w)
                    if not self.witnesses:
                        raise LightClientError(
                            "no witnesses remain after dropping faulty "
                            "ones; cannot cross-verify the primary")
                    continue
                raise ErrLightClientAttack(evidence)

    def _examine_divergence(self, witness: Provider,
                            trace: list[LightBlock],
                            conflicting: LightBlock, now: Timestamp):
        """detector.go examineConflictingHeaderAgainstTrace: walk the
        verified primary trace to the latest block the witness agrees
        with (the common block), verify the witness's own chain from
        there to the conflicting height, and if it verifies, this is a
        provable attack: build evidence for BOTH sides, report each to
        the opposing provider, and return the evidence against the
        primary (the caller raises)."""
        from ..types.evidence import (LightClientAttackEvidence,
                                      get_byzantine_validators)

        # find the latest common (agreed) block along the trace
        common = trace[0]
        for tb in trace[:-1]:
            try:
                wb = witness.light_block(tb.height)
            except ProviderError:
                break
            if wb.hash() != tb.hash():
                break
            common = tb
        # verify the witness's chain from the common root to the
        # conflicting header; failure = faulty witness, not an attack
        try:
            self._verify_skipping(witness, common, conflicting, now)
        except (LightClientError, ProviderError):
            return None

        target = trace[-1]
        ev_against_primary = LightClientAttackEvidence(
            conflicting_block=target,
            common_height=common.height,
            byzantine_validators=get_byzantine_validators(
                common.validator_set, conflicting.signed_header, target),
            total_voting_power=common.validator_set.total_voting_power(),
            timestamp=common.signed_header.header.time)
        ev_against_witness = LightClientAttackEvidence(
            conflicting_block=conflicting,
            common_height=common.height,
            byzantine_validators=get_byzantine_validators(
                common.validator_set, target.signed_header, conflicting),
            total_voting_power=common.validator_set.total_voting_power(),
            timestamp=common.signed_header.header.time)
        # each side learns about the other's misbehavior
        # (detector.go sends primary's evidence to witnesses and
        # vice versa); reporting failures don't mask the attack
        for provider, ev_item in ((witness, ev_against_primary),
                                  (self.primary, ev_against_witness)):
            try:
                provider.report_evidence(ev_item)
            except Exception:
                pass
        return ev_against_primary

    # -- provider plumbing -------------------------------------------------

    def _from_primary(self, height: int) -> LightBlock:
        try:
            return self.primary.light_block(height)
        except ProviderError:
            # primary failover: promote the first working witness
            # (client.go:1045 findNewPrimary)
            for i, w in enumerate(self.witnesses):
                try:
                    lb = w.light_block(height)
                except ProviderError:
                    continue
                self.witnesses.pop(i)
                self.witnesses.append(self.primary)
                self.primary = w
                return lb
            raise
