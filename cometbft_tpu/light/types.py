"""SignedHeader and LightBlock (types/light.go analog)."""

from __future__ import annotations

from dataclasses import dataclass

from ..libs import protowire as pw
from ..types.block import Commit, Header
from ..types.validator_set import ValidatorSet


@dataclass
class SignedHeader:
    """Header + the commit that sealed it (types/light.go:100)."""

    header: Header
    commit: Commit

    @property
    def height(self) -> int:
        return self.header.height

    @property
    def chain_id(self) -> str:
        return self.header.chain_id

    def hash(self) -> bytes | None:
        return self.header.hash()

    def validate_basic(self, chain_id: str) -> None:
        """types/light.go:134-162."""
        if self.header is None:
            raise ValueError("missing header")
        if self.commit is None:
            raise ValueError("missing commit")
        self.header.validate_basic()
        self.commit.validate_basic()
        if self.header.chain_id != chain_id:
            raise ValueError(
                f"header belongs to another chain {self.header.chain_id!r},"
                f" not {chain_id!r}")
        if self.commit.height != self.header.height:
            raise ValueError(
                f"header and commit height mismatch: {self.header.height} "
                f"vs {self.commit.height}")
        hhash = self.header.hash()
        if hhash != self.commit.block_id.hash:
            raise ValueError(
                f"commit signs block {self.commit.block_id.hash.hex()}, "
                f"header is block {hhash.hex()}")

    def to_proto(self) -> bytes:
        return (pw.Writer()
                .optional_message_field(1, self.header.to_proto())
                .optional_message_field(2, self.commit.to_proto())
                .bytes())

    @staticmethod
    def from_proto(payload: bytes) -> "SignedHeader":
        r = pw.Reader(payload)
        header = commit = None
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                header = Header.from_proto(r.read_bytes())
            elif f == 2:
                commit = Commit.from_proto(r.read_bytes())
            else:
                r.skip(w)
        return SignedHeader(header, commit)


@dataclass
class LightBlock:
    """SignedHeader + that height's validator set (types/light.go:28)."""

    signed_header: SignedHeader
    validator_set: ValidatorSet

    @property
    def height(self) -> int:
        return self.signed_header.height

    @property
    def header(self) -> Header:
        return self.signed_header.header

    def hash(self) -> bytes | None:
        return self.signed_header.hash()

    def validate_basic(self, chain_id: str) -> None:
        """types/light.go:46-72: both parts valid and consistent."""
        if self.signed_header is None:
            raise ValueError("missing signed header")
        if self.validator_set is None:
            raise ValueError("missing validator set")
        self.signed_header.validate_basic(chain_id)
        self.validator_set.validate_basic()
        if self.signed_header.header.validators_hash != \
                self.validator_set.hash():
            raise ValueError(
                "expected validator hash of header to match validator set")

    def to_proto(self) -> bytes:
        return (pw.Writer()
                .optional_message_field(1, self.signed_header.to_proto())
                .optional_message_field(2, self.validator_set.to_proto())
                .bytes())

    @staticmethod
    def from_proto(payload: bytes) -> "LightBlock":
        r = pw.Reader(payload)
        sh = vs = None
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                sh = SignedHeader.from_proto(r.read_bytes())
            elif f == 2:
                vs = ValidatorSet.from_proto(r.read_bytes())
            else:
                r.skip(w)
        return LightBlock(sh, vs)
