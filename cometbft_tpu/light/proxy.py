"""Light-client RPC proxy (reference cmd/cometbft/commands/light.go +
light/proxy/): serves a JSON-RPC subset where every header/commit
handed out has been light-verified against the trust root, so a wallet
can point at an untrusted full node through this proxy.
"""

from __future__ import annotations

import threading
from http.server import ThreadingHTTPServer

from ..rpc import serialize as ser
from ..rpc.server import _call_target, _err, make_json_handler


class LightProxy:
    """Verifying proxy over a light.Client."""

    def __init__(self, client, addr: str):
        self._client = client
        self._light_requests = 0
        self._light_headers = 0
        host, _, port = addr.replace("tcp://", "").rpartition(":")

        def dispatch(method, params, req_id):
            fn_name = _ROUTES.get(method)
            if fn_name is None:
                return _err(req_id, -32601,
                            f"method {method} not found (light proxy "
                            "serves verified routes only)")
            return _call_target(getattr(self, fn_name), params, req_id)

        self._httpd = ThreadingHTTPServer(
            (host or "127.0.0.1", int(port)),
            make_json_handler(dispatch, sorted(_ROUTES)))
        self._httpd.daemon_threads = True
        self.bound_addr = "%s:%d" % self._httpd.server_address
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="light-proxy",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()

    # -- verified handlers -------------------------------------------------

    def _verified_block(self, height):
        from ..types.timestamp import Timestamp
        h = int(height) if height else 0
        if h <= 0:
            lb = self._client.update(Timestamp.now())
            if lb is None:
                lb = self._client.latest_trusted()
        else:
            lb = self._client.verify_light_block_at_height(
                h, Timestamp.now())
        if lb is None:
            raise ValueError("no verifiable block")
        return lb

    def header(self, height=None) -> dict:
        lb = self._verified_block(height)
        return {"header": ser.header_json(lb.signed_header.header)}

    def commit(self, height=None) -> dict:
        lb = self._verified_block(height)
        return {
            "signed_header": {
                "header": ser.header_json(lb.signed_header.header),
                "commit": ser.commit_json(lb.signed_header.commit),
            },
            "canonical": True,
        }

    def validators(self, height=None) -> dict:
        lb = self._verified_block(height)
        vs = lb.validator_set
        return {
            "block_height": str(lb.height),
            "validators": [ser.validator_json(v) for v in vs.validators],
            "count": str(len(vs.validators)),
            "total": str(len(vs.validators)),
        }

    def status(self) -> dict:
        latest = self._client.latest_trusted()
        return {
            "node_info": {"moniker": "light-proxy"},
            "sync_info": {
                "latest_block_height":
                    str(latest.height) if latest else "0",
                "latest_block_hash":
                    ser.hex_upper(latest.hash()) if latest else "",
            },
        }

    # -- lightserve routes (same wire shape as rpc/core.py) ----------------

    def light_sync(self, trusted_height=None, target_height=None) -> dict:
        """Proxy-side light_sync: verify the target through the
        wrapped light client (the bisection trace lands in its trusted
        store), then serve the pivot-path blocks the store now holds —
        every block handed out went through verify_header."""
        import json

        from ..lightserve import skip_path
        from ..lightserve.codec import encode_payload

        target_lb = self._verified_block(target_height)
        target = target_lb.height
        trusted = int(trusted_height) if trusted_height else 0
        if trusted <= 0:
            first = self._client.store.first_light_block()
            trusted = first.height if first is not None else 1
        path = []
        blocks = []
        for h in skip_path(trusted, target):
            lb = target_lb if h == target \
                else self._client.trusted_light_block(h)
            if lb is None:
                continue
            path.append(h)
            blocks.append(json.loads(encode_payload(
                h, lb.signed_header.header, lb.signed_header.commit,
                lb.validator_set)))
        self._light_requests += 1
        self._light_headers += len(path)
        return {
            "trusted_height": str(trusted),
            "target_height": str(target),
            "path": [str(h) for h in path],
            "light_blocks": blocks,
            "coalesced": False,
        }

    def light_status(self) -> dict:
        latest = self._client.latest_trusted()
        first = self._client.store.first_light_block()
        return {
            "coalescing": False,
            "chain_id": self._client.chain_id,
            "latest_height": str(latest.height) if latest else "0",
            "base_height": str(first.height) if first else "0",
            "requests": str(self._light_requests),
            "headers_served": str(self._light_headers),
            "verify_windows": "0",
            "verify_sigs": "0",
            "failed_heights": "0",
            "coalesced_heights": "0",
            "inflight_heights": "0",
            "planner": {},
        }


_ROUTES = {"header": "header", "commit": "commit",
           "validators": "validators", "status": "status",
           "light_sync": "light_sync", "light_status": "light_status"}
