"""Remote signer: socket privval protocol
(reference privval/signer_client.go, signer_listener_endpoint.go,
signer_server.go, signer_dialer_endpoint.go, msgs.go).

Topology matches the reference: the NODE LISTENS on
`priv_validator_laddr`; the external signer process (HSM/KMS front-end)
DIALS IN and serves signing requests over one long-lived connection,
kept alive with pings.  Wire format: length-delimited protobuf
`privval.Message` (proto/cometbft/privval/v1/types.proto oneof tags
1-9), so an existing KMS speaking the CometBFT protocol lines up with
the same message framing.
"""

from __future__ import annotations

import socket
import threading
import time


from ..libs import lockrank
from ..libs import protowire as pw
from ..types.vote import Proposal, Vote

# Message oneof tags (types.proto:74-84)
T_PUBKEY_REQ = 1
T_PUBKEY_RESP = 2
T_SIGN_VOTE_REQ = 3
T_SIGNED_VOTE_RESP = 4
T_SIGN_PROPOSAL_REQ = 5
T_SIGNED_PROPOSAL_RESP = 6
T_PING_REQ = 7
T_PING_RESP = 8

DEFAULT_TIMEOUT_READ_WRITE = 5.0     # signer_endpoint.go
DEFAULT_TIMEOUT_ACCEPT = 30.0
DEFAULT_PING_INTERVAL = 3.0          # ~ timeout * 2/3


class RemoteSignerError(Exception):
    def __init__(self, code: int, description: str):
        super().__init__(f"remote signer error {code}: {description}")
        self.code = code
        self.description = description


def _wrap(tag: int, payload: bytes) -> bytes:
    return pw.Writer().message_field(tag, payload).bytes()


def _unwrap(raw: bytes) -> tuple[int, bytes]:
    r = pw.Reader(raw)
    while not r.at_end():
        f, w = r.read_tag()
        if w == pw.BYTES:
            return f, r.read_bytes()
        r.skip(w)
    raise ValueError("empty privval message")


def _err_proto(code: int, desc: str) -> bytes:
    return (pw.Writer().int_field(1, code)
            .string_field(2, desc).bytes())


def _parse_err(payload: bytes) -> RemoteSignerError:
    r = pw.Reader(payload)
    code, desc = 0, ""
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1 and w == pw.VARINT:
            code = r.read_int()
        elif f == 2 and w == pw.BYTES:
            desc = r.read_string()
        else:
            r.skip(w)
    return RemoteSignerError(code, desc)


class IdleTimeout(Exception):
    """Read timed out before ANY byte arrived — the stream is still in
    sync and the caller may safely retry."""


def _send_msg(sock: socket.socket, data: bytes) -> None:
    sock.sendall(pw.marshal_delimited(data))


def _recv_msg(sock: socket.socket) -> bytes | None:
    """Length-delimited read (libs/protoio semantics).

    A timeout with zero bytes consumed raises IdleTimeout (retryable);
    a timeout MID-message raises ValueError — the framing is desynced
    and the connection must be dropped."""
    n, shift, consumed = 0, 0, False
    while True:
        try:
            b = sock.recv(1)
        except socket.timeout:
            if not consumed:
                raise IdleTimeout() from None
            raise ValueError("timeout mid-message: stream desynced") \
                from None
        if not b:
            return None
        consumed = True
        n |= (b[0] & 0x7F) << shift
        if not (b[0] & 0x80):
            break
        shift += 7
        if shift > 35:
            raise ValueError("varint too long")
    if n > 1 << 20:
        raise ValueError("privval message too large")
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            raise ValueError("timeout mid-message: stream desynced") \
                from None
        if not chunk:
            return None
        buf += chunk
    return buf


class SignerListenerEndpoint:
    """Node side: accepts the signer's inbound connection and issues
    requests over it (signer_listener_endpoint.go)."""

    def __init__(self, addr: str,
                 timeout_read_write: float = DEFAULT_TIMEOUT_READ_WRITE,
                 timeout_accept: float = DEFAULT_TIMEOUT_ACCEPT):
        host, _, port = addr.replace("tcp://", "").rpartition(":")
        self._listener = socket.create_server(
            (host or "127.0.0.1", int(port)))
        self._listener.settimeout(timeout_accept)
        self.bound_addr = "%s:%d" % self._listener.getsockname()[:2]
        self._timeout = timeout_read_write
        self._conn: socket.socket | None = None
        self._mtx = lockrank.RankedLock("privval.signer")
        self._connected = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="privval-accept", daemon=True)
        self._stopped = False
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                conn, _ = self._listener.accept()
            except (socket.timeout, OSError):
                if self._stopped:
                    return
                continue
            conn.settimeout(self._timeout)
            with self._mtx:
                if self._conn is not None:
                    try:
                        self._conn.close()
                    except OSError:
                        pass
                self._conn = conn
            self._connected.set()

    def wait_for_connection(self, max_wait: float) -> bool:
        return self._connected.wait(timeout=max_wait)

    def is_connected(self) -> bool:
        return self._connected.is_set()

    def send_request(self, tag: int, payload: bytes) -> tuple[int, bytes]:
        with self._mtx:
            conn = self._conn
            if conn is None:
                raise RemoteSignerError(-1, "no signer connected")
            try:
                _send_msg(conn, _wrap(tag, payload))
                raw = _recv_msg(conn)
            except (OSError, socket.timeout, IdleTimeout,
                    ValueError) as e:
                # on the requester side ANY timeout/desync is fatal for
                # this connection: the in-flight request is lost
                self._drop_conn_locked()
                raise RemoteSignerError(-1, f"connection failed: {e}")
            if raw is None:
                self._drop_conn_locked()
                raise RemoteSignerError(-1, "signer closed connection")
            return _unwrap(raw)

    def _drop_conn_locked(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None
        self._connected.clear()

    def close(self) -> None:
        self._stopped = True
        with self._mtx:
            self._drop_conn_locked()
        try:
            self._listener.close()
        except OSError:
            pass


def _resp_field(payload: bytes, data_field: int,
                err_field: int = 2) -> bytes:
    """Extract `data_field` from a response, raising any RemoteSignerError."""
    r = pw.Reader(payload)
    data = b""
    err = None
    while not r.at_end():
        f, w = r.read_tag()
        if f == data_field and w == pw.BYTES:
            data = r.read_bytes()
        elif f == err_field and w == pw.BYTES:
            err = _parse_err(r.read_bytes())
        else:
            r.skip(w)
    if err is not None and (err.code or err.description):
        raise err
    return data


class SignerClient:
    """types.PrivValidator backed by the remote signer
    (signer_client.go) — drop-in for FilePV in the consensus state."""

    def __init__(self, endpoint: SignerListenerEndpoint, chain_id: str):
        self.endpoint = endpoint
        self.chain_id = chain_id

    def ping(self) -> bool:
        try:
            tag, _ = self.endpoint.send_request(T_PING_REQ, b"")
            return tag == T_PING_RESP
        except RemoteSignerError:
            return False

    def get_pub_key(self):
        from ..crypto import encoding as enc

        req = pw.Writer().string_field(1, self.chain_id).bytes()
        tag, payload = self.endpoint.send_request(T_PUBKEY_REQ, req)
        if tag != T_PUBKEY_RESP:
            raise RemoteSignerError(-1, f"unexpected response tag {tag}")
        r = pw.Reader(payload)
        key_bytes, key_type, err = b"", "", None
        while not r.at_end():
            f, w = r.read_tag()
            if f == 3 and w == pw.BYTES:
                key_bytes = r.read_bytes()
            elif f == 4 and w == pw.BYTES:
                key_type = r.read_string()
            elif f == 2 and w == pw.BYTES:
                err = _parse_err(r.read_bytes())
            else:
                r.skip(w)
        if err is not None and (err.code or err.description):
            raise err
        return enc.make_pubkey(key_type, key_bytes)

    def sign_vote(self, chain_id: str, vote: Vote,
                  sign_extension: bool = False) -> None:
        req = (pw.Writer()
               .message_field(1, vote.to_proto())
               .string_field(2, chain_id)
               .bool_field(3, not sign_extension).bytes())
        tag, payload = self.endpoint.send_request(T_SIGN_VOTE_REQ, req)
        if tag != T_SIGNED_VOTE_RESP:
            raise RemoteSignerError(-1, f"unexpected response tag {tag}")
        signed = Vote.from_proto(_resp_field(payload, 1))
        vote.signature = signed.signature
        vote.extension_signature = signed.extension_signature
        vote.timestamp = signed.timestamp

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        req = (pw.Writer()
               .message_field(1, proposal.to_proto())
               .string_field(2, chain_id).bytes())
        tag, payload = self.endpoint.send_request(T_SIGN_PROPOSAL_REQ, req)
        if tag != T_SIGNED_PROPOSAL_RESP:
            raise RemoteSignerError(-1, f"unexpected response tag {tag}")
        signed = Proposal.from_proto(_resp_field(payload, 1))
        proposal.signature = signed.signature
        proposal.timestamp = signed.timestamp


class SignerServer:
    """External signer process: dials the node and serves its FilePV
    over the socket (signer_server.go + signer_dialer_endpoint.go)."""

    def __init__(self, addr: str, chain_id: str, priv_validator,
                 timeout_read_write: float = DEFAULT_TIMEOUT_READ_WRITE,
                 max_retries: int = 10, retry_wait: float = 0.1):
        self.addr = addr.replace("tcp://", "")
        self.chain_id = chain_id
        self.pv = priv_validator
        self._timeout = timeout_read_write
        self._max_retries = max_retries
        self._retry_wait = retry_wait
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._run, name="signer-server", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _dial(self) -> socket.socket | None:
        host, _, port = self.addr.rpartition(":")
        for _ in range(self._max_retries):
            if self._stopped.is_set():
                return None
            try:
                conn = socket.create_connection(
                    (host, int(port)), timeout=self._timeout)
                conn.settimeout(self._timeout)
                return conn
            except OSError:
                time.sleep(self._retry_wait)
        return None

    def _run(self) -> None:
        while not self._stopped.is_set():
            conn = self._dial()
            if conn is None:
                return
            try:
                self._serve(conn)
            except (OSError, ValueError):
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _serve(self, conn: socket.socket) -> None:
        while not self._stopped.is_set():
            try:
                raw = _recv_msg(conn)
            except IdleTimeout:
                continue
            if raw is None:
                return
            tag, payload = _unwrap(raw)
            _send_msg(conn, self._handle(tag, payload))

    # signer_requestHandler.go DefaultValidationRequestHandler
    def _handle(self, tag: int, payload: bytes) -> bytes:
        if tag == T_PING_REQ:
            return _wrap(T_PING_RESP, b"")
        if tag == T_PUBKEY_REQ:
            pub = self.pv.get_pub_key()
            resp = (pw.Writer()
                    .bytes_field(3, pub.bytes())
                    .string_field(4, pub.type()).bytes())
            return _wrap(T_PUBKEY_RESP, resp)
        if tag == T_SIGN_VOTE_REQ:
            r = pw.Reader(payload)
            vote, chain_id, skip_ext = None, self.chain_id, False
            while not r.at_end():
                f, w = r.read_tag()
                if f == 1 and w == pw.BYTES:
                    vote = Vote.from_proto(r.read_bytes())
                elif f == 2 and w == pw.BYTES:
                    chain_id = r.read_string()
                elif f == 3 and w == pw.VARINT:
                    skip_ext = bool(r.read_uvarint())
                else:
                    r.skip(w)
            try:
                self.pv.sign_vote(chain_id, vote,
                                  sign_extension=not skip_ext)
                resp = pw.Writer().message_field(1, vote.to_proto()).bytes()
            except Exception as e:
                resp = pw.Writer().message_field(
                    2, _err_proto(1, str(e))).bytes()
            return _wrap(T_SIGNED_VOTE_RESP, resp)
        if tag == T_SIGN_PROPOSAL_REQ:
            r = pw.Reader(payload)
            proposal, chain_id = None, self.chain_id
            while not r.at_end():
                f, w = r.read_tag()
                if f == 1 and w == pw.BYTES:
                    proposal = Proposal.from_proto(r.read_bytes())
                elif f == 2 and w == pw.BYTES:
                    chain_id = r.read_string()
                else:
                    r.skip(w)
            try:
                self.pv.sign_proposal(chain_id, proposal)
                resp = pw.Writer().message_field(
                    1, proposal.to_proto()).bytes()
            except Exception as e:
                resp = pw.Writer().message_field(
                    2, _err_proto(1, str(e))).bytes()
            return _wrap(T_SIGNED_PROPOSAL_RESP, resp)
        return _wrap(tag + 1, pw.Writer().message_field(
            2, _err_proto(2, f"unsupported request tag {tag}")).bytes())
