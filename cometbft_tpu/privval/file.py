"""File-backed private validator with double-sign protection
(reference privval/file.go).

The LastSignState is persisted BEFORE a signature is released, so a
crash between signing and gossip can never produce two different
signatures for one (height, round, step): on restart, a re-sign of the
same HRS either replays the saved signature (same sign-bytes, or
differing only in timestamp) or errors out.
"""

from __future__ import annotations

import json
import os
import tempfile
from dataclasses import dataclass, field

from ..crypto import ed25519
from ..types import canonical
from ..types.timestamp import Timestamp
from ..types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE, Proposal, Vote

STEP_NONE = 0
STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3


def vote_to_step(vote: Vote) -> int:
    if vote.type == PREVOTE_TYPE:
        return STEP_PREVOTE
    if vote.type == PRECOMMIT_TYPE:
        return STEP_PRECOMMIT
    raise ValueError(f"unknown vote type {vote.type}")


def _write_file_atomic(path: str, data: bytes, mode: int = 0o600) -> None:
    """internal/tempfile analog: write-rename so readers never see a
    torn file."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-privval-")
    try:
        try:
            os.write(fd, data)
            os.fsync(fd)
        finally:
            os.close(fd)
        os.chmod(tmp, mode)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


class DoubleSignError(Exception):
    pass


@dataclass
class LastSignState:
    """privval/file.go FilePVLastSignState."""

    height: int = 0
    round: int = 0
    step: int = STEP_NONE
    signature: bytes = b""
    sign_bytes: bytes = b""
    file_path: str = ""

    def check_hrs(self, height: int, round_: int, step: int) -> bool:
        """True when (h,r,s) matches the last signed state and the
        previous signature should be replayed (file.go:100)."""
        if self.height > height:
            raise DoubleSignError(
                f"height regression: got {height}, last {self.height}")
        if self.height != height:
            return False
        if self.round > round_:
            raise DoubleSignError(
                f"round regression at height {height}: got {round_}, "
                f"last {self.round}")
        if self.round != round_:
            return False
        if self.step > step:
            raise DoubleSignError(
                f"step regression at {height}/{round_}: got {step}, "
                f"last {self.step}")
        if self.step == step:
            if not self.sign_bytes:
                raise DoubleSignError("no SignBytes found")
            if not self.signature:
                raise RuntimeError("signature absent with SignBytes present")
            return True
        return False

    def save(self) -> None:
        if not self.file_path:
            return
        payload = json.dumps({
            "height": str(self.height),
            "round": self.round,
            "step": self.step,
            "signature": self.signature.hex().upper(),
            "signbytes": self.sign_bytes.hex().upper(),
        }, indent=2).encode()
        _write_file_atomic(self.file_path, payload)

    @staticmethod
    def load(path: str) -> "LastSignState":
        with open(path, "rb") as f:
            obj = json.loads(f.read())
        return LastSignState(
            height=int(obj.get("height", "0")),
            round=int(obj.get("round", 0)),
            step=int(obj.get("step", 0)),
            signature=bytes.fromhex(obj.get("signature", "")),
            sign_bytes=bytes.fromhex(obj.get("signbytes", "")),
            file_path=path)

    def reset(self) -> None:
        self.height = 0
        self.round = 0
        self.step = STEP_NONE
        self.signature = b""
        self.sign_bytes = b""


def _only_differ_by_timestamp(last: bytes, new: bytes, ts_field: int
                              ) -> tuple[Timestamp | None, bool]:
    """file.go:442: equal after stripping the canonical timestamp."""
    if not last:
        return None, False
    last_z, last_ts = canonical.split_timestamp(last, ts_field)
    new_z, _ = canonical.split_timestamp(new, ts_field)
    if last_z == new_z:
        return last_ts, True
    return None, False


@dataclass
class FilePVKey:
    address: bytes = b""
    pub_key: object = None
    priv_key: object = None
    file_path: str = ""

    def save(self) -> None:
        if not self.file_path:
            return
        from ..libs import tmjson
        payload = json.dumps({
            "address": self.address.hex().upper(),
            "pub_key": tmjson.to_obj(self.pub_key),
            "priv_key": tmjson.to_obj(self.priv_key),
        }, indent=2).encode()
        _write_file_atomic(self.file_path, payload)

    @staticmethod
    def load(path: str) -> "FilePVKey":
        with open(path, "rb") as f:
            obj = json.loads(f.read())
        from ..libs import tmjson
        priv = tmjson.from_obj(obj["priv_key"])
        if isinstance(priv, dict):      # untyped legacy file: ed25519
            import base64
            priv = ed25519.PrivKey(
                base64.b64decode(obj["priv_key"]["value"]))
        pub = priv.pub_key()
        return FilePVKey(address=pub.address(), pub_key=pub, priv_key=priv,
                         file_path=path)


def _b64(b: bytes) -> str:
    import base64
    return base64.b64encode(b).decode()


class FilePV:
    """types.PrivValidator backed by two JSON files: key (immutable) and
    last-sign-state (mutable, saved before every signature release)."""

    def __init__(self, priv_key, key_file_path: str = "",
                 state_file_path: str = ""):
        pub = priv_key.pub_key()
        self.key = FilePVKey(address=pub.address(), pub_key=pub,
                             priv_key=priv_key, file_path=key_file_path)
        self.last_sign_state = LastSignState(file_path=state_file_path)

    # -- constructors ------------------------------------------------------
    @staticmethod
    def generate(key_file_path: str = "", state_file_path: str = "",
                 seed: bytes | None = None,
                 key_type: str = "ed25519") -> "FilePV":
        """privval/file.go GenFilePV; key_type mirrors the reference's
        `cometbft init --key-type` (ed25519 | secp256k1 | sr25519 —
        validator params additionally restrict which may validate)."""
        if key_type == "ed25519":
            priv = ed25519.PrivKey.generate(seed)
        elif key_type == "secp256k1":
            from ..crypto import secp256k1
            priv = secp256k1.PrivKey.generate(seed)
        elif key_type == "sr25519":
            from ..crypto import sr25519
            priv = sr25519.PrivKey.generate(seed)
        else:
            raise ValueError(f"unsupported key type {key_type!r}")
        return FilePV(priv, key_file_path, state_file_path)

    @staticmethod
    def load(key_file_path: str, state_file_path: str) -> "FilePV":
        key = FilePVKey.load(key_file_path)
        pv = FilePV(key.priv_key, key_file_path, state_file_path)
        if os.path.exists(state_file_path) and \
                os.path.getsize(state_file_path) > 0:
            pv.last_sign_state = LastSignState.load(state_file_path)
        return pv

    @staticmethod
    def load_or_generate(key_file_path: str, state_file_path: str,
                         key_type: str = "ed25519") -> "FilePV":
        if os.path.exists(key_file_path):
            return FilePV.load(key_file_path, state_file_path)
        pv = FilePV.generate(key_file_path, state_file_path,
                             key_type=key_type)
        pv.save()
        return pv

    # -- PrivValidator interface ------------------------------------------
    def get_address(self) -> bytes:
        return self.key.address

    def get_pub_key(self):
        return self.key.pub_key

    def sign_vote(self, chain_id: str, vote: Vote,
                  sign_extension: bool = False) -> None:
        """Sets vote.signature (and extension_signature); enforces the
        HRS double-sign rules (file.go:319)."""
        height, round_, step = vote.height, vote.round, vote_to_step(vote)
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)

        sign_bytes = vote.sign_bytes(chain_id)

        if sign_extension:
            # extensions are app-nondeterministic: always re-sign them
            # (file.go:331-349)
            if vote.type == PRECOMMIT_TYPE and not vote.block_id.is_nil():
                vote.extension_signature = self.key.priv_key.sign(
                    vote.extension_sign_bytes(chain_id))
            elif vote.extension:
                raise ValueError(
                    "unexpected vote extension on non-commit vote")
            else:
                vote.extension_signature = b""

        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                vote.signature = lss.signature
                return
            ts, ok = _only_differ_by_timestamp(
                lss.sign_bytes, sign_bytes, canonical.VOTE_TIMESTAMP_FIELD)
            if ok:
                vote.timestamp = ts
                vote.signature = lss.signature
                return
            raise DoubleSignError("conflicting data")

        sig = self.key.priv_key.sign(sign_bytes)
        self._save_signed(height, round_, step, sign_bytes, sig)
        vote.signature = sig

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        height, round_ = proposal.height, proposal.round
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, STEP_PROPOSE)

        sign_bytes = proposal.sign_bytes(chain_id)
        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                proposal.signature = lss.signature
                return
            ts, ok = _only_differ_by_timestamp(
                lss.sign_bytes, sign_bytes,
                canonical.PROPOSAL_TIMESTAMP_FIELD)
            if ok:
                proposal.timestamp = ts
                proposal.signature = lss.signature
                return
            raise DoubleSignError("conflicting data")

        sig = self.key.priv_key.sign(sign_bytes)
        self._save_signed(height, round_, STEP_PROPOSE, sign_bytes, sig)
        proposal.signature = sig

    def sign_bytes_raw(self, data: bytes) -> bytes:
        """file.go:285 SignBytes — arbitrary payloads (p2p auth etc)."""
        return self.key.priv_key.sign(data)

    # -- persistence -------------------------------------------------------
    def save(self) -> None:
        self.key.save()
        self.last_sign_state.save()

    def reset(self) -> None:
        self.last_sign_state.reset()
        self.save()

    def _save_signed(self, height: int, round_: int, step: int,
                     sign_bytes: bytes, sig: bytes) -> None:
        lss = self.last_sign_state
        lss.height = height
        lss.round = round_
        lss.step = step
        lss.signature = sig
        lss.sign_bytes = sign_bytes
        lss.save()
