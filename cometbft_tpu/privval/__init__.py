"""Validator key management (reference privval/)."""

from .file import FilePV, LastSignState  # noqa: F401
