"""Blocksync wire messages
(reference proto/cometbft/blocksync/v1/types.proto)."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..libs import protowire as pw
from ..types.block import Block, ExtendedCommit


@dataclass
class BlockRequest:
    height: int = 0
    FIELD = 1

    def to_proto(self) -> bytes:
        return pw.Writer().int_field(1, self.height).bytes()

    @staticmethod
    def from_proto(p: bytes) -> "BlockRequest":
        return BlockRequest(_read_height(p))


@dataclass
class NoBlockResponse:
    height: int = 0
    FIELD = 2

    def to_proto(self) -> bytes:
        return pw.Writer().int_field(1, self.height).bytes()

    @staticmethod
    def from_proto(p: bytes) -> "NoBlockResponse":
        return NoBlockResponse(_read_height(p))


@dataclass
class BlockResponse:
    block: Block | None = None
    ext_commit: ExtendedCommit | None = None
    FIELD = 3

    def to_proto(self) -> bytes:
        w = pw.Writer()
        if self.block is not None:
            w.message_field(1, self.block.to_proto())
        if self.ext_commit is not None:
            w.message_field(2, self.ext_commit.to_proto())
        return w.bytes()

    @staticmethod
    def from_proto(p: bytes) -> "BlockResponse":
        r = pw.Reader(p)
        m = BlockResponse()
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1 and w == pw.BYTES:
                m.block = Block.from_proto(r.read_bytes())
            elif f == 2 and w == pw.BYTES:
                m.ext_commit = ExtendedCommit.from_proto(r.read_bytes())
            else:
                r.skip(w)
        return m


@dataclass
class StatusRequest:
    FIELD = 4

    def to_proto(self) -> bytes:
        return b""

    @staticmethod
    def from_proto(p: bytes) -> "StatusRequest":
        return StatusRequest()


@dataclass
class StatusResponse:
    height: int = 0
    base: int = 0
    FIELD = 5

    def to_proto(self) -> bytes:
        return (pw.Writer().int_field(1, self.height)
                .int_field(2, self.base).bytes())

    @staticmethod
    def from_proto(p: bytes) -> "StatusResponse":
        r = pw.Reader(p)
        m = StatusResponse()
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1 and w == pw.VARINT:
                m.height = r.read_int()
            elif f == 2 and w == pw.VARINT:
                m.base = r.read_int()
            else:
                r.skip(w)
        return m


def _read_height(p: bytes) -> int:
    r = pw.Reader(p)
    h = 0
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1 and w == pw.VARINT:
            h = r.read_int()
        else:
            r.skip(w)
    return h


_TYPES = (BlockRequest, NoBlockResponse, BlockResponse, StatusRequest,
          StatusResponse)
_BY_FIELD = {cls.FIELD: cls for cls in _TYPES}


def wrap(msg) -> bytes:
    return pw.Writer().message_field(msg.FIELD, msg.to_proto()).bytes()


def wrap_block_response_bytes(block_bytes: bytes,
                              ext_commit=None) -> bytes:
    """The wrapped BlockResponse built straight from serialized block
    wire bytes — byte-identical to wrap(BlockResponse(block, ext))
    because block.to_proto() IS block_bytes.  The serve path uses this
    with BlockStore.load_block_bytes so a cache hit never decodes or
    re-encodes the block."""
    w = pw.Writer().message_field(1, block_bytes)
    if ext_commit is not None:
        w.message_field(2, ext_commit.to_proto())
    return (pw.Writer()
            .message_field(BlockResponse.FIELD, w.bytes()).bytes())


def unwrap(payload: bytes):
    r = pw.Reader(payload)
    while not r.at_end():
        f, w = r.read_tag()
        if w == pw.BYTES and f in _BY_FIELD:
            return _BY_FIELD[f].from_proto(r.read_bytes())
        r.skip(w)
    raise ValueError("empty blocksync Message")
