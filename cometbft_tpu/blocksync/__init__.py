"""Block sync (fast sync): catch up by downloading committed blocks
from peers (reference internal/blocksync/)."""

from .pool import BlockPool  # noqa: F401
from .reactor import BlocksyncReactor  # noqa: F401
