"""Blocksync reactor (reference internal/blocksync/reactor.go).

Channel 0x40. Serves stored blocks to catching-up peers; when started
in sync mode, drives a BlockPool and applies downloaded blocks after
verifying each with the NEXT block's LastCommit — the TPU-routed
`verify_commit_light` at reactor.go:546, the second BASELINE hot path.
On catch-up it hands off to the consensus reactor (SwitchToConsensus).
"""

from __future__ import annotations

import os
import threading
import time

from ..crypto import sigcache
from ..libs import tracetl
from ..libs.trace import span as trace_span
from ..p2p.base_reactor import Envelope, Reactor
from ..p2p.conn.connection import ChannelDescriptor
from ..types.block import BlockID
from ..types.part_set import PartSet
from . import messages as bm
from .pool import BlockPool

BLOCKSYNC_CHANNEL = 0x40
TRY_SYNC_INTERVAL = 0.01
# blocks whose LastCommit sigs batch into one device dispatch
# 48 from the r4b on-TPU depth sweep (ab_round4b_results.jsonl
# prod3_blocksync at 10k validators): monotone through 48 (159.7 at
# 24 vs 181.6 at 48 under the full kernel stack).  The pool keeps
# MAX_PENDING_REQUESTS=64 blocks in flight so a full window can fill.
VERIFY_WINDOW = 48
STATUS_UPDATE_INTERVAL = 10.0
SWITCH_TO_CONSENSUS_INTERVAL = 1.0
# overlapped verify pipeline depth (crypto/dispatch.py): collect+pack
# window N+1 while window N is on device and window N-1 applies/stores.
# 1 = the serial path; 2 = double buffering (the default)
PIPELINE_DEPTH = int(os.environ.get(
    "COMETBFT_TPU_BLOCKSYNC_PIPELINE", "2"))
# mesh round-robin for the verify pipeline: windows rotate over this
# many devices (ops/sharding.mesh_device_list semantics — 0 defers to
# COMETBFT_TPU_MESH_DEVICES, which is off unless set; -1/0-via-env
# means all local devices)
MESH_DEVICES = int(os.environ.get(
    "COMETBFT_TPU_BLOCKSYNC_MESH_DEVICES", "0"))
# QoS lane override for blocksync verify windows (crypto/sched.py):
# empty = schedule under the blocksync lane itself.  An operator
# catching a node up BEFORE it may join consensus can re-lane the sync
# traffic urgent (e.g. "light" or "evidence" class) — attribution
# (trace/ledger/cache) stays blocksync either way.
SCHED_LANE = os.environ.get(
    "COMETBFT_TPU_SCHED_BLOCKSYNC_LANE", "") or None


class BlocksyncReactor(Reactor):
    def __init__(self, state, block_exec, block_store, block_sync: bool,
                 consensus_reactor=None, peer_timeout: float | None = None):
        super().__init__("BlocksyncReactor")
        self.initial_state = state
        self.state = state
        self.block_exec = block_exec
        self.store = block_store
        self.block_sync = block_sync       # actively syncing?
        self.consensus_reactor = consensus_reactor
        self.peer_timeout = peer_timeout   # None -> pool.PEER_TIMEOUT
        self.pool = BlockPool(
            max(self.store.height() + 1, state.initial_height),
            self._send_block_request, self._on_peer_error,
            peer_timeout=peer_timeout)
        self._stop_sync = threading.Event()
        self.synced = not block_sync
        self.metrics = None        # BlockSyncMetrics when the node meters
        self.timeline = None       # per-node event timeline (tracetl)
        self.pipeline_depth = PIPELINE_DEPTH
        self.mesh_devices = MESH_DEVICES
        self._pipeline = None      # crypto/dispatch.VerifyPipeline

    def get_channels(self) -> list:
        return [ChannelDescriptor(
            BLOCKSYNC_CHANNEL, priority=5,
            send_queue_capacity=1000,
            recv_message_capacity=150 * 1024 * 1024)]

    def on_start(self) -> None:
        if self.metrics is not None:
            self.metrics.syncing.set(1 if self.block_sync else 0)
        if self.block_sync:
            self.pool.start()
            threading.Thread(target=self._pool_routine,
                             name="blocksync-pool", daemon=True).start()

    def on_stop(self) -> None:
        self._stop_sync.set()
        self.pool.stop()
        if self._pipeline is not None:
            self._pipeline.stop()
            self._pipeline = None

    def _get_pipeline(self):
        # return the LOCAL reference: on_stop may null self._pipeline
        # concurrently, and re-reading the attribute here handed the
        # pool routine a None mid-shutdown
        pipe = self._pipeline
        if pipe is None or not pipe.is_running():
            from ..crypto.dispatch import VerifyPipeline
            from ..ops import sharding
            devices = sharding.mesh_device_list(self.mesh_devices
                                                or None)
            depth = self.pipeline_depth if devices is None else \
                max(self.pipeline_depth, 2 * len(devices))
            pipe = VerifyPipeline(
                depth=depth, name="blocksync-pipeline",
                devices=devices if devices is not None else ())
            pipe.start()
            self._pipeline = pipe
        return pipe

    def switch_to_blocksync(self, state) -> None:
        """Begin block-syncing from a statesync-bootstrapped state
        (reference internal/blocksync/reactor.go SwitchToBlockSync):
        re-base the pool past the snapshot height and start the
        poolRoutine that was skipped at node start."""
        self.state = state
        self.initial_state = state
        self.synced = False
        self.block_sync = True
        if self.metrics is not None:
            self.metrics.syncing.set(1)
        self.pool = BlockPool(max(self.store.height() + 1,
                                  state.last_block_height + 1,
                                  state.initial_height),
                              self._send_block_request,
                              self._on_peer_error,
                              peer_timeout=self.peer_timeout)
        for peer in (self.switch.peers.list() if self.switch else []):
            peer.try_send(BLOCKSYNC_CHANNEL, bm.wrap(bm.StatusRequest()))
        self.pool.start()
        threading.Thread(target=self._pool_routine,
                         name="blocksync-pool", daemon=True).start()

    # -- peer lifecycle ----------------------------------------------------
    def add_peer(self, peer) -> None:
        peer.try_send(BLOCKSYNC_CHANNEL, bm.wrap(bm.StatusResponse(
            height=self.store.height(), base=self.store.base())))

    def remove_peer(self, peer, reason) -> None:
        self.pool.remove_peer(peer.id)

    # -- plumbing for the pool --------------------------------------------
    def _send_block_request(self, height: int, peer_id: str) -> None:
        peer = self.switch.peers.get(peer_id) if self.switch else None
        if peer is None:
            raise RuntimeError(f"peer {peer_id} gone")
        if not peer.try_send(BLOCKSYNC_CHANNEL,
                             bm.wrap(bm.BlockRequest(height))):
            raise RuntimeError(f"peer {peer_id} send queue full")

    def _on_peer_error(self, peer_id: str, reason: str) -> None:
        if self.switch is None:
            return
        peer = self.switch.peers.get(peer_id)
        if peer is not None:
            self.switch.stop_peer_for_error(peer, reason)

    # -- receive -----------------------------------------------------------
    def receive(self, envelope: Envelope) -> None:
        with trace_span("blocksync", "decode"), \
                tracetl.span_for(self, "blocksync", "decode"):
            msg = bm.unwrap(bytes(envelope.message))
        if envelope.tctx is not None:
            tl = tracetl.active(self)
            if tl is not None:
                tl.recv("blocksync", type(msg).__name__, envelope.tctx)
        peer = envelope.src
        if isinstance(msg, bm.BlockRequest):
            self._respond_to_block_request(peer, msg.height)
        elif isinstance(msg, bm.StatusRequest):
            peer.try_send(BLOCKSYNC_CHANNEL, bm.wrap(bm.StatusResponse(
                height=self.store.height(), base=self.store.base())))
        elif isinstance(msg, bm.BlockResponse):
            if msg.block is not None:
                self.pool.add_block(peer.id, msg.block, msg.ext_commit)
        elif isinstance(msg, bm.StatusResponse):
            self.pool.set_peer_range(peer.id, msg.base, msg.height)
        elif isinstance(msg, bm.NoBlockResponse):
            self.pool.no_block_response(peer.id, msg.height)

    def _respond_to_block_request(self, peer, height: int) -> None:
        # serve the serialized block directly: on a warm cache
        # (store.load_block_bytes) this is a bytes splice — no block
        # decode, no re-encode, no part split
        block_bytes = self.store.load_block_bytes(height)
        if block_bytes is None:
            peer.try_send(BLOCKSYNC_CHANNEL,
                          bm.wrap(bm.NoBlockResponse(height)))
            return
        ext = None
        raw_ext = self.store.load_extended_commit(height)
        if raw_ext is not None:
            from ..types.block import ExtendedCommit
            ext = ExtendedCommit.from_proto(raw_ext) \
                if isinstance(raw_ext, (bytes, bytearray)) else raw_ext
        tctx = None
        tl = tracetl.active(self)
        if tl is not None:
            # causal edge: the requester's recv ties its apply work to
            # this serve (round 0 — blocksync is height-only)
            tctx = tl.ctx(height, 0)
            tl.send("blocksync", "BlockResponse", tctx)
        peer.try_send(BLOCKSYNC_CHANNEL,
                      bm.wrap_block_response_bytes(block_bytes, ext),
                      tctx=tctx)

    # -- sync driver -------------------------------------------------------
    def _pool_routine(self) -> None:
        """reactor.go:306 poolRoutine."""
        last_status = 0.0
        last_switch_check = 0.0
        while not self._stop_sync.is_set() and self.is_running():
            now = time.monotonic()
            if now - last_status > STATUS_UPDATE_INTERVAL:
                last_status = now
                if self.switch is not None:
                    self.switch.try_broadcast(
                        BLOCKSYNC_CHANNEL, bm.wrap(bm.StatusRequest()))
            if now - last_switch_check > SWITCH_TO_CONSENSUS_INTERVAL:
                last_switch_check = now
                if self._maybe_switch_to_consensus():
                    return
            if not self._try_sync_one():
                time.sleep(TRY_SYNC_INTERVAL)

    def _try_sync_one(self) -> bool:
        """reactor.go:534 processBlock, WINDOWED: all the LastCommit
        signature checks for a run of downloaded blocks batch into ONE
        device dispatch (types.DeferredSigBatch — the BASELINE
        'blocksync replay' configuration), then blocks apply one by
        one.  Batching beyond the next height is gated on the headers
        carrying the CURRENT next_validators hash; a lying header
        cannot commit anything — apply-time validate_block re-checks
        the executed validator set before each block lands.

        With pipeline_depth >= 2 the overlapped path runs instead:
        window N+1 collects and host-packs while window N's dispatch
        is in flight on device and window N-1 applies/stores
        (_sync_pipelined); depth 1 keeps the strictly serial loop."""
        if self.pipeline_depth >= 2:
            return self._sync_pipelined()
        return self._sync_serial()

    def _sync_serial(self) -> bool:
        from ..types.validation import DeferredSigBatch

        window, after = self.pool.peek_window(VERIFY_WINDOW)
        usable = len(window) if after is not None else len(window) - 1
        if usable < 1:
            return False
        # a missing extended commit makes its block unusable — gate the
        # window BEFORE burning a device dispatch (reactor.go:540)
        for i in range(usable):
            block, ext = window[i]
            if ext is None and self.state.consensus_params \
                    .vote_extensions_enabled(block.header.height):
                if i == 0:
                    for pid in self.pool.redo_request(
                            block.header.height):
                        self._on_peer_error(pid,
                                            "missing extended commit")
                    return False
                usable = i
                break
        # quantize to a power of two so the device sees few distinct
        # batch shapes (each new shape is a one-off compile)
        while usable & (usable - 1):
            usable &= usable - 1
        blocks = [b for b, _ in window]
        commits = []
        for i in range(usable):
            nxt = blocks[i + 1] if i + 1 < len(window) else after
            commits.append(nxt.last_commit)

        # valset per window offset: exact for +0/+1; further only while
        # headers pin the unchanged next_validators hash
        next_hash = self.state.next_validators.hash() \
            if self.state.next_validators else None
        batch = DeferredSigBatch()
        verified = 0
        parts_ids = []
        collecting_h = None
        try:
            with trace_span("blocksync", "verify_dispatch"):
                for i in range(usable):
                    block = blocks[i]
                    collecting_h = block.header.height
                    if i == 0:
                        vals = self.state.validators
                    elif block.header.validators_hash == next_hash:
                        vals = self.state.next_validators
                    else:
                        break
                    parts = PartSet.from_data(block.to_proto())
                    bid = BlockID(block.hash(), parts.header)
                    parts_ids.append((parts, bid))
                    vals.verify_commit_light(
                        self.state.chain_id, bid, block.header.height,
                        commits[i], defer_to=batch)
                    verified += 1
                collecting_h = None
            # HOT PATH: one device dispatch for the whole window.
            # Verdicts land in the process-wide sigcache, so the
            # apply-time validate_block below (and the NEXT height's
            # LastCommit check at +1) re-verify for free.
            with trace_span("blocksync", "device"), \
                    sigcache.consumer("blocksync"):
                batch.verify()
        except Exception as e:
            # blame the failing height: a deferred sig failure carries
            # it as failed_ctx; structural errors (bad commit shape,
            # not enough power) fail while collecting that height
            bad_h = getattr(e, "failed_ctx", None) or collecting_h or \
                blocks[0].header.height
            for pid in self.pool.redo_request(bad_h):
                self._on_peer_error(pid, "served invalid block")
            return False

        progressed, _, _ = self._apply_window(blocks, window, parts_ids,
                                              commits, verified)
        return progressed

    def _apply_window(self, blocks, window, parts_ids, commits,
                      verified) -> tuple[bool, int, bool]:
        """Apply + store `verified` signature-verified blocks one by
        one (the serial tail of reactor.go:534 processBlock).  Returns
        (progressed, popped, clean): popped counts blocks actually
        landed; clean is False when a refetch/eviction interrupted the
        window — the pipelined path then drops its lookahead (those
        heights re-peek after the pool recovers)."""
        progressed = False
        popped = 0
        for i in range(verified):
            first = blocks[i]
            first_ext = window[i][1]
            ext_enabled = self.state.consensus_params \
                .vote_extensions_enabled(first.header.height)
            if ext_enabled and first_ext is None:
                # params changed mid-window (a block we just applied
                # enabled extensions): the pre-gate used the old
                # params — refetch, don't evict (reactor.go:540)
                for pid in self.pool.redo_request(first.header.height):
                    self._on_peer_error(pid, "missing extended commit")
                return progressed, popped, False
            parts, first_id = parts_ids[i]
            try:
                with trace_span("blocksync", "apply"), \
                        tracetl.span_for(self, "blocksync", "apply",
                                         height=first.header.height):
                    if ext_enabled:
                        first_ext.ensure_extensions(True)
                    # all-hits when the window's device dispatch (or a
                    # live consensus round) already resolved these
                    # LastCommit triples into the verdict cache
                    with sigcache.consumer("blocksync"):
                        self.block_exec.validate_block(self.state, first)
            except Exception:
                # evict BOTH suppliers (reactor.go:560): the next
                # block's LastCommit drove the batched verify
                for pid in self.pool.redo_request(first.header.height):
                    self._on_peer_error(pid, "served invalid block")
                return progressed, popped, False
            self.pool.pop_request()
            popped += 1
            with trace_span("blocksync", "store"), \
                    tracetl.span_for(self, "blocksync", "store",
                                     height=first.header.height):
                if ext_enabled:
                    self.store.save_block(first, parts,
                                          first_ext.to_commit(),
                                          ext_commit=first_ext.to_proto())
                else:
                    self.store.save_block(first, parts, commits[i])
            with trace_span("blocksync", "apply"), \
                    tracetl.span_for(self, "blocksync", "apply",
                                     height=first.header.height):
                self.state = self.block_exec.apply_verified_block(
                    self.state, first_id, first,
                    syncing_to_height=self.pool.max_peer_height())
            if self.metrics is not None:
                self.metrics.record_block(first, size_bytes=parts.byte_size)
            progressed = True
        return progressed, popped, True

    # -- overlapped pipeline ----------------------------------------------

    def _collect_ahead(self, offset: int):
        """Collect ONE verify window starting `offset` blocks past
        pool.height (the lookahead over in-flight windows): the same
        structure checks, power tallies, sign-bytes templating, and
        partset chunking as the serial path, with signature checks
        deferred into a DeferredSigBatch for the pipeline.

        Lookahead windows (offset > 0) are collected BEFORE earlier
        windows apply, so every one of their blocks must pin the
        CURRENT next_validators hash — the same trust discipline the
        serial path uses past height+1; apply-time validate_block
        re-checks the executed validator set before anything lands.
        Returns None when nothing (more) is collectable; peer blame
        for structural failures only fires at offset 0, where the
        state is current (a lookahead failure re-collects as the head
        window next pass and blames then)."""
        from ..types.validation import DeferredSigBatch

        window, after = self.pool.peek_window(VERIFY_WINDOW, offset)
        usable = len(window) if after is not None else len(window) - 1
        if usable < 1:
            return None
        for i in range(usable):
            block, ext = window[i]
            if ext is None and self.state.consensus_params \
                    .vote_extensions_enabled(block.header.height):
                if i == 0:
                    if offset == 0:
                        for pid in self.pool.redo_request(
                                block.header.height):
                            self._on_peer_error(
                                pid, "missing extended commit")
                    return None
                usable = i
                break
        while usable & (usable - 1):
            usable &= usable - 1
        blocks = [b for b, _ in window]
        commits = []
        for i in range(usable):
            nxt = blocks[i + 1] if i + 1 < len(window) else after
            commits.append(nxt.last_commit)

        next_hash = self.state.next_validators.hash() \
            if self.state.next_validators else None
        batch = DeferredSigBatch()
        verified = 0
        parts_ids = []
        collecting_h = None
        try:
            with trace_span("blocksync", "verify_dispatch",
                            offset=offset), \
                    trace_span("blocksync", "collect", offset=offset), \
                    tracetl.span_for(self, "blocksync", "collect",
                                     offset=offset):
                for i in range(usable):
                    block = blocks[i]
                    collecting_h = block.header.height
                    if offset == 0 and i == 0:
                        vals = self.state.validators
                    elif block.header.validators_hash == next_hash:
                        vals = self.state.next_validators
                    else:
                        break
                    parts = PartSet.from_data(block.to_proto())
                    bid = BlockID(block.hash(), parts.header)
                    parts_ids.append((parts, bid))
                    vals.verify_commit_light(
                        self.state.chain_id, bid, block.header.height,
                        commits[i], defer_to=batch)
                    verified += 1
        except Exception as e:
            if offset == 0:
                bad_h = getattr(e, "failed_ctx", None) \
                    or collecting_h or blocks[0].header.height
                for pid in self.pool.redo_request(bad_h):
                    self._on_peer_error(pid, "served invalid block")
            return None
        if verified < 1:
            return None
        return {"blocks": blocks, "window": window,
                "parts_ids": parts_ids, "commits": commits,
                "verified": verified, "batch": batch}

    def _sync_pipelined(self) -> bool:
        """The overlapped ingest loop: up to pipeline_depth windows in
        flight at once — window N+1 collects/packs (host threads)
        while window N's RLC dispatch runs on device and window N-1
        applies/stores.  Verdicts resolve strictly in submission
        order, and NO block applies before its window's verdict future
        resolved true; a reject or device fault abandons the lookahead
        (blocks stay in the pool — no loss) and the next pass retries
        through the normal blame path."""
        pipe = self._get_pipeline()
        inflight: list[dict] = []
        offset = 0
        progressed = False
        # yield back to the pool routine periodically so its status
        # broadcasts and switch-to-consensus checks keep their cadence;
        # past the deadline the fill stops and in-flight drains
        deadline = time.monotonic() + SWITCH_TO_CONSENSUS_INTERVAL
        # pipe.depth >= pipeline_depth: a mesh pipeline raises its
        # depth to keep every device's rotation slot fed
        fill_depth = max(self.pipeline_depth, pipe.depth)
        while True:
            while len(inflight) < fill_depth \
                    and not self._stop_sync.is_set() \
                    and time.monotonic() < deadline:
                rec = self._collect_ahead(offset)
                if rec is None:
                    break
                rec["verdict"] = rec.pop("batch").verify_async(
                    pipe, subsystem="blocksync", lane=SCHED_LANE)
                inflight.append(rec)
                offset += rec["verified"]
            if not inflight:
                return progressed
            rec = inflight.pop(0)
            try:
                # HOT PATH: the window's single device dispatch —
                # later windows are collecting/packing RIGHT NOW
                with trace_span("blocksync", "device_wait",
                                inflight=len(inflight) + 1), \
                        tracetl.span_for(self, "blocksync",
                                         "device_wait"):
                    rec["verdict"].wait()
            except Exception as e:
                # abandoned lookahead windows resolve in the
                # background; their blocks were never popped from the
                # pool, so nothing is lost — the next pass re-peeks
                bad_h = getattr(e, "failed_ctx", None) \
                    or rec["blocks"][0].header.height
                for pid in self.pool.redo_request(bad_h):
                    self._on_peer_error(pid, "served invalid block")
                return progressed
            applied, popped, clean = self._apply_window(
                rec["blocks"], rec["window"], rec["parts_ids"],
                rec["commits"], rec["verified"])
            progressed = progressed or applied
            offset -= rec["verified"]
            if not clean or popped != rec["verified"]:
                return progressed
            if self._stop_sync.is_set() or not self.is_running():
                return progressed

    def _maybe_switch_to_consensus(self) -> bool:
        """reactor.go:520: hand off when caught up."""
        if self.pool.is_caught_up():
            self.block_sync = False
            self.synced = True
            if self.metrics is not None:
                self.metrics.syncing.set(0)
            self._stop_sync.set()
            self.pool.stop()
            if self.consensus_reactor is not None:
                self.consensus_reactor.switch_to_consensus(self.state)
            return True
        return False
