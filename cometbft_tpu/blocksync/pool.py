"""BlockPool: schedules block downloads across peers
(reference internal/blocksync/pool.go).

Keeps a sliding window of in-flight height requests, each owned by a
requester; blocks are surfaced to the reactor IN ORDER via
peek_two_blocks (the next block is verified with the following block's
LastCommit before being applied).
"""

from __future__ import annotations

import random
import threading
import time

from ..libs import lockrank

from ..libs.service import BaseService

REQUEST_INTERVAL = 0.01          # pool.go requestInterval (10ms)
MAX_PENDING_REQUESTS = 64        # window size: >= the 48-block
                                 # verify window the r4b depth sweep
                                 # rewards (reactor.VERIFY_WINDOW)
MAX_PENDING_REQUESTS_PER_PEER = 20
PEER_TIMEOUT = 15.0              # pool.go peerTimeout
# retry jitter bound for refetches (_redo_request): N peers that all
# timed out on the same stalled height otherwise re-request in
# lockstep, hammering whichever peer the random choice converges on
RETRY_JITTER = 0.05


class _Peer:
    def __init__(self, peer_id: str, base: int, height: int):
        self.id = peer_id
        self.base = base
        self.height = height
        self.num_pending = 0
        self.timeout_at: float | None = None

    def arm_timeout(self, timeout: float | None = None) -> None:
        if self.timeout_at is None:
            self.timeout_at = time.monotonic() + (
                timeout if timeout is not None else PEER_TIMEOUT)

    def reset_timeout(self, timeout: float | None = None) -> None:
        """On every delivered block: an actively responsive peer must
        not expire mid-sync (pool.go decrPending)."""
        if self.num_pending > 0:
            self.timeout_at = time.monotonic() + (
                timeout if timeout is not None else PEER_TIMEOUT)
        else:
            self.timeout_at = None

    def disarm_if_idle(self) -> None:
        if self.num_pending == 0:
            self.timeout_at = None


class _Requester:
    """One in-flight height (pool.go bpRequester)."""

    def __init__(self, height: int):
        self.height = height
        self.peer_id: str | None = None
        self.block = None
        self.ext_commit = None
        self.excluded: set[str] = set()  # peers that failed this height
        self.not_before = 0.0            # jittered refetch hold-off


class BlockPool(BaseService):
    def __init__(self, start_height: int, send_request,
                 on_peer_error=None, peer_timeout: float | None = None,
                 retry_jitter: float | None = None):
        """send_request(height, peer_id) issues a BlockRequest;
        on_peer_error(peer_id, reason) reports misbehaving peers.
        peer_timeout/retry_jitter of None defer to the module knobs
        (PEER_TIMEOUT / RETRY_JITTER) at use time, the late binding
        the simnet tuner and tests monkeypatch."""
        super().__init__("BlockPool")
        self._mtx = lockrank.RankedRLock("blocksync.pool")
        self.start_height = start_height
        self.height = start_height       # next height to sync
        self.peer_timeout = peer_timeout
        self.retry_jitter = retry_jitter
        self._peers: dict[str, _Peer] = {}
        self._requesters: dict[int, _Requester] = {}
        self._send_request = send_request
        self._on_peer_error = on_peer_error or (lambda pid, r: None)
        self.last_advance = time.monotonic()
        self._thread: threading.Thread | None = None

    def _peer_timeout(self) -> float:
        return self.peer_timeout if self.peer_timeout is not None \
            else PEER_TIMEOUT

    def _retry_jitter(self) -> float:
        return self.retry_jitter if self.retry_jitter is not None \
            else RETRY_JITTER

    # -- lifecycle ---------------------------------------------------------
    def on_start(self) -> None:
        self._thread = threading.Thread(target=self._make_requesters_routine,
                                        name="blockpool", daemon=True)
        self._thread.start()

    def on_stop(self) -> None:
        pass

    def _make_requesters_routine(self) -> None:
        """pool.go:116: keep the request window full; unassigned or
        failed requesters are re-assigned on every pass (no recursion,
        no permanent orphans)."""
        while self.is_running():
            with self._mtx:
                pending = len(self._requesters)
                max_height = self._max_peer_height()
                next_height = self.height + pending
                if pending < MAX_PENDING_REQUESTS and \
                        next_height <= max_height and \
                        next_height not in self._requesters:
                    self._requesters[next_height] = _Requester(
                        next_height)
                # all unassigned requesters past their jittered
                # hold-off are assignment candidates
                now = time.monotonic()
                todo = [r for r in self._requesters.values()
                        if r.peer_id is None and r.block is None
                        and r.not_before <= now]
            progressed = False
            for req in todo:
                if self._assign_and_send(req):
                    progressed = True
                elif req.excluded and self._peers and \
                        all(p in req.excluded for p in self._peers):
                    # every live peer failed this height: forgive so the
                    # request can cycle rather than wedge
                    req.excluded.clear()
            if not progressed:
                time.sleep(REQUEST_INTERVAL)
            self._check_timeouts()

    def _assign_and_send(self, req: _Requester) -> bool:
        """Try once; on failure leave the requester unassigned for the
        next routine pass. Returns True if a request went out."""
        with self._mtx:
            candidates = [
                p for p in self._peers.values()
                if p.id not in req.excluded
                and p.base <= req.height <= p.height
                and p.num_pending < MAX_PENDING_REQUESTS_PER_PEER]
            if not candidates:
                return False
            peer = random.choice(candidates)
            req.peer_id = peer.id
            peer.num_pending += 1
            peer.arm_timeout(self._peer_timeout())
        try:
            self._send_request(req.height, peer.id)
            return True
        except Exception:
            with self._mtx:
                req.peer_id = None
                req.excluded.add(peer.id)
                live = self._peers.get(peer.id)
                if live is not None:
                    live.num_pending -= 1
                    live.disarm_if_idle()
            return False

    def _check_timeouts(self) -> None:
        now = time.monotonic()
        with self._mtx:
            expired = [p for p in self._peers.values()
                       if p.timeout_at is not None and now > p.timeout_at]
        for p in expired:
            self.remove_peer(p.id)
            self._on_peer_error(p.id, "blocksync request timeout")

    # -- peer management ---------------------------------------------------
    def set_peer_range(self, peer_id: str, base: int,
                       height: int) -> None:
        """From a StatusResponse (pool.go SetPeerRange)."""
        with self._mtx:
            p = self._peers.get(peer_id)
            if p is None:
                self._peers[peer_id] = _Peer(peer_id, base, height)
            else:
                p.base = base
                p.height = max(p.height, height)

    def remove_peer(self, peer_id: str) -> None:
        with self._mtx:
            self._peers.pop(peer_id, None)
            # its in-flight requests go back to the unassigned state;
            # the requesters routine re-assigns them
            for r in self._requesters.values():
                if r.peer_id == peer_id and r.block is None:
                    r.peer_id = None
                    r.excluded.add(peer_id)

    def _redo_request(self, height: int, exclude_peer: str) -> None:
        """Unassign so the requesters routine refetches from another
        peer (never recursive)."""
        with self._mtx:
            req = self._requesters.get(height)
            if req is None:
                return
            if req.peer_id is not None:
                p = self._peers.get(req.peer_id)
                # only an in-flight request still counts against the
                # peer; a delivered block was decremented in add_block
                if p is not None and req.block is None:
                    p.num_pending -= 1
                    p.disarm_if_idle()
            if exclude_peer:
                req.excluded.add(exclude_peer)
            req.peer_id = None
            req.block = None
            req.ext_commit = None
            # jitter the refetch so simultaneous timeouts across many
            # heights do not re-request (and re-time-out) in lockstep
            jitter = self._retry_jitter()
            if jitter > 0:
                req.not_before = time.monotonic() + \
                    random.uniform(0, jitter)

    def _max_peer_height(self) -> int:
        with self._mtx:
            return max((p.height for p in self._peers.values()),
                       default=0)

    def max_peer_height(self) -> int:
        return self._max_peer_height()

    # -- block intake ------------------------------------------------------
    def add_block(self, peer_id: str, block, ext_commit) -> None:
        """pool.go AddBlock."""
        height = block.header.height
        with self._mtx:
            req = self._requesters.get(height)
            if req is None or req.peer_id != peer_id:
                # unsolicited block: punish (pool.go:297)
                self._on_peer_error(
                    peer_id, f"unsolicited block at height {height}")
                return
            if req.block is not None:
                return  # duplicate response: ignore (requester.setBlock)
            req.block = block
            req.ext_commit = ext_commit
            p = self._peers.get(peer_id)
            if p is not None:
                p.num_pending -= 1
                p.reset_timeout(self._peer_timeout())

    def no_block_response(self, peer_id: str, height: int) -> None:
        self._redo_request(height, peer_id)

    # -- consumer ----------------------------------------------------------
    def peek_two_blocks(self):
        """(first, first_ext_commit, second) at self.height and +1."""
        with self._mtx:
            r1 = self._requesters.get(self.height)
            r2 = self._requesters.get(self.height + 1)
            first = r1.block if r1 else None
            ext = r1.ext_commit if r1 else None
            second = r2.block if r2 else None
            return first, ext, second

    def peek_window(self, max_blocks: int, offset: int = 0):
        """Consecutive downloaded blocks from self.height + offset: a
        list of (block, ext_commit) of length <= max_blocks, plus the
        block at the following height if present (its LastCommit
        verifies the last window entry).  The windowed verify path
        batches all the commits into one device dispatch
        (types.DeferredSigBatch); the overlapped pipeline peeks AHEAD
        of in-flight windows via `offset` so window N+1 collects while
        window N is on device."""
        with self._mtx:
            window = []
            h = self.height + offset
            while len(window) < max_blocks:
                r = self._requesters.get(h)
                if r is None or r.block is None:
                    break
                window.append((r.block, r.ext_commit))
                h += 1
            nxt = self._requesters.get(h)
            return window, (nxt.block if nxt else None)

    def pop_request(self) -> None:
        """The block at self.height was applied (pool.go PopRequest)."""
        with self._mtx:
            self._requesters.pop(self.height, None)
            self.height += 1
            self.last_advance = time.monotonic()

    def redo_request(self, height: int) -> list[str]:
        """First block failed verification: the peers that supplied BOTH
        blocks are suspect (the second's LastCommit drove the failed
        verify) — remove them and refetch (reactor.go:560-575).
        Returns the offending peer ids."""
        bad: list[str] = []
        with self._mtx:
            for h in (height, height + 1):
                req = self._requesters.get(h)
                if req is not None and req.peer_id:
                    bad.append(req.peer_id)
        for pid in bad:
            self.remove_peer(pid)
        for h in (height, height + 1):
            with self._mtx:
                r = self._requesters.get(h)
            if r is not None:
                for pid in bad:
                    self._redo_request(h, pid)
        return bad

    def is_caught_up(self) -> bool:
        """pool.go IsCaughtUp: within one block of the best peer."""
        with self._mtx:
            if not self._peers:
                return False
            return self.height >= max(
                p.height for p in self._peers.values())
