"""In-memory key=value store app (reference abci/example/kvstore/kvstore.go).

Exercises the full ABCI surface the way the reference example does:
- txs are "key=value" strings; CheckTx validates the shape
- "val:<base64 pubkey>!<power>" txs update the validator set
- app hash = 8-byte big-endian running tx count (deterministic, cheap)
- Query supports path "/key" lookups
- state snapshots at every height for statesync testing

State persists across Commit only in memory (height, app_hash, kv) —
the durable variant would write through a KVStore; the reference's
example is likewise memory-backed by default.
"""

from __future__ import annotations

import base64
import json

from ..libs import lockrank

from ..abci import types as at
from ..abci.application import BaseApplication

VALIDATOR_TX_PREFIX = "val:"

CODE_OK = 0
CODE_INVALID_TX_FORMAT = 1
CODE_UNKNOWN_ERROR = 2


class KVStoreApplication(BaseApplication):
    def __init__(self, snapshot_interval: int = 1,
                 snapshot_keep: int = 10):
        """snapshot_interval: take a snapshot every N heights (the
        reference kvstore's --snapshot-interval); snapshot_keep: how
        many to retain.  keep * interval is the serving WINDOW — a
        statesyncing peer must fetch all chunks before the chain
        advances past it, so fast chains want interval > 1."""
        self._lock = lockrank.RankedRLock("apps.kvstore")
        self.kv: dict[str, str] = {}
        self.height = 0
        self.app_hash = b"\x00" * 8
        self.tx_count = 0
        self.validator_updates: dict[str, int] = {}  # b64 pubkey -> power
        self._staged: list[tuple[str, str]] = []
        self._staged_vals: list[at.ValidatorUpdate] = []
        self._snapshots: dict[int, bytes] = {}
        self.snapshot_interval = max(1, snapshot_interval)
        self.snapshot_keep = max(1, snapshot_keep)

    # -- info/query --------------------------------------------------------

    def info(self, req):
        with self._lock:
            return at.InfoResponse(
                data=json.dumps({"size": len(self.kv)}),
                version="kvstore-tpu-0.1",
                app_version=1,
                last_block_height=self.height,
                last_block_app_hash=self.app_hash)

    def query(self, req):
        with self._lock:
            key = req.data.decode()
            value = self.kv.get(key)
            if value is None:
                return at.QueryResponse(code=CODE_OK, key=req.data,
                                        log="does not exist",
                                        height=self.height)
            return at.QueryResponse(code=CODE_OK, key=req.data,
                                    value=value.encode(), log="exists",
                                    height=self.height)

    # -- mempool -----------------------------------------------------------

    def check_tx(self, req):
        if self._parse_tx(req.tx) is None:
            return at.CheckTxResponse(
                code=CODE_INVALID_TX_FORMAT,
                log="tx must be key=value or val:pubkey!power")
        return at.CheckTxResponse(code=CODE_OK, gas_wanted=1)

    # -- consensus ---------------------------------------------------------

    def init_chain(self, req):
        with self._lock:
            for v in req.validators:
                b64 = base64.b64encode(v.pub_key_bytes).decode()
                self.validator_updates[b64] = v.power
            if req.initial_height:
                self.height = req.initial_height - 1
            return at.InitChainResponse(app_hash=self.app_hash)

    def process_proposal(self, req):
        for tx in req.txs:
            if self._parse_tx(tx) is None:
                return at.ProcessProposalResponse(
                    status=at.PROCESS_PROPOSAL_REJECT)
        return at.ProcessProposalResponse(status=at.PROCESS_PROPOSAL_ACCEPT)

    def finalize_block(self, req):
        """Deterministic and idempotent: all effects are STAGED here and
        applied in commit(), so crash-recovery re-execution of the same
        block (FinalizeBlock ran, Commit didn't) reproduces the same
        app_hash instead of double-counting."""
        with self._lock:
            self._staged = []
            self._staged_vals = []
            staged_count = 0
            results = []
            for tx in req.txs:
                parsed = self._parse_tx(tx)
                if parsed is None:
                    results.append(at.ExecTxResult(
                        code=CODE_INVALID_TX_FORMAT, log="invalid tx"))
                    continue
                kind, key, value = parsed
                if kind == "val":
                    power = int(value)
                    self._staged_vals.append(at.ValidatorUpdate(
                        power=power,
                        pub_key_bytes=base64.b64decode(key),
                        pub_key_type="ed25519"))
                else:
                    self._staged.append((key, value))
                staged_count += 1
                results.append(at.ExecTxResult(
                    code=CODE_OK,
                    events=[at.Event(type="app", attributes=[
                        at.EventAttribute("key", key, True),
                        at.EventAttribute("noindex_key", key, False),
                    ])]))
            new_hash = (self.tx_count + staged_count).to_bytes(8, "big")
            self._staged_count = staged_count
            self._pending_height = req.height
            self._pending_hash = new_hash
            return at.FinalizeBlockResponse(
                tx_results=results,
                validator_updates=list(self._staged_vals),
                app_hash=new_hash)

    def commit(self, req):
        with self._lock:
            for k, v in self._staged:
                self.kv[k] = v
            for vu in self._staged_vals:
                b64 = base64.b64encode(vu.pub_key_bytes).decode()
                self.validator_updates[b64] = vu.power
            self.tx_count += getattr(self, "_staged_count", 0)
            self._staged = []
            self._staged_vals = []
            self._staged_count = 0
            self.height = getattr(self, "_pending_height", self.height + 1)
            self.app_hash = getattr(self, "_pending_hash", self.app_hash)
            # clear so a commit without a preceding finalize_block falls
            # back to height+1 instead of replaying stale pending state
            for attr in ("_pending_height", "_pending_hash"):
                if hasattr(self, attr):
                    delattr(self, attr)
            if self.height % self.snapshot_interval == 0:
                self._snapshots[self.height] = self._snapshot_bytes()
                for h in sorted(self._snapshots)[:-self.snapshot_keep]:
                    del self._snapshots[h]
            return at.CommitResponse(retain_height=0)

    # -- statesync ---------------------------------------------------------

    SNAPSHOT_CHUNK = 65536

    def _snapshot_bytes(self) -> bytes:
        with self._lock:
            return json.dumps({
                "height": self.height,
                "app_hash": self.app_hash.hex(),
                "tx_count": self.tx_count,
                "kv": self.kv,
                "validators": self.validator_updates,
            }, sort_keys=True).encode()

    def list_snapshots(self, req):
        with self._lock:
            out = []
            for h, blob in sorted(self._snapshots.items()):
                n_chunks = max(1, (len(blob) + self.SNAPSHOT_CHUNK - 1)
                               // self.SNAPSHOT_CHUNK)
                from ..crypto.hash import sum_sha256
                out.append(at.Snapshot(height=h, format=1, chunks=n_chunks,
                                       hash=sum_sha256(blob)))
            return at.ListSnapshotsResponse(snapshots=out)

    def offer_snapshot(self, req):
        if req.snapshot.format != 1:
            return at.OfferSnapshotResponse(
                result=at.OFFER_SNAPSHOT_REJECT_FORMAT)
        self._restore = {"snapshot": req.snapshot, "chunks": {},
                         "app_hash": req.app_hash}
        return at.OfferSnapshotResponse(result=at.OFFER_SNAPSHOT_ACCEPT)

    def load_snapshot_chunk(self, req):
        blob = self._snapshots.get(req.height)
        if blob is None or req.format != 1:
            return at.LoadSnapshotChunkResponse()
        start = req.chunk * self.SNAPSHOT_CHUNK
        return at.LoadSnapshotChunkResponse(
            chunk=blob[start:start + self.SNAPSHOT_CHUNK])

    def apply_snapshot_chunk(self, req):
        rst = getattr(self, "_restore", None)
        if rst is None:
            return at.ApplySnapshotChunkResponse(
                result=at.APPLY_CHUNK_ABORT)
        rst["chunks"][req.index] = req.chunk
        snap = rst["snapshot"]
        if len(rst["chunks"]) < snap.chunks:
            return at.ApplySnapshotChunkResponse(
                result=at.APPLY_CHUNK_ACCEPT)
        blob = b"".join(rst["chunks"][i] for i in range(snap.chunks))
        from ..crypto.hash import sum_sha256
        if sum_sha256(blob) != snap.hash:
            self._restore = None
            return at.ApplySnapshotChunkResponse(
                result=at.APPLY_CHUNK_RETRY_SNAPSHOT)
        state = json.loads(blob)
        with self._lock:
            self.kv = dict(state["kv"])
            self.height = state["height"]
            self.app_hash = bytes.fromhex(state["app_hash"])
            self.tx_count = state["tx_count"]
            self.validator_updates = dict(state["validators"])
            self._snapshots[self.height] = blob
        self._restore = None
        return at.ApplySnapshotChunkResponse(result=at.APPLY_CHUNK_ACCEPT)

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _parse_tx(tx: bytes):
        """-> ("kv", key, value) | ("val", b64_pubkey, power_str) | None."""
        try:
            s = tx.decode()
        except UnicodeDecodeError:
            return None
        if s.startswith(VALIDATOR_TX_PREFIX):
            rest = s[len(VALIDATOR_TX_PREFIX):]
            if "!" not in rest:
                return None
            b64, _, power = rest.rpartition("!")
            try:
                base64.b64decode(b64, validate=True)
                int(power)
            except Exception:  # noqa: BLE001
                return None
            return "val", b64, power
        if "=" not in s:
            return None
        key, _, value = s.partition("=")
        if not key or not value:
            return None
        return "kv", key, value
