"""Example ABCI applications (reference abci/example/)."""

from .kvstore import KVStoreApplication  # noqa: F401
