"""Process-wide signature-verdict cache: first-seen verify, zero-cost
re-verify across consensus, blocksync, light, and evidence.

The hot path re-checks signatures the process already proved: at
height H+1 the node re-verifies H's LastCommit
(state/validation.validate_block -> verify_commit), duplicate gossip
votes from N peers each occupy a StreamingVerifier batch slot, and the
light-client / evidence paths re-dispatch identical (pubkey, msg, sig)
triples.  A signature verdict is an immutable fact of its inputs —
content-address it once and every later consumer gets the answer for a
SHA-256 instead of a device dispatch or an OpenSSL call.

Design:

- one SHA-256 over the length-framed (key_type, pubkey, msg, sig)
  concatenation is the cache key; the verdict is a bool.  Because the
  FULL triple is hashed, positive AND negative verdicts are cacheable
  and unpoisonable: an attacker who wants a False verdict cached for
  some triple must present that exact triple, whose verdict really is
  False (and caching it makes the rejection cheaper, not weaker);
- lock-striped bounded LRU: 16 stripes, each its own mutex +
  OrderedDict, so concurrent product paths (votestream worker,
  pipeline staging, blocksync collect) don't serialize on one lock;
- the cache is performance-only, never behavior: consumers partition
  into hits/misses and verify only the misses, producing bit-identical
  verdicts and byte-identical errors to the uncached path (pinned by
  tests/test_sigcache.py parity tests);
- seam discipline matches metrics/flightrec/trace: module-level
  enabled() check first, everything below is no-op-cheap when the
  cache is off (COMETBFT_TPU_SIGCACHE=0 or set_enabled(False)).

Instrumented end-to-end: CacheMetrics (libs/metrics.py, per-consumer
labels via the `consumer(...)` context manager), flightrec
EV_CACHE_LOOKUP / EV_CACHE_INSERT events on batch seams, and the
`cache` field on verify_dispatch tracetl spans (crypto/dispatch.py).
"""

from __future__ import annotations

import hashlib
import os
import threading
from ..libs import lockrank
from collections import OrderedDict

DEFAULT_CAPACITY = int(os.environ.get(
    "COMETBFT_TPU_SIGCACHE_CAPACITY", "131072"))
STRIPES = 16

# consumers: the product path that asked.  The default is "crypto" —
# a lookup below any labeled seam.
_tls = threading.local()

# the CLOSED consumer registry: every literal label handed to
# consumer(...) across the package, every key in
# latledger.DEFAULT_SLO_TARGETS, and every per-consumer metrics/ledger
# series key must come from this set (scripts/check_metrics.py rule 8
# lints both directions).  "crypto" is the unlabeled default; "bench"
# is the bench drivers' label; "probe" marks devhealth known-answer
# batches.
CONSUMERS = frozenset({
    "consensus", "blocksync", "light", "lightserve", "evidence",
    "crypto", "bench", "probe",
})

# QoS lane priorities over the closed consumer registry
# (crypto/sched.py): lower number = more urgent.  Every CONSUMERS
# label has exactly one entry and every key here is a registered
# consumer — scripts/check_metrics.py rule 9 lints both directions, so
# a new consumer cannot ship without declaring where it sits in the
# verify-plane dispatch order.  Votes outrank everything (consensus
# round time is bounded by vote-verify latency, not bulk throughput);
# evidence is next (equivocation proofs are consensus-adjacent);
# light/lightserve share a class (deficit round-robin keeps them fair
# to each other); blocksync bulk yields to all of the above; the
# unlabeled "crypto"/"bench" default class goes last.  "probe" windows
# never enter the submit queue (devhealth hand-stages them), but the
# label still declares a lane so the registry stays total.
LANES = {
    "consensus": 0,
    "probe": 0,
    "evidence": 1,
    "light": 2,
    "lightserve": 2,
    "blocksync": 3,
    "crypto": 4,
    "bench": 4,
}
# subsystems outside CONSUMERS (e.g. the bare "pipeline" default)
# schedule at the lowest priority class
DEFAULT_LANE_PRIORITY = 4


def lane_priority(label: str) -> int:
    """Dispatch priority class for a consumer label (lower = more
    urgent); unregistered labels fall into the default class."""
    return LANES.get(label, DEFAULT_LANE_PRIORITY)


class consumer:
    """Context manager labeling cache traffic with the product path
    (consensus / blocksync / light / evidence / ...) for the
    per-consumer CacheMetrics series.  Thread-local and reentrant
    (inner labels win)."""

    __slots__ = ("label", "_prev")

    def __init__(self, label: str):
        self.label = label
        self._prev = None

    def __enter__(self) -> "consumer":
        self._prev = getattr(_tls, "label", None)
        _tls.label = self.label
        return self

    def __exit__(self, *exc) -> bool:
        _tls.label = self._prev
        return False


def current_consumer() -> str:
    return getattr(_tls, "label", None) or "crypto"


def _pk_bytes(pk) -> bytes:
    return pk.bytes() if hasattr(pk, "bytes") else bytes(pk)


def _pk_type(pk) -> str:
    return pk.type() if hasattr(pk, "type") else "ed25519"


def key(pubkey, msg: bytes, sig: bytes,
        key_type: str | None = None) -> bytes:
    """Content address of one (pubkey, msg, sig) triple: a single
    SHA-256 over the length-framed concatenation (framing prevents
    boundary-shift collisions between fields; the key type is part of
    the material because the SAME raw key bytes mean different curves
    under different types).  Accepts a key object or raw bytes."""
    if key_type is None:
        key_type = _pk_type(pubkey)
    pk = _pk_bytes(pubkey)
    h = hashlib.sha256()
    h.update(key_type.encode())
    h.update(len(pk).to_bytes(4, "little"))
    h.update(pk)
    h.update(len(msg).to_bytes(4, "little"))
    h.update(msg)
    h.update(sig)
    return h.digest()


class SigVerdictCache:
    """Lock-striped bounded LRU mapping key() digests to bool verdicts.

    Raw counters live here (hits/misses/insertions/evictions/
    negative_hits); the module-level helpers fold them into the
    CacheMetrics bundle when a node installed one."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 stripes: int = STRIPES):
        self.capacity = max(int(capacity), stripes)
        self.stripes = stripes
        # ceil-divide so stripes * per_stripe >= capacity
        self._per_stripe = -(-self.capacity // stripes)
        self._locks = [lockrank.RankedLock("sigcache.stripe")
                       for _ in range(stripes)]
        self._maps: list[OrderedDict] = [
            OrderedDict() for _ in range(stripes)]
        self.hits = 0
        self.negative_hits = 0
        self.misses = 0
        self.insertions = 0
        self.evictions = 0

    def _stripe(self, k: bytes) -> int:
        # the key is a SHA-256 digest: any byte is uniform
        return k[0] % self.stripes

    def lookup(self, k: bytes) -> bool | None:
        """Verdict for a key() digest, None on miss.  A hit refreshes
        LRU recency.  Counter accounting is the CALLER'S job (the
        module-level get/partition helpers) so batch seams can account
        once per batch."""
        i = self._stripe(k)
        with self._locks[i]:
            m = self._maps[i]
            v = m.get(k)
            if v is None:
                return None
            m.move_to_end(k)
            return v

    def store(self, k: bytes, verdict: bool) -> int:
        """Insert one verdict; returns evictions performed (0 or 1).
        Re-inserting an existing key refreshes recency (verdicts are
        immutable facts — the value cannot change)."""
        i = self._stripe(k)
        with self._locks[i]:
            m = self._maps[i]
            if k in m:
                m.move_to_end(k)
                m[k] = bool(verdict)
                return 0
            m[k] = bool(verdict)
            if len(m) > self._per_stripe:
                m.popitem(last=False)
                return 1
            return 0

    def __len__(self) -> int:
        return sum(len(m) for m in self._maps)

    def clear(self) -> None:
        for i in range(self.stripes):
            with self._locks[i]:
                self._maps[i].clear()

    def stats(self) -> dict:
        looked = self.hits + self.misses
        return {
            "entries": len(self),
            "capacity": self.capacity,
            "hits": self.hits,
            "negative_hits": self.negative_hits,
            "misses": self.misses,
            "insertions": self.insertions,
            "evictions": self.evictions,
            "hit_rate": round(self.hits / looked, 4) if looked else 0.0,
        }


# -- process-wide default instance -------------------------------------------

_cache: SigVerdictCache | None = None
_cache_lock = lockrank.RankedLock("sigcache.global")
# tri-state runtime override: None defers to COMETBFT_TPU_SIGCACHE
# (default on); the A/B bench arms and the parity tests flip this
_enabled_override: bool | None = None


def cache() -> SigVerdictCache:
    global _cache
    with _cache_lock:
        if _cache is None:
            _cache = SigVerdictCache()
        return _cache


def reset(capacity: int | None = None) -> SigVerdictCache:
    """Fresh process-wide cache (tests and bench arms); returns it."""
    global _cache
    with _cache_lock:
        _cache = SigVerdictCache(
            capacity if capacity is not None else DEFAULT_CAPACITY)
        return _cache


def enabled() -> bool:
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get("COMETBFT_TPU_SIGCACHE", "1") != "0"


def set_enabled(v: bool | None) -> None:
    global _enabled_override
    _enabled_override = v


# -- instrumented operations -------------------------------------------------

def _metrics():
    from ..libs import metrics as libmetrics

    return libmetrics.cache_metrics()


def _account(label: str, hits: int, negs: int, misses: int) -> None:
    c = cache()
    c.hits += hits
    c.negative_hits += negs
    c.misses += misses
    cm = _metrics()
    if cm is not None:
        if hits:
            cm.hits.labels(label).inc(hits)
        if negs:
            cm.negative_hits.labels(label).inc(negs)
        if misses:
            cm.misses.labels(label).inc(misses)


def get(pubkey, msg: bytes, sig: bytes,
        key_type: str | None = None,
        label: str | None = None) -> bool | None:
    """Single-triple lookup: bool verdict or None (miss / disabled)."""
    if not enabled():
        return None
    v = cache().lookup(key(pubkey, msg, sig, key_type))
    if label is None:
        label = current_consumer()
    if v is None:
        _account(label, 0, 0, 1)
    else:
        _account(label, 1, 0 if v else 1, 0)
    return v


def insert(pubkey, msg: bytes, sig: bytes, verdict: bool,
           key_type: str | None = None,
           label: str | None = None) -> None:
    if not enabled():
        return
    c = cache()
    ev = c.store(key(pubkey, msg, sig, key_type), verdict)
    c.insertions += 1
    c.evictions += ev
    cm = _metrics()
    if cm is not None:
        cm.insertions.labels(label or current_consumer()).inc()
        if ev:
            cm.evictions.inc(ev)
        cm.entries.set(len(c))


def partition(items, label: str | None = None,
              count_misses: bool = True):
    """Batch consult: `items` is a sequence of (pubkey, msg, sig)
    (key objects or raw bytes).  Returns (verdicts, miss_idx) where
    verdicts has one bool-or-None slot per item (None = miss, verify
    it) and miss_idx lists the positions to dispatch.  Disabled cache
    = everything a miss, zero hashing.

    count_misses=False skips miss accounting — for re-check seams
    (votestream flush re-consults triples already counted at submit)
    so one signature never counts as two misses."""
    items = list(items)
    if not enabled() or not items:
        return [None] * len(items), list(range(len(items)))
    c = cache()
    verdicts: list[bool | None] = []
    miss_idx: list[int] = []
    hits = negs = 0
    for i, (pk, msg, sig) in enumerate(items):
        v = c.lookup(key(pk, msg, sig))
        verdicts.append(v)
        if v is None:
            miss_idx.append(i)
        else:
            hits += 1
            if not v:
                negs += 1
    if label is None:
        label = current_consumer()
    _account(label, hits, negs,
             len(miss_idx) if count_misses else 0)
    if hits and len(items) >= 2:
        from ..libs import flightrec

        flightrec.record(flightrec.EV_CACHE_LOOKUP, consumer=label,
                         batch=len(items), hits=hits, negative=negs,
                         misses=len(miss_idx))
    return verdicts, miss_idx


def insert_many(items, verdicts, label: str | None = None,
                key_type: str | None = None) -> None:
    """Batch populate: one (pubkey, msg, sig) + bool verdict per slot.
    The verdict-resolution seams (votestream flush, pipeline window
    publication, batch verifiers) call this so every computed verdict
    becomes a future hit.  key_type overrides per-item inference when
    the items carry raw key bytes of a known non-ed25519 type (the
    typed batch collectors in crypto/batch.py)."""
    if not enabled() or not items:
        return
    c = cache()
    ev = 0
    n = 0
    for (pk, msg, sig), v in zip(items, verdicts):
        ev += c.store(key(pk, msg, sig, key_type), bool(v))
        n += 1
    c.insertions += n
    c.evictions += ev
    if label is None:
        label = current_consumer()
    cm = _metrics()
    if cm is not None:
        cm.insertions.labels(label).inc(n)
        if ev:
            cm.evictions.inc(ev)
        cm.entries.set(len(c))
    if n >= 2:
        from ..libs import flightrec

        flightrec.record(flightrec.EV_CACHE_INSERT, consumer=label,
                         count=n, evicted=ev)
