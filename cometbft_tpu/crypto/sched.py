"""Deadline-aware QoS scheduler for the verify pipeline.

The pipeline's dispatch queue used to be a strict FIFO: one late vote
window submitted behind a saturating blocksync backlog waited for every
bulk window ahead of it, so the consensus p99 tracked the *bulk* queue
depth instead of the vote path's own cost.  This module gives the
pipeline priority lanes without changing its data structures: windows
still live in ``VerifyPipeline._windows`` in submission order, and the
scheduler is pure *selection* logic over that list — which unstaged
window to stage next, which staged window a freed device takes, and
whether a device should briefly hold idle for a more urgent window that
is still staging.

Design points (each load-bearing):

- **Scan-based, no shadow queues.**  Every decision is a scan of the
  pipeline's ``_windows`` under the pipeline's own condition variable.
  There is no second bookkeeping structure to fall out of sync with the
  watchdog / drain / brownout paths, and no new lock rank.
- **Lanes are consumer labels.**  ``sigcache.LANES`` maps every
  registered consumer label to a priority class (lower = more urgent).
  Labels outside the registry collapse into one ``default`` lane, so
  untagged traffic keeps exact global-FIFO semantics among itself.
- **Deadline promotion is the starvation guard.**  Strict priority
  alone would let a lightserve flood starve blocksync forever.  A
  window whose queue age exceeds its lane's declared p99 target
  (``latledger.target_for``) is promoted ahead of every normal class,
  FIFO among promoted peers — so the worst case wait for any lane is
  bounded by its own SLO target plus one window's service time.
- **Deficit round-robin inside a priority class.**  Lanes that share a
  class (e.g. ``light`` and ``lightserve``) split device time by
  signature count, not window count, so a flood of large windows from
  one label cannot starve small windows from its peer.
- **Disabled == FIFO.**  With ``enabled=False`` every window lands in
  one lane at one priority, and every selection degenerates to the
  head-of-queue scan the pipeline always had.  The A/B bench arms
  differ only by this flag.

Accounting happens under the pipeline cv (``note_dispatch``); event
*emission* (metrics counters, flight-recorder ``EV_SCHED_PREEMPT``) is
returned as a plain dict for the caller to pass to ``emit`` after
releasing the cv, keeping the hot section short.
"""

from __future__ import annotations

import os
import time

from ..libs import flightrec
from ..libs import latledger
from ..libs import metrics as libmetrics
from . import sigcache

# Deficit round-robin quantum, in signatures, credited to a same-class
# lane each time it is passed over.  Larger values trade fairness
# granularity for fewer rotation steps.
DEFAULT_QUANTUM = int(os.environ.get("COMETBFT_TPU_SCHED_QUANTUM", "256"))

# Longest a free device will sit idle (cause `sched_hold`) waiting for a
# strictly-higher-priority window that is actively staging, instead of
# taking lower-priority staged work.  0 disables holding entirely.
DEFAULT_HOLD_S = float(os.environ.get(
    "COMETBFT_TPU_SCHED_HOLD_MS", "2")) / 1000.0

# Effective priority of a deadline-promoted window: ahead of every
# normal class (sigcache lane classes start at 0).
_PROMOTED = -1

# Lane identity for labels outside the sigcache registry.  All untagged
# traffic shares this lane, preserving global FIFO among itself.
DEFAULT_LANE = "default"


class _LaneStats:
    __slots__ = ("windows", "sigs", "preemptions", "held_s")

    def __init__(self) -> None:
        self.windows = 0
        self.sigs = 0
        self.preemptions = 0
        self.held_s = 0.0


class QosScheduler:
    """Selection policy over the pipeline's window list.

    Every method that takes ``windows`` must be called with the
    pipeline's condition variable held; ``emit`` must be called with it
    released.  The clock is injectable so the ordering, promotion, and
    hold policies are testable with a fake clock.
    """

    def __init__(self, *, enabled: bool = True,
                 quantum: int | None = None,
                 hold_s: float | None = None,
                 clock=time.monotonic):
        self.enabled = enabled
        self.quantum = DEFAULT_QUANTUM if quantum is None else int(quantum)
        if self.quantum <= 0:
            self.quantum = 1
        self.hold_s = DEFAULT_HOLD_S if hold_s is None else float(hold_s)
        self._clock = clock
        self._seq = 0
        # DRR state for equal-priority lanes: label -> deficit in sigs,
        # plus a rotation cursor over the sorted label list.
        self._deficit: dict[str, float] = {}
        self._rr_idx = 0
        # device key -> monotonic time the hold started (a device only
        # appears here while it is deliberately idling for a higher
        # lane); key is the mesh device index, or None single-device.
        self._holds: dict = {}
        self._stats: dict[str, _LaneStats] = {}

    # -- lane resolution -----------------------------------------------------
    def lane_for(self, subsystem: str, lane: str | None = None) -> str:
        """Lane identity for a submission.  An explicit ``lane``
        override wins only when it names a registered lane label;
        anything else falls back to the subsystem, and subsystems
        outside the registry collapse into the shared default lane."""
        if lane is not None and lane in sigcache.LANES:
            return lane
        if subsystem in sigcache.LANES:
            return subsystem
        return DEFAULT_LANE

    def priority(self, label: str) -> int:
        if not self.enabled:
            return 0
        return sigcache.lane_priority(label)

    def note_enqueue(self, win, label: str) -> None:
        """Stamp scheduling fields on a window entering the queue."""
        win.lane = label
        win.prio = self.priority(label)
        win.seq = self._seq
        self._seq += 1
        win.enqueued_at = self._clock()
        win.held_since = None

    # -- ordering ------------------------------------------------------------
    def _eff_prio(self, win, now: float) -> int:
        """Priority class after deadline promotion: a window older than
        its lane's declared p99 target jumps every normal class."""
        if not self.enabled:
            return 0
        if now - win.enqueued_at > latledger.target_for(win.lane):
            return _PROMOTED
        return win.prio

    def next_unstaged(self, windows, now: float):
        """The unstaged window the staging thread should parse/pack
        next: most urgent effective class first, FIFO within it."""
        best = None
        best_key = None
        for w in windows:
            if w.staged or w.abandoned:
                continue
            key = (self._eff_prio(w, now), w.seq)
            if best_key is None or key < best_key:
                best, best_key = w, key
        return best

    def _eligible(self, windows, device_index, now: float):
        """Staged, undispatched lane-head windows for this device,
        each tagged with its effective priority.  Lane heads are per
        device: mesh windows are pinned to a chip at submit, and
        publication (not dispatch) enforces per-lane result order, so a
        lane's head on another chip never blocks this one."""
        lane_seen: set = set()
        out = []
        for w in windows:  # submission order == seq order
            if w.abandoned or w.result is not None:
                continue
            if device_index is not None and w.device_index != device_index:
                continue
            if w.lane in lane_seen:
                continue
            if w.dispatching:
                # In flight (a watchdog-replaced thread can see its
                # predecessor's wedged window): skip without blocking
                # the lane — parked results publish in lane order.
                continue
            lane_seen.add(w.lane)
            if not w.staged:
                # Within a lane staging is FIFO, so an unstaged lane
                # head means nothing later in that lane is staged
                # either; the lane waits.
                continue
            out.append((self._eff_prio(w, now), w))
        return out

    def _drr_pick(self, cands):
        """Deficit round-robin among equal-priority lane heads.

        ``cands`` is [(lane, window)] with one entry per lane.  A lane
        is served when its accumulated deficit covers the head window's
        signature count; otherwise it gains a quantum and the cursor
        rotates.  Deficits persist across picks; ``_gc_deficits``
        clears a lane's balance when it drains."""
        labels = sorted(lbl for lbl, _ in cands)
        heads = dict(cands)
        guard = 0
        while True:
            lbl = labels[self._rr_idx % len(labels)]
            w = heads[lbl]
            need = max(1, len(w.items))
            d = self._deficit.get(lbl, 0.0)
            # The flat guard bounds rotation at the worst case (a
            # max-batch window against the minimum quantum) so a
            # misconfigured quantum degrades to round-robin, never to
            # an unbounded spin.
            if d >= need or guard >= 1024:
                self._deficit[lbl] = max(0.0, d - need)
                self._rr_idx += 1
                return w
            self._deficit[lbl] = d + self.quantum
            self._rr_idx += 1
            guard += 1

    def _gc_deficits(self, windows) -> None:
        live = {w.lane for w in windows if w.result is None}
        for lbl in [l for l in self._deficit if l not in live]:
            del self._deficit[lbl]

    def pick_dispatch(self, windows, device_index, now: float):
        """Choose the staged window a free device should take.

        Returns ``(window, holding)``.  ``(None, True)`` means the
        device should stay idle (cause ``sched_hold``): a strictly
        higher-priority window is actively staging and the hold budget
        has not expired.  ``(None, False)`` means nothing to do."""
        self._gc_deficits(windows)
        elig = self._eligible(windows, device_index, now)
        if not elig:
            self._holds.pop(device_index, None)
            return None, False
        best_class = min(p for p, _ in elig)
        # Hold the device for a more urgent window mid-staging?
        if self.enabled and self.hold_s > 0:
            urgent_staging = any(
                not w.staged and not w.abandoned
                and getattr(w, "staging_active", False)
                and (device_index is None
                     or w.device_index == device_index)
                and self._eff_prio(w, now) < best_class
                for w in windows)
            if urgent_staging:
                since = self._holds.setdefault(device_index, now)
                if now - since < self.hold_s:
                    return None, True
        self._holds.pop(device_index, None)
        cands = [(w.lane, w) for p, w in elig if p == best_class]
        if len(cands) == 1:
            return cands[0][1], False
        # FIFO among promoted windows: fairness already satisfied by
        # the promotion deadline itself.
        if best_class == _PROMOTED:
            return min((w for _, w in cands), key=lambda w: w.seq), False
        return self._drr_pick(cands), False

    def holding(self, device_index) -> bool:
        return device_index in self._holds

    # -- accounting ----------------------------------------------------------
    def note_dispatch(self, win, windows, now: float) -> dict:
        """Book a dispatch under the cv; returns the event payload for
        ``emit`` (call it after releasing the cv)."""
        st = self._stats.setdefault(win.lane, _LaneStats())
        st.windows += 1
        st.sigs += len(win.items)
        held_s = 0.0
        if win.held_since is not None:
            held_s = max(0.0, now - win.held_since)
            st.held_s += held_s
            win.held_since = None
        overtook = 0
        for w in windows:
            if (w is not win and w.seq < win.seq and w.result is None
                    and not w.dispatching and not w.abandoned
                    and w.prio > win.prio):
                overtook += 1
                if w.held_since is None:
                    w.held_since = now
        if overtook:
            st.preemptions += 1
        return {"lane": win.lane, "batch": len(win.items),
                "overtook": overtook, "held_s": held_s,
                "deficit": self._deficit.get(win.lane, 0.0)}

    def emit(self, ev: dict | None) -> None:
        """Publish a dispatch event outside the pipeline cv."""
        if ev is None:
            return
        sm = libmetrics.scheduler_metrics()
        if sm is not None:
            lane = ev["lane"]
            sm.dispatched_windows.labels(lane).inc()
            sm.dispatched_sigs.labels(lane).inc(ev["batch"])
            sm.lane_deficit.labels(lane).set(ev["deficit"])
            if ev["overtook"]:
                sm.preemptions.labels(lane).inc()
            if ev["held_s"]:
                sm.held_seconds.labels(lane).inc(ev["held_s"])
        if ev["overtook"]:
            flightrec.record(flightrec.EV_SCHED_PREEMPT, lane=ev["lane"],
                             batch=ev["batch"], overtook=ev["overtook"])

    # -- window-formation advisory -------------------------------------------
    def seal_due(self, windows, label: str, now: float) -> bool:
        """Should an accumulator (votestream, coalescer) seal its
        in-formation window now instead of batching further?

        True only when the queue holds work from a *different*
        priority class — the preemption signal (higher class queued:
        our bulk should be cut short so it clears fast; lower class
        queued: we should seal now and jump it).  False on an empty
        queue (the accumulator's flush interval IS the designed
        latency; sealing per-item whenever the pipeline goes idle
        would defeat coalescing entirely) and under pure own-class
        backpressure, where batching up is the efficient move."""
        if not self.enabled:
            return False
        pr = self.priority(label)
        for w in windows:
            if w.result is not None or w.dispatching or w.abandoned:
                continue
            if self._eff_prio(w, now) != pr:
                return True
        return False

    # -- introspection -------------------------------------------------------
    def snapshot(self) -> dict:
        """Per-lane counters for benches and scenario checkers."""
        return {
            lbl: {"windows": st.windows, "sigs": st.sigs,
                  "preemptions": st.preemptions,
                  "held_s": st.held_s,
                  "deficit": self._deficit.get(lbl, 0.0)}
            for lbl, st in sorted(self._stats.items())
        }
