"""OpenPGP ASCII armor (reference crypto/armor/armor.go, which wraps
golang.org/x/crypto/openpgp/armor).

Wire format (RFC 4880 §6.2): an armor header line naming the block
type, optional `Key: Value` headers, a blank line, base64 body wrapped
at 64 columns, a CRC24 checksum line (`=` + 4 base64 chars), and the
tail line.  encode_armor/decode_armor mirror EncodeArmor/DecodeArmor.
"""

from __future__ import annotations

import base64

_CRC24_INIT = 0xB704CE
_CRC24_POLY = 0x1864CFB
_LINE_WIDTH = 64


class ArmorError(ValueError):
    """Malformed armor input (reference returns wrapped errors)."""


def _crc24(data: bytes) -> int:
    crc = _CRC24_INIT
    for b in data:
        crc ^= b << 16
        for _ in range(8):
            crc <<= 1
            if crc & 0x1000000:
                crc ^= _CRC24_POLY
    return crc & 0xFFFFFF


def encode_armor(block_type: str, headers: dict[str, str] | None,
                 data: bytes) -> str:
    """EncodeArmor (crypto/armor/armor.go:24)."""
    if not block_type or "\n" in block_type:
        raise ArmorError("invalid block type")
    lines = [f"-----BEGIN {block_type}-----"]
    for k, v in (headers or {}).items():
        if ":" in k or "\n" in k or "\n" in v:
            raise ArmorError(f"invalid armor header {k!r}")
        lines.append(f"{k}: {v}")
    lines.append("")
    b64 = base64.b64encode(data).decode()
    lines.extend(b64[i:i + _LINE_WIDTH]
                 for i in range(0, len(b64), _LINE_WIDTH))
    crc = _crc24(data).to_bytes(3, "big")
    lines.append("=" + base64.b64encode(crc).decode())
    lines.append(f"-----END {block_type}-----")
    return "\n".join(lines) + "\n"


def decode_armor(armor_str: str) -> tuple[str, dict[str, str], bytes]:
    """DecodeArmor (crypto/armor/armor.go:41): returns
    (block_type, headers, data); raises ArmorError on malformed input,
    a bad checksum, or a BEGIN/END type mismatch."""
    lines = armor_str.splitlines()
    i = 0
    while i < len(lines) and not lines[i].startswith("-----BEGIN "):
        i += 1
    if i == len(lines) or not lines[i].endswith("-----"):
        raise ArmorError("no armor begin line")
    block_type = lines[i][len("-----BEGIN "):-len("-----")]
    i += 1

    headers: dict[str, str] = {}
    while i < len(lines):
        line = lines[i].strip()
        if not line:
            i += 1
            break
        if ": " in line:
            k, _, v = line.partition(": ")
            headers[k] = v
            i += 1
        else:
            break                      # body starts without blank line

    b64_parts: list[str] = []
    crc_line = None
    end_type = None
    for j in range(i, len(lines)):
        line = lines[j].strip()
        if line.startswith("-----END ") and line.endswith("-----"):
            end_type = line[len("-----END "):-len("-----")]
            break
        if line.startswith("="):
            crc_line = line[1:]
            continue
        if line:
            b64_parts.append(line)
    if end_type is None:
        raise ArmorError("no armor end line")
    if end_type != block_type:
        raise ArmorError(
            f"armor type mismatch: BEGIN {block_type!r} vs END "
            f"{end_type!r}")
    try:
        data = base64.b64decode("".join(b64_parts), validate=True)
    except Exception as e:
        raise ArmorError(f"invalid armor body: {e}") from e
    if crc_line is not None:
        try:
            want = int.from_bytes(
                base64.b64decode(crc_line, validate=True), "big")
        except Exception as e:
            raise ArmorError(f"invalid armor checksum: {e}") from e
        if want != _crc24(data):
            raise ArmorError("armor checksum mismatch")
    return block_type, headers, data
