"""RFC-6962-style Merkle tree (the crypto/merkle analog).

Root hashing, inclusion proofs, and proof verification matching the
reference byte-for-byte (/root/reference/crypto/merkle/tree.go:11-61,
proof.go:79, hash.go: leaf prefix 0x00, inner prefix 0x01, split point =
largest power of two < n, empty tree = SHA-256 of nothing).

Host-side hashlib is used for small trees; `hash_leaves_device` batches
leaf hashing through the TPU SHA-256 kernel for large inputs (10k-entry
validator sets), where leaf hashing dominates.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def empty_hash() -> bytes:
    return _sha256(b"")


def leaf_hash(leaf: bytes) -> bytes:
    return _sha256(LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(INNER_PREFIX + left + right)


def split_point(n: int) -> int:
    """Largest power of 2 strictly less than n (tree.go getSplitPoint)."""
    if n < 1:
        raise ValueError("split_point requires n >= 1")
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def _root_from_leaf_hashes(hashes: list[bytes]) -> bytes:
    n = len(hashes)
    if n == 0:
        return empty_hash()
    if n == 1:
        return hashes[0]
    k = split_point(n)
    return inner_hash(_root_from_leaf_hashes(hashes[:k]),
                      _root_from_leaf_hashes(hashes[k:]))


def hash_from_byte_slices(items: list[bytes]) -> bytes:
    """Merkle root of arbitrary byte slices (tree.go:11)."""
    return _root_from_leaf_hashes([leaf_hash(x) for x in items])


def hash_leaves_device(items: list[bytes]) -> list[bytes]:
    """Batch the leaf hashes on the TPU SHA-256 kernel.

    For an n-leaf tree the n leaf hashes are the data-parallel bulk of
    the work; the ~n inner hashes form a log-depth tree we keep on host
    (their inputs depend on prior outputs, a poor fit for one batched
    kernel launch at these sizes).
    """
    from .hash import sum_sha256_many
    return sum_sha256_many([LEAF_PREFIX + x for x in items])


def hash_from_byte_slices_device(items: list[bytes]) -> bytes:
    return _root_from_leaf_hashes(hash_leaves_device(items))


@dataclass
class Proof:
    """Inclusion proof for item `index` of `total` (proof.go:28-47)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: list[bytes] = field(default_factory=list)

    def compute_root_hash(self) -> bytes | None:
        return _compute_hash_from_aunts(self.index, self.total,
                                        self.leaf_hash, self.aunts)

    def verify(self, root_hash: bytes, leaf: bytes) -> None:
        """Raises ValueError unless this proof places leaf under root."""
        if self.total < 0:
            raise ValueError("proof total must be positive")
        if self.index < 0:
            raise ValueError("proof index cannot be negative")
        if leaf_hash(leaf) != self.leaf_hash:
            raise ValueError("invalid leaf hash")
        computed = self.compute_root_hash()
        if computed != root_hash:
            raise ValueError(
                f"invalid root hash: wanted {root_hash.hex()} "
                f"got {computed.hex() if computed else None}")

    def to_proto(self) -> bytes:
        """Wire format of crypto.Proof (proto/cometbft/crypto/v1/proof.proto)."""
        from ..libs import protowire as pw
        w = (pw.Writer().int_field(1, self.total).int_field(2, self.index)
             .bytes_field(3, self.leaf_hash))
        for aunt in self.aunts:
            w.bytes_field(4, aunt)
        return w.bytes()

    @staticmethod
    def from_proto(payload: bytes) -> "Proof":
        from ..libs import protowire as pw
        r = pw.Reader(payload)
        total, index, leaf, aunts = 0, 0, b"", []
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1 and w == pw.VARINT:
                total = r.read_int()
            elif f == 2 and w == pw.VARINT:
                index = r.read_int()
            elif f == 3 and w == pw.BYTES:
                leaf = r.read_bytes()
            elif f == 4 and w == pw.BYTES:
                aunts.append(r.read_bytes())
            else:
                r.skip(w)
        return Proof(total=total, index=index, leaf_hash=leaf, aunts=aunts)


def _compute_hash_from_aunts(index: int, total: int, leaf: bytes,
                             aunts: list[bytes]) -> bytes | None:
    if index >= total or index < 0 or total <= 0:
        return None
    if total == 1:
        if aunts:
            return None
        return leaf
    if not aunts:
        return None
    k = split_point(total)
    if index < k:
        left = _compute_hash_from_aunts(index, k, leaf, aunts[:-1])
        if left is None:
            return None
        return inner_hash(left, aunts[-1])
    right = _compute_hash_from_aunts(index - k, total - k, leaf, aunts[:-1])
    if right is None:
        return None
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: list[bytes]) -> tuple[bytes, list[Proof]]:
    """Root + one proof per item (proof.go ProofsFromByteSlices)."""
    trails, root = _trails_from_leaf_hashes([leaf_hash(x) for x in items])
    proofs = [
        Proof(total=len(items), index=i, leaf_hash=t.hash,
              aunts=t.flatten_aunts())
        for i, t in enumerate(trails)
    ]
    return root.hash if root else empty_hash(), proofs


class _Node:
    __slots__ = ("hash", "parent", "left", "right")

    def __init__(self, h: bytes):
        self.hash = h
        self.parent = self.left = self.right = None

    def flatten_aunts(self) -> list[bytes]:
        out = []
        node = self
        while node.parent is not None:
            sibling = (node.parent.right if node.parent.left is node
                       else node.parent.left)
            if sibling is not None:
                out.append(sibling.hash)
            node = node.parent
        return out


def _trails_from_leaf_hashes(hashes: list[bytes]):
    n = len(hashes)
    if n == 0:
        return [], None
    if n == 1:
        node = _Node(hashes[0])
        return [node], node
    k = split_point(n)
    lefts, left_root = _trails_from_leaf_hashes(hashes[:k])
    rights, right_root = _trails_from_leaf_hashes(hashes[k:])
    root = _Node(inner_hash(left_root.hash, right_root.hash))
    root.left, root.right = left_root, right_root
    left_root.parent = right_root.parent = root
    return lefts + rights, root
