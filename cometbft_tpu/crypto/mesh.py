"""Mesh-aware verify dispatch: the multi-device shipping layer.

Promotes the dryrun/validation artifacts (ops/sharding.py,
ops/msm_shard.py, __graft_entry__.dryrun_multichip) into the dispatch
path crypto/batch.py and crypto/dispatch.py actually run.  Three
shapes of parallelism, per ops/sharding.py's design note:

- per-signature verdict kernel: embarrassingly parallel along the
  batch axis — sharded over the 1-D mesh with ONE verdict-bitmap
  gather (ops/sharding.verify_batch_sharded; buckets auto-sized so the
  mesh divides them, ops/sharding.auto_bucket);
- RLC whole-batch kernel: stays single-chip per dispatch.  With >1
  chip a multi-commit window SPLITS ACROSS chips — contiguous chunks,
  one RLC program per chip (split_rlc_verify), each program placed by
  committing its packed inputs to its device.  Chunk verdicts preserve
  the per-chunk reject structure, so a reject localizes with the
  sharded per-signature kernel exactly like the single-chip fallback;
- window round-robin: crypto/dispatch.VerifyPipeline(devices=...)
  rotates depth-K windows over the mesh with per-device in-flight
  tracking and a per-device drain-to-host fault path.

Everything here is CPU-verifiable on the 8-virtual-device mesh
(tests/conftest.py forces xla_force_host_platform_device_count=8); the
same code runs unchanged on a real TPU mesh.  Multi-device dispatch is
OPT-IN via the COMETBFT_TPU_MESH_DEVICES knob or explicit device
lists — see ops/sharding.mesh_device_list.
"""

from __future__ import annotations

import os
import time

import numpy as np

# one RLC program per chip only pays once each chip's chunk amortizes
# its own dispatch + per-chunk pack; below this window size the
# single-device RLC (or the sharded per-signature kernel) wins
MIN_SPLIT = int(os.environ.get("COMETBFT_TPU_MESH_MIN_SPLIT", "256"))


def split_spans(n: int, ndev: int) -> list[tuple[int, int]]:
    """Contiguous near-equal [start, end) chunks, every chunk
    non-empty; fewer spans than devices when n < ndev."""
    ndev = max(1, min(ndev, n))
    base, rem = divmod(n, ndev)
    spans, start = [], 0
    for i in range(ndev):
        end = start + base + (1 if i < rem else 0)
        spans.append((start, end))
        start = end
    return spans


def _healthy_devices(devices):
    """Filter the mesh rotation through the process health registry
    (crypto/devhealth.py): quarantined chips drop out of the split.
    Falls back to the full list when no registry is installed or when
    EVERY chip is benched — a split onto quarantined chips is still
    better than an unannounced behavior change here; the pipeline's
    brownout path is what actually owns the all-dead case."""
    from . import devhealth

    reg = devhealth.registry()
    if reg is None:
        return devices
    usable = [d for i, d in enumerate(devices) if reg.usable(str(i))]
    return usable if usable else devices


def _count_dispatch(i: int, n: int = 0) -> None:
    from ..libs import devprof
    from ..libs import metrics as libmetrics

    dm = libmetrics.device_metrics()
    if dm is not None:
        dm.mesh_dispatches.labels(str(i)).inc()
    # split-RLC chunks bypass the pipeline's per-device accounts; a
    # counter-track sample keeps them visible on the devprof timeline
    rec = devprof.recorder()
    if rec is not None:
        rec.counter("mesh_split_chunk_sigs/dev%d" % i, n)


def split_rlc_verify(pubkeys: list[bytes], parsed, devices,
                     use_cache: bool | None = None):
    """One multi-commit window split ACROSS the mesh: chunk i packs on
    the host, commits to devices[i], and dispatches its own RLC
    program; every chip's program is in flight before any verdict is
    read back.  Returns the per-chunk bool list (len == number of
    spans), or None when any chunk fails structural packing — the
    caller localizes per signature either way."""
    from . import ed25519 as ed

    n = len(pubkeys)
    spans = split_spans(n, len(devices))
    packs = []
    for a, b in spans:
        m = b - a
        packed = ed.pack_rlc(pubkeys[a:b], [b""] * m, [b""] * m,
                             parsed=parsed[a:b])
        if packed is None:
            return None
        packs.append(packed)
    outs = []
    for i, (packed, dev_) in enumerate(zip(packs, devices)):
        outs.append(ed.rlc_verify_async(packed, use_cache=use_cache,
                                        device=dev_))
        _count_dispatch(i, spans[i][1] - spans[i][0])
    return [bool(np.asarray(o)) for o in outs]


def maybe_split_verify(pubkeys: list[bytes], parsed,
                       min_split: int | None = None):
    """The crypto/batch._device_verify hook: None when the mesh split
    does not apply (mesh off, too few devices, window under
    MIN_SPLIT); otherwise the whole-window RLC verdict (True = every
    chunk verified; False = some chunk rejected, localize)."""
    n = len(pubkeys)
    if n < (min_split if min_split is not None else MIN_SPLIT):
        return None
    from ..ops import sharding

    devices = sharding.mesh_device_list(None)
    if devices is None:
        return None
    devices = _healthy_devices(devices)
    if len(devices) < 2:
        return None
    verdicts = split_rlc_verify(pubkeys, parsed, devices)
    if verdicts is None:
        return False
    return all(verdicts)


def split_rlc_verify_hash(pubkeys: list[bytes], msgs: list[bytes],
                          parsed, devices):
    """split_rlc_verify for the fused hash-to-scalar kernel: each
    chunk's pack carries its own message blocks (blocks_hi/lo travel to
    that chunk's chip with the rest of the pack), so the device-hash
    mode splits across a mesh exactly like the host-hash mode.
    `parsed` is a parse_batch result ((r_enc, s) | None).  Propagates
    pack_rlc_device_hash's ValueError on an oversized message."""
    from . import ed25519 as ed

    n = len(pubkeys)
    spans = split_spans(n, len(devices))
    packs = []
    for a, b in spans:
        packed = ed.pack_rlc_device_hash(pubkeys[a:b], msgs[a:b],
                                         [b""] * (b - a),
                                         parsed=parsed[a:b])
        if packed is None:
            return None
        packs.append(packed)
    outs = []
    for i, (packed, dev_) in enumerate(zip(packs, devices)):
        outs.append(ed.rlc_verify_hash_async(packed, device=dev_))
        _count_dispatch(i, spans[i][1] - spans[i][0])
    return [bool(np.asarray(o)) for o in outs]


def maybe_split_verify_hash(pubkeys: list[bytes], msgs: list[bytes],
                            parsed, min_split: int | None = None):
    """maybe_split_verify for the device-hash mode (see
    crypto/batch._device_verify_hash)."""
    n = len(pubkeys)
    if n < (min_split if min_split is not None else MIN_SPLIT):
        return None
    from ..ops import sharding

    devices = sharding.mesh_device_list(None)
    if devices is None:
        return None
    devices = _healthy_devices(devices)
    if len(devices) < 2:
        return None
    verdicts = split_rlc_verify_hash(pubkeys, msgs, parsed, devices)
    if verdicts is None:
        return False
    return all(verdicts)


def verify_batch_mesh(pubkeys: list[bytes], parsed):
    """Per-signature verdicts with the batch axis sharded over the
    mesh and the bucket auto-sized from device_count() — the
    embarrassingly-parallel path, one verdict-bitmap gather."""
    from ..ops import ed25519 as dev  # noqa: F401 (bucket constants)
    from ..ops import sharding
    from . import ed25519 as ed

    n = len(pubkeys)
    bucket = sharding.auto_bucket(n)
    a, r, s, h, valid = ed.pack_batch(pubkeys, [b""] * n, [b""] * n,
                                      bucket, parsed=parsed)
    verdict = np.asarray(sharding.verify_batch_sharded(a, r, s, h))
    return (verdict & valid)[:n].tolist()


def split_secp_verify(pubkeys: list[bytes], msgs: list[bytes],
                      sigs: list[bytes], devices):
    """split_rlc_verify for the unified secp256k1 MSM path: chunk i
    packs on the host (Joye-Tunstall recode + distinct-key table
    lookup through the QTableCache, keyed per device so each chip
    keeps its own resident copy) and dispatches its own MSM program;
    all chips are in flight before any verdict is read back.  Returns
    per-signature verdicts in submission order — the MSM verdicts are
    already per-signature, so unlike the RLC split there is no
    localization round to run on reject."""
    from . import secp256k1 as sk

    n = len(pubkeys)
    spans = split_spans(n, len(devices))
    outs = []
    for i, ((a, b), dev_) in enumerate(zip(spans, devices)):
        outs.append(sk.verify_msm_async(pubkeys[a:b], msgs[a:b],
                                        sigs[a:b], device=dev_))
        _count_dispatch(i, b - a)
    verdicts: list[bool] = []
    for verdict, valid, m in outs:
        out = np.asarray(verdict) & valid
        verdicts.extend(bool(v) for v in out[:m])
    return verdicts


def maybe_split_secp_verify(pubkeys: list[bytes], msgs: list[bytes],
                            sigs: list[bytes],
                            min_split: int | None = None):
    """The TpuSecp256k1BatchVerifier hook: None when the mesh split
    does not apply (mesh off, too few devices, window under
    MIN_SPLIT); otherwise the per-signature verdict list."""
    n = len(pubkeys)
    if n < (min_split if min_split is not None else MIN_SPLIT):
        return None
    from ..ops import sharding

    devices = sharding.mesh_device_list(None)
    if devices is None:
        return None
    devices = _healthy_devices(devices)
    if len(devices) < 2:
        return None
    return split_secp_verify(pubkeys, msgs, sigs, devices)


# -- CPU-mesh bench arm ------------------------------------------------------

def _demo_sigs(n: int, n_keys: int = 16, n_unique: int = 64):
    """Deterministic valid (pks, msgs, sigs): n_unique real signatures
    tiled to n (verdict parity does not need distinct messages, and
    pure-python signing at bench sizes would dominate the run)."""
    from . import ed25519_ref as ref

    keys = [ref.keygen(bytes([i + 1]) * 32) for i in range(n_keys)]
    uniq = []
    for i in range(min(n, n_unique)):
        seed, pub = keys[i % n_keys]
        msg = i.to_bytes(4, "little") * 6
        uniq.append((pub, msg, ref.sign(seed, msg)))
    tiled = [uniq[i % len(uniq)] for i in range(n)]
    return ([t[0] for t in tiled], [t[1] for t in tiled],
            [t[2] for t in tiled])


def bench_cpu_mesh(n: int = 512, rounds: int = 2) -> dict:
    """The bench.py multichip_* extras, run inside a CPU-forced child
    process with the 8-virtual-device mesh: sharded-vs-unsharded
    verdict parity (byte-identical bitmaps) plus scaling-efficiency
    numbers.  The real-chip arm rides the relay ledger — these numbers
    validate the dispatch machinery, not ICI bandwidth (8 virtual
    devices share one host's cores).

    Sized for the CPU mesh: the child lives inside bench.py's 600 s
    extras envelope (subprocess timeout 580 s) and an XLA-CPU RLC
    compile is minutes per fresh shape, so the RLC arms run small
    fixed windows on the width-16 program shapes the multichip dryrun
    and tier-1 mesh tests already hold in the persistent compile
    cache."""
    import jax

    from ..ops import ed25519 as dev
    from ..ops import sharding
    from . import ed25519 as ed

    ndev = sharding.device_count()
    pks, msgs, sigs = _demo_sigs(n)
    parsed = ed.parse_and_hash(pks, msgs, sigs)
    bucket = sharding.auto_bucket(n)
    a, r, s, h, valid = ed.pack_batch(pks, msgs, sigs, bucket,
                                      parsed=parsed)

    def timed(fn):
        out = np.asarray(fn())          # compile + warm
        t0 = time.perf_counter()
        for _ in range(rounds):
            got = np.asarray(fn())
        dt = (time.perf_counter() - t0) / rounds
        return out, got, dt

    un_v, _, un_dt = timed(lambda: dev.verify_batch_device(a, r, s, h))
    sh_v, _, sh_dt = timed(
        lambda: sharding.verify_batch_sharded(a, r, s, h))
    parity = un_v.tobytes() == sh_v.tobytes()
    assert bool((un_v & valid)[:n].all()), "bench batch must verify"

    # split-RLC across two chips vs one placed cached-A RLC program.
    # Both arms reuse the EXACT programs __graft_entry__'s multichip
    # dryrun compiles (16 sigs split 2-way = fused width-8 on devices
    # 0 and 1; 16 sigs cached-A width-16 placed on device 1) — a
    # fresh width-n RLC compile on XLA-CPU is minutes and would eat
    # the extras envelope.
    n_rlc = min(n, 16)
    rdevs = list(jax.devices())[:2]
    sp_parsed = ed.parse_and_hash(pks[:n_rlc], msgs[:n_rlc],
                                  sigs[:n_rlc])
    split_ok = split_rlc_verify(pks[:n_rlc], sp_parsed, rdevs)
    t0 = time.perf_counter()
    for _ in range(rounds):
        split_ok = split_rlc_verify(pks[:n_rlc], sp_parsed, rdevs)
    split_dt = (time.perf_counter() - t0) / rounds
    packed = ed.pack_rlc(pks[:n_rlc], [b""] * n_rlc,
                         [b""] * n_rlc, parsed=sp_parsed)
    single_ok = ed.rlc_verify(packed, use_cache=True, device=rdevs[-1])
    t0 = time.perf_counter()
    for _ in range(rounds):
        single_ok = ed.rlc_verify(packed, use_cache=True,
                                  device=rdevs[-1])
    single_dt = (time.perf_counter() - t0) / rounds
    assert split_ok is not None and all(split_ok) and single_ok, \
        "bench RLC must verify on both arms"

    return {
        "multichip_devices": ndev,
        "multichip_batch": n,
        "multichip_parity": bool(parity),
        "multichip_sharded_sigs_per_sec": round(n / sh_dt, 1),
        "multichip_unsharded_sigs_per_sec": round(n / un_dt, 1),
        # perfect data-parallel scaling would be ndev: virtual devices
        # share one host, so this measures dispatch overhead, not ICI
        "multichip_scaling_efficiency": round(
            un_dt / (sh_dt * ndev), 4) if sh_dt else 0.0,
        "multichip_split_rlc_sigs_per_sec": round(n_rlc / split_dt, 1),
        "multichip_single_rlc_sigs_per_sec": round(n_rlc / single_dt,
                                                   1),
    }


def _bench_child_main() -> None:  # pragma: no cover - subprocess entry
    """bench.py re-exec target: prints one JSON dict on stdout."""
    import json
    import sys

    n = int(os.environ.get("COMETBFT_TPU_MESH_BENCH_N", "512"))
    print(json.dumps(bench_cpu_mesh(n)))
    sys.stdout.flush()


if __name__ == "__main__":  # pragma: no cover
    _bench_child_main()
