"""ristretto255 (RFC 9496) over the edwards25519 field — the group
under sr25519/schnorrkel.

Points are internally extended-Edwards (x, y, z, t) integer tuples
(shared with ed25519_ref); encodings are the canonical 32-byte
ristretto strings.  Decoded points are guaranteed torsion-free, which
is what lets sr25519 batch verification reuse the cofactored ed25519
device kernel: on the prime-order subgroup the cofactored and
cofactorless equations coincide.
"""

from __future__ import annotations

from . import ed25519_ref as ed

P = ed.P
D = ed.D
SQRT_M1 = ed.SQRT_M1

def _is_negative(x: int) -> bool:
    return (x % P) & 1 == 1


def _abs(x: int) -> int:
    x %= P
    return P - x if _is_negative(x) else x


def _sqrt_ratio_m1(u: int, v: int) -> tuple[bool, int]:
    """(was_square, sqrt(u/v) or sqrt(i*u/v)), RFC 9496 §4.2."""
    u, v = u % P, v % P
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    correct = check == u % P
    flipped = check == (-u) % P
    flipped_i = check == (-u) % P * SQRT_M1 % P
    if flipped or flipped_i:
        r = r * SQRT_M1 % P
    was_square = correct or flipped
    return was_square, _abs(r)


# constant 1/sqrt(a-d) with a = -1 (RFC 9496 §4.1)
_ok, INVSQRT_A_MINUS_D = _sqrt_ratio_m1(1, (-1 - D) % P)
assert _ok


def decode(enc: bytes):
    """32-byte ristretto string -> extended point, or None if invalid."""
    if len(enc) != 32:
        return None
    s = int.from_bytes(enc, "little")
    if s >= P or _is_negative(s):
        return None
    ss = s * s % P
    u1 = (1 - ss) % P
    u2 = (1 + ss) % P
    u2_sqr = u2 * u2 % P
    v = (-(D * u1 % P * u1) - u2_sqr) % P
    was_square, invsqrt = _sqrt_ratio_m1(1, v * u2_sqr % P)
    den_x = invsqrt * u2 % P
    den_y = invsqrt * den_x % P * v % P
    x = _abs(2 * s % P * den_x % P)
    y = u1 * den_y % P
    t = x * y % P
    if not was_square or _is_negative(t) or y == 0:
        return None
    return (x, y, 1, t)


def encode(p) -> bytes:
    """Extended point -> canonical 32-byte ristretto string
    (RFC 9496 §4.3.2)."""
    x0, y0, z0, t0 = p
    u1 = (z0 + y0) % P * ((z0 - y0) % P) % P
    u2 = x0 * y0 % P
    _, invsqrt = _sqrt_ratio_m1(1, u1 * u2 % P * u2 % P)
    den1 = invsqrt * u1 % P
    den2 = invsqrt * u2 % P
    z_inv = den1 * den2 % P * t0 % P
    ix0 = x0 * SQRT_M1 % P
    iy0 = y0 * SQRT_M1 % P
    enchanted = den1 * INVSQRT_A_MINUS_D % P
    rotate = _is_negative(t0 * z_inv % P)
    if rotate:
        x, y, den_inv = iy0, ix0, enchanted
    else:
        x, y, den_inv = x0, y0, den2
    if _is_negative(x * z_inv % P):
        y = (-y) % P
    s = _abs(den_inv * ((z0 - y) % P) % P)
    return s.to_bytes(32, "little")


def eq(p, q) -> bool:
    """Ristretto equality (RFC 9496 §4.5): cosets compare equal."""
    x1, y1, _, _ = p
    x2, y2, _, _ = q
    return (x1 * y2 - y1 * x2) % P == 0 or \
        (y1 * y2 - x1 * x2) % P == 0


BASEPOINT = ed.B                     # same generator as edwards25519
add = ed.point_add
mul = ed.point_mul
neg = ed.point_neg
IDENTITY = (0, 1, 1, 0)
