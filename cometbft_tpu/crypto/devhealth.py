"""Per-device health state machine: the verify plane's availability
contract with the accelerator.

The FPGA ECDSA-engine literature (arxiv 2112.02229) treats the
accelerator as an unreliable offload engine behind a host-side
supervisor, and the EdDSA committee-consensus study (arxiv 2302.00418)
shows verification cost directly bounds consensus liveness.  Before
this module the pipeline's answer to a faulting chip was a one-shot
drain-to-host and an immediate resume (crypto/dispatch.py cleared its
fault flag the moment the queue emptied), so a flapping device
thrashed drain -> resume -> fault forever and a HUNG dispatch was
never detected at all.

The circuit breaker here closes that gap.  Each device walks

    HEALTHY --fault--> SUSPECT --fault-rate/hang--> QUARANTINED
       ^                                                |
       |                                       backoff expired
       +------- probe ok ------- PROBING <--------------+
                                    |
                                    +-- probe fail --> QUARANTINED
                                        (backoff doubles)

- HEALTHY: in rotation.  SUSPECT: a recent fault inside
  ``fault_window_s``; still in rotation (one transient error must not
  eject a chip — tests pin that a single drain recovers on-device).
- QUARANTINED: ``quarantine_after`` faults inside the window, or one
  hang.  Out of rotation: the pipeline routes this device's windows to
  the host and round-robins new windows onto healthy chips.
- PROBING: a known-answer probe batch (``probe_items``) is in flight.
  Probes are the ONLY device traffic a quarantined chip sees; they are
  scheduled with exponential backoff (``probe_backoff_s`` doubling to
  ``probe_backoff_max_s``) so a dead chip costs O(log) probes, not a
  retry storm.  A probe passes only when the verdict vector matches
  ``probe_expected`` exactly — a forging device (all-true) fails the
  deliberately-corrupted lane, a draining device raises.

Every transition drives DeviceMetrics (device_health_state gauge,
device_quarantines_total, device_probes_total) and flightrec
(EV_DEVICE_QUARANTINE / EV_DEVICE_PROBE) through the same process
seams the rest of the crypto layer uses.  scripts/check_metrics.py
lints literal ``.transition(dev, "<state>")`` / ``.probe_result(dev,
"<result>")`` call sites against the HEALTH_STATES / PROBE_RESULTS
registries below, the same closed-vocabulary discipline as devprof's
DISPATCH_KINDS.
"""

from __future__ import annotations

import os
import time

from ..libs import lockrank

HEALTH_HEALTHY = "healthy"
HEALTH_SUSPECT = "suspect"
HEALTH_QUARANTINED = "quarantined"
HEALTH_PROBING = "probing"
# closed registries — scripts/check_metrics.py parses these (AST, no
# import) and lints every literal call site against them
HEALTH_STATES = frozenset({"healthy", "suspect", "quarantined",
                           "probing"})
PROBE_OK = "ok"
PROBE_FAIL = "fail"
PROBE_RESULTS = frozenset({"ok", "fail"})

# numeric codes for the device_health_state gauge (dashboards alert on
# `>= 2`: quarantined or probing = out of rotation)
STATE_CODES = {HEALTH_HEALTHY: 0, HEALTH_SUSPECT: 1,
               HEALTH_QUARANTINED: 2, HEALTH_PROBING: 3}

DEFAULT_QUARANTINE_AFTER = int(os.environ.get(
    "COMETBFT_TPU_QUARANTINE_AFTER", "3"))
DEFAULT_FAULT_WINDOW_S = float(os.environ.get(
    "COMETBFT_TPU_FAULT_WINDOW_S", "30"))
DEFAULT_PROBE_BACKOFF_S = float(os.environ.get(
    "COMETBFT_TPU_PROBE_BACKOFF_S", "0.5"))
DEFAULT_PROBE_BACKOFF_MAX_S = float(os.environ.get(
    "COMETBFT_TPU_PROBE_BACKOFF_MAX_S", "30"))


class _DeviceRecord:
    __slots__ = ("state", "fault_times", "quarantines", "probes_ok",
                 "probes_failed", "backoff_s", "next_probe_at",
                 "last_quarantine_t", "recovery_seconds", "last_reason")

    def __init__(self, backoff_s: float):
        self.state = HEALTH_HEALTHY
        self.fault_times: list[float] = []
        self.quarantines = 0
        self.probes_ok = 0
        self.probes_failed = 0
        self.backoff_s = backoff_s
        self.next_probe_at = 0.0
        self.last_quarantine_t: float | None = None
        # quarantine-entry -> probe-ok durations (newest last): the
        # chaos bench's chaos_flap_recovery_seconds reads these
        self.recovery_seconds: list[float] = []
        self.last_reason: str | None = None


class HealthRegistry:
    """Thread-safe per-device state machine (module docstring).

    Devices are keyed by the pipeline's device string ("0", "1", ...).
    The clock is injectable so tests drive transitions without
    sleeping.  Methods never call back into the pipeline — the
    pipeline holds its own condition variable while consulting this
    registry, so the lock order is always pipeline-cv -> registry."""

    def __init__(self, quarantine_after: int | None = None,
                 fault_window_s: float | None = None,
                 probe_backoff_s: float | None = None,
                 probe_backoff_max_s: float | None = None,
                 clock=time.monotonic):
        self.quarantine_after = max(1, quarantine_after
                                    if quarantine_after is not None
                                    else DEFAULT_QUARANTINE_AFTER)
        self.fault_window_s = (fault_window_s
                               if fault_window_s is not None
                               else DEFAULT_FAULT_WINDOW_S)
        self.probe_backoff_s = (probe_backoff_s
                                if probe_backoff_s is not None
                                else DEFAULT_PROBE_BACKOFF_S)
        self.probe_backoff_max_s = (probe_backoff_max_s
                                    if probe_backoff_max_s is not None
                                    else DEFAULT_PROBE_BACKOFF_MAX_S)
        self._clock = clock
        # RLock: the note_*/probe_result entry points hold it while
        # funneling through transition()
        self._mtx = lockrank.RankedRLock("devhealth.registry")
        self._recs: dict[str, _DeviceRecord] = {}

    def _rec(self, device: str) -> _DeviceRecord:
        r = self._recs.get(device)
        if r is None:
            r = self._recs[device] = _DeviceRecord(self.probe_backoff_s)
        return r

    # -- queries -----------------------------------------------------------

    def state(self, device: str) -> str:
        with self._mtx:
            r = self._recs.get(device)
            return r.state if r is not None else HEALTH_HEALTHY

    def usable(self, device: str) -> bool:
        """In rotation for real traffic: healthy or suspect.  A
        quarantined/probing device sees only probe batches."""
        return self.state(device) in (HEALTH_HEALTHY, HEALTH_SUSPECT)

    def all_quarantined(self, devices) -> bool:
        """Every listed device out of rotation — the pipeline's
        brownout predicate."""
        devices = list(devices)
        if not devices:
            return False
        return all(not self.usable(d) for d in devices)

    def quarantines(self, device: str) -> int:
        with self._mtx:
            r = self._recs.get(device)
            return r.quarantines if r is not None else 0

    def recovery_seconds(self, device: str) -> list[float]:
        """Quarantine-entry -> probe-ok durations, newest last."""
        with self._mtx:
            r = self._recs.get(device)
            return list(r.recovery_seconds) if r is not None else []

    def snapshot(self) -> dict:
        """Introspection dump (pprof /debug/pprof/devhealth, chaos
        artifacts): per-device state + counters."""
        with self._mtx:
            now = self._clock()
            return {dev: {"state": r.state,
                          "faults_in_window": len(
                              [t for t in r.fault_times
                               if now - t <= self.fault_window_s]),
                          "quarantines": r.quarantines,
                          "probes_ok": r.probes_ok,
                          "probes_failed": r.probes_failed,
                          "backoff_s": r.backoff_s,
                          "recovery_seconds":
                              list(r.recovery_seconds),
                          "last_reason": r.last_reason}
                    for dev, r in sorted(self._recs.items())}

    def dump_text(self) -> str:
        lines = ["devhealth: per-device circuit breaker state", ""]
        snap = self.snapshot()
        if not snap:
            lines.append("  (no devices tracked)")
        for dev, s in snap.items():
            lines.append(
                "  dev %s: %-11s quarantines=%d probes=%d/%d "
                "faults_in_window=%d backoff=%.2fs%s" % (
                    dev, s["state"], s["quarantines"], s["probes_ok"],
                    s["probes_ok"] + s["probes_failed"],
                    s["faults_in_window"], s["backoff_s"],
                    (" reason=%s" % s["last_reason"])
                    if s["last_reason"] else ""))
        return "\n".join(lines)

    # -- transitions -------------------------------------------------------

    def note_ok(self, device: str) -> None:
        """A real window dispatched clean on this device.  SUSPECT
        clears back to HEALTHY once the fault window has drained —
        interleaved successes never mask a flap's fault rate."""
        with self._mtx:
            r = self._recs.get(device)
            if r is None or r.state != HEALTH_SUSPECT:
                return
            now = self._clock()
            r.fault_times = [t for t in r.fault_times
                             if now - t <= self.fault_window_s]
            if not r.fault_times:
                self.transition(device, "healthy")

    def note_fault(self, device: str, reason: str = "fault") -> bool:
        """A device dispatch raised.  Returns True when this fault
        tripped the breaker (the device just quarantined)."""
        with self._mtx:
            r = self._rec(device)
            if r.state in (HEALTH_QUARANTINED, HEALTH_PROBING):
                return False
            now = self._clock()
            r.fault_times = [t for t in r.fault_times
                             if now - t <= self.fault_window_s]
            r.fault_times.append(now)
            r.last_reason = reason
            if len(r.fault_times) >= self.quarantine_after:
                self.transition(device, "quarantined", reason=reason)
                return True
            if r.state == HEALTH_HEALTHY:
                self.transition(device, "suspect", reason=reason)
            return False

    def note_hang(self, device: str) -> None:
        """The watchdog caught a dispatch past its deadline: straight
        to quarantine — a wedged chip gets no second fault."""
        with self._mtx:
            r = self._rec(device)
            r.last_reason = "hang"
            if r.state != HEALTH_QUARANTINED:
                self.transition(device, "quarantined", reason="hang")

    def due_probe(self, device: str) -> bool:
        """Quarantined and past its backoff: claim the probe slot
        (transitions to PROBING) and return True.  The caller MUST
        follow up with probe_result()."""
        with self._mtx:
            r = self._recs.get(device)
            if r is None or r.state != HEALTH_QUARANTINED:
                return False
            if self._clock() < r.next_probe_at:
                return False
            self.transition(device, "probing")
            return True

    def probe_result(self, device: str, result: str) -> None:
        """Verdict of a known-answer probe batch: "ok" returns the
        device to rotation and resets its backoff; "fail" doubles the
        backoff and re-quarantines."""
        if result not in PROBE_RESULTS:
            raise ValueError("unknown probe result %r" % (result,))
        with self._mtx:
            r = self._rec(device)
            now = self._clock()
            if result == PROBE_OK:
                r.probes_ok += 1
                r.fault_times = []
                r.backoff_s = self.probe_backoff_s
                if r.last_quarantine_t is not None:
                    r.recovery_seconds.append(
                        now - r.last_quarantine_t)
                    r.last_quarantine_t = None
                self.transition(device, "healthy")
            else:
                r.probes_failed += 1
                r.backoff_s = min(r.backoff_s * 2.0,
                                  self.probe_backoff_max_s)
                self.transition(device, "quarantined",
                                reason="probe_fail")
            self._record_probe(device, result, r.backoff_s)

    def transition(self, device: str, state: str,
                   reason: str | None = None) -> None:
        """Canonical transition funnel: every state change lands here,
        driving the health gauge, the quarantine counter and the
        flightrec breadcrumb.  Call sites pass LITERAL states so the
        check_metrics rule-7 lint sees them."""
        if state not in HEALTH_STATES:
            raise ValueError("unknown health state %r" % (state,))
        with self._mtx:
            r = self._rec(device)
            old = r.state
            if old == state:
                return
            r.state = state
            now = self._clock()
            fresh = False
            if state == HEALTH_QUARANTINED:
                # re-entry from a failed probe keeps the doubled
                # backoff and the original outage start; only a fresh
                # outage (from rotation) resets them
                fresh = old in (HEALTH_HEALTHY, HEALTH_SUSPECT)
                if fresh:
                    r.quarantines += 1
                    r.last_quarantine_t = now
                    r.backoff_s = self.probe_backoff_s
                r.next_probe_at = now + r.backoff_s
            self._record_transition(device, old, state, reason, fresh,
                                    r.backoff_s)

    # -- observability -----------------------------------------------------

    def _record_transition(self, device: str, old: str, state: str,
                           reason: str | None, fresh: bool,
                           backoff_s: float) -> None:
        from ..libs import flightrec
        from ..libs import metrics as libmetrics

        dm = libmetrics.device_metrics()
        if dm is not None:
            dm.health_state.labels(device).set(STATE_CODES[state])
            if state == HEALTH_QUARANTINED and fresh:
                dm.quarantines.labels(device).inc()
        if state == HEALTH_QUARANTINED:
            flightrec.record(flightrec.EV_DEVICE_QUARANTINE,
                             device=device, prev=old,
                             reason=reason or "fault_rate",
                             fresh=fresh,
                             backoff_s=round(backoff_s, 4))

    def _record_probe(self, device: str, result: str,
                      backoff_s: float) -> None:
        from ..libs import flightrec
        from ..libs import metrics as libmetrics

        dm = libmetrics.device_metrics()
        if dm is not None:
            dm.probes.labels(device, result).inc()
        flightrec.record(flightrec.EV_DEVICE_PROBE, device=device,
                         result=result, backoff_s=round(backoff_s, 4))


# -- known-answer probe batch -------------------------------------------------

# small enough that a probe is one cheap dispatch; >= 2 lanes so the
# RLC path (not just the per-signature kernel) is exercised, and the
# last lane is deliberately corrupted so a FORGING device (all-true
# without verifying) fails the probe just like a dead one
_PROBE_N = 4
_probe_cache = None


def probe_items():
    """Deterministic (pubkey, msg, sig) probe triples: _PROBE_N - 1
    valid signatures plus one corrupted lane.  Built once per process
    (pure-python signing), never inserted into the verdict cache by
    the pipeline's probe path."""
    global _probe_cache
    if _probe_cache is None:
        from .ed25519 import PrivKey

        items = []
        for i in range(_PROBE_N):
            priv = PrivKey.generate(bytes([0xD0 + i]) * 32)
            msg = b"devhealth-probe-%d" % i
            sig = priv.sign(msg)
            if i == _PROBE_N - 1:
                sig = sig[:4] + bytes([sig[4] ^ 0x55]) + sig[5:]
            items.append((priv.pub_key(), msg, sig))
        _probe_cache = tuple(items)
    return _probe_cache


def probe_expected() -> list[bool]:
    """The exact verdict vector a healthy device must return for
    probe_items() — anything else (including all-true) fails."""
    return [True] * (_PROBE_N - 1) + [False]


# -- process-wide seam --------------------------------------------------------

_registry: HealthRegistry | None = None


def set_registry(reg: HealthRegistry | None) -> None:
    """Install the process-wide registry (node wiring).  Pipelines
    constructed without an explicit `health=` adopt it so every
    dispatch engine in the process shares one view of the chips."""
    global _registry
    _registry = reg


def registry() -> HealthRegistry | None:
    return _registry
