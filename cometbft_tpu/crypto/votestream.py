"""Streaming signature verification: the deadline-flushed accumulator
between live consensus and the device (SURVEY §7 "latency vs
throughput"; the per-vote hot path is the reference's
types/vote_set.go:219-232 -> ed25519.go:181).

Gossiped votes are PRE-verified off the consensus-state thread: the
reactor submits (pubkey, sign_bytes, sig) as soon as a VoteMessage
arrives and attaches the resulting future to the vote; VoteSet.add_vote
consumes the verdict if (and only if) the submitted triple matches what
it would itself verify.  The verifier batches concurrent submissions:

- a worker collects submissions until the oldest has waited
  flush_interval or the batch hits max_batch;
- small flushes take the host fast path (OpenSSL verify with ZIP-215
  fallback, crypto/ed25519.PubKey.verify_signature) — one vote in
  steady-state consensus must not pay a device round-trip;
- flushes >= device_threshold go to the device RLC kernel with
  per-signature localization (crypto/batch._device_verify) — vote
  floods (late-joiner catchup, large validator sets) amortize onto the
  accelerator.

This mirrors MConnection's flush throttle (reference
p2p/conn/connection.go 10ms flushThrottle): latency-bounded batching at
the seam where throughput spikes.
"""

from __future__ import annotations

import os
import threading
import time
from ..libs import lockrank
from concurrent.futures import Future

from ..libs.service import BaseService

_FLUSH_INTERVAL = float(os.environ.get("COMETBFT_TPU_VOTE_FLUSH_MS", "2")) \
    / 1000.0
_DEVICE_THRESHOLD = int(os.environ.get(
    "COMETBFT_TPU_VOTE_DEVICE_THRESHOLD", "256"))
_MAX_BATCH = 4096
# how often the accumulating worker re-checks the pipeline's QoS seal
# advisory (qos_seal_due) while a batch is forming; only matters when
# flush_interval is large relative to it.  5ms keeps the worker's
# wake rate low (the advisory's empty-queue fast path makes each
# check a couple of attribute reads) while staying well inside the
# 50ms consensus SLO
_SEAL_POLL_S = 0.005


class StreamingVerifier(BaseService):
    """Deadline-flushed ed25519 verify accumulator."""

    def __init__(self, flush_interval: float = _FLUSH_INTERVAL,
                 device_threshold: int = _DEVICE_THRESHOLD,
                 max_batch: int = _MAX_BATCH, pipeline=None,
                 warmup: bool | None = None):
        super().__init__("StreamingVerifier")
        self.flush_interval = flush_interval
        self.device_threshold = device_threshold
        self.max_batch = max_batch
        # overlapped dispatch engine (crypto/dispatch.py); None = the
        # process-wide default, created lazily at first device flush
        self._pipeline = pipeline
        # pre-warm the device vote path at start (see _prewarm); None
        # defers to COMETBFT_TPU_VOTE_PREWARM, else warms only when a
        # real accelerator is attached
        self.warmup = warmup
        self.warmed = threading.Event()
        # (pubkey, msg, sig, future, trace_ctx_or_None)
        self._pending: list[tuple] = []
        # in-flight dedupe: triple -> the future already queued for it,
        # so two peers flooding the same vote share one batch slot
        self._inflight: dict[tuple, Future] = {}
        self._cv = lockrank.RankedCondition(name="votestream.cv")
        self._thread: threading.Thread | None = None
        self._stopping = False
        self.flushes = 0
        self.device_flushes = 0
        self.verified = 0
        self.coalesced = 0
        self.cache_hits = 0

    # -- service -----------------------------------------------------------

    def on_start(self) -> None:
        self._stopping = False
        self._thread = threading.Thread(
            target=self._worker, name="vote-verify-stream", daemon=True)
        self._thread.start()
        if self._should_warm():
            threading.Thread(target=self._prewarm,
                             name="vote-verify-warmup",
                             daemon=True).start()
        else:
            self.warmed.set()

    def _should_warm(self) -> bool:
        if self.warmup is not None:
            return self.warmup
        env = os.environ.get("COMETBFT_TPU_VOTE_PREWARM")
        if env is not None:
            return env == "1"
        # default policy: warm only with a real accelerator attached.
        # On the XLA-CPU backend the warmup COMPILE is itself the only
        # cold cost, and paying it at every test-process start would
        # dwarf what it saves.
        try:
            import jax

            return jax.default_backend() != "cpu"
        except Exception:
            return False

    def _prewarm(self) -> None:
        """Compile + dispatch one dummy device batch at start so the
        first real vote flood hits warm kernels: the 31.9 ms cold p99
        outlier on the flush=1ms latency ladder (latency_bench_r5.jsonl,
        VERDICT item 8) was one first-flush compile+dispatch, paid at
        the worst possible time.  Distinct keys size the A-side MSM
        width like a real device_threshold-sized flood, so the warmed
        RLC program shape is the one floods actually hit."""
        try:
            from . import ed25519_ref as ref
            from .dispatch import default_pipeline

            n = max(2, min(self.device_threshold, 256))
            items = []
            for i in range(n):
                seed, pub = ref.keygen(i.to_bytes(32, "little"))
                msg = b"cometbft-tpu-vote-prewarm-" + i.to_bytes(
                    4, "little")
                items.append((pub, msg, ref.sign(seed, msg)))
            pipe = self._pipeline if self._pipeline is not None \
                else default_pipeline()
            # lat=() opts the warmup window out of the latency ledger:
            # a 300s compile row would poison the consensus p99
            handle = pipe.submit(items, subsystem="consensus",
                                 device_threshold=2, lat=())
            handle.result(timeout=300)
        except Exception:  # pragma: no cover - warmup must never wedge
            pass
        finally:
            self.warmed.set()

    def on_stop(self) -> None:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=2)

    # -- API ---------------------------------------------------------------

    def submit(self, pubkey: bytes, msg: bytes, sig: bytes,
               ctx=None) -> Future:
        """Queue one signature; the future resolves to a bool verdict.
        The caller keeps (pubkey, msg, sig) to check the verdict applies
        to what it meant to verify.  ``ctx`` is an optional trace
        context (libs/tracetl.py) tagging the flush events with the
        consensus height/round that triggered the verify.

        Two fast exits before a batch slot is occupied:
        - verdict-cache hit (crypto/sigcache.py): the triple was
          already proved somewhere in the process — the returned
          future is ALREADY RESOLVED;
        - in-flight duplicate: the same triple is already queued (a
          second peer flooding the same vote) — the existing future is
          returned, one device verification serves both."""
        from . import sigcache
        from ..libs import latledger

        fut: Future = lockrank.TrackedFuture()
        # one latency-ledger request per submitted vote: resolved at
        # whichever seam answers (cache here, host/device at flush, or
        # coalesced onto the original's resolution)
        req = latledger.submit(1, consumer="consensus")
        if sigcache.enabled():
            v = sigcache.get(pubkey, msg, sig, key_type="ed25519",
                             label="consensus")
            if v is not None:
                self.cache_hits += 1
                fut.set_result(v)
                if req is not None:
                    req.resolve("cache")
                return fut
        with self._cv:
            if self._stopping or self._thread is None:
                fut.set_result(_host_verify(pubkey, msg, sig))
                if req is not None:
                    req.resolve("host")
                return fut
            triple = (pubkey, msg, sig)
            existing = self._inflight.get(triple)
            if existing is not None and not existing.done():
                self.coalesced += 1
                from ..libs import metrics as libmetrics

                cm = libmetrics.cache_metrics()
                if cm is not None:
                    cm.votestream_coalesced.inc()
                if req is not None:
                    # the duplicate's whole wait is the original's
                    # resolution: its row lands as coalesce_wait, and
                    # the original keeps its own decomposition
                    existing.add_done_callback(
                        lambda f, r=req: r.resolve_coalesced())
                return existing
            self._inflight[triple] = fut
            # the done-callback fires on resolve AND on cancel, so a
            # canceled slot stops absorbing new duplicates
            fut.add_done_callback(
                lambda f, t=triple: self._forget(t, f))
            self._pending.append((pubkey, msg, sig, fut, ctx, req))
            self._cv.notify()
        return fut

    def _forget(self, triple: tuple, fut: Future) -> None:
        with self._cv:
            if self._inflight.get(triple) is fut:
                del self._inflight[triple]

    def _seal_due(self) -> bool:
        """QoS preemption signal (VerifyPipeline.qos_seal_due): should
        the in-formation vote window seal now instead of waiting out
        the flush interval?  Peeks the pipeline this verifier would
        flush through — WITHOUT lazily creating one — and defers to
        its scheduler.  Rank-legal under self._cv: votestream.cv
        orders below dispatch.cv (libs/lockrank.py)."""
        pipe = self._pipeline
        if pipe is None:
            from . import dispatch

            pipe = dispatch._default
        # getattr: injected test pipelines are plain stubs with only
        # submit(); no advisory means no early seal
        seal = getattr(pipe, "qos_seal_due", None) \
            if pipe is not None else None
        if seal is None:
            return False
        return seal("consensus")

    # -- worker ------------------------------------------------------------

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._stopping:
                    self._cv.wait(timeout=0.1)
                if self._stopping:
                    batch, self._pending = self._pending, []
                else:
                    # deadline accumulation: let the batch grow until the
                    # OLDEST submission has waited flush_interval — or
                    # until the pipeline's QoS scheduler says sealing
                    # now beats batching further (cross-class work is
                    # queued behind us), so a single late vote never
                    # rides out the full interval behind a blocksync
                    # burst
                    deadline = time.monotonic() + self.flush_interval
                    while (len(self._pending) < self.max_batch
                           and not self._stopping):
                        left = deadline - time.monotonic()
                        if left <= 0:
                            break
                        if self._seal_due():
                            break
                        self._cv.wait(timeout=min(left, _SEAL_POLL_S))
                    batch, self._pending = self._pending, []
            if batch:
                self._flush(batch)
            if self._stopping:
                with self._cv:
                    leftover, self._pending = self._pending, []
                if leftover:
                    self._flush(leftover)
                return

    def _flush(self, batch) -> None:
        from . import sigcache
        from ..libs import devprof as libdevprof

        # devprof accounting (libs/devprof.py): below device_threshold
        # the worker thread IS the verify engine — account it under
        # device "0" like the pipeline's single-device loop does, so a
        # live consensus run (4-val simnet bench) still reads an
        # occupancy + idle-cause partition.  The gap since the last
        # mark was spent collecting the flood batch (or, on the early
        # returns below, the cache absorbed the whole flush) — either
        # way the engine was starved of work, not slow: no_work.
        dp = libdevprof.recorder()
        if dp is not None:
            dp.advance("0", libdevprof.IDLE_NO_WORK)

        # consumers cancel futures they already verified inline
        batch = [b for b in batch if not b[3].cancelled()]
        if not batch:
            return
        # late cache hits: verdicts inserted since submit (blocksync,
        # a previous flush, an inline verify) resolve here without
        # occupying a batch slot.  Misses were already counted at
        # submit time, so this re-check only accounts hits.
        cache_hits = 0
        if sigcache.enabled():
            verdicts, miss_idx = sigcache.partition(
                [(b[0], b[1], b[2]) for b in batch],
                label="consensus", count_misses=False)
            for b, v in zip(batch, verdicts):
                if v is not None and b[3].set_running_or_notify_cancel():
                    b[3].set_result(v)
                    if b[5] is not None:
                        b[5].resolve("cache")
            cache_hits = len(batch) - len(miss_idx)
            batch = [batch[i] for i in miss_idx]
            if not batch:
                return
        self.flushes += 1
        self.verified += len(batch)
        from ..libs import flightrec
        from ..libs import metrics as libmetrics
        from ..libs import trace as libtrace
        from ..libs import tracetl

        t0 = time.monotonic()
        if len(batch) >= self.device_threshold:
            try:
                # the vote-verify dispatch IS the consensus hot path
                # the stage-span framework exists for.  submit() is
                # non-blocking past backpressure: the worker returns to
                # COLLECTING the next flood batch while this window
                # packs/dispatches — the flood path no longer stalls on
                # a synchronous device round-trip.
                with libtrace.span("consensus", "verify_dispatch"), \
                        tracetl.span_for(self, "consensus",
                                         "verify_dispatch",
                                         cache=cache_hits):
                    self._flush_device(batch)
                return
            except Exception as e:
                # submit-time trouble (device errors mid-flight are
                # handled inside the pipeline's drain path): host
                # verdicts are still correct, but the operator must be
                # able to see it
                rec = flightrec.recorder()
                if rec is not None:
                    rec.record(flightrec.EV_DEVICE_FALLBACK,
                               batch=len(batch),
                               error=type(e).__name__)
                    rec.dump_to_log(
                        "device verify flush failed: %r" % e)
        path = "host"
        with libtrace.span("consensus", "verify_dispatch"), \
                tracetl.span_for(self, "consensus", "verify_dispatch",
                                 cache=cache_hits):
            for pk, msg, sig, fut, _, req in batch:
                # verdict first, future second: a consumer that
                # cancel-raced this flush (Preverified.verdict_for)
                # still gets the verdict CACHED, so its inline
                # re-verify is the last time the triple costs anything
                # (earlier votes' verify time IS this vote's queue
                # wait — the dispatch stamp cuts per vote)
                if req is not None:
                    req.stamp("dispatch")
                v = _host_verify(pk, msg, sig)
                sigcache.insert(pk, msg, sig, v, key_type="ed25519",
                                label="consensus")
                if req is not None:
                    req.stamp("compute_end")
                if fut.set_running_or_notify_cancel():
                    fut.set_result(v)
                if req is not None:
                    req.resolve(path)
        if dp is not None:
            dp.advance("0", libdevprof.BUSY, path=path)
        dm = libmetrics.device_metrics()
        if dm is not None:
            dm.flushes.labels(path).inc()
            dm.batch_size.labels(path).observe(len(batch))
            dm.flush_latency_seconds.labels(path).observe(
                time.monotonic() - t0)
        flightrec.record(flightrec.EV_VERIFY_FLUSH, path=path,
                         batch=len(batch), inflight=0, staged=0,
                         cache_hits=cache_hits,
                         **tracetl.ctx_fields(_batch_ctx(batch)))

    def _flush_device(self, batch) -> None:
        """Submit the flood batch through the overlapped pipeline and
        resolve the vote futures from its completion callback; the
        pipeline records the flush metrics/flightrec event (with its
        in-flight + staging depths) when the window resolves, and its
        drain path guarantees host verdicts on any device failure —
        the futures ALWAYS resolve to a bool."""
        from .dispatch import default_pipeline

        self.device_flushes += 1
        pipe = self._pipeline if self._pipeline is not None \
            else default_pipeline()
        # the per-vote ledger requests ride the window: the pipeline
        # stamps staging/dispatch/compute and resolves each with the
        # window's path, so queue_wait covers the pending-queue wait
        # from the ORIGINAL submit, not the flush
        lat = [b[5] for b in batch if b[5] is not None] or None
        handle = pipe.submit(
            [(pk, msg, sig) for pk, msg, sig, *_ in batch],
            subsystem="consensus", device_threshold=2,
            ctx=_batch_ctx(batch), lat=lat)

        def _resolve(h):
            from . import sigcache

            try:
                _, verdicts = h.result(timeout=0)
            except Exception:           # pragma: no cover - defensive
                verdicts = None
            if verdicts is None:
                for pk, msg, sig, fut, _, _ in batch:
                    v = _host_verify(pk, msg, sig)
                    sigcache.insert(pk, msg, sig, v,
                                    key_type="ed25519",
                                    label="consensus")
                    if fut.set_running_or_notify_cancel():
                        fut.set_result(v)
                return
            # verdicts for cancel-raced futures were inserted into the
            # verdict cache by the pipeline at window publication —
            # nothing re-verifies them even though set_running fails
            for (_, _, _, fut, _, _), ok in zip(batch, verdicts):
                if fut.set_running_or_notify_cancel():
                    fut.set_result(bool(ok))

        handle.add_done_callback(_resolve)


def _batch_ctx(batch):
    """First non-None trace context in the batch: a flush is one event,
    and the oldest submission is the one whose latency it bounds."""
    for entry in batch:
        if entry[4] is not None:
            return entry[4]
    return None


def _host_verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    from .ed25519 import PUBKEY_SIZE, PubKey

    if len(pk) != PUBKEY_SIZE:
        return False
    try:
        return PubKey(pk).verify_signature(msg, sig)
    except Exception:
        return False


# -- process-wide default instance ------------------------------------------

_default: StreamingVerifier | None = None
_default_lock = lockrank.RankedLock("votestream.default")


def default_verifier() -> StreamingVerifier:
    """Lazily-started shared instance (all reactors in a process feed
    one accumulator, maximizing batch opportunities)."""
    global _default
    with _default_lock:
        if _default is None or not _default.is_running():
            _default = StreamingVerifier()
            _default.start()
        return _default


class Preverified:
    """Verdict attached to a Vote by the reactor: the consumed-by
    VoteSet contract is exact-triple equality."""

    __slots__ = ("pubkey", "msg", "sig", "future")

    def __init__(self, pubkey: bytes, msg: bytes, sig: bytes,
                 future: Future):
        self.pubkey = pubkey
        self.msg = msg
        self.sig = sig
        self.future = future

    def verdict_for(self, pubkey: bytes, msg: bytes, sig: bytes):
        """Bool verdict if this preverification covers (pubkey, msg,
        sig) exactly AND already resolved; None otherwise.  Never
        blocks: the caller's inline verify costs microseconds, so a
        pending future is CANCELED (dropping it from the worker's
        batch — no duplicated work) and the caller verifies inline.
        During floods the state thread lags the verifier and futures
        are resolved by the time they are consumed — that is the case
        this path accelerates."""
        if (pubkey, msg, sig) != (self.pubkey, self.msg, self.sig):
            return None
        fut = self.future
        if fut.done() and not fut.cancelled():
            try:
                return bool(fut.result(timeout=0))
            except Exception:
                return None
        fut.cancel()
        return None
