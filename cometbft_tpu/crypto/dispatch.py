"""Overlapped host/device verify pipeline: the depth-K dispatch engine
between the product paths and the accelerator.

The blocksync residual profile (docs/PERF.md "Blocksync residual
bottleneck") shows the product path host-bound: ~240 ms/block of
strictly SERIAL collect -> host_pack -> device -> apply -> store
against ~6 ms of amortized device time.  The committee-verification
literature (arxiv 2112.02229 FPGA ECDSA, arxiv 2302.00418 EdDSA
committee consensus) gets its system-level wins from keeping the
verification stages CONCURRENT, not from faster primitives — this
module is that reformulation for the TPU seam:

- submit(items) returns immediately with a WindowHandle future;
- a STAGING thread runs the host work (SHA-512 sign-bytes hashing via
  parse_and_hash, signed-digit recode via pack_rlc) for window N+1
  while window N's RLC dispatch is in flight — hashlib and numpy
  release the GIL, so a small worker pool genuinely parallelizes the
  per-window parse+hash across cores (parse_and_hash_parallel);
- a DEVICE thread dispatches packed windows in QoS order (crypto/
  sched.py): priority lanes keyed by consumer label, deadline
  promotion, and deficit round-robin between equal-priority lanes.
  Verdicts still resolve in PER-LANE submission order — the ordering
  contract blocksync's apply loop and the light client's store loop
  rely on holds within each consumer's own stream, and with QoS off
  (COMETBFT_TPU_SCHED=0, or qos=False) everything shares one lane and
  the queue is exactly the old global FIFO;
- depth-K backpressure: submit() blocks once K windows are unresolved,
  bounding staging memory to K double-buffered windows.

Failure semantics match the serial path exactly: an RLC reject falls
back to the per-signature verdict kernel (crypto/batch._device_verify
does both), and a DEVICE ERROR on an in-flight window drains the
pipeline — the faulted window and everything staged behind it resolve
through the host path, per-signature, so no caller ever commits on a
verdict that did not actually verify.  The drain is observable:
flightrec EV_PIPELINE_DRAIN / EV_DEVICE_FALLBACK events and the
DeviceMetrics pipeline gauges (in-flight windows, staging depth)
record the timeline.

The seam discipline matches votestream/trace/flightrec: with no
pipeline constructed nothing runs; trace spans land under the
SUBMITTER'S subsystem (blocksync/light/consensus) so the overlap is
visible per product path, not aggregated away.
"""

from __future__ import annotations

import os
import threading
import time
from ..libs import lockrank
from concurrent.futures import Future, ThreadPoolExecutor

from ..libs.service import BaseService
from . import sched as qos_sched

# depth 2 = classic double buffering (pack N+1 while N is on device);
# deeper helps only when device time >> host time per window
DEFAULT_DEPTH = int(os.environ.get("COMETBFT_TPU_PIPELINE_DEPTH", "2"))
# the host pool parallelizes WITHIN a window (parse_and_hash chunks);
# hashlib releases the GIL so this scales to real cores.  Sized from
# the machine (one core stays free for the device thread) instead of
# the old static min(4, cpu_count) cap, which left a 16-core host
# hashing on 4 threads; COMETBFT_TPU_PIPELINE_WORKERS pins it exactly.
DEFAULT_HOST_WORKERS = int(
    os.environ.get("COMETBFT_TPU_PIPELINE_WORKERS", "0")) or \
    max(1, (os.cpu_count() or 2) - 1)
_MIN_PARALLEL_CHUNK = 256
# below this many signatures the hash runs INLINE on the staging
# thread: the pool handoff (submit + futures + result gather) costs
# more than hashlib saves on a tiny votestream flush
PARSE_INLINE_THRESHOLD = int(os.environ.get(
    "COMETBFT_TPU_PARSE_INLINE_THRESHOLD",
    str(2 * _MIN_PARALLEL_CHUNK)))
# hung-dispatch watchdog: a device call in flight past this deadline
# marks the device hung — the window (and everything staged behind it)
# resolves on the host, the wedged thread is abandoned + replaced, and
# the device quarantines (crypto/devhealth.py).  The default is
# deliberately generous: a COLD XLA compile on CPU legitimately runs
# minutes, and a tripped watchdog on a merely-compiling chip would
# quarantine every device at first use.  0 disables the watchdog.
DEFAULT_DISPATCH_DEADLINE_S = float(os.environ.get(
    "COMETBFT_TPU_DISPATCH_DEADLINE_S", "600"))
# brownout shape: with EVERY device quarantined the pipeline degrades
# to pure host fallback — a tighter queue bound and a shrunken window
# cap (max_window(), consumed by blocksync's collector) keep the
# consensus hot path latency-bounded instead of livelocked
BROWNOUT_DEPTH = int(os.environ.get(
    "COMETBFT_TPU_BROWNOUT_DEPTH", "2"))
BROWNOUT_MAX_WINDOW = int(os.environ.get(
    "COMETBFT_TPU_BROWNOUT_MAX_WINDOW", "256"))
# deadline-aware QoS dispatch (crypto/sched.py): priority lanes,
# deficit round-robin, bounded device holds.  On by default; 0 reverts
# every pipeline in the process to the plain global-FIFO queue (the
# bench A/B arms toggle the constructor flag instead).
DEFAULT_QOS = os.environ.get("COMETBFT_TPU_SCHED", "1") != "0"


def parse_and_hash_parallel(pubkeys, msgs, sigs, pool=None,
                            workers: int | None = None):
    """ed25519.parse_and_hash fanned across a thread pool in chunks.

    Byte-identical to the serial function (pinned by
    tests/test_dispatch.py): chunking only partitions the index space.
    Small batches (under PARSE_INLINE_THRESHOLD, or pool=None) stay
    serial — the fan-out overhead beats the hashing there.
    """
    from . import ed25519 as ed

    n = len(pubkeys)
    nworkers = workers if workers is not None else DEFAULT_HOST_WORKERS
    if pool is None or nworkers <= 1 or n < PARSE_INLINE_THRESHOLD:
        return ed.parse_and_hash(pubkeys, msgs, sigs)
    chunk = max(_MIN_PARALLEL_CHUNK, -(-n // nworkers))
    spans = [(i, min(i + chunk, n)) for i in range(0, n, chunk)]
    futs = [pool.submit(ed.parse_and_hash, pubkeys[a:b], msgs[a:b],
                        sigs[a:b]) for a, b in spans]
    out = []
    for f in futs:
        out.extend(f.result())
    return out


def _pk_bytes(pk) -> bytes:
    return pk.bytes() if hasattr(pk, "bytes") else bytes(pk)


def _key_type(pk) -> str:
    return pk.type() if hasattr(pk, "type") else "ed25519"


def _verify_one(pk, msg: bytes, sig: bytes) -> bool:
    """Host single-verify for any item shape the pipeline accepts
    (raw 32-byte ed25519 pubkeys or key objects); backend errors map
    to invalid, agreeing with crypto/batch.safe_verify."""
    from . import batch as cb

    if hasattr(pk, "verify_signature"):
        return cb.safe_verify(pk, msg, sig)
    from .votestream import _host_verify

    return _host_verify(_pk_bytes(pk), msg, sig)


def _lat_stamp(handle: "WindowHandle", name: str) -> None:
    """Stamp a lifecycle cut on every latency-ledger request riding
    this window (libs/latledger.py); free when none are attached."""
    lat = handle.lat
    if lat:
        for req in lat:
            req.stamp(name)


class WindowHandle:
    """Future for one submitted window; resolves to (ok, verdicts)
    in submission order.  `path` records how the verdicts were
    produced once resolved: device / host / drain."""

    __slots__ = ("_future", "ctx", "subsystem", "path", "n",
                 "submitted_at", "resolved_at", "lat")

    def __init__(self, n: int, subsystem: str, ctx):
        # TrackedFuture is the sanitizer seam: a window future that
        # gets garbage-collected carrying an unretrieved exception is
        # a swallowed verify failure, and the leak fixture fails the
        # test that dropped it (libs/lockrank.py)
        self._future: Future = lockrank.TrackedFuture()
        self.ctx = ctx
        self.subsystem = subsystem
        self.path: str | None = None
        self.n = n
        self.submitted_at = time.monotonic()
        self.resolved_at: float | None = None
        # latency-ledger requests riding this window (None when the
        # ledger is off); committed — per request, with the window's
        # resolution path — the moment the future resolves, on
        # whichever thread resolved it
        self.lat: list | None = None

    def result(self, timeout: float | None = None):
        return self._future.result(timeout)

    def done(self) -> bool:
        return self._future.done()

    def add_done_callback(self, fn) -> None:
        self._future.add_done_callback(lambda _f: fn(self))

    # internal — idempotent: the watchdog may host-resolve a hung
    # window while its wedged dispatch thread is still inside the
    # device call; whichever lands second is a no-op, never an error
    def _resolve(self, ok: bool, verdicts: list, path: str) -> None:
        if self._future.done():
            return
        self.path = path
        self.resolved_at = time.monotonic()
        try:
            if self._future.set_running_or_notify_cancel():
                self._future.set_result((ok, list(verdicts)))
        except Exception:      # lost the watchdog race mid-set
            pass
        if self.lat:
            for req in self.lat:
                req.resolve(path)

    def _fail(self, exc: BaseException) -> None:
        if self._future.done():
            return
        self.resolved_at = time.monotonic()
        try:
            if self._future.set_running_or_notify_cancel():
                self._future.set_exception(exc)
        except Exception:      # lost the watchdog race mid-set
            pass
        if self.lat:
            for req in self.lat:
                req.resolve("error")


class _Window:
    __slots__ = ("items", "handle", "threshold", "mode", "pks",
                 "msgs", "parsed", "packed", "verifier", "staged",
                 "device_s", "device_index", "dispatching", "result",
                 "all_items", "cached", "dispatch_started",
                 "abandoned", "lane", "prio", "seq", "enqueued_at",
                 "held_since", "staging_active")

    def __init__(self, items, handle, threshold):
        # items = the MISSES after the verdict-cache partition (what
        # actually stages + dispatches); all_items/cached keep the
        # original window so verdicts merge back to one bool per
        # submitted item.  cached is None when nothing was partitioned.
        self.items = items
        self.handle = handle
        self.threshold = threshold
        self.all_items = items
        self.cached = None
        self.mode = None          # "ed" | "ed_hash" | "mixed" | "host"
        self.pks = None
        self.msgs = None          # kept for ed_hash reject localization
        self.parsed = None
        self.packed = None
        self.verifier = None
        self.staged = False
        self.device_s = 0.0
        # mesh round-robin state (devices=... pipelines): the assigned
        # device slot, whether its device thread picked it up, and the
        # computed (ok, verdicts, path) awaiting in-order publication
        self.device_index = 0
        self.dispatching = False
        self.result = None
        # watchdog state: when the dispatch call started, and whether
        # the watchdog host-resolved this window out from under a
        # wedged dispatch thread (the thread discards its result)
        self.dispatch_started = None
        self.abandoned = False
        # QoS scheduling state (crypto/sched.py), stamped by
        # QosScheduler.note_enqueue when the window enters the queue;
        # probe windows keep the defaults (they never enter _windows)
        self.lane = qos_sched.DEFAULT_LANE
        self.prio = 0
        self.seq = 0
        self.enqueued_at = 0.0
        self.held_since = None
        self.staging_active = False


class VerifyPipeline(BaseService):
    """Depth-K overlapped verify dispatch engine (module docstring)."""

    def __init__(self, depth: int = DEFAULT_DEPTH,
                 host_workers: int | None = None,
                 dispatch_fn=None, name: str = "VerifyPipeline",
                 devices=None, health=None,
                 dispatch_deadline_s: float | None = None,
                 qos: bool | None = None):
        super().__init__(name)
        # deadline-aware QoS dispatch order (crypto/sched.py); None
        # defers to COMETBFT_TPU_SCHED.  Off = one lane = exact FIFO.
        self.qos = DEFAULT_QOS if qos is None else bool(qos)
        self._sched = qos_sched.QosScheduler(enabled=self.qos)
        self.depth = max(1, depth)
        self.host_workers = (host_workers if host_workers is not None
                             else DEFAULT_HOST_WORKERS)
        # test/profiling seam: replaces the device-verify call; takes
        # the _Window, returns (ok, verdicts) or raises (exercising the
        # drain path exactly as a real device failure would)
        self._dispatch_fn = dispatch_fn
        # mesh round-robin: with >1 devices, windows are assigned
        # submission-index % n_devices, each device runs its own
        # dispatch thread, and verdicts still PUBLISH in submission
        # order (the blocksync/light ordering contract).  None defers
        # to the COMETBFT_TPU_MESH_DEVICES knob (off unless set); pass
        # an empty tuple to force single-device.  Callers should size
        # depth >= 2 * n_devices or the backpressure window starves
        # the rotation.
        if devices is None:
            try:
                from ..ops import sharding as _sharding

                devices = _sharding.mesh_device_list(None)
            except Exception:
                devices = None
        self.devices = list(devices) if devices is not None \
            and len(devices) > 1 else None
        # device health circuit breaker (crypto/devhealth.py): the
        # dispatch rotation skips quarantined devices, faults feed the
        # state machine, and recovery probes return chips to rotation.
        # None adopts the process registry (node wiring) or a private
        # one, so a bare VerifyPipeline() still has the full machinery.
        from . import devhealth as _devhealth

        self.health = health if health is not None else \
            (_devhealth.registry() or _devhealth.HealthRegistry())
        self.dispatch_deadline_s = (
            dispatch_deadline_s if dispatch_deadline_s is not None
            else DEFAULT_DISPATCH_DEADLINE_S)
        self._cv = lockrank.RankedCondition(name="dispatch.cv")
        self._windows: list[_Window] = []
        self._slots = threading.BoundedSemaphore(self.depth)
        self._pool: ThreadPoolExecutor | None = None
        self._staging: threading.Thread | None = None
        self._device: threading.Thread | None = None
        self._dev_threads: list[threading.Thread] = []
        self._stopping = False
        self._faulted = False      # draining after a device error
        self._dev_faulted: set[int] = set()   # per-device drain (mesh)
        # watchdog plumbing: per-device thread GENERATIONS (a wedged
        # dispatch thread is abandoned by bumping its device's gen and
        # spawning a replacement; the old thread sees the stale gen and
        # discards everything), in-flight probe registrations, the
        # health-aware round-robin cursor, and brownout latch
        self._gens: dict[str, int] = {}
        self._probe_inflight: dict[str, tuple[float, _Window]] = {}
        self._rr = 0
        self._brownout = False
        # brownout priority admission: waiting submitters by lane
        # priority class, so the tightened queue admits the most
        # urgent lane first and sheds the lowest lanes (under _cv)
        self._bo_waiters: dict[int, int] = {}
        self._watchdog: threading.Thread | None = None
        self._wd_wake = threading.Event()
        # per-object timeline override (libs/tracetl.py): lets a harness
        # attribute this pipeline's host_pack/device spans to one node's
        # timeline; None defers to the process seam
        self.timeline = None
        # stats (tests + bench introspection)
        self.submitted = 0
        self.resolved = 0
        self.device_windows = 0
        self.host_windows = 0
        self.drained_windows = 0
        self.faults = 0

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        self._stopping = False
        self._gens = {}
        self._probe_inflight = {}
        self._wd_wake = threading.Event()
        self._brownout = self.in_brownout()
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, self.host_workers),
            thread_name_prefix=f"{self._name}-host")
        self._staging = threading.Thread(
            target=self._staging_loop, name=f"{self._name}-staging",
            daemon=True)
        self._staging.start()
        if self.devices is not None:
            self._dev_threads = [
                threading.Thread(
                    target=self._mesh_device_loop, args=(i, 0),
                    name=f"{self._name}-device-{i}", daemon=True)
                for i in range(len(self.devices))]
            for th in self._dev_threads:
                th.start()
        else:
            self._device = threading.Thread(
                target=self._device_loop, args=(0,),
                name=f"{self._name}-device", daemon=True)
            self._device.start()
        if self.dispatch_deadline_s and self.dispatch_deadline_s > 0:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop,
                name=f"{self._name}-watchdog", daemon=True)
            self._watchdog.start()

    def on_stop(self) -> None:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        self._wd_wake.set()
        for th in (self._staging, self._device, self._watchdog,
                   *self._dev_threads):
            if th is not None:
                th.join(timeout=5)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        # a submit that raced stop() may have left windows behind the
        # exited threads: answer them on the host, free their slots
        with self._cv:
            leftovers, self._windows = list(self._windows), []
        for w in leftovers:
            t0 = time.monotonic()
            ok, verdicts = self._host_fallback(w)
            ok, verdicts = self._merge_cache(w, ok, verdicts)
            w.handle._resolve(ok, verdicts, "host")
            self._record_flush(w, "host", t0)
            try:
                self._slots.release()
            except ValueError:  # pragma: no cover
                pass

    def __enter__(self) -> "VerifyPipeline":
        if not self.is_running():
            self.start()
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- introspection -----------------------------------------------------

    @property
    def inflight(self) -> int:
        """Windows submitted and not yet resolved."""
        with self._cv:
            return len(self._windows)

    @property
    def staged(self) -> int:
        """Windows packed and waiting on the device thread."""
        with self._cv:
            return sum(1 for w in self._windows if w.staged)

    # -- device health / brownout ------------------------------------------

    def _device_keys(self) -> list[str]:
        if self.devices is not None:
            return [str(i) for i in range(len(self.devices))]
        return ["0"]

    def in_brownout(self) -> bool:
        """True when EVERY device this pipeline dispatches to is
        quarantined: verdicts still flow (pure host fallback) but the
        queue bound tightens to BROWNOUT_DEPTH and max_window() asks
        callers to shrink their windows."""
        return self.health.all_quarantined(self._device_keys())

    def max_window(self) -> int | None:
        """Advisory window-size cap for collectors; None = no cap."""
        return BROWNOUT_MAX_WINDOW if self._brownout else None

    def _check_brownout(self) -> None:
        """Re-derive the brownout latch from health state; record the
        edge transitions so the operator sees when the verify plane
        degraded to host-only and when a probe lifted it."""
        now_bo = self.in_brownout()
        with self._cv:
            was, self._brownout = self._brownout, now_bo
            if was != now_bo:
                self._cv.notify_all()
        if was != now_bo:
            from ..libs import flightrec

            flightrec.record(flightrec.EV_BROWNOUT, entered=now_bo,
                             depth=BROWNOUT_DEPTH,
                             max_window=BROWNOUT_MAX_WINDOW)
            rec = flightrec.recorder()
            if rec is not None and now_bo:
                rec.dump_to_log("verify-plane brownout: every device "
                                "quarantined, host-only fallback")

    def _pick_device_locked(self) -> int:
        """Health-aware round-robin over usable devices (called under
        self._cv at submit).  All quarantined -> plain rotation: the
        windows stage host-mode anyway and keep per-device queues
        drained."""
        if self.devices is None:
            return 0
        n = len(self.devices)
        usable = [i for i in range(n)
                  if self.health.usable(str(i))] or list(range(n))
        pick = usable[self._rr % len(usable)]
        self._rr += 1
        return pick

    def _gauge(self) -> None:
        from ..libs import devprof
        from ..libs import metrics as libmetrics

        dm = libmetrics.device_metrics()
        rec = devprof.recorder()
        if dm is None and rec is None:
            return
        with self._cv:
            n = len(self._windows)
            s = sum(1 for w in self._windows if w.staged)
            per_dev = None
            if self.devices is not None:
                per_dev = [0] * len(self.devices)
                for w in self._windows:
                    per_dev[w.device_index] += 1
        if dm is not None:
            dm.pipeline_inflight.set(n)
            dm.pipeline_staged.set(s)
            if per_dev is not None:
                for i, c in enumerate(per_dev):
                    dm.pipeline_device_inflight.labels(str(i)).set(c)
        if rec is not None:
            # Perfetto counter tracks: queue depth + per-device
            # in-flight windows under the occupancy tracks
            rec.counter("pipeline_queue_depth", n)
            rec.counter("pipeline_staged_windows", s)
            if per_dev is not None:
                for i, c in enumerate(per_dev):
                    rec.counter("inflight_windows/dev%d" % i, c)

    def _idle_cause(self, device_index: int | None = None) -> str:
        """Why a dispatch thread is about to wait — called under
        self._cv when a devprof recorder is installed.  drain: the
        pipeline (or this mesh device) is fault-draining; staging: a
        window exists for this device but its host work has not
        finished; no_work: the submit queue is empty (including
        cache-starved — fully-cached windows resolve at submit and
        never reach a device); backpressure: windows exist but none
        are dispatchable here (slots held by other devices' windows,
        or computed heads awaiting in-order publication);
        sched_hold: the QoS scheduler is deliberately keeping this
        chip idle — a strictly-higher-priority window is mid-staging
        and the bounded hold (COMETBFT_TPU_SCHED_HOLD_MS) beats
        burning the device on lower-lane work."""
        from ..libs import devprof

        if self._sched.holding(device_index):
            return devprof.IDLE_SCHED_HOLD
        if device_index is None:
            if self._faulted:
                return devprof.IDLE_DRAIN
            if not self.health.usable("0"):
                return devprof.IDLE_QUARANTINE
            mine = self._windows
        else:
            if device_index in self._dev_faulted:
                return devprof.IDLE_DRAIN
            if not self.health.usable(str(device_index)):
                return devprof.IDLE_QUARANTINE
            mine = [w for w in self._windows
                    if w.device_index == device_index]
        if any(not w.staged for w in mine):
            return devprof.IDLE_STAGING
        if not self._windows:
            return devprof.IDLE_NO_WORK
        return devprof.IDLE_BACKPRESSURE

    # -- API ---------------------------------------------------------------

    def submit(self, items, *, subsystem: str = "pipeline", ctx=None,
               device_threshold: int | None = None,
               lat=None, lane: str | None = None) -> WindowHandle:
        """Queue one window of (pubkey, msg, sig) items; blocks when
        `depth` windows are already unresolved (backpressure).  The
        returned handle resolves — in per-lane submission order — to
        (ok, verdicts) with one bool per item.

        `lat` threads caller-created latency-ledger requests
        (libs/latledger.py) onto the window so a seam that already
        stamped its own queue wait (votestream, the light coalescer)
        is not double-counted; None (the default) opens one ledger
        request covering the whole window when a recorder is
        installed.

        `lane` overrides the QoS lane this window schedules under
        (crypto/sched.py) without changing `subsystem`, which keeps
        naming the trace/ledger/cache attribution — e.g. a blocksync
        window re-laned urgent still books its latency as blocksync.
        Must be a label registered in sigcache.LANES; anything else
        falls back to the subsystem's own lane."""
        if device_threshold is None:
            from . import batch as cb

            device_threshold = cb.DEVICE_THRESHOLD
        from . import sigcache

        items = list(items)
        handle = WindowHandle(len(items), subsystem, ctx)
        if lat is None and items:
            from ..libs import latledger

            req = latledger.submit(
                len(items),
                consumer=subsystem if subsystem in sigcache.CONSUMERS
                else None)
            lat = [req] if req is not None else None
        handle.lat = lat
        if not items:
            handle._resolve(False, [], "host")
            return handle
        # verdict-cache partition (crypto/sigcache.py): only misses
        # stage and dispatch; cached verdicts merge back at window
        # publication.  A fully-cached window resolves RIGHT HERE —
        # no slot, no staging, no device.
        cached = None
        misses = items
        if sigcache.enabled():
            verdicts, miss_idx = sigcache.partition(
                items, label=subsystem)
            if not miss_idx:
                full = [bool(v) for v in verdicts]
                handle._resolve(all(full), full, "cache")
                self._record_cache_window(handle, len(items))
                return handle
            if len(miss_idx) < len(items):
                cached = verdicts
                misses = [items[i] for i in miss_idx]
        if self._stopping or self._staging is None \
                or not self.is_running():
            # late submissions still answer, synchronously on the host
            # (the votestream submit-after-stop contract)
            verdicts = [_verify_one(pk, m, s) for pk, m, s in items]
            handle._resolve(all(verdicts), verdicts, "host")
            return handle
        label = self._sched.lane_for(subsystem, lane)
        prio = self._sched.priority(label)
        self._slots.acquire()
        win = _Window(misses, handle, device_threshold)
        win.all_items = items
        win.cached = cached
        with self._cv:
            # brownout: beyond the depth-K slot bound, hold submitters
            # to a tighter queue so host-only verify latency stays
            # bounded instead of piling K windows of backlog.  The
            # admission is priority-aware: while a strictly more
            # urgent lane is also waiting, this submitter yields its
            # queue spot — the degraded capacity sheds the lowest
            # lanes first.
            self._bo_waiters[prio] = self._bo_waiters.get(prio, 0) + 1
            try:
                while not self._stopping and self._brownout \
                        and (len(self._windows) >= BROWNOUT_DEPTH
                             or any(c and p < prio for p, c
                                    in self._bo_waiters.items())):
                    self._cv.wait(timeout=0.05)
            finally:
                self._bo_waiters[prio] -= 1
                if not self._bo_waiters[prio]:
                    del self._bo_waiters[prio]
            win.device_index = self._pick_device_locked()
            self._sched.note_enqueue(win, label)
            self._windows.append(win)
            self.submitted += 1
            self._cv.notify_all()
        self._gauge()
        return handle

    def qos_seal_due(self, consumer: str) -> bool:
        """Window-formation advisory for accumulators (votestream, the
        light coalescer): True when sealing the in-formation window
        NOW beats batching further — the queue holds work from a
        *different* priority class (a higher lane queued means this
        bulk window should be cut short so it clears fast; a lower
        lane queued means this urgent window should seal and jump
        it).  False with QoS off, on an empty queue (the accumulator's
        flush interval is the designed latency), and under pure
        own-class backpressure — there batching up stays the
        efficient move."""
        if not self.qos or not self.is_running():
            return False
        # lock-free peek: accumulators poll this at millisecond
        # cadence while a batch forms, and the common case is an
        # empty queue — a stale read only delays/advances an advisory
        # by one poll tick, so don't tax the dispatch cv for it
        if not self._windows:
            return False
        with self._cv:
            return self._sched.seal_due(self._windows, consumer,
                                        time.monotonic())

    def scheduler_snapshot(self) -> dict:
        """Per-lane dispatch counters (benches, chaos checkers)."""
        with self._cv:
            return self._sched.snapshot()

    def drain(self, timeout: float | None = None) -> bool:
        """Block until every submitted window has resolved."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cv:
            while self._windows:
                left = None if deadline is None else \
                    deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._cv.wait(timeout=left if left is not None else 0.1)
        return True

    # -- staging (host pack) -----------------------------------------------

    def _next_unstaged(self) -> _Window | None:
        # QoS order: most urgent effective class first, FIFO within it
        # (with QoS off this is exactly the old first-unstaged scan)
        return self._sched.next_unstaged(self._windows,
                                         time.monotonic())

    def _staging_loop(self) -> None:
        from ..libs import trace as libtrace
        from ..libs import tracetl
        from . import ed25519 as ed

        while True:
            with self._cv:
                while self._next_unstaged() is None \
                        and not self._stopping:
                    self._cv.wait(timeout=0.1)
                if self._stopping and self._next_unstaged() is None:
                    return
                win = self._next_unstaged()
                # visible to pick_dispatch: a free device may briefly
                # hold for this window if it outranks the staged work
                win.staging_active = True
            # span name decided UP FRONT from the knob (not win.mode,
            # set inside _stage): in device-hash mode the staging
            # thread's job shrinks to splice+pack, and the split
            # host_splice/device_hash names keep tracetl's critical
            # path decomposition summing exactly (both map into the
            # existing host_pack/device segments)
            stage_span = "host_splice" if (
                ed.device_hash_enabled()
                and os.environ.get("COMETBFT_TPU_PROVIDER",
                                   "auto") != "cpu") else "host_pack"
            _lat_stamp(win.handle, "stage_start")
            try:
                with libtrace.span(win.handle.subsystem, stage_span,
                                   inflight=len(self._windows)), \
                        tracetl.span_for(
                            self, win.handle.subsystem, stage_span,
                            **tracetl.ctx_fields(win.handle.ctx)):
                    self._stage(win)
            except Exception:
                # a staging failure must not wedge the queue: route the
                # window to the host path for verdicts
                win.mode = "host"
            _lat_stamp(win.handle, "stage_end")
            with self._cv:
                win.staging_active = False
                win.staged = True
                self._cv.notify_all()
            self._gauge()

    def _stage(self, win: _Window) -> None:
        """Host work for one window: key-type split, parallel SHA-512
        parse+hash, RLC packing (signed-digit recode) — everything the
        device dispatch needs, done while the PREVIOUS window is on
        device."""
        items = win.items
        if self._brownout:
            # every device quarantined: skip the device staging work
            # entirely, the window can only resolve on the host
            win.mode = "host"
            return
        provider = os.environ.get("COMETBFT_TPU_PROVIDER", "auto")
        all_ed = all(_key_type(pk) == "ed25519" for pk, _, _ in items)
        if provider == "cpu" or len(items) < max(1, win.threshold):
            win.mode = "host"
            return
        if not all_ed:
            # mixed key types: batch.MixedBatchVerifier handles the
            # per-type split (its sub-batches dispatch concurrently);
            # the device thread runs verify() so ordering holds
            from . import batch as cb

            bv = cb.MixedBatchVerifier()
            for pk, m, s in items:
                bv.add(pk, m, s)
            win.mode = "mixed"
            win.verifier = bv
            return
        from . import ed25519 as ed

        pks = [_pk_bytes(pk) for pk, _, _ in items]
        msgs = [m for _, m, _ in items]
        sigs = [s for _, _, s in items]
        win.pks = pks
        n = len(pks)
        if ed.device_hash_enabled() and n >= 2:
            # fused hash-to-scalar staging: structural parse + splice
            # only — hashing, zh aggregation and the A-side recode run
            # on device.  Structural rejects and oversized messages
            # fall through to the host-hash staging below (the drain
            # path is unchanged; the fallback is observable).
            parsed = ed.parse_batch(pks, sigs)
            if all(p is not None for p in parsed):
                try:
                    win.packed = ed.pack_rlc_device_hash(
                        pks, msgs, sigs, parsed=parsed)
                    win.parsed = parsed
                    win.msgs = msgs
                    win.mode = "ed_hash"
                    return
                except ValueError:
                    self._record_hash_fallback(n)
        win.parsed = parse_and_hash_parallel(
            pks, msgs, sigs, pool=self._pool,
            workers=self.host_workers)
        if n >= 2:
            # pack (aggregation + recode) here so the device thread
            # only dispatches; None = structural reject, the device
            # stage localizes with the per-signature kernel
            win.packed = ed.pack_rlc(pks, [b""] * n, [b""] * n,
                                     parsed=win.parsed)
        win.mode = "ed"

    def _record_hash_fallback(self, n: int) -> None:
        """A window left the device-hash path (message exceeded the
        static SHA-512 block bucket): count it and leave a flightrec
        breadcrumb — the window still verifies via host-hash staging."""
        from ..libs import flightrec
        from ..libs import metrics as libmetrics

        dm = libmetrics.device_metrics()
        if dm is not None:
            dm.device_hash_fallbacks.inc()
        flightrec.record(flightrec.EV_DEVICE_HASH_FALLBACK, batch=n)

    # -- device (ordered dispatch) -------------------------------------

    def _device_loop(self, gen: int = 0) -> None:
        from ..libs import devprof

        dev = "0"
        while True:
            # devprof accounting (libs/devprof.py): classify WHY this
            # thread is about to wait (under the lock, where the queue
            # state is coherent), then attribute the waited gap to that
            # cause on wake — so busy + attributed idle partition the
            # device's wall-clock exactly
            rec = devprof.recorder()
            cause = devprof.IDLE_NO_WORK
            probe = False
            ev = None
            with self._cv:
                while True:
                    if gen != self._gens.get(dev, 0):
                        # the watchdog abandoned this thread (hung
                        # dispatch) and a replacement owns the queue
                        return
                    if self._probe_due_locked(dev):
                        probe = True
                        break
                    win, holding = self._sched.pick_dispatch(
                        self._windows, None, time.monotonic())
                    if win is not None:
                        win.dispatching = True
                        win.dispatch_started = time.monotonic()
                        _lat_stamp(win.handle, "dispatch")
                        ev = self._sched.note_dispatch(
                            win, self._windows, win.dispatch_started)
                        break
                    if self._stopping and not self._windows:
                        return
                    if rec is not None:
                        cause = self._idle_cause()
                    # stopping with an unstaged head: the staging loop
                    # drains every submitted window before exiting.  A
                    # QoS hold wakes on its own (short) budget so the
                    # held device re-evaluates promptly.
                    self._cv.wait(timeout=max(0.001, self._sched.hold_s)
                                  if holding else 0.05)
                    if rec is not None:
                        rec.advance(dev, cause)
            if rec is not None:
                # close the residual gap (lock wakeup to dispatch
                # start) under the last known cause
                rec.advance(dev, cause)
            if probe:
                self._run_probe(dev, None, gen)
                continue
            self._sched.emit(ev)
            self._resolve_window(win)
            with self._cv:
                stale = gen != self._gens.get(dev, 0) or win.abandoned
            if stale:
                # the watchdog host-resolved this window (and did the
                # pop/release bookkeeping) while we were wedged in the
                # device call; everything downstream is not ours
                return
            if rec is not None:
                path = win.handle.path
                if path in ("device", "host"):
                    rec.advance(dev, devprof.BUSY, path=path)
                else:                     # drain (or a failed resolve)
                    rec.advance(dev, devprof.IDLE_DRAIN)
            with self._cv:
                # under QoS the resolved window need not be the head
                # (it may have overtaken earlier lower-lane windows):
                # remove by identity
                try:
                    self._windows.remove(win)
                except ValueError:  # watchdog already popped it
                    pass
                if not self._windows:
                    # queue empty: a drain ends here, device dispatch
                    # resumes for subsequent submissions
                    self._faulted = False
                self.resolved += 1
                self._cv.notify_all()
            self._slots.release()
            self._gauge()

    def _compute_verdicts(self, win: _Window, faulted: bool,
                          device=None, device_index=None,
                          quarantined: bool = False):
        """The path decision + verdict computation shared by the
        single-device loop and the per-device mesh loops; returns
        (ok, verdicts, path)."""
        if faulted and win.mode in ("ed", "ed_hash", "mixed"):
            # draining after a device fault: everything staged
            # behind the faulted window resolves on the host
            ok, verdicts = self._host_fallback(win)
            self.drained_windows += 1
            return ok, verdicts, "drain"
        if win.mode == "host":
            ok, verdicts = self._host_fallback(win)
            self.host_windows += 1
            return ok, verdicts, "host"
        if quarantined:
            # circuit breaker open: the staged work is not trusted to
            # this device — host path, NOT a drain (the pipeline is
            # healthy, only this chip is benched awaiting a probe)
            ok, verdicts = self._host_fallback(win)
            self.host_windows += 1
            return ok, verdicts, "host"
        try:
            ok, verdicts = self._device_dispatch(win, device=device)
            if win.abandoned:
                return ok, verdicts, "device"
            self.device_windows += 1
            self.health.note_ok(str(device_index)
                                if device_index is not None else "0")
            return ok, verdicts, "device"
        except Exception as e:
            if win.abandoned:
                # a wedged device call erupting AFTER the watchdog
                # already handled this window: the hang was counted
                # (note_hang, quarantine) when the thread was
                # abandoned — feeding this stale error to the health
                # machine would re-quarantine a chip that may have
                # since probed back to healthy
                return False, [False] * len(win.items), "error"
            # device trouble mid-pipeline: drain.  The host
            # path is still correct; the operator must see
            # the fault and the drain in the timeline.
            self._fault(e, win, device_index=device_index)
            ok, verdicts = self._host_fallback(win)
            self.drained_windows += 1
            return ok, verdicts, "drain"

    def _merge_cache(self, win: _Window, ok: bool, verdicts: list):
        """Window publication: insert every COMPUTED verdict into the
        verdict cache (this is a resolution seam — even verdicts whose
        consumer cancel-raced the window become future hits), then
        merge with the cached slots back to one bool per submitted
        item."""
        from . import sigcache

        if win.items:
            sigcache.insert_many(win.items, verdicts,
                                 label=win.handle.subsystem)
        if win.cached is None:
            return ok, verdicts
        merged = list(win.cached)
        it = iter(verdicts)
        for i, v in enumerate(merged):
            if v is None:
                merged[i] = bool(next(it))
            else:
                merged[i] = bool(v)
        return all(merged) and bool(merged), merged

    def _cache_hits(self, win: _Window) -> int:
        return len(win.all_items) - len(win.items)

    def _record_cache_window(self, handle: WindowHandle,
                             n: int) -> None:
        """A fully-cached window resolved at submit: record it like a
        flush so the path mix (device/host/cache) reads in one series."""
        from ..libs import flightrec
        from ..libs import metrics as libmetrics
        from ..libs import tracetl

        dm = libmetrics.device_metrics()
        if dm is not None:
            dm.flushes.labels("cache").inc()
            dm.batch_size.labels("cache").observe(n)
            if handle.resolved_at is not None:
                dm.flush_latency_seconds.labels("cache").observe(
                    handle.resolved_at - handle.submitted_at)
        flightrec.record(
            flightrec.EV_VERIFY_FLUSH, path="cache", batch=n,
            cache_hits=n, subsystem=handle.subsystem,
            inflight=len(self._windows), staged=self.staged,
            **tracetl.ctx_fields(handle.ctx))

    def _record_flush(self, win: _Window, path: str, t0: float) -> None:
        from ..libs import flightrec
        from ..libs import metrics as libmetrics
        from ..libs import tracetl

        dm = libmetrics.device_metrics()
        if dm is not None:
            dm.flushes.labels(path).inc()
            dm.batch_size.labels(path).observe(len(win.items))
            dm.flush_latency_seconds.labels(path).observe(
                time.monotonic() - t0)
            if self.devices is not None and path == "device":
                dm.mesh_dispatches.labels(
                    str(win.device_index)).inc()
        flightrec.record(
            flightrec.EV_VERIFY_FLUSH, path=path,
            batch=len(win.items),
            cache_hits=self._cache_hits(win),
            subsystem=win.handle.subsystem,
            inflight=len(self._windows), staged=self.staged,
            **tracetl.ctx_fields(win.handle.ctx))

    def _resolve_window(self, win: _Window) -> None:
        from ..libs import trace as libtrace
        from ..libs import tracetl

        t0 = time.monotonic()
        path = "host"
        dev_span = "device_hash" if win.mode == "ed_hash" else "device"
        try:
            with libtrace.span(win.handle.subsystem, dev_span,
                               inflight=len(self._windows)), \
                    tracetl.span_for(
                        self, win.handle.subsystem, dev_span,
                        cache=self._cache_hits(win),
                        **tracetl.ctx_fields(win.handle.ctx)):
                ok, verdicts, path = self._compute_verdicts(
                    win, self._faulted,
                    quarantined=not self.health.usable("0"))
            if win.abandoned:
                # the watchdog already host-resolved this window
                return
            win.device_s = time.monotonic() - t0
            _lat_stamp(win.handle, "compute_end")
            ok, verdicts = self._merge_cache(win, ok, verdicts)
            win.handle._resolve(ok, verdicts, path)
        except BaseException as e:  # pragma: no cover - defensive
            if win.abandoned:
                return
            win.handle._fail(e)
            path = "error"
        finally:
            if not win.abandoned:
                self._record_flush(win, path, t0)

    # -- mesh round-robin (one dispatch thread per device) ---------------

    def _mesh_device_loop(self, idx: int, gen: int = 0) -> None:
        from ..libs import devprof
        from ..libs import trace as libtrace
        from ..libs import tracetl

        dev = str(idx)
        while True:
            # same devprof gap-attribution discipline as _device_loop,
            # per mesh device: classify the wait under the lock,
            # attribute the gap on wake
            rec = devprof.recorder()
            cause = devprof.IDLE_NO_WORK
            probe = False
            ev = None
            with self._cv:
                while True:
                    if gen != self._gens.get(dev, 0):
                        # abandoned by the watchdog; the replacement
                        # thread owns this device's queue now
                        return
                    if self._probe_due_locked(dev):
                        probe = True
                        break
                    win, holding = self._sched.pick_dispatch(
                        self._windows, idx, time.monotonic())
                    if win is not None:
                        win.dispatching = True
                        win.dispatch_started = time.monotonic()
                        _lat_stamp(win.handle, "dispatch")
                        ev = self._sched.note_dispatch(
                            win, self._windows, win.dispatch_started)
                        break
                    if self._stopping and not any(
                            w.device_index == idx and w.result is None
                            for w in self._windows):
                        return
                    if rec is not None:
                        cause = self._idle_cause(device_index=idx)
                    self._cv.wait(timeout=max(0.001, self._sched.hold_s)
                                  if holding else 0.05)
                    if rec is not None:
                        rec.advance(dev, cause)
                faulted = idx in self._dev_faulted
                quarantined = not self.health.usable(dev)
            if rec is not None:
                rec.advance(dev, cause)
            if probe:
                self._run_probe(dev, self.devices[idx], gen)
                continue
            self._sched.emit(ev)
            t0 = time.monotonic()
            path = "host"
            dev_span = "device_hash" if win.mode == "ed_hash" \
                else "device"
            try:
                with libtrace.span(win.handle.subsystem, dev_span,
                                   inflight=len(self._windows),
                                   device=idx), \
                        tracetl.span_for(
                            self, win.handle.subsystem, dev_span,
                            device=idx, cache=self._cache_hits(win),
                            **tracetl.ctx_fields(win.handle.ctx)):
                    ok, verdicts, path = self._compute_verdicts(
                        win, faulted, device=self.devices[idx],
                        device_index=idx, quarantined=quarantined)
                win.device_s = time.monotonic() - t0
                _lat_stamp(win.handle, "compute_end")
                ok, verdicts = self._merge_cache(win, ok, verdicts)
                with self._cv:
                    if gen != self._gens.get(dev, 0) or win.abandoned:
                        # the watchdog resolved this window while we
                        # were wedged; discard everything
                        return
                    win.result = (ok, verdicts, path)
            except BaseException as e:  # pragma: no cover - defensive
                with self._cv:
                    if gen != self._gens.get(dev, 0) or win.abandoned:
                        return
                    win.result = (None, e, "error")
                path = "error"
            if rec is not None:
                if path in ("device", "host"):
                    rec.advance(dev, devprof.BUSY, path=path)
                else:
                    rec.advance(dev, devprof.IDLE_DRAIN)
            self._record_flush(win, path, t0)
            self._publish_resolved(idx)

    def _publish_resolved(self, idx: int) -> None:
        """Pop and resolve every computed window that is the head of
        its LANE — verdicts publish in per-lane submission order no
        matter which device finished first.  With QoS off every
        window shares one lane, making this exactly the old
        global-head publication."""
        done: list[_Window] = []
        with self._cv:
            blocked: set = set()
            i = 0
            while i < len(self._windows):
                w = self._windows[i]
                if w.result is not None and w.lane not in blocked:
                    done.append(self._windows.pop(i))
                    self.resolved += 1
                    continue
                blocked.add(w.lane)
                i += 1
            if idx in self._dev_faulted and not any(
                    w.device_index == idx for w in self._windows):
                # this device's queue drained: device dispatch resumes
                # for its subsequent windows
                self._dev_faulted.discard(idx)
            if done:
                self._cv.notify_all()
        for w in done:
            ok, verdicts, path = w.result
            if path == "error":  # pragma: no cover - defensive
                w.handle._fail(verdicts)
            else:
                w.handle._resolve(ok, verdicts, path)
            self._slots.release()
        if done:
            self._gauge()

    def _device_dispatch(self, win: _Window, device=None):
        if self._dispatch_fn is not None:
            return self._dispatch_fn(win)
        if win.mode == "mixed":
            return win.verifier.verify()
        from . import batch as cb

        if win.mode == "ed_hash":
            return cb._device_verify_hash(win.pks, win.msgs,
                                          win.parsed,
                                          packed=win.packed,
                                          device=device)
        return cb._device_verify(win.pks, win.parsed,
                                 packed=win.packed, device=device)

    def _host_fallback(self, win: _Window):
        verdicts = [_verify_one(pk, m, s) for pk, m, s in win.items]
        return all(verdicts) and bool(verdicts), verdicts

    def _fault(self, exc: Exception, win: _Window,
               device_index: int | None = None) -> None:
        from ..libs import flightrec
        from ..libs import metrics as libmetrics
        from ..libs import tracetl

        with self._cv:
            if device_index is None:
                self._faulted = True
            else:
                # mesh mode: only THIS device drains — windows
                # round-robined onto the other devices keep
                # dispatching (per-device fault isolation)
                self._dev_faulted.add(device_index)
            self.faults += 1
            staged_behind = sum(1 for w in self._windows if w.staged)
        dm = libmetrics.device_metrics()
        if dm is not None:
            dm.pipeline_drains.inc()
            if device_index is not None:
                dm.pipeline_device_drains.labels(
                    str(device_index)).inc()
        rec = flightrec.recorder()
        ctxf = tracetl.ctx_fields(win.handle.ctx)
        flightrec.record(flightrec.EV_DEVICE_FALLBACK,
                         batch=len(win.items),
                         error=type(exc).__name__, **ctxf)
        flightrec.record(flightrec.EV_PIPELINE_DRAIN,
                         batch=len(win.items),
                         inflight=len(self._windows),
                         staged=staged_behind,
                         device=device_index,
                         error=type(exc).__name__, **ctxf)
        if rec is not None:
            rec.dump_to_log(
                "pipeline device dispatch failed, draining: %r" % exc)
        # feed the health state machine: repeated faults inside the
        # window trip the quarantine circuit breaker and pull this
        # device out of the dispatch rotation
        self.health.note_fault(
            str(device_index) if device_index is not None else "0",
            reason=type(exc).__name__)
        self._check_brownout()

    # -- hung-dispatch watchdog ------------------------------------------

    def _watchdog_loop(self) -> None:
        """Deadline enforcement for in-flight device work: a dispatch
        (or probe) that outlives dispatch_deadline_s is resolved on the
        host, its wedged thread abandoned + replaced, and its device
        quarantined as hung.  The futures contract survives a wedge:
        no window is ever left unresolved."""
        deadline = self.dispatch_deadline_s
        interval = max(0.02, min(1.0, deadline / 4.0))
        while not self._stopping:
            self._wd_wake.wait(timeout=interval)
            if self._stopping:
                return
            self._scan_hung()

    def _scan_hung(self) -> None:
        deadline = self.dispatch_deadline_s
        now = time.monotonic()
        hung = None
        hung_probe = None
        with self._cv:
            for w in self._windows:
                if w.dispatching and not w.abandoned \
                        and w.result is None \
                        and not w.handle.done() \
                        and w.dispatch_started is not None \
                        and now - w.dispatch_started > deadline:
                    hung = w
                    break
            if hung is None:
                for d, (t0, _w) in self._probe_inflight.items():
                    if now - t0 > deadline:
                        hung_probe = d
                        break
        if hung is not None:
            self._handle_hang(hung, now)
        elif hung_probe is not None:
            self._handle_probe_hang(hung_probe, now)

    def _handle_hang(self, win: _Window, now: float) -> None:
        idx = win.device_index if self.devices is not None else None
        dev = str(idx) if idx is not None else "0"
        with self._cv:
            # re-check under the lock: the wedged thread may have
            # finished between the scan and here
            if win.abandoned or win.result is not None \
                    or win.handle.done() \
                    or win not in self._windows:
                return
            win.abandoned = True
            waited = now - (win.dispatch_started or now)
            self.faults += 1
            if idx is None:
                self._faulted = True
            else:
                self._dev_faulted.add(idx)
            # abandon the wedged thread: bump its generation (it will
            # discard its result and exit when the device call ever
            # returns) and hand the queue to a fresh replacement
            gen = self._gens.get(dev, 0) + 1
            self._gens[dev] = gen
            staged_behind = sum(1 for w in self._windows if w.staged)
            self._cv.notify_all()
        self._spawn_dispatch_thread(idx, gen)
        self.health.note_hang(dev)
        self._check_brownout()
        self._record_watchdog(dev, win, waited, staged_behind)
        # answer the hung window on the host so its future resolves —
        # the consumer contract survives the wedge
        ok, verdicts = self._host_fallback(win)
        ok, verdicts = self._merge_cache(win, ok, verdicts)
        self.drained_windows += 1
        if self.devices is None:
            win.handle._resolve(ok, verdicts, "drain")
            with self._cv:
                if self._windows and self._windows[0] is win:
                    self._windows.pop(0)
                else:
                    # QoS dispatch order: the hung window need not be
                    # the queue head (it may have overtaken earlier
                    # lower-lane windows)
                    try:
                        self._windows.remove(win)
                    except ValueError:
                        pass
                if not self._windows:
                    # the hung window was the whole queue: the drain
                    # ends here, same as _device_loop's post-resolve —
                    # otherwise the fault latch outlives the outage and
                    # a probed-healthy chip never gets work again
                    self._faulted = False
                self.resolved += 1
                self._cv.notify_all()
            self._slots.release()
            self._record_flush(win, "drain",
                               win.dispatch_started or now)
            self._gauge()
        else:
            # mesh: park the verdicts on the window and let the
            # in-order publisher resolve it (submission-order contract)
            with self._cv:
                win.result = (ok, verdicts, "drain")
            self._record_flush(win, "drain",
                               win.dispatch_started or now)
            self._publish_resolved(idx)

    def _handle_probe_hang(self, dev: str, now: float) -> None:
        with self._cv:
            entry = self._probe_inflight.pop(dev, None)
            if entry is None:
                return
            t0, win = entry
            waited = now - t0
            gen = self._gens.get(dev, 0) + 1
            self._gens[dev] = gen
        idx = int(dev) if self.devices is not None else None
        self._spawn_dispatch_thread(idx, gen)
        self._record_watchdog(dev, win, waited, 0)
        # a hung probe is a failed probe: stay quarantined, back off
        self.health.probe_result(dev, "fail")
        self._check_brownout()

    def _spawn_dispatch_thread(self, idx: int | None,
                               gen: int) -> None:
        if idx is None:
            th = threading.Thread(
                target=self._device_loop, args=(gen,),
                name=f"{self._name}-device-r{gen}", daemon=True)
            self._device = th
        else:
            th = threading.Thread(
                target=self._mesh_device_loop, args=(idx, gen),
                name=f"{self._name}-device-{idx}-r{gen}", daemon=True)
            self._dev_threads.append(th)
        th.start()

    def _record_watchdog(self, dev: str, win: _Window, waited: float,
                         staged_behind: int) -> None:
        from ..libs import flightrec
        from ..libs import metrics as libmetrics

        dm = libmetrics.device_metrics()
        if dm is not None:
            dm.watchdog_timeouts.labels(dev).inc()
        flightrec.record(flightrec.EV_WATCHDOG_TIMEOUT, device=dev,
                         batch=len(win.items), waited_s=round(waited, 3),
                         deadline_s=self.dispatch_deadline_s,
                         staged=staged_behind,
                         subsystem=win.handle.subsystem)
        rec = flightrec.recorder()
        if rec is not None:
            rec.dump_to_log(
                "pipeline dispatch hung on device %s (%.1fs > %.1fs "
                "deadline), host-resolving" %
                (dev, waited, self.dispatch_deadline_s))

    # -- recovery probes (known-answer batches) --------------------------

    def _probe_due_locked(self, dev: str) -> bool:
        """Called under self._cv from the dispatch wait loops: True
        when this quarantined device's probe backoff has elapsed (the
        health registry flips it to PROBING as a side effect)."""
        if self._stopping or dev in self._probe_inflight:
            return False
        return self.health.due_probe(dev)

    def _run_probe(self, dev: str, device, gen: int) -> None:
        """Dispatch the known-answer probe batch on a quarantined
        device.  Expected verdicts (one lane deliberately corrupt)
        must match EXACTLY — a chip that forges or flips lanes stays
        benched.  Probe verdicts never touch the verdict cache."""
        from . import devhealth as _devhealth
        from ..libs import devprof
        from ..libs import trace as libtrace
        from ..libs import tracetl

        if self._stopping:
            self.health.transition(dev, "quarantined")
            return
        win = self._make_probe_window(dev)
        with self._cv:
            self._probe_inflight[dev] = (time.monotonic(), win)
        passed = False
        try:
            with libtrace.span("pipeline", "device_probe",
                               device=dev), \
                    tracetl.span_for(self, "pipeline", "device_probe",
                                     device=dev):
                _ok, verdicts = self._device_dispatch(
                    win, device=device)
            passed = [bool(v) for v in verdicts] == \
                _devhealth.probe_expected()
        except Exception:
            passed = False
        with self._cv:
            self._probe_inflight.pop(dev, None)
            stale = gen != self._gens.get(dev, 0)
        if stale:
            # the watchdog already failed this probe and replaced us
            return
        rec = devprof.recorder()
        if rec is not None:
            rec.advance(dev, devprof.BUSY, path="probe")
        if passed:
            self.health.probe_result(dev, "ok")
        else:
            self.health.probe_result(dev, "fail")
        self._check_brownout()

    def _make_probe_window(self, dev: str) -> _Window:
        """Hand-staged known-answer window: bypasses _stage (whose
        provider/threshold gates would route it to the host — the
        whole point is to exercise the DEVICE path)."""
        from . import devhealth as _devhealth
        from . import ed25519 as ed

        items = list(_devhealth.probe_items())
        handle = WindowHandle(len(items), "probe", None)
        win = _Window(items, handle, 1)
        pks = [_pk_bytes(pk) for pk, _, _ in items]
        msgs = [m for _, m, _ in items]
        sigs = [s for _, _, s in items]
        win.pks = pks
        win.parsed = ed.parse_and_hash(pks, msgs, sigs)
        win.packed = ed.pack_rlc(pks, [b""] * len(pks),
                                 [b""] * len(pks), parsed=win.parsed)
        win.mode = "ed"
        win.staged = True
        win.device_index = int(dev) if self.devices is not None else 0
        return win


# -- process-wide default instance ------------------------------------------

_default: VerifyPipeline | None = None
_default_lock = lockrank.RankedLock("dispatch.default")


def default_pipeline() -> VerifyPipeline:
    """Lazily-started shared engine: all product paths in a process
    share one ordered dispatch queue (the axon discipline is one TPU
    stream per process anyway)."""
    global _default
    with _default_lock:
        if _default is None or not _default.is_running():
            _default = VerifyPipeline()
            _default.start()
        return _default
