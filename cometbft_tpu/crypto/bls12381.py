"""BLS12-381 min-pk keys over the native C++ library
(native/bls12381/ — pairing, hash-to-G2, compressed encodings).

The reference gates this scheme behind a build tag with a stub
exposing Enabled=False (/root/reference/crypto/bls12381/key.go:1-20;
real impl key_bls12381.go via the CGO blst library — its only native
code path).  Here the gate is the presence of the compiled shared
library: `enabled()` is False until `build()` (or `make -C
native/bls12381`) produces libbls12381.so; the native path is our
from-scratch C++ (fp.h/fp_tower.h/curve.h/pairing.h).

Wire shapes match the reference: 48-byte compressed G1 pubkeys,
96-byte compressed G2 signatures, 32-byte scalars, key type
"bls12_381", address = first 20 bytes of SHA-256(pubkey).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from ..libs import lockrank
from dataclasses import dataclass

from .hash import sum_sha256

KEY_TYPE = "bls12_381"
PUBKEY_SIZE = 48
PRIVKEY_SIZE = 32
SIGNATURE_SIZE = 96

# Reference key_bls12381.go MaxMsgLen: messages longer than 32 bytes
# are SHA-256 pre-hashed before BLS signing/verification (vote and
# commit sign-bytes always exceed 32 bytes).  Messages SHORTER than 32
# bytes are signable but never verifiable in the reference — its
# VerifySignature does a [32]byte conversion that panics for short
# input (key_bls12381.go:137) — so verify_signature maps them to False
# rather than diverging by accepting what a reference node cannot.
MAX_MSG_LEN = 32


def _prehash(msg: bytes) -> bytes:
    return sum_sha256(msg) if len(msg) > MAX_MSG_LEN else msg

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native", "bls12381")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libbls12381.so")

_lib = None
_lib_lock = lockrank.RankedLock("bls12381.lib")


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            return None
        lib = ctypes.CDLL(_LIB_PATH)
        for name, args in {
            "bls_keygen": [ctypes.c_char_p, ctypes.c_char_p],
            "bls_sk_to_pk": [ctypes.c_char_p, ctypes.c_char_p],
            "bls_sign": [ctypes.c_char_p, ctypes.c_char_p,
                         ctypes.c_size_t, ctypes.c_char_p],
            "bls_verify": [ctypes.c_char_p, ctypes.c_char_p,
                           ctypes.c_size_t, ctypes.c_char_p],
            "bls_pk_validate": [ctypes.c_char_p],
            "bls_aggregate_sigs": [ctypes.c_char_p, ctypes.c_size_t,
                                   ctypes.c_char_p],
            "bls_aggregate_pks": [ctypes.c_char_p, ctypes.c_size_t,
                                  ctypes.c_char_p],
            "bls_selftest": [],
            "bls_sha256": [ctypes.c_char_p, ctypes.c_size_t,
                           ctypes.c_char_p],
            "bls_expand_message_xmd": [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
                ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t],
            "bls_hash_to_g2_compressed": [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
                ctypes.c_size_t, ctypes.c_char_p],
        }.items():
            fn = getattr(lib, name)
            fn.argtypes = args
            fn.restype = ctypes.c_int
        if lib.bls_selftest() != 0:
            raise RuntimeError("bls12381 native self-test failed")
        _lib = lib
        return _lib


def enabled() -> bool:
    """Reference key.go Enabled analog: True iff the native library is
    built and passes its self-test."""
    try:
        return _load() is not None
    except Exception:
        return False


def build(force: bool = False) -> bool:
    """Compile the native library (the reference's `-tags bls12381`
    analog).  Returns enabled(); never raises — a missing toolchain or
    failed compile leaves the scheme gated off.  Rebuilds when any
    native source is newer than the .so."""
    global _lib

    src = os.path.join(_NATIVE_DIR, "bls.cc")
    if not os.path.exists(src):
        return enabled()
    stale = True
    if os.path.exists(_LIB_PATH) and not force:
        lib_mtime = os.path.getmtime(_LIB_PATH)
        stale = any(
            os.path.getmtime(os.path.join(_NATIVE_DIR, f)) > lib_mtime
            for f in os.listdir(_NATIVE_DIR)
            if f.endswith((".cc", ".h")))
    if stale:
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", _LIB_PATH, src],
                check=True, capture_output=True, cwd=_NATIVE_DIR)
        except (OSError, subprocess.CalledProcessError):
            return False
        with _lib_lock:
            _lib = None          # reload the fresh build
    return enabled()


def _require():
    lib = _load()
    if lib is None:
        raise RuntimeError(
            "bls12381 is not enabled; run "
            "cometbft_tpu.crypto.bls12381.build() "
            "(reference analog: build tag bls12381, key.go:1)")
    return lib


@dataclass(frozen=True)
class PubKey:
    data: bytes

    def __post_init__(self):
        if len(self.data) != PUBKEY_SIZE:
            raise ValueError("bls12_381 pubkey must be 48 bytes")

    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def address(self) -> bytes:
        return sum_sha256(self.data)[:20]

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE:
            return False
        if len(msg) < MAX_MSG_LEN:
            return False  # unverifiable in the reference (see MAX_MSG_LEN)
        lib = _require()
        msg = _prehash(msg)
        return bool(lib.bls_verify(self.data, msg, len(msg), sig))

    def validate(self) -> bool:
        return bool(_require().bls_pk_validate(self.data))

    def __bytes__(self):
        return self.data


@dataclass(frozen=True)
class PrivKey:
    data: bytes

    def __post_init__(self):
        if len(self.data) != PRIVKEY_SIZE:
            raise ValueError("bls12_381 privkey must be 32 bytes")

    @staticmethod
    def generate(seed: bytes | None = None) -> "PrivKey":
        import secrets

        lib = _require()
        seed = seed if seed is not None else secrets.token_bytes(32)
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        out = ctypes.create_string_buffer(PRIVKEY_SIZE)
        if not lib.bls_keygen(seed, out):
            raise RuntimeError("bls keygen failed")
        return PrivKey(out.raw)

    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def pub_key(self) -> PubKey:
        lib = _require()
        out = ctypes.create_string_buffer(PUBKEY_SIZE)
        if not lib.bls_sk_to_pk(self.data, out):
            raise RuntimeError("invalid bls secret key")
        return PubKey(out.raw)

    def sign(self, msg: bytes) -> bytes:
        lib = _require()
        msg = _prehash(msg)
        out = ctypes.create_string_buffer(SIGNATURE_SIZE)
        if not lib.bls_sign(self.data, msg, len(msg), out):
            raise RuntimeError("bls sign failed")
        return out.raw


def aggregate_signatures(sigs: list[bytes]) -> bytes:
    lib = _require()
    buf = b"".join(sigs)
    if len(buf) != SIGNATURE_SIZE * len(sigs):
        raise ValueError("bad signature lengths")
    out = ctypes.create_string_buffer(SIGNATURE_SIZE)
    if not lib.bls_aggregate_sigs(buf, len(sigs), out):
        raise ValueError("invalid signature in aggregate")
    return out.raw


def aggregate_pubkeys(pks: list[bytes]) -> bytes:
    lib = _require()
    buf = b"".join(pks)
    if len(buf) != PUBKEY_SIZE * len(pks):
        raise ValueError("bad pubkey lengths")
    out = ctypes.create_string_buffer(PUBKEY_SIZE)
    if not lib.bls_aggregate_pks(buf, len(pks), out):
        raise ValueError("invalid pubkey in aggregate")
    return out.raw


def expand_message_xmd(msg: bytes, dst: bytes, length: int) -> bytes:
    lib = _require()
    out = ctypes.create_string_buffer(length)
    lib.bls_expand_message_xmd(msg, len(msg), dst, len(dst), out, length)
    return out.raw


def hash_to_g2(msg: bytes, dst: bytes) -> bytes:
    """RFC 9380 hash-to-G2, compressed output (test/KAT surface)."""
    lib = _require()
    out = ctypes.create_string_buffer(SIGNATURE_SIZE)
    if not lib.bls_hash_to_g2_compressed(msg, len(msg), dst, len(dst), out):
        raise RuntimeError("hash_to_g2 failed")
    return out.raw
