"""Pure-Python Ed25519 (RFC 8032 + ZIP-215 verify semantics).

Host-side reference implementation used for: signing (not a hot path —
the reference signs one vote at a time, /root/reference/privval/file.go),
key generation, the static base-point window tables consumed by the TPU
kernel, and cross-checking the device kernels in tests.  Written from the
RFC 8032 specification math; independent of the Go reference codebase.
"""

from __future__ import annotations

import hashlib
import os

P = (1 << 255) - 19
L = (1 << 252) + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

# base point
_BY = (4 * pow(5, P - 2, P)) % P
_BX = None  # filled below


def _recover_x(y: int, sign: int) -> int | None:
    """x from y per RFC 8032 5.1.3; None if not on curve."""
    if y >= (1 << 255):
        return None
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    x = (u * pow(v, P - 2, P)) % P
    x = pow(x, (P + 3) // 8, P)
    if (x * x - u * pow(v, P - 2, P)) % P != 0:
        x = (x * SQRT_M1) % P
    if (v * x * x - u) % P != 0:
        return None
    if x == 0 and sign == 1:
        return None
    if x % 2 != sign:
        x = P - x
    return x


_BX = _recover_x(_BY, 0)
B = (_BX, _BY, 1, (_BX * _BY) % P)  # extended coords (X, Y, Z, T)
IDENT = (0, 1, 1, 0)


def point_add(p, q):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = ((Y1 - X1) * (Y2 - X2)) % P
    Bv = ((Y1 + X1) * (Y2 + X2)) % P
    C = (2 * T1 * T2 * D) % P
    Dv = (2 * Z1 * Z2) % P
    E, F, G, H = (Bv - A) % P, (Dv - C) % P, (Dv + C) % P, (Bv + A) % P
    return ((E * F) % P, (G * H) % P, (F * G) % P, (E * H) % P)


def point_double(p):
    return point_add(p, p)


def point_neg(p):
    X, Y, Z, T = p
    return ((-X) % P, Y, Z, (-T) % P)


def point_mul(k: int, p):
    acc = IDENT
    while k:
        if k & 1:
            acc = point_add(acc, p)
        p = point_double(p)
        k >>= 1
    return acc


def point_eq(p, q) -> bool:
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def point_compress(p) -> bytes:
    X, Y, Z, _ = p
    zi = pow(Z, P - 2, P)
    x, y = (X * zi) % P, (Y * zi) % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def point_decompress(enc: bytes, zip215: bool = True):
    """Decode a point.  ZIP-215 mode skips the canonical-y check."""
    if len(enc) != 32:
        return None
    val = int.from_bytes(enc, "little")
    sign = val >> 255
    y = val & ((1 << 255) - 1)
    if not zip215 and y >= P:
        return None
    # ZIP-215 accepts non-canonical y; arithmetic reduces it implicitly
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    if v == 0:
        return None
    x = pow((u * pow(v, P - 2, P)) % P, (P + 3) // 8, P)
    if (v * x * x - u) % P != 0:
        x = (x * SQRT_M1) % P
    if (v * x * x - u) % P != 0:
        return None
    if x == 0 and sign == 1:
        return None
    if x % 2 != sign:
        x = P - x
    return (x, y % P, 1, (x * (y % P)) % P)


# ---------------------------------------------------------------------------
# keys / sign / verify
# ---------------------------------------------------------------------------

def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def pubkey_from_seed(seed: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    return point_compress(point_mul(_clamp(h), B))


def keygen(seed: bytes | None = None) -> tuple[bytes, bytes]:
    """Returns (seed32, pubkey32)."""
    seed = seed if seed is not None else os.urandom(32)
    return seed, pubkey_from_seed(seed)


def sign(seed: bytes, msg: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = _clamp(h)
    prefix = h[32:]
    A = point_compress(point_mul(a, B))
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    R = point_compress(point_mul(r, B))
    k = int.from_bytes(hashlib.sha512(R + A + msg).digest(), "little") % L
    s = (r + k * a) % L
    return R + s.to_bytes(32, "little")


def verify(pubkey: bytes, msg: bytes, sig: bytes) -> bool:
    """ZIP-215 cofactored verification: [8][s]B == [8]R + [8][k]A."""
    if len(sig) != 64 or len(pubkey) != 32:
        return False
    A = point_decompress(pubkey)
    R = point_decompress(sig[:32])
    if A is None or R is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    k = int.from_bytes(hashlib.sha512(sig[:32] + pubkey + msg).digest(),
                       "little") % L
    lhs = point_mul(8 * s, B)
    rhs = point_add(point_mul(8, R), point_mul(8 * k, A))
    return point_eq(lhs, rhs)


def base_window_table(width_bits: int = 4) -> list[tuple[int, int, int, int]]:
    """[k]B for k in 0..2**w-1, extended affine-Z coords, for device tables."""
    out = [IDENT]
    for k in range(1, 1 << width_bits):
        out.append(point_add(out[-1], B))
    return out
