"""Pure-Python X25519 + ChaCha20-Poly1305 (RFC 7748 / RFC 8439).

The p2p SecretConnection handshake (p2p/conn/secret_connection.py)
normally rides the `cryptography` wheel for these two primitives.  This
module is the dependency-free fallback: the SAME algorithms, bit-for-bit
wire compatible (a fallback node interoperates with a wheel-backed one),
implemented on Python integers — slower, but plenty for the loopback
testnets the e2e runner drives and for containers that ship without the
wheel.  Correctness is pinned against the RFC test vectors in
tests/test_aead.py.

Exports mirror the slices of the `cryptography` API the handshake uses:
``x25519(scalar, u)`` / ``x25519_base(scalar)`` and a
``ChaCha20Poly1305`` class with ``encrypt(nonce, data, aad)`` /
``decrypt(nonce, data, aad)`` (decrypt raises ValueError on a bad tag).
"""

from __future__ import annotations

import hmac
import struct

# -- X25519 (RFC 7748) -------------------------------------------------------

_P = 2 ** 255 - 19
_A24 = 121665
_BASE_U = (9).to_bytes(32, "little")


def _decode_scalar(k: bytes) -> int:
    if len(k) != 32:
        raise ValueError("X25519 scalar must be 32 bytes")
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(b, "little")


def _decode_u(u: bytes) -> int:
    if len(u) != 32:
        raise ValueError("X25519 u-coordinate must be 32 bytes")
    b = bytearray(u)
    b[31] &= 127                    # RFC 7748: mask the top bit
    return int.from_bytes(b, "little") % _P


def x25519(scalar: bytes, u: bytes) -> bytes:
    """Montgomery-ladder scalar multiplication on Curve25519."""
    k = _decode_scalar(scalar)
    x1 = _decode_u(u)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in range(254, -1, -1):
        kt = (k >> t) & 1
        swap ^= kt
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = da + cb
        x3 = x3 * x3 % _P
        z3 = da - cb
        z3 = x1 * (z3 * z3 % _P) % _P
        x2 = aa * bb % _P
        z2 = e * (aa + _A24 * e) % _P
    if swap:
        x2, z2 = x3, z3
    out = x2 * pow(z2, _P - 2, _P) % _P
    return out.to_bytes(32, "little")


def x25519_base(scalar: bytes) -> bytes:
    """Public key for a private scalar (u = 9)."""
    return x25519(scalar, _BASE_U)


# -- ChaCha20 (RFC 8439 section 2.3) -----------------------------------------

_SIGMA = struct.unpack("<4I", b"expand 32-byte k")
_MASK = 0xFFFFFFFF


def _chacha20_block(key_words, counter: int, nonce_words) -> bytes:
    x0, x1, x2, x3 = _SIGMA
    x4, x5, x6, x7, x8, x9, x10, x11 = key_words
    x12 = counter & _MASK
    x13, x14, x15 = nonce_words
    s = (x0, x1, x2, x3, x4, x5, x6, x7,
         x8, x9, x10, x11, x12, x13, x14, x15)
    for _ in range(10):             # 10 double rounds = 20 rounds
        # column round
        x0 = (x0 + x4) & _MASK; x12 ^= x0; x12 = ((x12 << 16) | (x12 >> 16)) & _MASK  # noqa: E702
        x8 = (x8 + x12) & _MASK; x4 ^= x8; x4 = ((x4 << 12) | (x4 >> 20)) & _MASK  # noqa: E702
        x0 = (x0 + x4) & _MASK; x12 ^= x0; x12 = ((x12 << 8) | (x12 >> 24)) & _MASK  # noqa: E702
        x8 = (x8 + x12) & _MASK; x4 ^= x8; x4 = ((x4 << 7) | (x4 >> 25)) & _MASK  # noqa: E702
        x1 = (x1 + x5) & _MASK; x13 ^= x1; x13 = ((x13 << 16) | (x13 >> 16)) & _MASK  # noqa: E702
        x9 = (x9 + x13) & _MASK; x5 ^= x9; x5 = ((x5 << 12) | (x5 >> 20)) & _MASK  # noqa: E702
        x1 = (x1 + x5) & _MASK; x13 ^= x1; x13 = ((x13 << 8) | (x13 >> 24)) & _MASK  # noqa: E702
        x9 = (x9 + x13) & _MASK; x5 ^= x9; x5 = ((x5 << 7) | (x5 >> 25)) & _MASK  # noqa: E702
        x2 = (x2 + x6) & _MASK; x14 ^= x2; x14 = ((x14 << 16) | (x14 >> 16)) & _MASK  # noqa: E702
        x10 = (x10 + x14) & _MASK; x6 ^= x10; x6 = ((x6 << 12) | (x6 >> 20)) & _MASK  # noqa: E702
        x2 = (x2 + x6) & _MASK; x14 ^= x2; x14 = ((x14 << 8) | (x14 >> 24)) & _MASK  # noqa: E702
        x10 = (x10 + x14) & _MASK; x6 ^= x10; x6 = ((x6 << 7) | (x6 >> 25)) & _MASK  # noqa: E702
        x3 = (x3 + x7) & _MASK; x15 ^= x3; x15 = ((x15 << 16) | (x15 >> 16)) & _MASK  # noqa: E702
        x11 = (x11 + x15) & _MASK; x7 ^= x11; x7 = ((x7 << 12) | (x7 >> 20)) & _MASK  # noqa: E702
        x3 = (x3 + x7) & _MASK; x15 ^= x3; x15 = ((x15 << 8) | (x15 >> 24)) & _MASK  # noqa: E702
        x11 = (x11 + x15) & _MASK; x7 ^= x11; x7 = ((x7 << 7) | (x7 >> 25)) & _MASK  # noqa: E702
        # diagonal round
        x0 = (x0 + x5) & _MASK; x15 ^= x0; x15 = ((x15 << 16) | (x15 >> 16)) & _MASK  # noqa: E702
        x10 = (x10 + x15) & _MASK; x5 ^= x10; x5 = ((x5 << 12) | (x5 >> 20)) & _MASK  # noqa: E702
        x0 = (x0 + x5) & _MASK; x15 ^= x0; x15 = ((x15 << 8) | (x15 >> 24)) & _MASK  # noqa: E702
        x10 = (x10 + x15) & _MASK; x5 ^= x10; x5 = ((x5 << 7) | (x5 >> 25)) & _MASK  # noqa: E702
        x1 = (x1 + x6) & _MASK; x12 ^= x1; x12 = ((x12 << 16) | (x12 >> 16)) & _MASK  # noqa: E702
        x11 = (x11 + x12) & _MASK; x6 ^= x11; x6 = ((x6 << 12) | (x6 >> 20)) & _MASK  # noqa: E702
        x1 = (x1 + x6) & _MASK; x12 ^= x1; x12 = ((x12 << 8) | (x12 >> 24)) & _MASK  # noqa: E702
        x11 = (x11 + x12) & _MASK; x6 ^= x11; x6 = ((x6 << 7) | (x6 >> 25)) & _MASK  # noqa: E702
        x2 = (x2 + x7) & _MASK; x13 ^= x2; x13 = ((x13 << 16) | (x13 >> 16)) & _MASK  # noqa: E702
        x8 = (x8 + x13) & _MASK; x7 ^= x8; x7 = ((x7 << 12) | (x7 >> 20)) & _MASK  # noqa: E702
        x2 = (x2 + x7) & _MASK; x13 ^= x2; x13 = ((x13 << 8) | (x13 >> 24)) & _MASK  # noqa: E702
        x8 = (x8 + x13) & _MASK; x7 ^= x8; x7 = ((x7 << 7) | (x7 >> 25)) & _MASK  # noqa: E702
        x3 = (x3 + x4) & _MASK; x14 ^= x3; x14 = ((x14 << 16) | (x14 >> 16)) & _MASK  # noqa: E702
        x9 = (x9 + x14) & _MASK; x4 ^= x9; x4 = ((x4 << 12) | (x4 >> 20)) & _MASK  # noqa: E702
        x3 = (x3 + x4) & _MASK; x14 ^= x3; x14 = ((x14 << 8) | (x14 >> 24)) & _MASK  # noqa: E702
        x9 = (x9 + x14) & _MASK; x4 ^= x9; x4 = ((x4 << 7) | (x4 >> 25)) & _MASK  # noqa: E702
    out = (x0, x1, x2, x3, x4, x5, x6, x7,
           x8, x9, x10, x11, x12, x13, x14, x15)
    return struct.pack("<16I", *((a + b) & _MASK
                                 for a, b in zip(out, s)))


def chacha20_xor(key: bytes, counter: int, nonce: bytes,
                 data: bytes) -> bytes:
    """XOR `data` with the ChaCha20 keystream (encrypt == decrypt)."""
    key_words = struct.unpack("<8I", key)
    nonce_words = struct.unpack("<3I", nonce)
    out = bytearray(len(data))
    for i in range(0, len(data), 64):
        block = _chacha20_block(key_words, counter + (i >> 6),
                                nonce_words)
        chunk = data[i:i + 64]
        out[i:i + len(chunk)] = bytes(
            a ^ b for a, b in zip(chunk, block))
    return bytes(out)


# -- Poly1305 (RFC 8439 section 2.5) -----------------------------------------

_POLY_P = (1 << 130) - 5
_R_CLAMP = 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF


def poly1305_mac(key: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key[:16], "little") & _R_CLAMP
    s = int.from_bytes(key[16:32], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i:i + 16]
        n = int.from_bytes(block, "little") | (1 << (8 * len(block)))
        acc = (acc + n) * r % _POLY_P
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


# -- AEAD_CHACHA20_POLY1305 (RFC 8439 section 2.8) ---------------------------

def _pad16(data: bytes) -> bytes:
    rem = len(data) % 16
    return b"\x00" * (16 - rem) if rem else b""


class ChaCha20Poly1305:
    """Drop-in for cryptography's ChaCha20Poly1305 as SecretConnection
    uses it: 12-byte nonces, ciphertext||16-byte tag, ValueError on
    authentication failure."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)

    def _tag(self, nonce: bytes, ct: bytes, aad: bytes) -> bytes:
        otk = _chacha20_block(
            struct.unpack("<8I", self._key), 0,
            struct.unpack("<3I", nonce))[:32]
        mac_data = (aad + _pad16(aad) + ct + _pad16(ct)
                    + struct.pack("<QQ", len(aad), len(ct)))
        return poly1305_mac(otk, mac_data)

    def encrypt(self, nonce: bytes, data: bytes,
                aad: bytes | None) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        aad = aad or b""
        ct = chacha20_xor(self._key, 1, nonce, data)
        return ct + self._tag(nonce, ct, aad)

    def decrypt(self, nonce: bytes, data: bytes,
                aad: bytes | None) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        if len(data) < 16:
            raise ValueError("ciphertext too short")
        aad = aad or b""
        ct, tag = data[:-16], data[-16:]
        if not hmac.compare_digest(self._tag(nonce, ct, aad), tag):
            raise ValueError("authentication tag mismatch")
        return chacha20_xor(self._key, 1, nonce, ct)
