"""sr25519: Schnorr signatures over ristretto255 with Merlin
transcripts (schnorrkel), the reference's third consensus key type
(/root/reference/crypto/sr25519/: privkey.go, pubkey.go, batch.go via
curve25519-voi's schnorrkel port).

Wire format and transcript layout follow the schnorrkel spec (the
Merlin layer is pinned by the crate's own equivalence-test vector in
tests/test_sr25519.py; no external schnorrkel SIGNATURE vector is
available in this offline build, so cross-implementation acceptance
rests on the transcript pin + the RFC 9496 ristretto vectors):
  context   : SigningContext(b"") — the reference's empty context
              (privkey.go:18 NewSigningContext([]byte{}))
  transcript: proto-name "Schnorr-sig", commit pk, commit R,
              challenge "sign:c" (64 bytes, reduced mod L)
  signature : R_ristretto(32) || s_LE(32) with bit 7 of byte 63 set
              (the schnorrkel "signature marker")

Batch verification rides the SAME TPU device kernel as ed25519: the
verify equation s*B = R + k*A is over edwards25519 points, ristretto
decoding guarantees the points are torsion-free, and on the prime-order
subgroup the device's cofactored check equals schnorrkel's cofactorless
one.  The host re-encodes the decoded points in Edwards compressed form
for the kernel and supplies the Merlin challenge k in place of the
SHA-512 ed25519 challenge.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from . import ed25519_ref as ed
from . import ristretto as rst
from .hash import sum_sha256
from .strobe import Transcript

KEY_TYPE = "sr25519"
PUBKEY_SIZE = 32
PRIVKEY_SIZE = 64        # scalar(32) || nonce(32)
SIGNATURE_SIZE = 64
L = ed.L


def _signing_transcript(msg: bytes) -> Transcript:
    """signing_context(b"").bytes(msg), the reference's context."""
    t = Transcript(b"SigningContext")
    t.append_message(b"", b"")
    t.append_message(b"sign-bytes", msg)
    return t


def _reduce_wide(b: bytes) -> int:
    return int.from_bytes(b, "little") % L


def challenge_scalar(msg: bytes, pub_enc: bytes, r_enc: bytes) -> int:
    """The verification challenge k for (pub, R, msg) — shared by the
    single and batch paths."""
    t = _signing_transcript(msg)
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pub_enc)
    t.append_message(b"sign:R", r_enc)
    return _reduce_wide(t.challenge_bytes(b"sign:c", 64))


@dataclass(frozen=True)
class PubKey:
    data: bytes

    def __post_init__(self):
        if len(self.data) != PUBKEY_SIZE:
            raise ValueError("sr25519 pubkey must be 32 bytes")

    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def address(self) -> bytes:
        """First 20 bytes of SHA-256 (the reference's address rule,
        pubkey.go Address)."""
        return sum_sha256(self.data)[:20]

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE:
            return False
        if sig[63] & 0x80 == 0:      # schnorrkel signature marker
            return False
        r_enc = sig[:32]
        s_bytes = bytes(sig[32:63]) + bytes([sig[63] & 0x7F])
        s = int.from_bytes(s_bytes, "little")
        if s >= L:
            return False
        a_pt = rst.decode(self.data)
        r_pt = rst.decode(r_enc)
        if a_pt is None or r_pt is None:
            return False
        k = challenge_scalar(msg, self.data, r_enc)
        # s*B == R + k*A
        lhs = ed.point_mul(s, ed.B)
        rhs = ed.point_add(r_pt, ed.point_mul(k, a_pt))
        return rst.eq(lhs, rhs)

    def __bytes__(self):
        return self.data


@dataclass(frozen=True)
class PrivKey:
    data: bytes              # scalar(32, LE) || nonce(32)

    def __post_init__(self):
        if len(self.data) != PRIVKEY_SIZE:
            raise ValueError("sr25519 privkey must be 64 bytes")

    @staticmethod
    def generate(seed: bytes | None = None) -> "PrivKey":
        if seed is None:
            seed = os.urandom(32)
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        # derive scalar + nonce from the seed (our own KDF; schnorrkel
        # accepts any scalar — wire compat is about signatures, not
        # key derivation)
        import hashlib
        h = hashlib.sha512(b"cometbft-tpu/sr25519" + seed).digest()
        scalar = _reduce_wide(h)
        return PrivKey(scalar.to_bytes(32, "little") + h[32:])

    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    @property
    def _scalar(self) -> int:
        return int.from_bytes(self.data[:32], "little") % L

    def pub_key(self) -> PubKey:
        return PubKey(rst.encode(ed.point_mul(self._scalar, ed.B)))

    def sign(self, msg: bytes) -> bytes:
        pub_enc = self.pub_key().data
        t = _signing_transcript(msg)
        t.append_message(b"proto-name", b"Schnorr-sig")
        t.append_message(b"sign:pk", pub_enc)
        # deterministic witness from the nonce half + transcript state
        # (schnorrkel mixes the nonce into the transcript rng the same
        # way; any r yields a valid signature)
        wt = t.clone()
        wt.append_message(b"proto-witness", self.data[32:])
        r = _reduce_wide(wt.challenge_bytes(b"witness", 64))
        r_enc = rst.encode(ed.point_mul(r, ed.B))
        t.append_message(b"sign:R", r_enc)
        k = _reduce_wide(t.challenge_bytes(b"sign:c", 64))
        s = (k * self._scalar + r) % L
        s_bytes = bytearray(s.to_bytes(32, "little"))
        s_bytes[31] |= 0x80
        return r_enc + bytes(s_bytes)


def to_edwards_inputs(pub: bytes, msg: bytes, sig: bytes
                      ) -> tuple[bytes, bytes, int, int] | None:
    """Translate an sr25519 (pub, msg, sig) into the ed25519 device
    kernel's input domain: Edwards-compressed A and R, scalar s, and
    the Merlin challenge k standing in for SHA512(R||A||M) mod L.
    Returns None on structural rejection."""
    if len(sig) != SIGNATURE_SIZE or len(pub) != PUBKEY_SIZE:
        return None
    if sig[63] & 0x80 == 0:
        return None
    s = int.from_bytes(bytes(sig[32:63]) + bytes([sig[63] & 0x7F]),
                       "little")
    if s >= L:
        return None
    a_pt = rst.decode(pub)
    r_pt = rst.decode(sig[:32])
    if a_pt is None or r_pt is None:
        return None
    k = challenge_scalar(msg, pub, sig[:32])
    return (ed.point_compress(a_pt), ed.point_compress(r_pt), s, k)
