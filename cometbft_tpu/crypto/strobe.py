"""Keccak-f[1600], STROBE-128, and Merlin transcripts — the transcript
machinery under sr25519/schnorrkel (reference analog: the merlin and
schnorrkel crates behind /root/reference/crypto/sr25519 via
curve25519-voi).

Implemented from the specs (FIPS 202 permutation; STROBE v1.0.2 as
specialized by merlin's strobe.rs; the Merlin transcript protocol).
The merlin equivalence-test vector in tests/test_sr25519.py pins the
whole stack.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# keccak-f[1600]
# ---------------------------------------------------------------------------

_MASK64 = (1 << 64) - 1

_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# rho rotation offsets and pi lane permutation, derived per FIPS 202
_ROTC = [[0] * 5 for _ in range(5)]
_x, _y = 1, 0
for _t in range(24):
    _ROTC[_x][_y] = ((_t + 1) * (_t + 2) // 2) % 64
    _x, _y = _y, (2 * _x + 3 * _y) % 5


def _rol(v: int, n: int) -> int:
    n %= 64
    return ((v << n) | (v >> (64 - n))) & _MASK64


def keccak_f1600(lanes: list[int]) -> list[int]:
    """In-place permutation over 25 64-bit lanes (x + 5y indexing)."""
    a = lanes
    for rnd in range(24):
        # theta
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20]
             for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rol(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] ^= d[x]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rol(
                    a[x + 5 * y], _ROTC[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] = b[x + 5 * y] ^ (
                    (~b[(x + 1) % 5 + 5 * y]) & b[(x + 2) % 5 + 5 * y]
                ) & _MASK64
        # iota
        a[0] ^= _RC[rnd]
    return a


def _keccak_bytes(state: bytearray) -> None:
    lanes = [int.from_bytes(state[8 * i:8 * i + 8], "little")
             for i in range(25)]
    keccak_f1600(lanes)
    for i, lane in enumerate(lanes):
        state[8 * i:8 * i + 8] = lane.to_bytes(8, "little")


# ---------------------------------------------------------------------------
# STROBE-128 (merlin's specialization, strobe.rs)
# ---------------------------------------------------------------------------

STROBE_R = 166

FLAG_I = 1
FLAG_A = 1 << 1
FLAG_C = 1 << 2
FLAG_T = 1 << 3
FLAG_M = 1 << 4
FLAG_K = 1 << 5


class Strobe128:
    def __init__(self, protocol_label: bytes):
        st = bytearray(200)
        st[0:6] = bytes([1, STROBE_R + 2, 1, 0, 1, 96])
        st[6:18] = b"STROBEv1.0.2"
        _keccak_bytes(st)
        self.state = st
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(protocol_label, False)

    # -- duplex ------------------------------------------------------------

    def _run_f(self) -> None:
        self.state[self.pos] ^= self.pos_begin
        self.state[self.pos + 1] ^= 0x04
        self.state[STROBE_R + 1] ^= 0x80
        _keccak_bytes(self.state)
        self.pos = 0
        self.pos_begin = 0

    def _absorb(self, data: bytes) -> None:
        for byte in data:
            self.state[self.pos] ^= byte
            self.pos += 1
            if self.pos == STROBE_R:
                self._run_f()

    def _overwrite(self, data: bytes) -> None:
        for byte in data:
            self.state[self.pos] = byte
            self.pos += 1
            if self.pos == STROBE_R:
                self._run_f()

    def _squeeze(self, n: int) -> bytes:
        out = bytearray(n)
        for i in range(n):
            out[i] = self.state[self.pos]
            self.state[self.pos] = 0
            self.pos += 1
            if self.pos == STROBE_R:
                self._run_f()
        return bytes(out)

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            if self.cur_flags != flags:
                raise ValueError("STROBE op flag mismatch on continuation")
            return
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        if (flags & (FLAG_C | FLAG_K)) and self.pos != 0:
            self._run_f()

    # -- merlin's op subset ------------------------------------------------

    def meta_ad(self, data: bytes, more: bool) -> None:
        self._begin_op(FLAG_M | FLAG_A, more)
        self._absorb(data)

    def ad(self, data: bytes, more: bool) -> None:
        self._begin_op(FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool = False) -> bytes:
        self._begin_op(FLAG_I | FLAG_A | FLAG_C, more)
        return self._squeeze(n)

    def key(self, data: bytes, more: bool = False) -> None:
        """Rekey (KEY op).  Unused by our transcript consumers (the
        deterministic sr25519 witness uses clone+append instead of
        merlin's TranscriptRng), kept for STROBE-op completeness."""
        self._begin_op(FLAG_A | FLAG_C, more)
        self._overwrite(data)


# ---------------------------------------------------------------------------
# merlin transcript
# ---------------------------------------------------------------------------

def _le32(n: int) -> bytes:
    return n.to_bytes(4, "little")


class Transcript:
    """merlin::Transcript."""

    def __init__(self, label: bytes):
        self.strobe = Strobe128(b"Merlin v1.0")
        self.append_message(b"dom-sep", label)

    def clone(self) -> "Transcript":
        t = Transcript.__new__(Transcript)
        t.strobe = Strobe128.__new__(Strobe128)
        t.strobe.state = bytearray(self.strobe.state)
        t.strobe.pos = self.strobe.pos
        t.strobe.pos_begin = self.strobe.pos_begin
        t.strobe.cur_flags = self.strobe.cur_flags
        return t

    def append_message(self, label: bytes, message: bytes) -> None:
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(_le32(len(message)), True)
        self.strobe.ad(message, False)

    def append_u64(self, label: bytes, value: int) -> None:
        self.append_message(label, value.to_bytes(8, "little"))

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(_le32(n), True)
        return self.strobe.prf(n)
