"""secp256k1 ECDSA keys: sign/verify + Bitcoin-style addresses.

Semantics mirror the reference (/root/reference/crypto/secp256k1/secp256k1.go):
- 32-byte privkeys, 33-byte compressed pubkeys (02/03 || x).
- Sign = ECDSA over SHA-256(msg) with RFC 6979 deterministic nonces,
  output 64-byte R||S in lower-S form (secp256k1.go:129-142).
- Verify rejects signatures not in lower-S form — the malleability rule
  (secp256k1.go:193-219).
- Address = RIPEMD160(SHA256(compressed pubkey)) (secp256k1.go:158-170).

The curve math is from-scratch Python (verify correctness oracle, signing,
key derivation); when the OpenSSL-backed `cryptography` package is present
its ECDSA verify is used as the fast path (same accept set: OpenSSL also
performs standard ECDSA; the lower-S gate is applied before dispatch).
"""

from __future__ import annotations

import hashlib
import hmac
import os
from dataclasses import dataclass

from .hash import sum_sha256
from ..libs import lockrank

KEY_TYPE = "secp256k1"
PRIVKEY_SIZE = 32
PUBKEY_SIZE = 33
SIGNATURE_SIZE = 64

# curve parameters (SEC2 2.4.1)
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


def _inv(a: int, m: int) -> int:
    return pow(a, m - 2, m)


# Jacobian point arithmetic (None = infinity); variable-time is fine for
# verification (public data); signing uses it too — acceptable for a
# validator whose key lives in FilePV, same trust model as the reference's
# btcec pure-Go path.

def _jadd(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1z1 = z1 * z1 % P
    z2z2 = z2 * z2 % P
    u1 = x1 * z2z2 % P
    u2 = x2 * z1z1 % P
    s1 = y1 * z2 * z2z2 % P
    s2 = y2 * z1 * z1z1 % P
    if u1 == u2:
        if s1 != s2:
            return None
        return _jdbl(p)
    h = (u2 - u1) % P
    i = 4 * h * h % P
    j = h * i % P
    r = 2 * (s2 - s1) % P
    v = u1 * i % P
    x3 = (r * r - j - 2 * v) % P
    y3 = (r * (v - x3) - 2 * s1 * j) % P
    z3 = 2 * h * z1 * z2 % P
    return x3, y3, z3


def _jdbl(p):
    if p is None:
        return None
    x1, y1, z1 = p
    a = x1 * x1 % P
    b = y1 * y1 % P
    c = b * b % P
    d = 2 * ((x1 + b) * (x1 + b) - a - c) % P
    e = 3 * a % P
    f = e * e % P
    x3 = (f - 2 * d) % P
    y3 = (e * (d - x3) - 8 * c) % P
    z3 = 2 * y1 * z1 % P
    return x3, y3, z3


def _jmul(k: int, pt):
    """Double-and-add scalar multiplication."""
    acc = None
    add = pt
    while k:
        if k & 1:
            acc = _jadd(acc, add)
        add = _jdbl(add)
        k >>= 1
    return acc


def _jaffine(p):
    if p is None:
        return None
    x, y, z = p
    zi = _inv(z, P)
    zi2 = zi * zi % P
    return x * zi2 % P, y * zi2 * zi % P


_G = (GX, GY, 1)


def _compress(x: int, y: int) -> bytes:
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def _decompress(data: bytes) -> tuple[int, int] | None:
    if len(data) != PUBKEY_SIZE or data[0] not in (2, 3):
        return None
    x = int.from_bytes(data[1:], "big")
    if x >= P:
        return None
    y2 = (pow(x, 3, P) + 7) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        return None
    if (y & 1) != (data[0] & 1):
        y = P - y
    return x, y


def _rfc6979_k(x: int, h1: bytes) -> int:
    """RFC 6979 §3.2 deterministic nonce for SHA-256 / secp256k1."""
    qlen_bytes = 32
    v = b"\x01" * 32
    key = b"\x00" * 32
    x_b = x.to_bytes(qlen_bytes, "big")
    # bits2octets: h1 interpreted mod N then padded
    z = int.from_bytes(h1, "big") % N
    z_b = z.to_bytes(qlen_bytes, "big")
    key = hmac.new(key, v + b"\x00" + x_b + z_b, hashlib.sha256).digest()
    v = hmac.new(key, v, hashlib.sha256).digest()
    key = hmac.new(key, v + b"\x01" + x_b + z_b, hashlib.sha256).digest()
    v = hmac.new(key, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(key, v, hashlib.sha256).digest()
        k = int.from_bytes(v, "big")
        if 1 <= k < N:
            return k
        key = hmac.new(key, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(key, v, hashlib.sha256).digest()


def parse_signature(sig: bytes) -> tuple[int, int] | None:
    """The ONE place the signature accept-set is defined (64-byte R||S,
    1 <= r,s < n, lower-S malleability rule — secp256k1.go:205-214);
    used by both the host verify and the device batch packer so the
    accept sets cannot drift."""
    if len(sig) != SIGNATURE_SIZE:
        return None
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    if not (1 <= r < N and 1 <= s < N):
        return None
    if s > N // 2:
        return None
    return r, s


def _verify_py(pub_xy: tuple[int, int], digest: bytes, r: int, s: int) -> bool:
    """Textbook ECDSA verify over the already-parsed values."""
    e = int.from_bytes(digest, "big")
    w = _inv(s, N)
    u1 = e * w % N
    u2 = r * w % N
    pt = _jadd(_jmul(u1, _G), _jmul(u2, pub_xy + (1,)))
    aff = _jaffine(pt)
    if aff is None:
        return False
    return aff[0] % N == r


def _verify_openssl(pub_bytes: bytes, msg: bytes, r: int, s: int) -> bool:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        encode_dss_signature)

    try:
        pub = ec.EllipticCurvePublicKey.from_encoded_point(
            ec.SECP256K1(), pub_bytes)
    except ValueError:
        return False
    der = encode_dss_signature(r, s)
    try:
        pub.verify(der, msg, ec.ECDSA(hashes.SHA256()))
        return True
    except InvalidSignature:
        return False


try:  # fast path availability probe
    import cryptography  # noqa: F401
    _HAVE_OPENSSL = os.environ.get("COMETBFT_TPU_PURE_SECP", "") != "1"
except ImportError:  # pragma: no cover
    _HAVE_OPENSSL = False


@dataclass(frozen=True)
class PubKey:
    data: bytes

    def __post_init__(self):
        if len(self.data) != PUBKEY_SIZE:
            raise ValueError("secp256k1 pubkey must be 33 bytes")

    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def address(self) -> bytes:
        """RIPEMD160(SHA256(compressed pubkey)) — secp256k1.go:158."""
        return hashlib.new("ripemd160", sum_sha256(self.data)).digest()

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        parsed = parse_signature(sig)
        if parsed is None:
            return False
        r, s = parsed
        if _HAVE_OPENSSL:
            return _verify_openssl(self.data, msg, r, s)
        xy = _decompress(self.data)
        if xy is None:
            return False
        return _verify_py(xy, sum_sha256(msg), r, s)

    def __bytes__(self):
        return self.data


@dataclass(frozen=True)
class PrivKey:
    data: bytes

    def __post_init__(self):
        if len(self.data) != PRIVKEY_SIZE:
            raise ValueError("secp256k1 privkey must be 32 bytes")
        d = int.from_bytes(self.data, "big")
        if not (1 <= d < N):
            raise ValueError("secp256k1 privkey out of range")

    @staticmethod
    def generate(seed: bytes | None = None) -> "PrivKey":
        """Random key, or the reference's hash-to-key rule for a seed:
        k = (SHA256(seed) mod (n-1)) + 1 (secp256k1.go:106-126)."""
        if seed is None:
            while True:
                raw = os.urandom(32)
                d = int.from_bytes(raw, "big")
                if 1 <= d < N:
                    return PrivKey(raw)
        fe = int.from_bytes(sum_sha256(seed), "big") % (N - 1) + 1
        return PrivKey(fe.to_bytes(32, "big"))

    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def pub_key(self) -> PubKey:
        x, y = _jaffine(_jmul(int.from_bytes(self.data, "big"), _G))
        return PubKey(_compress(x, y))

    def sign(self, msg: bytes) -> bytes:
        """64-byte R||S, lower-S, RFC 6979 nonce (secp256k1.go:129-142)."""
        d = int.from_bytes(self.data, "big")
        digest = sum_sha256(msg)
        e = int.from_bytes(digest, "big")
        k = _rfc6979_k(d, digest)
        while True:
            x, _y = _jaffine(_jmul(k, _G))
            r = x % N
            s = _inv(k, N) * (e + r * d) % N
            if r and s:
                break
            k = (k + 1) % N  # vanishing r/s: probability ~2^-256
        if s > N // 2:
            s = N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")


# -- device batch packing ---------------------------------------------------

def pack_batch(pubkeys: list[bytes], msgs: list[bytes], sigs: list[bytes],
               batch_size: int):
    """Pack an ECDSA batch for ops/secp256k1.verify_kernel.

    Host side per signature (all cheap bigint work): structural checks
    (lengths, 1 <= r,s < n, lower-S), pubkey decompression, e = SHA-256,
    w = s^-1 mod n, u1 = e*w, u2 = r*w, and MSB-first 4-bit window
    recoding of u1/u2.  Entries failing a structural check get a benign
    filler whose verdict is False by construction (u1 = 1, u2 = 0,
    r = 0: x(G) != 0).

    Returns (qx, qy, u1_nibs, u2_nibs, r_limbs, rn_limbs, rn_valid,
    valid) with the kernel's limbs-first layouts.
    """
    import numpy as np

    from ..ops import fe_secp as fs

    n = len(pubkeys)
    assert batch_size >= n
    qx = np.zeros((batch_size, fs.NLIMBS), np.int32)
    qy = np.zeros((batch_size, fs.NLIMBS), np.int32)
    u1n = np.zeros((batch_size, 64), np.int32)
    u2n = np.zeros((batch_size, 64), np.int32)
    r_l = np.zeros((batch_size, fs.NLIMBS), np.int32)
    rn_l = np.zeros((batch_size, fs.NLIMBS), np.int32)
    rn_ok = np.zeros(batch_size, bool)
    valid = np.zeros(batch_size, bool)

    def nibs(v: int) -> np.ndarray:
        out = np.zeros(64, np.int32)
        for j in range(63, -1, -1):
            out[j] = v & 0xF
            v >>= 4
        return out

    gx_l = fs.int_to_limbs(GX)
    gy_l = fs.int_to_limbs(GY)
    filler_u1 = nibs(1)
    for i in range(batch_size):
        ok = False
        if i < n:
            parsed = parse_signature(sigs[i])
            if parsed is not None:
                r, s = parsed
                xy = _decompress(pubkeys[i])
                if xy is not None:
                    e = int.from_bytes(sum_sha256(msgs[i]), "big")
                    w = _inv(s, N)
                    u1, u2 = e * w % N, r * w % N
                    qx[i] = fs.int_to_limbs(xy[0])
                    qy[i] = fs.int_to_limbs(xy[1])
                    u1n[i] = nibs(u1)
                    u2n[i] = nibs(u2)
                    r_l[i] = fs.int_to_limbs(r)
                    if r + N < P:
                        rn_l[i] = fs.int_to_limbs(r + N)
                        rn_ok[i] = True
                    ok = True
        if not ok:
            qx[i], qy[i] = gx_l, gy_l
            u1n[i] = filler_u1
        valid[i] = ok
    return (np.ascontiguousarray(qx.T), np.ascontiguousarray(qy.T),
            np.ascontiguousarray(u1n.T), np.ascontiguousarray(u2n.T),
            np.ascontiguousarray(r_l.T), np.ascontiguousarray(rn_l.T),
            rn_ok, valid)


# -- unified MSM batch path (ops/msm.py engine) ------------------------------

def msm_enabled() -> bool:
    """The engine on/off knob (A/B seam: bench arms, simnet parity
    tests, and the operator escape hatch back to the ladder)."""
    return os.environ.get("COMETBFT_TPU_SECP_MSM", "1") != "0"


# distinct-key axis pad grid: bounds the number of compiled
# (batch, nkeys) kernel shapes the same way ops/ed25519.pad_width
# bounds MSM side widths
_KEY_WIDTHS = (4, 8, 16, 32, 64, 96, 128, 192, 256)


def _key_pad(k: int) -> int:
    for w in _KEY_WIDTHS:
        if k <= w:
            return w
    base = _KEY_WIDTHS[-1]
    return ((k + base - 1) // base) * base


def pack_msm_batch(pubkeys: list[bytes], msgs: list[bytes],
                   sigs: list[bytes], batch_size: int) -> dict:
    """Pack an ECDSA batch for ops/secp256k1.msm_verify_kernel.

    Host work per signature: the same structural checks / u1, u2
    derivation as pack_batch, then odd-normalization (u + n when u is
    even — n*P = infinity, cofactor 1, so the value is unchanged and
    u' < 2n < 2^257 stays inside the window span) and the vectorized
    Joye-Tunstall odd recode (ops/msm.recode_jt) — NO per-signature
    64-iteration digit loop, which made pack_batch itself a ~30k
    sigs/s host ceiling.

    Each pack draws a fresh blinding scalar t with ``secrets`` and
    ships S = t*G; see the soundness note in ops/secp256k1.py.

    Returns a dict: keys_x/keys_y (22, K) distinct-key affine coords
    (K padded onto _KEY_WIDTHS, fillers = G), key_id bytes (cache key
    for the per-key tables), gid (B,) int32 key slot per lane,
    g_rows/g_neg (32, B) and q_rows/q_neg (52, B) odd-window digits,
    r_limbs/rn_limbs (22, B), rn_valid/valid (B,), s_pt (3, 22).
    """
    import secrets

    import numpy as np

    from ..ops import fe_secp as fs
    from ..ops import msm

    n = len(pubkeys)
    assert batch_size >= n
    u1o = [1] * batch_size
    u2o = [1] * batch_size
    gid = np.zeros(batch_size, np.int32)
    r_l = np.zeros((batch_size, fs.NLIMBS), np.int32)
    rn_l = np.zeros((batch_size, fs.NLIMBS), np.int32)
    rn_ok = np.zeros(batch_size, bool)
    valid = np.zeros(batch_size, bool)
    key_slot: dict[bytes, int] = {}
    key_xy: list[tuple[int, int]] = []
    key_order: list[bytes] = []
    decomp: dict[bytes, tuple[int, int] | None] = {}

    for i in range(n):
        parsed = parse_signature(sigs[i])
        if parsed is None:
            continue
        r, s = parsed
        pk = pubkeys[i]
        if pk not in decomp:
            decomp[pk] = _decompress(pk)
        xy = decomp[pk]
        if xy is None:
            continue
        e = int.from_bytes(sum_sha256(msgs[i]), "big")
        w = _inv(s, N)
        u1, u2 = e * w % N, r * w % N
        slot = key_slot.get(pk)
        if slot is None:
            slot = key_slot[pk] = len(key_order)
            key_order.append(pk)
            key_xy.append(xy)
        gid[i] = slot
        u1o[i] = u1 if u1 & 1 else u1 + N
        u2o[i] = u2 if u2 & 1 else u2 + N
        r_l[i] = fs.int_to_limbs(r)
        if r + N < P:
            rn_l[i] = fs.int_to_limbs(r + N)
            rn_ok[i] = True
        valid[i] = True

    nk = _key_pad(max(1, len(key_order)))
    keys_x = np.zeros((nk, fs.NLIMBS), np.int32)
    keys_y = np.zeros((nk, fs.NLIMBS), np.int32)
    for k, (x, y) in enumerate(key_xy):
        keys_x[k] = fs.int_to_limbs(x)
        keys_y[k] = fs.int_to_limbs(y)
    gx_l, gy_l = fs.int_to_limbs(GX), fs.int_to_limbs(GY)
    for k in range(len(key_xy), nk):
        keys_x[k], keys_y[k] = gx_l, gy_l

    from ..ops.secp256k1 import MSM_NG, MSM_NQ, MSM_WG, MSM_WQ
    g_rows, g_neg = msm.recode_jt(u1o, MSM_WG, MSM_NG)
    q_rows, q_neg = msm.recode_jt(u2o, MSM_WQ, MSM_NQ)

    t = secrets.randbelow(N - 1) + 1
    sx, sy = _jaffine(_jmul(t, _G))
    s_pt = np.stack([fs.int_to_limbs(sx), fs.int_to_limbs(sy),
                     np.asarray(fs.ONE_LIMBS, np.int32)])

    return {
        "keys_x": np.ascontiguousarray(keys_x.T),
        "keys_y": np.ascontiguousarray(keys_y.T),
        "key_id": b"".join(key_order) + b"|%d" % nk,
        "gid": gid,
        "g_rows": g_rows, "g_neg": g_neg,
        "q_rows": q_rows, "q_neg": q_neg,
        "r_limbs": np.ascontiguousarray(r_l.T),
        "rn_limbs": np.ascontiguousarray(rn_l.T),
        "rn_valid": rn_ok, "valid": valid, "s_pt": s_pt,
    }


class QTableCache:
    """Device cache of per-key secp256k1 MSM window tables.

    The ATableCache access pattern (crypto/ed25519.py) in Weierstrass
    flavor: a validator set's distinct pubkeys produce the same
    (keys_x, keys_y) every commit, so the device-batched table build
    (52 windows x 16 odd rows per key, ~215 KB/key of HBM) runs once
    per key set and every later commit's MSM dispatch gathers from
    resident tables.  Keyed by the packed key bytes + device;
    LRU-bounded by a byte budget (COMETBFT_TPU_Q_CACHE_BYTES, default
    128 MiB ~ 600 keys).  Thread-safe.
    """

    def __init__(self, max_bytes: int | None = None):
        import collections

        self._max_bytes = (max_bytes if max_bytes is not None else
                           int(os.environ.get(
                               "COMETBFT_TPU_Q_CACHE_BYTES",
                               str(128 << 20))))
        self._entries = collections.OrderedDict()  # key -> (entry, nbytes)
        self._bytes = 0
        self._lock = lockrank.RankedLock("secp256k1.qtable")
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @property
    def bytes_resident(self) -> int:
        return self._bytes

    def _gauge(self, dm) -> None:
        if dm is not None:
            dm.q_table_cache_bytes.set(self._bytes)

    def get(self, key_id: bytes, keys_x, keys_y, device=None):
        """(qtab, q_corr) device arrays for one packed key set,
        building (and admitting) on miss.  `device` places the tables
        on a specific mesh device and keys the entry by it — each chip
        in a round-robin dispatch keeps its own resident copy."""
        from ..libs import metrics as libmetrics
        from ..ops import secp256k1 as dev_ops

        dm = libmetrics.device_metrics()
        key = (key_id, device)
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self.hits += 1
                if dm is not None:
                    dm.q_table_cache_hits.inc()
                return self._entries[key][0]
        entry = dev_ops.build_q_msm_tables_device(keys_x, keys_y,
                                                 device=device)
        qtab, _ = entry
        nbytes = int(qtab.size) * qtab.dtype.itemsize
        with self._lock:
            self.misses += 1
            if dm is not None:
                dm.q_table_cache_misses.inc()
            if nbytes > self._max_bytes:
                self._gauge(dm)
                return entry
            if key not in self._entries:
                self._entries[key] = (entry, nbytes)
                self._bytes += nbytes
                while self._bytes > self._max_bytes and \
                        len(self._entries) > 1:
                    _, (_, freed) = self._entries.popitem(last=False)
                    self._bytes -= freed
                    self.evictions += 1
            self._gauge(dm)
            return self._entries[key][0]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0


_Q_CACHE: QTableCache | None = None


def q_table_cache() -> QTableCache:
    global _Q_CACHE
    if _Q_CACHE is None:
        _Q_CACHE = QTableCache()
    return _Q_CACHE


def verify_msm_async(pubkeys: list[bytes], msgs: list[bytes],
                     sigs: list[bytes], batch_size: int | None = None,
                     device=None):
    """Pack + table lookup + kernel dispatch WITHOUT the host sync:
    returns (device verdict array, host valid mask, n).  The mesh
    split (crypto/mesh.split_secp_verify) uses this to put every
    chip's program in flight before reading any verdict back."""
    from ..ops import ed25519 as ed_ops
    from ..ops import secp256k1 as dev_ops

    n = len(pubkeys)
    if batch_size is None:
        batch_size = ed_ops.bucket_size(n)      # same bucket discipline
    pk = pack_msm_batch(pubkeys, msgs, sigs, batch_size)
    qtab, q_corr = q_table_cache().get(
        pk["key_id"], pk["keys_x"], pk["keys_y"], device=device)
    verdict = dev_ops.verify_batch_msm_device(
        qtab, q_corr, pk["gid"], pk["g_rows"], pk["g_neg"],
        pk["q_rows"], pk["q_neg"], pk["r_limbs"], pk["rn_limbs"],
        pk["rn_valid"], pk["s_pt"], device=device)
    return verdict, pk["valid"], n


def verify_msm_batch(pubkeys: list[bytes], msgs: list[bytes],
                     sigs: list[bytes], device=None) -> list[bool]:
    """Whole-batch ECDSA verdicts through the unified MSM engine:
    per-signature booleans in submission order (the engine's verdicts
    ARE per-signature, so rejects need no localization round)."""
    import numpy as np

    verdict, valid, n = verify_msm_async(pubkeys, msgs, sigs,
                                         device=device)
    out = np.asarray(verdict) & valid
    return [bool(v) for v in out[:n]]
