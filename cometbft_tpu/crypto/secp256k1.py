"""secp256k1 ECDSA keys: sign/verify + Bitcoin-style addresses.

Semantics mirror the reference (/root/reference/crypto/secp256k1/secp256k1.go):
- 32-byte privkeys, 33-byte compressed pubkeys (02/03 || x).
- Sign = ECDSA over SHA-256(msg) with RFC 6979 deterministic nonces,
  output 64-byte R||S in lower-S form (secp256k1.go:129-142).
- Verify rejects signatures not in lower-S form — the malleability rule
  (secp256k1.go:193-219).
- Address = RIPEMD160(SHA256(compressed pubkey)) (secp256k1.go:158-170).

The curve math is from-scratch Python (verify correctness oracle, signing,
key derivation); when the OpenSSL-backed `cryptography` package is present
its ECDSA verify is used as the fast path (same accept set: OpenSSL also
performs standard ECDSA; the lower-S gate is applied before dispatch).
"""

from __future__ import annotations

import hashlib
import hmac
import os
from dataclasses import dataclass

from .hash import sum_sha256

KEY_TYPE = "secp256k1"
PRIVKEY_SIZE = 32
PUBKEY_SIZE = 33
SIGNATURE_SIZE = 64

# curve parameters (SEC2 2.4.1)
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


def _inv(a: int, m: int) -> int:
    return pow(a, m - 2, m)


# Jacobian point arithmetic (None = infinity); variable-time is fine for
# verification (public data); signing uses it too — acceptable for a
# validator whose key lives in FilePV, same trust model as the reference's
# btcec pure-Go path.

def _jadd(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1z1 = z1 * z1 % P
    z2z2 = z2 * z2 % P
    u1 = x1 * z2z2 % P
    u2 = x2 * z1z1 % P
    s1 = y1 * z2 * z2z2 % P
    s2 = y2 * z1 * z1z1 % P
    if u1 == u2:
        if s1 != s2:
            return None
        return _jdbl(p)
    h = (u2 - u1) % P
    i = 4 * h * h % P
    j = h * i % P
    r = 2 * (s2 - s1) % P
    v = u1 * i % P
    x3 = (r * r - j - 2 * v) % P
    y3 = (r * (v - x3) - 2 * s1 * j) % P
    z3 = 2 * h * z1 * z2 % P
    return x3, y3, z3


def _jdbl(p):
    if p is None:
        return None
    x1, y1, z1 = p
    a = x1 * x1 % P
    b = y1 * y1 % P
    c = b * b % P
    d = 2 * ((x1 + b) * (x1 + b) - a - c) % P
    e = 3 * a % P
    f = e * e % P
    x3 = (f - 2 * d) % P
    y3 = (e * (d - x3) - 8 * c) % P
    z3 = 2 * y1 * z1 % P
    return x3, y3, z3


def _jmul(k: int, pt):
    """Double-and-add scalar multiplication."""
    acc = None
    add = pt
    while k:
        if k & 1:
            acc = _jadd(acc, add)
        add = _jdbl(add)
        k >>= 1
    return acc


def _jaffine(p):
    if p is None:
        return None
    x, y, z = p
    zi = _inv(z, P)
    zi2 = zi * zi % P
    return x * zi2 % P, y * zi2 * zi % P


_G = (GX, GY, 1)


def _compress(x: int, y: int) -> bytes:
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def _decompress(data: bytes) -> tuple[int, int] | None:
    if len(data) != PUBKEY_SIZE or data[0] not in (2, 3):
        return None
    x = int.from_bytes(data[1:], "big")
    if x >= P:
        return None
    y2 = (pow(x, 3, P) + 7) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        return None
    if (y & 1) != (data[0] & 1):
        y = P - y
    return x, y


def _rfc6979_k(x: int, h1: bytes) -> int:
    """RFC 6979 §3.2 deterministic nonce for SHA-256 / secp256k1."""
    qlen_bytes = 32
    v = b"\x01" * 32
    key = b"\x00" * 32
    x_b = x.to_bytes(qlen_bytes, "big")
    # bits2octets: h1 interpreted mod N then padded
    z = int.from_bytes(h1, "big") % N
    z_b = z.to_bytes(qlen_bytes, "big")
    key = hmac.new(key, v + b"\x00" + x_b + z_b, hashlib.sha256).digest()
    v = hmac.new(key, v, hashlib.sha256).digest()
    key = hmac.new(key, v + b"\x01" + x_b + z_b, hashlib.sha256).digest()
    v = hmac.new(key, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(key, v, hashlib.sha256).digest()
        k = int.from_bytes(v, "big")
        if 1 <= k < N:
            return k
        key = hmac.new(key, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(key, v, hashlib.sha256).digest()


def parse_signature(sig: bytes) -> tuple[int, int] | None:
    """The ONE place the signature accept-set is defined (64-byte R||S,
    1 <= r,s < n, lower-S malleability rule — secp256k1.go:205-214);
    used by both the host verify and the device batch packer so the
    accept sets cannot drift."""
    if len(sig) != SIGNATURE_SIZE:
        return None
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    if not (1 <= r < N and 1 <= s < N):
        return None
    if s > N // 2:
        return None
    return r, s


def _verify_py(pub_xy: tuple[int, int], digest: bytes, r: int, s: int) -> bool:
    """Textbook ECDSA verify over the already-parsed values."""
    e = int.from_bytes(digest, "big")
    w = _inv(s, N)
    u1 = e * w % N
    u2 = r * w % N
    pt = _jadd(_jmul(u1, _G), _jmul(u2, pub_xy + (1,)))
    aff = _jaffine(pt)
    if aff is None:
        return False
    return aff[0] % N == r


def _verify_openssl(pub_bytes: bytes, msg: bytes, r: int, s: int) -> bool:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        encode_dss_signature)

    try:
        pub = ec.EllipticCurvePublicKey.from_encoded_point(
            ec.SECP256K1(), pub_bytes)
    except ValueError:
        return False
    der = encode_dss_signature(r, s)
    try:
        pub.verify(der, msg, ec.ECDSA(hashes.SHA256()))
        return True
    except InvalidSignature:
        return False


try:  # fast path availability probe
    import cryptography  # noqa: F401
    _HAVE_OPENSSL = os.environ.get("COMETBFT_TPU_PURE_SECP", "") != "1"
except ImportError:  # pragma: no cover
    _HAVE_OPENSSL = False


@dataclass(frozen=True)
class PubKey:
    data: bytes

    def __post_init__(self):
        if len(self.data) != PUBKEY_SIZE:
            raise ValueError("secp256k1 pubkey must be 33 bytes")

    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def address(self) -> bytes:
        """RIPEMD160(SHA256(compressed pubkey)) — secp256k1.go:158."""
        return hashlib.new("ripemd160", sum_sha256(self.data)).digest()

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        parsed = parse_signature(sig)
        if parsed is None:
            return False
        r, s = parsed
        if _HAVE_OPENSSL:
            return _verify_openssl(self.data, msg, r, s)
        xy = _decompress(self.data)
        if xy is None:
            return False
        return _verify_py(xy, sum_sha256(msg), r, s)

    def __bytes__(self):
        return self.data


@dataclass(frozen=True)
class PrivKey:
    data: bytes

    def __post_init__(self):
        if len(self.data) != PRIVKEY_SIZE:
            raise ValueError("secp256k1 privkey must be 32 bytes")
        d = int.from_bytes(self.data, "big")
        if not (1 <= d < N):
            raise ValueError("secp256k1 privkey out of range")

    @staticmethod
    def generate(seed: bytes | None = None) -> "PrivKey":
        """Random key, or the reference's hash-to-key rule for a seed:
        k = (SHA256(seed) mod (n-1)) + 1 (secp256k1.go:106-126)."""
        if seed is None:
            while True:
                raw = os.urandom(32)
                d = int.from_bytes(raw, "big")
                if 1 <= d < N:
                    return PrivKey(raw)
        fe = int.from_bytes(sum_sha256(seed), "big") % (N - 1) + 1
        return PrivKey(fe.to_bytes(32, "big"))

    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def pub_key(self) -> PubKey:
        x, y = _jaffine(_jmul(int.from_bytes(self.data, "big"), _G))
        return PubKey(_compress(x, y))

    def sign(self, msg: bytes) -> bytes:
        """64-byte R||S, lower-S, RFC 6979 nonce (secp256k1.go:129-142)."""
        d = int.from_bytes(self.data, "big")
        digest = sum_sha256(msg)
        e = int.from_bytes(digest, "big")
        k = _rfc6979_k(d, digest)
        while True:
            x, _y = _jaffine(_jmul(k, _G))
            r = x % N
            s = _inv(k, N) * (e + r * d) % N
            if r and s:
                break
            k = (k + 1) % N  # vanishing r/s: probability ~2^-256
        if s > N // 2:
            s = N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")


# -- device batch packing ---------------------------------------------------

def pack_batch(pubkeys: list[bytes], msgs: list[bytes], sigs: list[bytes],
               batch_size: int):
    """Pack an ECDSA batch for ops/secp256k1.verify_kernel.

    Host side per signature (all cheap bigint work): structural checks
    (lengths, 1 <= r,s < n, lower-S), pubkey decompression, e = SHA-256,
    w = s^-1 mod n, u1 = e*w, u2 = r*w, and MSB-first 4-bit window
    recoding of u1/u2.  Entries failing a structural check get a benign
    filler whose verdict is False by construction (u1 = 1, u2 = 0,
    r = 0: x(G) != 0).

    Returns (qx, qy, u1_nibs, u2_nibs, r_limbs, rn_limbs, rn_valid,
    valid) with the kernel's limbs-first layouts.
    """
    import numpy as np

    from ..ops import fe_secp as fs

    n = len(pubkeys)
    assert batch_size >= n
    qx = np.zeros((batch_size, fs.NLIMBS), np.int32)
    qy = np.zeros((batch_size, fs.NLIMBS), np.int32)
    u1n = np.zeros((batch_size, 64), np.int32)
    u2n = np.zeros((batch_size, 64), np.int32)
    r_l = np.zeros((batch_size, fs.NLIMBS), np.int32)
    rn_l = np.zeros((batch_size, fs.NLIMBS), np.int32)
    rn_ok = np.zeros(batch_size, bool)
    valid = np.zeros(batch_size, bool)

    def nibs(v: int) -> np.ndarray:
        out = np.zeros(64, np.int32)
        for j in range(63, -1, -1):
            out[j] = v & 0xF
            v >>= 4
        return out

    gx_l = fs.int_to_limbs(GX)
    gy_l = fs.int_to_limbs(GY)
    filler_u1 = nibs(1)
    for i in range(batch_size):
        ok = False
        if i < n:
            parsed = parse_signature(sigs[i])
            if parsed is not None:
                r, s = parsed
                xy = _decompress(pubkeys[i])
                if xy is not None:
                    e = int.from_bytes(sum_sha256(msgs[i]), "big")
                    w = _inv(s, N)
                    u1, u2 = e * w % N, r * w % N
                    qx[i] = fs.int_to_limbs(xy[0])
                    qy[i] = fs.int_to_limbs(xy[1])
                    u1n[i] = nibs(u1)
                    u2n[i] = nibs(u2)
                    r_l[i] = fs.int_to_limbs(r)
                    if r + N < P:
                        rn_l[i] = fs.int_to_limbs(r + N)
                        rn_ok[i] = True
                    ok = True
        if not ok:
            qx[i], qy[i] = gx_l, gy_l
            u1n[i] = filler_u1
        valid[i] = ok
    return (np.ascontiguousarray(qx.T), np.ascontiguousarray(qy.T),
            np.ascontiguousarray(u1n.T), np.ascontiguousarray(u2n.T),
            np.ascontiguousarray(r_l.T), np.ascontiguousarray(rn_l.T),
            rn_ok, valid)
