"""BatchVerifier: the accelerator seam.

The reference dispatches batch verification by key type and falls back to
per-signature CPU verify below a threshold
(/root/reference/crypto/batch/batch.go:12-35, types/validation.go:13).
Here the same seam routes to either:

- TpuBatchVerifier: one jitted JAX program verifying the whole batch on
  the accelerator (per-signature verdicts come out as a bitmap), or
- CpuBatchVerifier: host loop, used below the device threshold and as the
  parity oracle in tests.

Unlike the reference (which refuses mixed-keytype batches,
types/validation.go:18 AllKeysHaveSameType), mixed batches are split by
key type and each sub-batch is dispatched to its own verifier.
"""

from __future__ import annotations

import os
from typing import Protocol

from . import ed25519 as ed
from . import sigcache


class BatchVerifier(Protocol):
    def add(self, pubkey, msg: bytes, sig: bytes) -> None: ...
    def verify(self) -> tuple[bool, list[bool]]: ...
    def count(self) -> int: ...


class _SigCollector:
    """Shared add/count scaffolding: items are (pubkey_bytes, msg, sig).

    verify() wraps the subclass _verify_items() and POPULATES the
    signature-verdict cache with every computed verdict — batch
    verifiers are a resolution seam (crypto/sigcache.py); consulting
    is the callers' job (types/validation partitions before building
    the verifier), so a miss is never double-counted here."""

    KEY_TYPE = "ed25519"

    def __init__(self):
        self._items: list[tuple[bytes, bytes, bytes]] = []

    def add(self, pubkey, msg: bytes, sig: bytes) -> None:
        pk = pubkey.bytes() if hasattr(pubkey, "bytes") else bytes(pubkey)
        self._items.append((pk, msg, sig))

    def count(self) -> int:
        return len(self._items)

    def verify(self) -> tuple[bool, list[bool]]:
        ok, verdicts = self._verify_items()
        if self._items:
            sigcache.insert_many(self._items, verdicts,
                                 key_type=self.KEY_TYPE)
        return ok, verdicts


class _CpuLoopVerifier(_SigCollector):
    """Host-side per-signature loop (parity oracle for a device path);
    subclasses provide _check(pk, msg, sig) -> bool."""

    def _verify_items(self) -> tuple[bool, list[bool]]:
        verdicts = []
        for pk, m, s in self._items:
            try:
                verdicts.append(bool(self._check(pk, m, s)))
            except ValueError:
                verdicts.append(False)
        return all(verdicts) and bool(verdicts), verdicts


class CpuEd25519BatchVerifier(_CpuLoopVerifier):
    """ZIP-215 host loop (crypto/ed25519_ref)."""

    def _check(self, pk, m, s):
        from . import ed25519_ref as ref
        return ref.verify(pk, m, s)


class TpuEd25519BatchVerifier(_SigCollector):
    """Packs the batch into uint32 arrays and runs the device kernel.

    Batch sizes are bucketed (ops/ed25519.BATCH_BUCKETS) so the jitted
    kernel compiles once per bucket; slots past the real batch are masked.
    """

    def _verify_items(self) -> tuple[bool, list[bool]]:
        if not self._items:
            return False, []
        pks = [i[0] for i in self._items]
        # parse + hash ONCE; both device packings build from this
        parsed = ed.parse_and_hash(pks, [i[1] for i in self._items],
                                   [i[2] for i in self._items])
        return _device_verify(pks, parsed)


# sentinel: "no precomputed RLC packing" (None is a real pack_rlc
# result meaning structural reject, so it cannot double as the default)
_NO_PACK = object()


def _device_verify(pubkeys: list[bytes], parsed, packed=_NO_PACK,
                   device=None) -> tuple[bool, list[bool]]:
    """Shared device dispatch for any Edwards-domain batch: RLC fast
    path first, per-signature kernel for verdict localization on
    failure — the reference's verifyCommitBatch -> verifyCommitSingle
    pattern (/root/reference/types/validation.go:115).  `packed`
    accepts a pack_rlc result computed ahead of time (the overlapped
    pipeline packs window N+1 while window N is on device).

    `device` commits the dispatch to one specific mesh device (the
    pipeline's round-robin placement, crypto/dispatch.py); with
    device=None and a configured mesh, a large window instead SPLITS
    across every device — one RLC program per chip
    (crypto/mesh.maybe_split_verify), falling back to the
    batch-axis-sharded per-signature kernel for localization."""
    import numpy as np

    from ..ops import ed25519 as dev
    from ..ops import sharding

    n = len(pubkeys)
    if n >= 2:
        rlc_ok = None
        if packed is _NO_PACK and device is None:
            from . import mesh

            rlc_ok = mesh.maybe_split_verify(pubkeys, parsed)
        if rlc_ok is None:
            if packed is _NO_PACK:
                packed = ed.pack_rlc(pubkeys, [b""] * n, [b""] * n,
                                     parsed=parsed)
            rlc_ok = packed is not None and \
                ed.rlc_verify(packed, device=device)
        if rlc_ok:
            return True, [True] * n
        from ..libs import flightrec
        from ..libs import metrics as libmetrics

        dm = libmetrics.device_metrics()
        if dm is not None:
            dm.rlc_fallbacks.inc()
        flightrec.record(flightrec.EV_RLC_FALLBACK, batch=n)
    if device is not None:
        import jax

        bucket = dev.bucket_size(n)
        a, r, s, h, valid = ed.pack_batch(pubkeys, [b""] * n, [b""] * n,
                                          bucket, parsed=parsed)
        a, r, s, h = (jax.device_put(x, device) for x in (a, r, s, h))
        verdict = np.asarray(dev.verify_batch_device(a, r, s, h))
    else:
        bucket = sharding.auto_bucket(n)
        a, r, s, h, valid = ed.pack_batch(pubkeys, [b""] * n, [b""] * n,
                                          bucket, parsed=parsed)
        verdict = np.asarray(sharding.verify_batch_sharded(a, r, s, h))
    verdict = verdict & valid
    out = verdict[:n].tolist()
    return all(out) and bool(out), out


def _device_verify_hash(pubkeys: list[bytes], msgs: list[bytes], parsed,
                        packed=_NO_PACK,
                        device=None) -> tuple[bool, list[bool]]:
    """_device_verify with FUSED hash-to-scalar: h = SHA512(R||A||M)
    mod L, the per-pubkey aggregation and the A-side recode all run on
    device (ops/ed25519.rlc_verify_hash_kernel) — no digest ever
    crosses back to the host, including the per-signature localization
    kernel on a reject.  `parsed` is a parse_batch result
    ((r_enc, s) | None; no h).  Raises ValueError("message exceeds
    max_blocks") when a message outgrows the static block bucket — the
    dispatch layer's host-fallback trigger."""
    import numpy as np

    from ..ops import ed25519 as dev

    n = len(pubkeys)
    if n >= 2:
        rlc_ok = None
        if packed is _NO_PACK and device is None:
            from . import mesh

            rlc_ok = mesh.maybe_split_verify_hash(pubkeys, msgs, parsed)
        if rlc_ok is None:
            if packed is _NO_PACK:
                packed = ed.pack_rlc_device_hash(pubkeys, msgs,
                                                 [b""] * n, parsed=parsed)
            rlc_ok = packed is not None and \
                ed.rlc_verify_hash(packed, device=device)
        if rlc_ok:
            return True, [True] * n
        from ..libs import flightrec
        from ..libs import metrics as libmetrics

        dm = libmetrics.device_metrics()
        if dm is not None:
            dm.rlc_fallbacks.inc()
        flightrec.record(flightrec.EV_RLC_FALLBACK, batch=n)
    bucket = dev.bucket_size(n)
    a, r, s, bh, bl, nb, valid = ed.pack_batch_device_hash(
        pubkeys, msgs, [b""] * n, bucket, parsed=parsed)
    if device is not None:
        import jax

        a, r, s, bh, bl, nb = (jax.device_put(x, device)
                               for x in (a, r, s, bh, bl, nb))
    verdict = np.asarray(dev.verify_batch_hash_device(a, r, s, bh, bl,
                                                      nb))
    verdict = verdict & valid
    out = verdict[:n].tolist()
    return all(out) and bool(out), out


class CpuSecp256k1BatchVerifier(_CpuLoopVerifier):
    """Parity oracle for the secp256k1 device path."""

    KEY_TYPE = "secp256k1"

    def _check(self, pk, m, s):
        from . import secp256k1 as sk
        return sk.PubKey(pk).verify_signature(m, s)


class TpuSecp256k1BatchVerifier(_SigCollector):
    """ECDSA batch on the device.  Default path: the unified MSM
    engine (ops/msm.py + ops/secp256k1.msm_verify_kernel) — the whole
    commit's checks become two shared-table multi-products (u1·G
    against a baked G window table, u2·Q against QTableCache-resident
    per-key tables), ~1250 field-muls/signature vs ~4224 for the
    ladder.  ECDSA admits no RLC whole-batch equation (each check
    compares an x-coordinate), so verdicts stay per-signature — which
    also means rejects need no localization round.  Set
    COMETBFT_TPU_SECP_MSM=0 to fall back to the per-signature Straus
    ladder (ops/secp256k1.verify_kernel) — the bench A/B arm and the
    operator escape hatch.  The reference refuses to batch secp256k1
    at all (crypto/batch/batch.go:12)."""

    KEY_TYPE = "secp256k1"

    def _verify_items(self) -> tuple[bool, list[bool]]:
        import numpy as np

        from ..ops import ed25519 as ed_dev
        from ..ops import secp256k1 as dev
        from . import secp256k1 as sk

        n = len(self._items)
        if n == 0:
            return False, []
        pubkeys = [i[0] for i in self._items]
        msgs = [i[1] for i in self._items]
        sigs = [i[2] for i in self._items]
        if sk.msm_enabled():
            from . import mesh
            out = mesh.maybe_split_secp_verify(pubkeys, msgs, sigs)
            if out is None:
                out = sk.verify_msm_batch(pubkeys, msgs, sigs)
            return all(out) and bool(out), out
        bucket = ed_dev.bucket_size(n)      # same bucketing discipline
        packed = sk.pack_batch(pubkeys, msgs, sigs, bucket)
        valid = packed[-1]
        verdict = np.asarray(dev.verify_batch_device(*packed[:-1]))
        verdict = verdict & valid
        out = verdict[:n].tolist()
        return all(out) and bool(out), out


class CpuSr25519BatchVerifier(_CpuLoopVerifier):
    """Parity oracle for the sr25519 device path."""

    KEY_TYPE = "sr25519"

    def _check(self, pk, m, s):
        from . import sr25519 as sr
        return sr.PubKey(pk).verify_signature(m, s)


class TpuSr25519BatchVerifier(_SigCollector):
    """sr25519 batches on the ed25519 device kernels: ristretto points
    re-encoded in Edwards form, Merlin challenges in place of the
    SHA-512 challenge (see crypto/sr25519.to_edwards_inputs; the
    reference's analog is sr25519.BatchVerifier in batch.go)."""

    KEY_TYPE = "sr25519"

    def _verify_items(self) -> tuple[bool, list[bool]]:
        from . import sr25519 as sr

        n = len(self._items)
        if n == 0:
            return False, []
        # host: ristretto decode + transcript challenges; parsed feeds
        # the SAME packers as ed25519 (ed_pub stands in for pubkeys[i],
        # k for the hash h)
        ed_pubs, parsed = [], []
        for pk, m, s in self._items:
            t = sr.to_edwards_inputs(pk, m, s)
            if t is None:
                ed_pubs.append(b"\x00" * 32)
                parsed.append(None)
            else:
                a_ed, r_ed, s_int, k = t
                ed_pubs.append(a_ed)
                parsed.append((r_ed, s_int, k))
        return _device_verify(ed_pubs, parsed)


# device threshold: below this many signatures the host loop wins (the
# reference's analog is batchVerifyThreshold=2, types/validation.go:13;
# ours is higher because the device round-trip has fixed cost).
DEVICE_THRESHOLD = int(os.environ.get("COMETBFT_TPU_BATCH_THRESHOLD", "8"))

# secp256k1 has no RLC batch equation — its device kernel verifies
# per-signature Straus chains, so the per-sig device advantage is far
# smaller than ed25519's and the ~70 ms dispatch floor dominates small
# batches.  Measured: host 889 sigs/s (1.12 ms/sig, recorded in
# docs/PERF.md); device (r5 width sweep, ab_round5_results.jsonl
# secp_batch_ab): 6613 sigs/s at batch 1024, 27583 at 4096, 27383 at
# 16383 — marginal device cost ~36 us/sig once dispatch overhead
# amortizes.  Fixed+marginal crossover ~= 70 sigs; 96 leaves margin
# for relay jitter.
SECP_DEVICE_THRESHOLD = int(os.environ.get(
    "COMETBFT_TPU_SECP_THRESHOLD", "96"))


def _device_threshold(key_type: str) -> int:
    if key_type == "secp256k1":
        return max(DEVICE_THRESHOLD, SECP_DEVICE_THRESHOLD)
    return DEVICE_THRESHOLD


def safe_verify(pub_key, msg: bytes, sig: bytes) -> bool:
    """verify_signature with backend errors mapped to invalid.

    The single source of truth for how malformed input or an
    unavailable native backend (bls12381 without its .so) is handled:
    every host single-verify loop — here, types/validation.py's commit
    loop, and DeferredSigBatch — must agree, or the same commit could
    crash one path and merely fail another.

    Routes through the signature-verdict cache: a triple verified
    anywhere in the process (vote stream, a batch window, a previous
    commit check) answers here for one SHA-256; a fresh verdict is
    inserted so the NEXT consumer gets the hit."""
    v = sigcache.get(pub_key, msg, sig)
    if v is not None:
        return v
    try:
        v = bool(pub_key.verify_signature(msg, sig))
    except Exception:
        v = False
    sigcache.insert(pub_key, msg, sig, v)
    return v

# the reference batches only ed25519 & sr25519 (crypto/batch/batch.go:
# 12-35); we also batch secp256k1 on device (a BASELINE.json target)
_SUPPORTED = {"ed25519", "sr25519", "secp256k1"}

_CPU_BY_TYPE = {"ed25519": CpuEd25519BatchVerifier,
                "sr25519": CpuSr25519BatchVerifier,
                "secp256k1": CpuSecp256k1BatchVerifier}
_TPU_BY_TYPE = {"ed25519": TpuEd25519BatchVerifier,
                "sr25519": TpuSr25519BatchVerifier,
                "secp256k1": TpuSecp256k1BatchVerifier}


def supports_batch_verifier(key_type: str) -> bool:
    return key_type in _SUPPORTED


def create_batch_verifier(key_type: str = "ed25519", n_hint: int = 0,
                          provider: str | None = None) -> BatchVerifier:
    provider = provider or os.environ.get("COMETBFT_TPU_PROVIDER", "auto")
    if key_type not in _SUPPORTED:
        raise ValueError(f"no batch verifier for key type {key_type}")
    if provider == "cpu":
        return _CPU_BY_TYPE[key_type]()
    if provider == "tpu":
        return _TPU_BY_TYPE[key_type]()
    # auto: pick by expected batch size (per-keytype crossover — secp
    # lacks an RLC equation, so its device win starts much later)
    if n_hint and n_hint < _device_threshold(key_type):
        return _CPU_BY_TYPE[key_type]()
    return _TPU_BY_TYPE[key_type]()


class MixedBatchVerifier:
    """Routes a mixed-keytype batch to per-type verifiers.

    The reference refuses mixed batches outright
    (types/validation.go:18); handling them on-device is a BASELINE.json
    target, so this wrapper keys each added signature by pubkey type and
    merges verdicts in insertion order.
    """

    def __init__(self, provider: str | None = None):
        self._provider = provider
        self._items: dict[str, list] = {}
        self._order: list[tuple[str, int] | None] = []
        self._singles: list[tuple[object, bytes, bytes]] = []

    def add(self, pubkey, msg: bytes, sig: bytes) -> None:
        kt = pubkey.type() if hasattr(pubkey, "type") else "ed25519"
        if not supports_batch_verifier(kt):
            # no batch kernel for this key type: fall back to the key's own
            # single-verify at verify() time instead of erroring mid-add
            self._order.append(None)
            self._singles.append((pubkey, msg, sig))
            return
        items = self._items.setdefault(kt, [])
        self._order.append((kt, len(items)))
        items.append((pubkey, msg, sig))

    def count(self) -> int:
        return len(self._order)

    def _verify_subtype(self, kt: str, items) -> list[bool]:
        sub = create_batch_verifier(kt, n_hint=len(items),
                                    provider=self._provider)
        for pk, msg, sig in items:
            sub.add(pk, msg, sig)
        return sub.verify()[1]

    def verify(self) -> tuple[bool, list[bool]]:
        # per-type verifiers are created HERE so n_hint can route
        # sub-threshold sub-batches (e.g. a lone secp256k1 validator in
        # an ed25519 set) to the cheap host loop instead of a device
        # dispatch + cold kernel compile.  Sub-batches of DIFFERENT key
        # types are independent programs, so they dispatch
        # concurrently: the device pipelines them and the host loops
        # release the GIL in OpenSSL/numpy.
        results = {}
        if len(self._items) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                    max_workers=len(self._items),
                    thread_name_prefix="mixed-batch") as ex:
                futs = {kt: ex.submit(self._verify_subtype, kt, items)
                        for kt, items in self._items.items()}
                results = {kt: f.result() for kt, f in futs.items()}
        else:
            for kt, items in self._items.items():
                results[kt] = self._verify_subtype(kt, items)
        singles = iter(self._singles)
        out = []
        for slot in self._order:
            if slot is None:
                pk, msg, sig = next(singles)
                out.append(safe_verify(pk, msg, sig))
            else:
                kt, i = slot
                out.append(results[kt][i])
        return all(out) and bool(out), out
