"""Host-side Ed25519 API: keys, signing, and the TPU batch-verify bridge.

Mirrors the seam of the reference's crypto/ed25519 package
(/root/reference/crypto/ed25519/ed25519.go: PrivKey.Sign :45,
PubKey.VerifySignature :181, BatchVerifier :208) but the batch path packs
signatures into uint32 device arrays and runs one jitted TPU program
(ops/ed25519.verify_kernel) instead of per-signature CPU verification.
"""

from __future__ import annotations

import os

from dataclasses import dataclass

import numpy as np

from functools import lru_cache

from . import ed25519_ref as ref
from ..libs import lockrank
from .hash import sum_sha256

KEY_TYPE = "ed25519"
PUBKEY_SIZE = 32
PRIVKEY_SIZE = 64          # seed || pubkey, like the reference golang layout
SIGNATURE_SIZE = 64
L = ref.L


@dataclass(frozen=True)
class PubKey:
    data: bytes

    def __post_init__(self):
        if len(self.data) != PUBKEY_SIZE:
            raise ValueError("ed25519 pubkey must be 32 bytes")

    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def address(self) -> bytes:
        """First 20 bytes of SHA-256, the reference's address rule."""
        return sum_sha256(self.data)[:20]

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        """Single verify — the live-consensus per-vote hot path
        (reference types/vote_set.go:219-223 -> ed25519.go:181).

        Fast path: OpenSSL's strict cofactorless RFC-8032 verify.  Its
        accept set is a SUBSET of ZIP-215 (sB = R + hA implies
        [8]sB = [8]R + [8]hA, and it only accepts canonical encodings
        ZIP-215 also accepts), so True is always final; only a rejection
        falls back to the from-scratch ZIP-215 reference check, keeping
        batch/single semantics identical while honest signatures cost
        ~100 us instead of ~4 ms of pure-Python bignum math.
        """
        fast = _openssl_verifier(self.data)
        if fast is not None:
            if fast(msg, sig):
                return True
        return ref.verify(self.data, msg, sig)

    def __bytes__(self):
        return self.data


@lru_cache(maxsize=4096)
def _openssl_verifier(pub: bytes):
    """Parsed-key cache, the analog of the reference's 4096-entry
    expanded-pubkey LRU (ed25519.go:64-70). Returns None if OpenSSL is
    unavailable or the key fails to parse (non-canonical encodings the
    ZIP-215 path must judge)."""
    try:
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PublicKey)
    except ImportError:  # pragma: no cover
        return None
    try:
        key = Ed25519PublicKey.from_public_bytes(pub)
    except ValueError:
        return None

    def check(msg: bytes, sig: bytes) -> bool:
        try:
            key.verify(sig, msg)
            return True
        except (InvalidSignature, ValueError):
            return False

    return check


@dataclass(frozen=True)
class PrivKey:
    data: bytes              # seed(32) || pubkey(32)

    def __post_init__(self):
        if len(self.data) != PRIVKEY_SIZE:
            raise ValueError("ed25519 privkey must be 64 bytes")

    @staticmethod
    def generate(seed: bytes | None = None) -> "PrivKey":
        seed, pub = ref.keygen(seed)
        return PrivKey(seed + pub)

    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def pub_key(self) -> PubKey:
        return PubKey(self.data[32:])

    def sign(self, msg: bytes) -> bytes:
        # Prefer the constant-time OpenSSL path (the pure-Python reference
        # signer is variable-time and only safe for tests/tools).
        try:
            from cryptography.hazmat.primitives.asymmetric.ed25519 import (
                Ed25519PrivateKey)
            return Ed25519PrivateKey.from_private_bytes(
                self.data[:32]).sign(msg)
        except ImportError:  # pragma: no cover
            return ref.sign(self.data[:32], msg)


def parse_signature(sig: bytes) -> tuple[bytes, int] | None:
    """Split sig into (R_enc, s) and range-check s < L (RFC 8032 / ZIP-215)."""
    if len(sig) != SIGNATURE_SIZE:
        return None
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return None
    return sig[:32], s


def parse_and_hash(pubkeys: list[bytes], msgs: list[bytes],
                   sigs: list[bytes]) -> list[tuple[bytes, int, int] | None]:
    """Host-side structural parse + hash, done ONCE per batch: for each
    entry (r_enc, s, h = SHA512(R||A||M) mod L) or None on a structural
    reject.  Both device packings (per-signature and RLC) build from
    this, so a fallback never re-hashes messages."""
    import hashlib

    out = []
    for pk, msg, sig in zip(pubkeys, msgs, sigs):
        parsed = parse_signature(sig) if len(pk) == PUBKEY_SIZE else None
        if parsed is None:
            out.append(None)
            continue
        r_enc, s = parsed
        h = int.from_bytes(
            hashlib.sha512(r_enc + pk + msg).digest(), "little") % L
        out.append((r_enc, s, h))
    return out


def pack_batch(pubkeys: list[bytes], msgs: list[bytes], sigs: list[bytes],
               batch_size: int, parsed=None):
    """Pack a signature batch into device-ready numpy arrays.

    h = SHA512(R||A||M) mod L is computed HERE on the host (hashlib is
    C-speed; it overlaps with device work and keeps the device program
    small — round-2 redesign, see ops/ed25519.py).  Entries failing
    host-side structural checks (bad lengths, s >= L) get a
    pre-determined False verdict via the `valid` mask; their slots are
    filled with benign data so the kernel stays branch-free.

    Arrays are LIMBS-FIRST (v3 kernel layout: batch in the minor/lane
    dimension): returns (a_words (8,B), r_words (8,B), s_limbs (16,B),
    h_limbs (16,B), valid (B,)).
    """
    from ..ops import limbs as lb

    n = len(pubkeys)
    assert batch_size >= n
    if parsed is None:
        parsed = parse_and_hash(pubkeys, msgs, sigs)
    valid = np.zeros(batch_size, dtype=bool)
    a_words = np.zeros((batch_size, 8), dtype=np.uint32)
    r_words = np.zeros((batch_size, 8), dtype=np.uint32)
    s_limbs = np.zeros((batch_size, 16), dtype=np.uint32)
    h_limbs = np.zeros((batch_size, 16), dtype=np.uint32)
    dummy = ref.point_compress(ref.B)
    for i in range(n):
        if parsed[i] is None:
            continue
        r_enc, s, h = parsed[i]
        valid[i] = True
        a_words[i] = np.frombuffer(pubkeys[i], dtype=np.uint32)
        r_words[i] = np.frombuffer(r_enc, dtype=np.uint32)
        s_limbs[i] = lb.int_to_limbs(s, 16)
        h_limbs[i] = lb.int_to_limbs(h, 16)
    # benign filler so decompression of invalid slots still succeeds
    filler = np.frombuffer(dummy, dtype=np.uint32)
    a_words[~valid] = filler
    r_words[~valid] = filler
    return (np.ascontiguousarray(a_words.T),
            np.ascontiguousarray(r_words.T),
            np.ascontiguousarray(s_limbs.T),
            np.ascontiguousarray(h_limbs.T), valid)


NDIG_128 = 26       # signed-5-bit digits covering 128-bit z (+carry)
NDIG_256 = 52       # covering scalars < L (253 bits, +carry)


def _recode_nbytes(ndig: int) -> int:
    """Little-endian byte width of _recode_w5's raw input rows."""
    return (5 * ndig + 7) // 8 + 1


def _recode_w5_scalar(values: list[int], ndig: int, width: int):
    """Pure-Python reference recoding, one value and one digit at a
    time (the pre-vectorization semantics, LSB-up carry sweep): the
    parity oracle `_recode_w5` and the device-side recode are pinned
    against in tests/test_recode.py."""
    mag = np.zeros((width, ndig), np.int32)
    neg = np.zeros((width, ndig), bool)
    for i, v in enumerate(values):
        assert v < 1 << (5 * ndig), \
            "scalar out of range for recoding width"
        digs = [(v >> (5 * j)) & 31 for j in range(ndig)]
        carry = 0
        for j in range(ndig):
            d = digs[j] + carry
            carry = 1 if d > 15 else 0
            digs[j] = d - 32 if d > 15 else d
        assert carry == 0, "scalar out of range for recoding width"
        mag[i] = [abs(d) for d in digs]
        neg[i] = [d < 0 for d in digs]
    return (np.ascontiguousarray(mag.T[::-1]),
            np.ascontiguousarray(neg.T[::-1]))


def _recode_w5(values, ndig: int, width: int):
    """Signed radix-32 recoding: each value becomes ndig digits in
    [-16, 15], emitted MSB-first as separate magnitude (int32) and sign
    (bool) arrays of shape (ndig, width).  Pad columns beyond
    len(values) stay zero (identity contribution).

    Fully vectorized via the bias trick: the signed digits of x are the
    plain base-32 digits of x + BIAS minus 16, where
    BIAS = sum_j 16*32**j — pre-paying the worst-case borrow turns the
    old data-dependent carry sweep into one addition plus static bit
    extraction, and is the exact algorithm the device recode
    (ops/ed25519._recode_w5_device) runs.  `values` is either a
    list[int] or an already-raw (n, _recode_nbytes(ndig)) uint8 array
    of little-endian bytes (the device-hash packer hands z straight
    from its random byte block, never materializing Python ints)."""
    n = len(values)
    mag = np.zeros((width, ndig), np.int32)
    neg = np.zeros((width, ndig), bool)
    if n:
        nbytes = _recode_nbytes(ndig)
        if isinstance(values, np.ndarray):
            assert values.shape == (n, nbytes) and values.dtype == np.uint8
            raw = values.astype(np.uint16)
        else:
            assert max(values) < 1 << (5 * ndig), \
                "scalar out of range for recoding width"
            raw = np.frombuffer(
                b"".join(v.to_bytes(nbytes, "little") for v in values),
                dtype=np.uint8).reshape(n, nbytes).astype(np.uint16)
        bias = np.frombuffer(
            sum(16 << (5 * j) for j in range(ndig)).to_bytes(
                nbytes, "little"), dtype=np.uint8).astype(np.uint16)
        acc = raw + bias                      # per-byte sums < 2**9
        carry = np.zeros(n, np.uint16)
        for k in range(nbytes):
            t = acc[:, k] + carry
            acc[:, k] = t & 0xFF
            carry = t >> 8
        assert not carry.any(), "scalar out of range for recoding width"
        digs = np.empty((n, ndig), np.int16)
        for j in range(ndig):
            off = 5 * j
            k, sh = off >> 3, off & 7
            word = acc[:, k] | (acc[:, k + 1] << 8)
            digs[:, j] = (((word >> sh) & 31).astype(np.int16)) - 16
        mag[:n] = np.abs(digs)
        neg[:n] = digs < 0
    return (np.ascontiguousarray(mag.T[::-1]),
            np.ascontiguousarray(neg.T[::-1]))


def _neg_b_encoding() -> bytes:
    """Compressed -B: flip the x-sign bit of the base point encoding."""
    enc = bytearray(ref.point_compress(ref.B))
    enc[31] ^= 0x80
    return bytes(enc)


_NEG_B_ENC = None


def pack_rlc(pubkeys: list[bytes], msgs: list[bytes], sigs: list[bytes],
             parsed=None):
    """Pack a batch for the device RLC kernel (ops/ed25519.rlc_verify_kernel).

    Host work per signature: h = SHA512(R||A||M) mod L (via
    parse_and_hash, shared with the per-signature packing), a random
    128-bit z, zh = z*h mod L.  Two preprocessing steps shrink the
    device program (v4 kernel, split A/R MSMs):

    - REPEATED pubkeys aggregate: zh coefficients for the same 32-byte
      A encoding are summed mod L, so the A-side MSM runs over DISTINCT
      keys only (a 150-validator set verifying 10k commits costs 150 A
      slots, not 1.5M).
    - the fixed-base term c = sum z_i*s_i mod L rides in A slot 0 as
      (-B, c).

    Both batches pad to bucketed widths (ops/ed25519.pad_width); pad
    slots hold the base point with zero scalar and contribute the
    identity.  Scalars are recoded host-side into signed 5-bit window
    digits (_recode_w5).

    Returns (a_words (8,K), r_words (8,N), a_mag (52,K), a_neg (52,K),
    r_mag (26,N), r_neg (26,N)) limbs-first/MSB-first, or None if any
    entry fails structural checks (caller falls back to the
    per-signature kernel for verdicts).
    """
    import secrets

    global _NEG_B_ENC
    if _NEG_B_ENC is None:
        _NEG_B_ENC = _neg_b_encoding()

    n = len(pubkeys)
    if n == 0:
        return None
    if parsed is None:
        parsed = parse_and_hash(pubkeys, msgs, sigs)
    agg: dict[bytes, int] = {}
    c = 0
    r_encs = []
    zs = []
    for i in range(n):
        if parsed[i] is None:
            return None
        r_enc, s, h = parsed[i]
        z = secrets.randbits(128) | (1 << 127)
        pk = pubkeys[i]
        agg[pk] = (agg.get(pk, 0) + z * h) % L
        c = (c + z * s) % L
        r_encs.append(r_enc)
        zs.append(z)

    from ..ops import ed25519 as dev

    k = 1 + len(agg)
    kbatch = dev.pad_width(k)
    nbatch = dev.pad_width(n)
    a_words = np.zeros((kbatch, 8), dtype=np.uint32)
    r_words = np.zeros((nbatch, 8), dtype=np.uint32)

    filler = np.frombuffer(ref.point_compress(ref.B), dtype=np.uint32)
    a_words[:] = filler
    r_words[:] = filler
    a_words[0] = np.frombuffer(_NEG_B_ENC, dtype=np.uint32)
    a_scalars = [c] + list(agg.values())
    for j, pk in enumerate(agg.keys(), start=1):
        a_words[j] = np.frombuffer(pk, dtype=np.uint32)
    for i in range(n):
        r_words[i] = np.frombuffer(r_encs[i], dtype=np.uint32)
    a_mag, a_neg = _recode_w5(a_scalars, NDIG_256, kbatch)
    r_mag, r_neg = _recode_w5(zs, NDIG_128, nbatch)
    return (np.ascontiguousarray(a_words.T),
            np.ascontiguousarray(r_words.T),
            a_mag, a_neg, r_mag, r_neg)


# ---------------------------------------------------------------------------
# device-side hash-to-scalar packing (COMETBFT_TPU_DEVICE_HASH)
# ---------------------------------------------------------------------------
#
# The fused kernel (ops/ed25519.rlc_verify_hash_kernel) computes
# h = SHA512(R||A||M) mod L, zh = z*h, the per-pubkey aggregation AND
# the signed-window recode on device; the host's per-signature work
# shrinks to a structural parse plus one columnar message-pad.  No
# digest or scalar ever crosses back to the host.


def device_hash_enabled() -> bool:
    """Env knob for the fused device-hash verify path.  Read per call
    (cheap) so tests and operators can flip it without reloads."""
    return os.environ.get("COMETBFT_TPU_DEVICE_HASH", "0") == "1"


# Static SHA-512 block bucket for R||A||M messages.  Vote sign-bytes
# are ~110-130 bytes; +64 for R||A and +17 padding overhead needs 3
# blocks.  Messages that exceed the bucket raise ValueError from
# sha2._pad, which the dispatch layer turns into a host-hash fallback
# (flightrec EV_DEVICE_HASH_FALLBACK + DeviceMetrics counter).
DEVICE_HASH_MAX_BLOCKS = int(os.environ.get(
    "COMETBFT_TPU_DEVICE_HASH_BLOCKS", "3"))


def parse_batch(pubkeys: list[bytes],
                sigs: list[bytes]) -> list[tuple[bytes, int] | None]:
    """Structural parse ONLY (lengths, s < L) — the host side of the
    device-hash path, where parse_and_hash's hashlib loop never runs."""
    return [parse_signature(sig) if len(pk) == PUBKEY_SIZE else None
            for pk, sig in zip(pubkeys, sigs)]


def pack_rlc_device_hash(pubkeys: list[bytes], msgs: list[bytes],
                         sigs: list[bytes], parsed=None,
                         max_blocks: int | None = None):
    """Pack a batch for the fused hash-to-scalar RLC kernel.

    `parsed` is a parse_batch result ((r_enc, s) | None per entry — no
    h).  Host work per signature: a 128-bit z draw (one vectorized
    block), c += z*s mod L, and the R||A||M byte splice; hashing,
    per-pubkey zh aggregation and the A-side recode all move on-device.

    Returns the kernel's positional argument tuple
    (a_words (8,K), r_words (8,N), base_limbs (K,16), z_limbs (N,8),
    group_ids (N,), blocks_hi/lo (N,B,16), n_blocks (N,),
    r_mag/r_neg (26,N)), or None if any entry fails structural checks.
    Raises ValueError("message exceeds max_blocks") when a message
    outgrows the static block bucket — the caller's fallback trigger.
    """
    import secrets

    from ..ops import ed25519 as dev
    from ..ops import limbs as lb
    from ..ops import sha2

    global _NEG_B_ENC
    if _NEG_B_ENC is None:
        _NEG_B_ENC = _neg_b_encoding()

    n = len(pubkeys)
    if n == 0:
        return None
    if parsed is None:
        parsed = parse_batch(pubkeys, sigs)
    if max_blocks is None:
        max_blocks = DEVICE_HASH_MAX_BLOCKS

    zraw = np.frombuffer(secrets.token_bytes(16 * n),
                         dtype=np.uint8).reshape(n, 16).copy()
    zraw[:, 15] |= 0x80                    # pin the top bit, like pack_rlc

    if any(p is None for p in parsed):
        return None

    # callers that parsed ahead of time (crypto/batch._device_verify_hash,
    # crypto/mesh.split_rlc_verify_hash) pass placeholder sigs; the
    # 64-byte rows rebuild from parsed's (r_enc, s)
    if len(sigs[0]) != 64:
        sigs = [r_enc + s.to_bytes(32, "little") for r_enc, s in parsed]

    # fully vectorized from here: parse_batch guaranteed every sig is
    # 64 bytes and every key 32, so the whole batch flattens into two
    # matrices and the per-signature Python loop disappears.
    nbatch = dev.pad_width(n)
    filler = np.frombuffer(ref.point_compress(ref.B), dtype=np.uint32)
    sig_mat = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(n, 64)
    pk_mat = np.frombuffer(b"".join(pubkeys), dtype=np.uint8).reshape(n, 32)
    r_words = np.empty((nbatch, 8), dtype=np.uint32)
    r_words[:] = filler
    r_words[:n] = np.ascontiguousarray(sig_mat[:, :32]).view(np.uint32)

    # group ids without the per-signature dict walk: unique keys via a
    # byte-comparing sort, remapped to FIRST-APPEARANCE order so slot
    # assignment matches the host-hash packer exactly
    pk_void = pk_mat.view(np.dtype((np.void, 32))).ravel()
    _, first_idx, inv = np.unique(pk_void, return_index=True,
                                  return_inverse=True)
    n_keys = len(first_idx)
    remap = np.empty(n_keys, dtype=np.int32)
    remap[np.argsort(first_idx)] = np.arange(n_keys, dtype=np.int32)
    group_ids = np.zeros(nbatch, dtype=np.int32)
    group_ids[:n] = remap[inv] + 1

    # c = sum(z_i * s_i) mod L as one uint16-limb convolution: column
    # sums are bounded by 8n * 2^32, far under 2^64, so a single
    # big-int fold replaces n per-signature 384-bit modmuls
    s16 = np.ascontiguousarray(sig_mat[:, 32:]).view(np.uint16)
    z16 = zraw.view(np.uint16)
    cols = np.zeros(23, dtype=np.uint64)
    for j in range(8):
        cols[j:j + 16] += (z16[:, j:j + 1].astype(np.uint64)
                           * s16).sum(axis=0)
    c = sum(int(v) << (16 * k) for k, v in enumerate(cols)) % L

    # columnar R||A||M assembly straight into the padded block matrix:
    # the 64-byte prefix is two matrix copies, the message bytes one
    # reshape when lengths are uniform (the vote case), and
    # pad_sha512_matrix finishes 0x80/bit-length in place
    mlens = np.fromiter((len(m) for m in msgs), dtype=np.int64, count=n)
    lens = np.zeros(nbatch, dtype=np.int64)
    lens[:n] = 64 + mlens
    if int(lens.max()) + 1 + 16 > max_blocks * 128:
        raise ValueError("message exceeds max_blocks")
    mat = np.zeros((nbatch, max_blocks * 128), dtype=np.uint8)
    mat[:n, :32] = sig_mat[:, :32]
    mat[:n, 32:64] = pk_mat
    flat = np.frombuffer(b"".join(msgs), dtype=np.uint8)
    m0 = int(mlens[0])
    if np.all(mlens == m0):
        mat[:n, 64:64 + m0] = flat.reshape(n, m0)
    else:
        colix = np.arange(max_blocks * 128, dtype=np.int64)
        mask = (colix[None, :] >= 64) & (colix[None, :] < lens[:n, None])
        mat[:n][mask] = flat
    blocks_hi, blocks_lo, n_blocks = sha2.pad_sha512_matrix(mat, lens)
    n_blocks[n:] = 0                       # fillers: z = 0 keeps them inert

    kbatch = dev.pad_width(1 + n_keys)
    a_words = np.empty((kbatch, 8), dtype=np.uint32)
    a_words[:] = filler
    a_words[0] = np.frombuffer(_NEG_B_ENC, dtype=np.uint32)
    a_words[1:1 + n_keys] = np.ascontiguousarray(
        pk_mat[np.sort(first_idx)]).view(np.uint32)
    base_limbs = np.zeros((kbatch, 16), dtype=np.uint32)
    base_limbs[0] = lb.int_to_limbs(c, 16)

    z_limbs = np.zeros((nbatch, 8), dtype=np.uint32)
    z_limbs[:n] = zraw[:, 0::2].astype(np.uint32) | \
        (zraw[:, 1::2].astype(np.uint32) << 8)
    zbytes = np.zeros((n, _recode_nbytes(NDIG_128)), dtype=np.uint8)
    zbytes[:, :16] = zraw
    r_mag, r_neg = _recode_w5(zbytes, NDIG_128, nbatch)
    return (np.ascontiguousarray(a_words.T),
            np.ascontiguousarray(r_words.T),
            base_limbs, z_limbs, group_ids,
            blocks_hi, blocks_lo, n_blocks, r_mag, r_neg)


def pack_batch_device_hash(pubkeys: list[bytes], msgs: list[bytes],
                           sigs: list[bytes], batch_size: int,
                           parsed=None, max_blocks: int | None = None):
    """Per-signature packing with device-side hashing — the reject
    localization arm of the fused mode (digests stay on device even
    when a batch fails and individual verdicts are needed).

    Returns (a_words (8,B), r_words (8,B), s_limbs (16,B),
    blocks_hi/lo (B,Bk,16), n_blocks (B,), valid (B,)); raises
    ValueError on an oversized message like pack_rlc_device_hash.
    """
    from ..ops import limbs as lb
    from ..ops import sha2

    n = len(pubkeys)
    assert batch_size >= n
    if parsed is None:
        parsed = parse_batch(pubkeys, sigs)
    if max_blocks is None:
        max_blocks = DEVICE_HASH_MAX_BLOCKS
    valid = np.zeros(batch_size, dtype=bool)
    a_words = np.zeros((batch_size, 8), dtype=np.uint32)
    r_words = np.zeros((batch_size, 8), dtype=np.uint32)
    s_limbs = np.zeros((batch_size, 16), dtype=np.uint32)
    hash_msgs = []
    for i in range(n):
        if parsed[i] is None:
            hash_msgs.append(b"")
            continue
        r_enc, s = parsed[i]
        valid[i] = True
        a_words[i] = np.frombuffer(pubkeys[i], dtype=np.uint32)
        r_words[i] = np.frombuffer(r_enc, dtype=np.uint32)
        s_limbs[i] = lb.int_to_limbs(s, 16)
        hash_msgs.append(r_enc + pubkeys[i] + msgs[i])
    blocks_hi, blocks_lo, n_blocks = sha2.pad_sha512(
        hash_msgs + [b""] * (batch_size - n), max_blocks)
    n_blocks[~valid] = 0
    filler = np.frombuffer(ref.point_compress(ref.B), dtype=np.uint32)
    a_words[~valid] = filler
    r_words[~valid] = filler
    return (np.ascontiguousarray(a_words.T),
            np.ascontiguousarray(r_words.T),
            np.ascontiguousarray(s_limbs.T),
            blocks_hi, blocks_lo, n_blocks, valid)


def rlc_verify_hash_async(packed, device=None):
    """Fused-kernel dispatch without the host sync (see
    rlc_verify_async).  The A-table cache is not plumbed through this
    kernel yet: the fused program recodes its A scalars on device, and
    the cacheable part (decompression + table build) is a smaller
    fraction of its runtime than of the host-hash kernel's."""
    from ..ops import ed25519 as dev

    if device is not None:
        import jax

        packed = tuple(jax.device_put(np.asarray(x), device)
                       for x in packed)
    return dev.rlc_verify_hash_device(*packed)


def rlc_verify_hash(packed, device=None) -> bool:
    return bool(np.asarray(rlc_verify_hash_async(packed, device=device)))


# one cached A-table slot: 17 rows x 4 coords x 20 int32 limbs
BYTES_PER_A_SLOT = 17 * 4 * 20 * 4


class ATableCache:
    """Device cache of decompressed A-side window tables.

    A validator set's distinct pubkeys produce the same packed a_words
    every commit (pack_rlc's aggregation preserves first-seen order,
    which follows the address-sorted validator iteration), so the
    decompression + 17-row table build — the whole per-key cost of the
    A-side MSM — can live in HBM across dispatches.  The reference
    caches expanded pubkeys for the same access pattern
    (/root/reference/crypto/ed25519/ed25519.go:64-70); here the cached
    object is the device-resident table, so a 10k-header light-client
    sync pays the valset decompression once, not 10k times.

    Keyed by the raw a_words bytes; LRU-bounded primarily by a BYTE
    budget: one table is 17*4*20*4 = 5440 bytes per padded A slot, so
    a 10k-validator set pins ~56 MB of HBM — round 3's entry-count cap
    of 8 could silently hold ~0.45 GB.  The budget
    (COMETBFT_TPU_A_CACHE_BYTES, default 128 MiB) is accounted per
    admission and exported via DeviceMetrics; a generous entry cap
    remains as a secondary bound so a flood of tiny valsets cannot
    grow the dict without limit.  Thread-safe.
    """

    def __init__(self, capacity: int = 128, max_bytes: int | None = None):
        import collections

        self._cap = capacity
        self._max_bytes = (max_bytes if max_bytes is not None else
                           int(os.environ.get(
                               "COMETBFT_TPU_A_CACHE_BYTES",
                               str(128 << 20))))
        self._entries = collections.OrderedDict()   # key -> (entry, nbytes)
        self._bytes = 0
        self._seen: collections.OrderedDict = collections.OrderedDict()
        self._lock = lockrank.RankedLock("ed25519.atable")
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @property
    def bytes_resident(self) -> int:
        return self._bytes

    @staticmethod
    def _entry_bytes(entry) -> int:
        a_tab, _ = entry
        return int(a_tab.size) * a_tab.dtype.itemsize

    def _gauge_bytes(self, dm) -> None:
        if dm is not None:
            dm.a_table_cache_bytes.set(self._bytes)

    def get(self, a_words: np.ndarray, device=None):
        """(8, K) packed encodings -> (device table, device ok-flag).

        `device` places the built table on a specific mesh device (and
        keys the entry by it): each chip in a round-robin dispatch
        keeps its own resident copy of a hot valset's tables, so a
        window dispatched to chip i never pulls a table across ICI."""
        from ..libs import metrics as libmetrics

        dm = libmetrics.device_metrics()
        key = (a_words.tobytes(), device)
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self.hits += 1
                if dm is not None:
                    dm.a_table_cache_hits.inc()
                return self._entries[key][0]
        from ..ops import ed25519 as dev

        if device is not None:
            import jax

            a_words = jax.device_put(np.ascontiguousarray(a_words),
                                     device)
        entry = dev.build_a_tables_device(a_words)
        nbytes = self._entry_bytes(entry)
        with self._lock:
            self.misses += 1
            if dm is not None:
                dm.a_table_cache_misses.inc()
            if nbytes > self._max_bytes:
                # a table larger than the whole budget would evict
                # everything and then be evicted itself: serve it
                # un-admitted
                self._gauge_bytes(dm)
                return entry
            if key not in self._entries:
                # a concurrent miss may have admitted this key while we
                # built outside the lock: admitting again would count
                # nbytes twice against the budget forever
                self._entries[key] = (entry, nbytes)
                self._bytes += nbytes
                while (self._bytes > self._max_bytes
                       or len(self._entries) > self._cap):
                    _, (_, freed) = self._entries.popitem(last=False)
                    self._bytes -= freed
                    self.evictions += 1
            self._gauge_bytes(dm)
        return entry

    # Below this many A slots the cached kernel can't win: the saved
    # decompression/table work is proportional to K, while the split
    # into two dispatches (and, on cold caches, a fresh compile of the
    # cached-kernel shape) is constant.  Small-K batches — live
    # consensus vote flushes — stay on the fused kernel.
    MIN_K = int(os.environ.get("COMETBFT_TPU_A_CACHE_MIN_K", "64"))

    def get_if_worthwhile(self, a_words: np.ndarray, device=None):
        """Entry if cached; else None — and only SECOND sightings of a
        large-K key trigger a build.  One-shot batches (streaming vote
        flushes have nondeterministic signer subsets/order, so nearly
        every flush is a fresh key) must not thrash the LRU with ~MB
        device tables; a repeated large valset (light client windows,
        blocksync) shows up identically twice and earns its table."""
        import hashlib

        if a_words.shape[-1] < self.MIN_K:
            return None
        # a table the budget can never admit must stay on the fused
        # kernel: routing it through get() would rebuild the table on
        # EVERY sighting and still pay the split-dispatch overhead
        if a_words.shape[-1] * BYTES_PER_A_SLOT > self._max_bytes:
            return None
        key = (a_words.tobytes(), device)
        with self._lock:
            if key in self._entries:
                pass                       # hit: fall through to get()
            else:
                digest = (hashlib.sha256(key[0]).digest(), device)
                if digest not in self._seen:
                    self._seen[digest] = True
                    while len(self._seen) > 64:
                        self._seen.popitem(last=False)
                    return None            # first sighting: stay fused
        return self.get(a_words, device=device)


_A_TABLE_CACHE = ATableCache(
    capacity=int(os.environ.get("COMETBFT_TPU_A_CACHE_CAP", "8")))

USE_A_CACHE = os.environ.get("COMETBFT_TPU_A_CACHE", "1") == "1"


def rlc_verify_async(packed, use_cache: bool | None = None,
                     device=None):
    """rlc_verify without the host sync: returns the (device-resident)
    verdict bit array so a caller splitting one window across a mesh
    (crypto/mesh.split_rlc_verify) can dispatch every chip's RLC
    program before blocking on any of them.

    `device` commits the packed arrays (and the cached A-table, keyed
    per device) to that device before dispatch, which is how jit
    placement works: the program runs where its committed inputs live.
    None keeps the default-device behavior byte-identical."""
    from ..ops import ed25519 as dev

    a_words, r_words, a_mag, a_neg, r_mag, r_neg = packed
    a_np = np.asarray(a_words)
    entry = None
    if use_cache is True:
        entry = _A_TABLE_CACHE.get(a_np, device=device)
    elif use_cache is None and USE_A_CACHE:
        entry = _A_TABLE_CACHE.get_if_worthwhile(a_np, device=device)
    if device is not None:
        import jax

        r_words, a_mag, a_neg, r_mag, r_neg = (
            jax.device_put(np.asarray(x), device)
            for x in (r_words, a_mag, a_neg, r_mag, r_neg))
        if entry is None:
            a_words = jax.device_put(a_np, device)
    if entry is not None:
        a_tab, a_ok = entry
        return dev.rlc_verify_device_cached_a(
            a_tab, a_ok, r_words, a_mag, a_neg, r_mag, r_neg)
    return dev.rlc_verify_device(a_words, r_words,
                                 a_mag, a_neg, r_mag, r_neg)


def rlc_verify(packed, use_cache: bool | None = None,
               device=None) -> bool:
    """Dispatch a pack_rlc batch through the A-table cache when it
    pays.  use_cache=True forces the cached kernel (benchmarks /
    callers that KNOW the valset repeats), False forces the fused
    kernel, None (the default policy, COMETBFT_TPU_A_CACHE=0 disables)
    uses a cached table only for valsets seen before — one-shot
    batches keep the single fused dispatch.  Returns the verdict bit."""
    return bool(np.asarray(rlc_verify_async(
        packed, use_cache=use_cache, device=device)))
