"""Host-side Ed25519 API: keys, signing, and the TPU batch-verify bridge.

Mirrors the seam of the reference's crypto/ed25519 package
(/root/reference/crypto/ed25519/ed25519.go: PrivKey.Sign :45,
PubKey.VerifySignature :181, BatchVerifier :208) but the batch path packs
signatures into uint32 device arrays and runs one jitted TPU program
(ops/ed25519.verify_kernel) instead of per-signature CPU verification.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from . import ed25519_ref as ref
from .hash import sum_sha256

KEY_TYPE = "ed25519"
PUBKEY_SIZE = 32
PRIVKEY_SIZE = 64          # seed || pubkey, like the reference golang layout
SIGNATURE_SIZE = 64
L = ref.L


@dataclass(frozen=True)
class PubKey:
    data: bytes

    def __post_init__(self):
        if len(self.data) != PUBKEY_SIZE:
            raise ValueError("ed25519 pubkey must be 32 bytes")

    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def address(self) -> bytes:
        """First 20 bytes of SHA-256, the reference's address rule."""
        return sum_sha256(self.data)[:20]

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        return ref.verify(self.data, msg, sig)

    def __bytes__(self):
        return self.data


@dataclass(frozen=True)
class PrivKey:
    data: bytes              # seed(32) || pubkey(32)

    def __post_init__(self):
        if len(self.data) != PRIVKEY_SIZE:
            raise ValueError("ed25519 privkey must be 64 bytes")

    @staticmethod
    def generate(seed: bytes | None = None) -> "PrivKey":
        seed, pub = ref.keygen(seed)
        return PrivKey(seed + pub)

    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def pub_key(self) -> PubKey:
        return PubKey(self.data[32:])

    def sign(self, msg: bytes) -> bytes:
        # Prefer the constant-time OpenSSL path (the pure-Python reference
        # signer is variable-time and only safe for tests/tools).
        try:
            from cryptography.hazmat.primitives.asymmetric.ed25519 import (
                Ed25519PrivateKey)
            return Ed25519PrivateKey.from_private_bytes(
                self.data[:32]).sign(msg)
        except ImportError:  # pragma: no cover
            return ref.sign(self.data[:32], msg)


def parse_signature(sig: bytes) -> tuple[bytes, int] | None:
    """Split sig into (R_enc, s) and range-check s < L (RFC 8032 / ZIP-215)."""
    if len(sig) != SIGNATURE_SIZE:
        return None
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return None
    return sig[:32], s


def pack_batch(pubkeys: list[bytes], msgs: list[bytes], sigs: list[bytes],
               batch_size: int, max_blocks: int):
    """Pack a signature batch into device-ready numpy arrays.

    Entries that fail host-side structural checks (bad lengths, s >= L) get
    a pre-determined False verdict via the `valid` mask; their slots are
    filled with benign data so the kernel stays branch-free.
    Returns (a_words, r_words, s_limbs, msg_hi, msg_lo, n_blocks, valid).
    """
    from ..ops import limbs as lb
    from ..ops import sha2

    n = len(pubkeys)
    assert batch_size >= n
    valid = np.zeros(batch_size, dtype=bool)
    a_words = np.zeros((batch_size, 8), dtype=np.uint32)
    r_words = np.zeros((batch_size, 8), dtype=np.uint32)
    s_limbs = np.zeros((batch_size, 16), dtype=np.uint32)
    hash_msgs = []
    dummy = ref.point_compress(ref.B)
    for i in range(batch_size):
        if i >= n:
            hash_msgs.append(b"")
            continue
        pk, msg, sig = pubkeys[i], msgs[i], sigs[i]
        parsed = parse_signature(sig) if len(pk) == PUBKEY_SIZE else None
        if parsed is None:
            hash_msgs.append(b"")
            continue
        r_enc, s = parsed
        valid[i] = True
        a_words[i] = np.frombuffer(pk, dtype=np.uint32)
        r_words[i] = np.frombuffer(r_enc, dtype=np.uint32)
        s_limbs[i] = lb.int_to_limbs(s, 16)
        hash_msgs.append(r_enc + pk + msg)
    # benign filler so decompression of invalid slots still succeeds
    filler = np.frombuffer(dummy, dtype=np.uint32)
    a_words[~valid] = filler
    r_words[~valid] = filler
    msg_hi, msg_lo, n_blocks = sha2.pad_sha512(hash_msgs, max_blocks)
    return a_words, r_words, s_limbs, msg_hi, msg_lo, n_blocks, valid


_BLOCK_BUCKETS = (2, 4, 8, 16, 32, 64)


def max_blocks_for(msgs: list[bytes]) -> int:
    """SHA-512 block count for the longest R||A||M input, rounded up to a
    bucket so the jitted kernel compiles once per (batch, blocks) bucket
    rather than once per distinct message length."""
    longest = max((len(m) for m in msgs), default=0) + 64
    need = (longest + 1 + 16 + 127) // 128
    for b in _BLOCK_BUCKETS:
        if need <= b:
            return b
    return need
