"""tmhash equivalent: SHA-256 with the 20-byte truncated variant.

Reference: /root/reference/crypto/tmhash/hash.go (Sum, SumTruncated).
Host path uses hashlib; bulk device hashing lives in ops/sha2.py.
"""

import hashlib

TRUNCATED_SIZE = 20


def sum_sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sum_truncated(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()[:TRUNCATED_SIZE]
