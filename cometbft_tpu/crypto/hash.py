"""tmhash equivalent: SHA-256 with the 20-byte truncated variant.

Reference: /root/reference/crypto/tmhash/hash.go (Sum, SumTruncated).
Host path uses hashlib; bulk device hashing lives in ops/sha2.py.
"""

import hashlib
import os

TRUNCATED_SIZE = 20

# below this many messages the device round-trip costs more than hashlib
DEVICE_HASH_THRESHOLD = int(os.environ.get(
    "COMETBFT_TPU_HASH_THRESHOLD", "512"))


def sum_sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sum_truncated(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()[:TRUNCATED_SIZE]


def sum_sha256_many(msgs: list[bytes]) -> list[bytes]:
    """Batched SHA-256: device kernel for big batches, hashlib below the
    threshold (a 10k-validator set hash is ~10k leaf hashes in one
    launch; a 4-item header field hash is not worth a transfer)."""
    if len(msgs) < DEVICE_HASH_THRESHOLD:
        return [hashlib.sha256(m).digest() for m in msgs]
    import numpy as np
    from ..ops import sha2
    blocks, n_blocks = sha2.pad_sha256(msgs)
    digests = np.asarray(sha2.sha256_blocks(blocks, n_blocks))
    return [sha2.digest256_to_bytes(digests[i]) for i in range(len(msgs))]
