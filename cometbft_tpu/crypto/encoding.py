"""PubKey <-> proto encoding (crypto/encoding/codec.go analog).

The wire message is cometbft.crypto.v1.PublicKey, a oneof:
  bytes ed25519 = 1; bytes secp256k1 = 2; bytes bls12381 = 3;
(/root/reference/proto/cometbft/crypto/v1/keys.proto:13-18).
These bytes feed SimpleValidator hashing (types/validator.go:118-131),
so they are consensus-critical.
"""

from __future__ import annotations

from ..libs import protowire as pw

_FIELD_BY_TYPE = {"ed25519": 1, "secp256k1": 2, "bls12_381": 3}
_TYPE_BY_FIELD = {v: k for k, v in _FIELD_BY_TYPE.items()}


def pubkey_to_proto(pubkey) -> bytes:
    """Marshal a PubKey into PublicKey message bytes."""
    field = _FIELD_BY_TYPE.get(pubkey.type())
    if field is None:
        raise ValueError(f"unsupported pubkey type {pubkey.type()}")
    return pw.Writer().bytes_field(field, pubkey.bytes()).bytes()


def pubkey_from_proto(payload: bytes):
    """Unmarshal PublicKey message bytes into a PubKey object."""
    r = pw.Reader(payload)
    while not r.at_end():
        field, wire = r.read_tag()
        if wire == pw.BYTES and field in _TYPE_BY_FIELD:
            data = r.read_bytes()
            return make_pubkey(_TYPE_BY_FIELD[field], data)
        r.skip(wire)
    raise ValueError("empty PublicKey message")


def make_pubkey(key_type: str, data: bytes):
    if key_type == "ed25519":
        from . import ed25519
        return ed25519.PubKey(data)
    if key_type == "secp256k1":
        from . import secp256k1
        return secp256k1.PubKey(data)
    if key_type == "bls12_381":
        # gated like the reference build tag (bls12381.enabled());
        # constructing the key only needs the bytes — verification
        # raises if the native library is absent
        from . import bls12381
        return bls12381.PubKey(data)
    raise ValueError(f"unsupported pubkey type {key_type}")
