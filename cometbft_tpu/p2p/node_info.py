"""NodeInfo: identity + capability exchange at connection upgrade
(reference p2p/node_info.go)."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..libs import protowire as pw

MAX_NODE_INFO_SIZE = 10240


class NodeInfoError(Exception):
    pass


@dataclass
class ProtocolVersion:
    p2p: int = 9       # version/version.go P2PProtocol
    block: int = 11    # BlockProtocol
    app: int = 0

    def to_proto(self) -> bytes:
        return (pw.Writer().uvarint_field(1, self.p2p)
                .uvarint_field(2, self.block)
                .uvarint_field(3, self.app).bytes())

    @staticmethod
    def from_proto(p: bytes) -> "ProtocolVersion":
        r = pw.Reader(p)
        m = ProtocolVersion(0, 0, 0)
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1 and w == pw.VARINT:
                m.p2p = r.read_uvarint()
            elif f == 2 and w == pw.VARINT:
                m.block = r.read_uvarint()
            elif f == 3 and w == pw.VARINT:
                m.app = r.read_uvarint()
            else:
                r.skip(w)
        return m


@dataclass
class NodeInfo:
    """p2p.DefaultNodeInfo."""
    protocol_version: ProtocolVersion = field(
        default_factory=ProtocolVersion)
    node_id: str = ""
    listen_addr: str = ""
    network: str = ""          # chain id
    version: str = ""
    channels: bytes = b""
    moniker: str = ""
    # other: tx_index on/off, rpc address
    tx_index: str = "on"
    rpc_address: str = ""

    def validate_basic(self) -> None:
        if len(self.node_id) != 40:
            raise NodeInfoError(f"invalid node ID {self.node_id!r}")
        if len(self.channels) > 16:
            raise NodeInfoError("too many channels")
        if len(set(self.channels)) != len(self.channels):
            raise NodeInfoError("duplicate channel id")
        if len(self.moniker) > 255:
            raise NodeInfoError("moniker too long")

    def compatible_with(self, other: "NodeInfo") -> None:
        """node_info.go CompatibleWith: same block protocol + network,
        and at least one common channel."""
        if self.protocol_version.block != other.protocol_version.block:
            raise NodeInfoError(
                f"peer has different block protocol: "
                f"{other.protocol_version.block} vs "
                f"{self.protocol_version.block}")
        if self.network != other.network:
            raise NodeInfoError(
                f"peer is on network {other.network!r}, we are on "
                f"{self.network!r}")
        if self.channels and other.channels and not (
                set(self.channels) & set(other.channels)):
            raise NodeInfoError("no common channels")

    def to_proto(self) -> bytes:
        return (pw.Writer()
                .message_field(1, self.protocol_version.to_proto())
                .string_field(2, self.node_id)
                .string_field(3, self.listen_addr)
                .string_field(4, self.network)
                .string_field(5, self.version)
                .bytes_field(6, self.channels)
                .string_field(7, self.moniker)
                .string_field(8, self.tx_index)
                .string_field(9, self.rpc_address).bytes())

    @staticmethod
    def from_proto(p: bytes) -> "NodeInfo":
        r = pw.Reader(p)
        m = NodeInfo()
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1 and w == pw.BYTES:
                m.protocol_version = ProtocolVersion.from_proto(
                    r.read_bytes())
            elif f == 2 and w == pw.BYTES:
                m.node_id = r.read_string()
            elif f == 3 and w == pw.BYTES:
                m.listen_addr = r.read_string()
            elif f == 4 and w == pw.BYTES:
                m.network = r.read_string()
            elif f == 5 and w == pw.BYTES:
                m.version = r.read_string()
            elif f == 6 and w == pw.BYTES:
                m.channels = r.read_bytes()
            elif f == 7 and w == pw.BYTES:
                m.moniker = r.read_string()
            elif f == 8 and w == pw.BYTES:
                m.tx_index = r.read_string()
            elif f == 9 and w == pw.BYTES:
                m.rpc_address = r.read_string()
            else:
                r.skip(w)
        return m
