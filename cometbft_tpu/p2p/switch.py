"""Switch: reactor registry + peer lifecycle
(reference p2p/switch.go).

Reactors register channel descriptors; the switch upgrades inbound and
dialed connections into Peers, fans incoming packets out to the owning
reactor, broadcasts to all peers, evicts on error, and redials
persistent peers with exponential backoff.
"""

from __future__ import annotations

import random
import threading
import time

from ..libs import lockrank

from ..libs.service import BaseService
from .base_reactor import Envelope, Reactor
from .conn.connection import ChannelDescriptor, MConnection
from .node_info import NodeInfo
from .peer import Peer, PeerSet
from .transport import MultiplexTransport, parse_addr

MAX_NUM_INBOUND_PEERS = 40
MAX_NUM_OUTBOUND_PEERS = 10
RECONNECT_ATTEMPTS = 20
RECONNECT_BASE_WAIT = 1.0


class SwitchError(Exception):
    pass


class Switch(BaseService):
    def __init__(self, transport: MultiplexTransport,
                 listen_addr: str = ""):
        super().__init__("Switch")
        self.transport = transport
        self.listen_addr = listen_addr
        self.reactors: dict[str, Reactor] = {}
        self.channel_descs: list[ChannelDescriptor] = []
        self.reactors_by_ch: dict[int, Reactor] = {}
        self.peers = PeerSet()
        self.dialing: set[str] = set()
        # optional P2PMetrics (libs/metrics.py), assigned by the node
        self.metrics = None
        # optional conn wrapper applied to every peer connection before
        # the MConnection is built (latency emulation, fault injection)
        self.conn_wrap = None
        self.reconnecting: set[str] = set()
        self.persistent_peers: set[str] = set()  # addresses 'id@host:port'
        self._mtx = lockrank.RankedLock("p2p.switch")
        from concurrent.futures import ThreadPoolExecutor
        self._broadcast_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="sw-bcast")
        self.bound_addr: str | None = None
        self.max_inbound = MAX_NUM_INBOUND_PEERS
        self.max_outbound = MAX_NUM_OUTBOUND_PEERS

    # -- reactors ----------------------------------------------------------
    def add_reactor(self, name: str, reactor: Reactor) -> Reactor:
        """switch.go:165 AddReactor."""
        for desc in reactor.get_channels():
            if desc.id in self.reactors_by_ch:
                raise SwitchError(
                    f"channel {desc.id:#x} already registered")
            self.channel_descs.append(desc)
            self.reactors_by_ch[desc.id] = reactor
        self.reactors[name] = reactor
        reactor.set_switch(self)
        return reactor

    def reactor(self, name: str) -> Reactor | None:
        return self.reactors.get(name)

    # -- lifecycle ---------------------------------------------------------
    def on_start(self) -> None:
        for reactor in self.reactors.values():
            reactor.start()
        if self.listen_addr:
            self.bound_addr = self.transport.listen(
                self.listen_addr, self._accept_peer)

    def on_stop(self) -> None:
        self.transport.close()
        for peer in self.peers.list():
            self.stop_peer_gracefully(peer)
        for reactor in self.reactors.values():
            reactor.stop()
        self._broadcast_pool.shutdown(wait=False)

    # -- peer intake -------------------------------------------------------
    def _accept_peer(self, conn, node_info: NodeInfo) -> None:
        inbound = sum(1 for p in self.peers.list() if not p.outbound)
        if inbound >= self.max_inbound:
            conn.close()
            return
        self._add_peer_conn(conn, node_info, outbound=False,
                            socket_addr=getattr(conn, "remote_addr", ""))

    def dial_peer(self, addr: str, persistent: bool = False) -> Peer:
        """Dial 'id@host:port' and add the peer (switch.go DialPeer...)."""
        peer_id, _, _ = parse_addr(addr)
        with self._mtx:
            if peer_id and (self.peers.has(peer_id)
                            or peer_id in self.dialing):
                raise SwitchError(f"already connected/dialing {peer_id}")
            self.dialing.add(peer_id)
        try:
            conn, node_info = self.transport.dial(addr)
            if persistent:
                self.persistent_peers.add(addr)
            return self._add_peer_conn(conn, node_info, outbound=True,
                                       persistent=persistent,
                                       socket_addr=addr)
        finally:
            with self._mtx:
                self.dialing.discard(peer_id)

    def dial_peers_async(self, addrs: list[str],
                         persistent: bool = False) -> None:
        for addr in addrs:
            threading.Thread(
                target=self._dial_ignore_errors, args=(addr, persistent),
                daemon=True).start()

    def _dial_ignore_errors(self, addr: str, persistent: bool) -> None:
        try:
            self.dial_peer(addr, persistent)
        except Exception:
            if persistent:
                self._reconnect_to(addr)

    def _add_peer_conn(self, conn, node_info: NodeInfo, outbound: bool,
                       persistent: bool = False,
                       socket_addr: str = "") -> Peer:
        peer_ref: list = [None]

        def on_receive(ch_id: int, msg_bytes: bytes, tctx=None) -> None:
            reactor = self.reactors_by_ch.get(ch_id)
            if reactor is None:
                raise SwitchError(f"no reactor for channel {ch_id:#x}")
            reactor.receive(Envelope(src=peer_ref[0], message=msg_bytes,
                                     channel_id=ch_id, tctx=tctx))

        def on_error(e: Exception) -> None:
            if peer_ref[0] is not None:
                self.stop_peer_for_error(peer_ref[0], e)

        if self.conn_wrap is not None:
            conn = self.conn_wrap(conn)
        mconn = MConnection(conn, self.channel_descs, on_receive,
                            on_error)
        mconn.metrics = self.metrics
        peer = Peer(node_info, mconn, outbound, persistent, socket_addr)
        peer_ref[0] = peer

        # reserve the peer slot atomically BEFORE touching reactor
        # state: a simultaneous cross-dial must not clobber the live
        # peer's reactor state or leak its connection
        try:
            self.peers.add(peer)
        except ValueError as e:
            conn.close()
            raise SwitchError(str(e)) from e
        self._update_peer_gauge()
        try:
            for reactor in self.reactors.values():
                reactor.init_peer(peer)
            peer.start()
            for reactor in self.reactors.values():
                reactor.add_peer(peer)
        except Exception:
            self.peers.remove(peer)
            self._update_peer_gauge()
            conn.close()
            raise
        return peer

    def _update_peer_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.peers.set(self.peers.size())

    # -- peer removal ------------------------------------------------------
    def stop_peer_for_error(self, peer: Peer, reason) -> None:
        """switch.go:324."""
        if not self._remove_peer(peer, reason):
            return
        if peer.persistent and peer.socket_addr:
            threading.Thread(target=self._reconnect_to,
                             args=(peer.socket_addr,),
                             daemon=True).start()

    def stop_peer_gracefully(self, peer: Peer) -> None:
        self._remove_peer(peer, None)

    def _remove_peer(self, peer: Peer, reason) -> bool:
        if not self.peers.remove(peer):
            return False
        self._update_peer_gauge()
        peer.stop()
        for reactor in self.reactors.values():
            reactor.remove_peer(peer, reason)
        return True

    def _reconnect_to(self, addr: str) -> None:
        """Exponential backoff redial (switch.go:391)."""
        with self._mtx:
            if addr in self.reconnecting:
                return
            self.reconnecting.add(addr)
        try:
            for attempt in range(RECONNECT_ATTEMPTS):
                if not self.is_running():
                    return
                wait = RECONNECT_BASE_WAIT * (1.5 ** attempt) * \
                    (0.8 + 0.4 * random.random())
                time.sleep(min(wait, 30.0))
                try:
                    self.dial_peer(addr, persistent=True)
                    return
                except Exception:
                    continue
        finally:
            with self._mtx:
                self.reconnecting.discard(addr)

    # -- messaging ---------------------------------------------------------
    def broadcast(self, channel_id: int, msg_bytes: bytes) -> None:
        """Fan out to every peer (switch.go:271 Broadcast); returns
        immediately, sends run on a shared pool feeding the peers' send
        queues (not a thread per message)."""
        for peer in self.peers.list():
            self._broadcast_pool.submit(peer.send, channel_id, msg_bytes)

    def try_broadcast(self, channel_id: int, msg_bytes: bytes) -> None:
        for peer in self.peers.list():
            peer.try_send(channel_id, msg_bytes)

    def num_peers(self) -> dict:
        outbound = sum(1 for p in self.peers.list() if p.outbound)
        total = self.peers.size()
        return {"outbound": outbound, "inbound": total - outbound,
                "dialing": len(self.dialing)}
