"""FuzzedConnection: fault injection for peer links
(reference p2p/fuzz.go).

Wraps a connection (SecretConnection or anything with write/read/close)
and injects faults per the config:

- "delay": sleep up to max_delay before each write — models slow/
  congested links; the protocol must stay live.
- "drop": with probability p, swallow a write while reporting success —
  models packet loss past the transport's guarantees.  Because peer
  traffic is AEAD-framed, a dropped frame desyncs the receiver's nonce
  stream, which must surface as a clean SecretConnectionError eviction,
  never a hang or a crash.

The reference starts fuzzing after a delay (fuzz.go start), so
handshakes always complete; mirrored here.
"""

from __future__ import annotations

import queue
import random
import threading
import time

from ..libs import lockrank


class FuzzConfig:
    MODE_DELAY = "delay"
    MODE_DROP = "drop"

    def __init__(self, mode: str = MODE_DELAY, prob_drop: float = 0.1,
                 max_delay: float = 0.01, start_after: float = 0.0,
                 seed: int | None = None):
        self.mode = mode
        self.prob_drop = prob_drop
        self.max_delay = max_delay
        self.start_after = start_after
        self.seed = seed


class FuzzedConnection:
    def __init__(self, conn, config: FuzzConfig | None = None):
        self._conn = conn
        self.config = config or FuzzConfig()
        self._rand = random.Random(self.config.seed)
        self._start = time.monotonic() + self.config.start_after
        self._mtx = lockrank.RankedLock("p2p.fuzz")

    def _active(self) -> bool:
        return time.monotonic() >= self._start

    def _fuzz_write(self) -> bool:
        """Returns True if the write should be swallowed."""
        if not self._active():
            return False
        # draw the fault under the lock, sleep outside it: a delay
        # held under _mtx would serialize every other writer behind
        # this connection's fuzz draw (check_concurrency rule C3)
        delay = 0.0
        swallow = False
        with self._mtx:
            if self.config.mode == FuzzConfig.MODE_DELAY:
                delay = self._rand.random() * self.config.max_delay
            elif self.config.mode == FuzzConfig.MODE_DROP:
                swallow = self._rand.random() < self.config.prob_drop
        if delay > 0:
            time.sleep(delay)
        return swallow

    # -- conn interface ----------------------------------------------------

    def write(self, data: bytes) -> int:
        if self._fuzz_write():
            return len(data)          # swallowed: pretend success
        return self._conn.write(data)

    def read(self) -> bytes:
        return self._conn.read()

    def close(self) -> None:
        self._conn.close()

    def __getattr__(self, name):
        return getattr(self._conn, name)


class LatencyConnection:
    """WAN latency emulation: every frame is DELIVERED one-way-delay
    late, without throttling the sender (the reference injects per-zone
    latency with tc netem in its e2e containers,
    test/e2e/pkg/latency/; here the switch's conn_wrap seam applies the
    same shape to a subprocess testnet).

    Writes enqueue (due-time, frame); a pump thread releases them in
    order once due — so a burst of block parts stays a burst, merely
    shifted, unlike a sleep-in-write() model whose link would have a
    one-frame bandwidth-delay product.  A delivery failure is surfaced
    on the NEXT write, matching how a real socket reports asynchronous
    resets."""

    # bounded so a stalled link still exerts backpressure on the
    # sender (MConnection's flow control relies on write() blocking);
    # sized to keep a 100 ms pipe full at far more frames than the
    # send-rate limiter can produce
    MAX_QUEUED = 1024

    def __init__(self, conn, delay_s: float):
        self._conn = conn
        self._delay = delay_s
        self._q: queue.Queue = queue.Queue(maxsize=self.MAX_QUEUED)
        self._err: Exception | None = None
        threading.Thread(target=self._pump, daemon=True,
                         name="latency-pump").start()

    def _pump(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            due, data = item
            wait = due - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            try:
                self._conn.write(data)
            except Exception as e:          # surfaced on the next write
                self._err = e
                return

    def write(self, data: bytes) -> int:
        due = time.monotonic() + self._delay
        while True:
            if self._err is not None:   # incl. after the pump died: a
                raise self._err         # full queue must not deadlock
            try:
                self._q.put((due, data), timeout=1.0)
                return len(data)
            except queue.Full:
                continue

    def read(self) -> bytes:
        return self._conn.read()

    def close(self) -> None:
        self._err = self._err or OSError("connection closed")
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass                        # pump dies on the closed socket
        self._conn.close()

    def __getattr__(self, name):
        return getattr(self._conn, name)
