"""FuzzedConnection: fault injection for peer links
(reference p2p/fuzz.go).

Wraps a connection (SecretConnection or anything with write/read/close)
and injects faults per the config:

- "delay": sleep up to max_delay before each write — models slow/
  congested links; the protocol must stay live.
- "drop": with probability p, swallow a write while reporting success —
  models packet loss past the transport's guarantees.  Because peer
  traffic is AEAD-framed, a dropped frame desyncs the receiver's nonce
  stream, which must surface as a clean SecretConnectionError eviction,
  never a hang or a crash.

The reference starts fuzzing after a delay (fuzz.go start), so
handshakes always complete; mirrored here.
"""

from __future__ import annotations

import random
import threading
import time


class FuzzConfig:
    MODE_DELAY = "delay"
    MODE_DROP = "drop"

    def __init__(self, mode: str = MODE_DELAY, prob_drop: float = 0.1,
                 max_delay: float = 0.01, start_after: float = 0.0,
                 seed: int | None = None):
        self.mode = mode
        self.prob_drop = prob_drop
        self.max_delay = max_delay
        self.start_after = start_after
        self.seed = seed


class FuzzedConnection:
    def __init__(self, conn, config: FuzzConfig | None = None):
        self._conn = conn
        self.config = config or FuzzConfig()
        self._rand = random.Random(self.config.seed)
        self._start = time.monotonic() + self.config.start_after
        self._mtx = threading.Lock()

    def _active(self) -> bool:
        return time.monotonic() >= self._start

    def _fuzz_write(self) -> bool:
        """Returns True if the write should be swallowed."""
        if not self._active():
            return False
        with self._mtx:
            if self.config.mode == FuzzConfig.MODE_DELAY:
                delay = self._rand.random() * self.config.max_delay
                if delay > 0:
                    time.sleep(delay)
                return False
            if self.config.mode == FuzzConfig.MODE_DROP:
                return self._rand.random() < self.config.prob_drop
        return False

    # -- conn interface ----------------------------------------------------

    def write(self, data: bytes) -> int:
        if self._fuzz_write():
            return len(data)          # swallowed: pretend success
        return self._conn.write(data)

    def read(self) -> bytes:
        return self._conn.read()

    def close(self) -> None:
        self._conn.close()

    def __getattr__(self, name):
        return getattr(self._conn, name)
