"""MConnection: per-peer multiplexed connection
(reference p2p/conn/connection.go).

N priority channels share one encrypted stream. Messages are split into
<=1024-byte packets; the send routine repeatedly picks the channel with
the lowest recently-sent/priority ratio (connection.go sendPacketMsg),
batching packets for up to the 10ms flush throttle. Ping/pong probes
detect dead peers; send and receive are rate-limited via flowrate
monitors.
"""

from __future__ import annotations

import queue
import struct
import threading
import time

from ...libs import protowire as pw
from ...libs.flowrate import Monitor
from ...libs.service import BaseService

MAX_PACKET_MSG_PAYLOAD_SIZE = 1024
FLUSH_THROTTLE = 0.01          # 10ms (connection.go:38)
PING_INTERVAL = 60.0
PONG_TIMEOUT = 45.0
DEFAULT_SEND_RATE = 5 * 1024 * 1024  # 5 MB/s (config.go)
DEFAULT_RECV_RATE = 5 * 1024 * 1024
DEFAULT_SEND_QUEUE_CAPACITY = 1
DEFAULT_RECV_MESSAGE_CAPACITY = 22 * 1024 * 1024


class MConnectionError(Exception):
    pass


# -- packet wire format (conn.proto Packet oneof) ---------------------------

def _pack_ping() -> bytes:
    return pw.Writer().message_field(1, b"").bytes()


def _pack_pong() -> bytes:
    return pw.Writer().message_field(2, b"").bytes()


def _pack_msg(channel_id: int, eof: bool, data: bytes) -> bytes:
    inner = (pw.Writer().uvarint_field(1, channel_id)
             .bool_field(2, eof).bytes_field(3, data).bytes())
    return pw.Writer().message_field(3, inner).bytes()


def _pack_ctx(channel_id: int, ctx) -> bytes:
    """Oneof field 4 (this repo's extension): a trace context
    (libs/tracetl.py (origin, height, round, seq)) for the NEXT
    msg-EOF on `channel_id`.  Real-TCP conns cannot ship the per-frame
    context list the simnet transport carries out-of-band, so the
    context rides the wire as its own tiny packet immediately ahead of
    the message-EOF packet it describes — which is what makes
    cross-PROCESS flow edges and NTP-style clock-offset solving
    (fleetobs/clocksync.py) possible on real testnets."""
    origin, height, round_, seq = ctx
    inner = (pw.Writer().uvarint_field(1, channel_id)
             .bytes_field(2, str(origin).encode())
             .uvarint_field(3, int(height)).uvarint_field(4, int(round_))
             .uvarint_field(5, int(seq)).bytes())
    return pw.Writer().message_field(4, inner).bytes()


def _unpack_packet(payload: bytes):
    """-> ('ping'|'pong'|'msg', channel_id, eof, data)
    or ('ctx', channel_id, False, (origin, height, round, seq))."""
    r = pw.Reader(payload)
    while not r.at_end():
        f, w = r.read_tag()
        if w != pw.BYTES:
            r.skip(w)
            continue
        body = r.read_bytes()
        if f == 1:
            return ("ping", 0, False, b"")
        if f == 2:
            return ("pong", 0, False, b"")
        if f == 3:
            rr = pw.Reader(body)
            ch, eof, data = 0, False, b""
            while not rr.at_end():
                ff, ww = rr.read_tag()
                if ff == 1 and ww == pw.VARINT:
                    ch = rr.read_uvarint()
                elif ff == 2 and ww == pw.VARINT:
                    eof = bool(rr.read_uvarint())
                elif ff == 3 and ww == pw.BYTES:
                    data = rr.read_bytes()
                else:
                    rr.skip(ww)
            return ("msg", ch, eof, data)
        if f == 4:
            rr = pw.Reader(body)
            ch, origin, height, round_, seq = 0, "", 0, 0, 0
            while not rr.at_end():
                ff, ww = rr.read_tag()
                if ff == 1 and ww == pw.VARINT:
                    ch = rr.read_uvarint()
                elif ff == 2 and ww == pw.BYTES:
                    origin = rr.read_bytes().decode("utf-8", "replace")
                elif ff == 3 and ww == pw.VARINT:
                    height = rr.read_uvarint()
                elif ff == 4 and ww == pw.VARINT:
                    round_ = rr.read_uvarint()
                elif ff == 5 and ww == pw.VARINT:
                    seq = rr.read_uvarint()
                else:
                    rr.skip(ww)
            return ("ctx", ch, False, (origin, height, round_, seq))
        r.skip(w)
    raise MConnectionError("empty packet")


class ChannelDescriptor:
    """connection.go:748 ChannelDescriptor."""

    def __init__(self, channel_id: int, priority: int = 1,
                 send_queue_capacity: int = DEFAULT_SEND_QUEUE_CAPACITY,
                 recv_message_capacity: int = DEFAULT_RECV_MESSAGE_CAPACITY,
                 recv_buffer_capacity: int = 4096):
        self.id = channel_id
        self.priority = max(priority, 1)
        self.send_queue_capacity = send_queue_capacity
        self.recv_message_capacity = recv_message_capacity
        self.recv_buffer_capacity = recv_buffer_capacity


class _Channel:
    """connection.go channel: send queue + recv reassembly buffer."""

    def __init__(self, desc: ChannelDescriptor):
        self.desc = desc
        # queue of (msg_bytes, trace_ctx_or_None): the context rides
        # next to the message through packetization so the EOF packet
        # of THIS message — and nothing else — carries it on the wire
        self.send_queue: queue.Queue[tuple] = queue.Queue(
            desc.send_queue_capacity)
        self.sending: bytes | None = None
        self.sending_ctx = None
        self.sent_pos = 0
        self.recently_sent = 0       # exponentially decayed
        self.recv_buf = b""

    def is_send_pending(self) -> bool:
        return self.sending is not None or not self.send_queue.empty()

    def next_packet(self) -> tuple:
        """Pop the next <=1024-byte packet of the in-flight message;
        -> (packet, eof, trace_ctx) — ctx is meaningful only at eof."""
        if self.sending is None:
            self.sending, self.sending_ctx = self.send_queue.get_nowait()
            self.sent_pos = 0
        chunk = self.sending[self.sent_pos:
                             self.sent_pos + MAX_PACKET_MSG_PAYLOAD_SIZE]
        self.sent_pos += len(chunk)
        eof = self.sent_pos >= len(self.sending)
        pkt = _pack_msg(self.desc.id, eof, chunk)
        ctx = None
        if eof:
            ctx = self.sending_ctx
            self.sending = None
            self.sending_ctx = None
            self.sent_pos = 0
        self.recently_sent += len(pkt)
        return pkt, eof, ctx

    def recv_packet(self, eof: bool, data: bytes) -> bytes | None:
        """Append a packet; return the whole message when eof."""
        if len(self.recv_buf) + len(data) > \
                self.desc.recv_message_capacity:
            raise MConnectionError(
                f"recv msg exceeds capacity on channel {self.desc.id}")
        self.recv_buf += data
        if eof:
            msg, self.recv_buf = self.recv_buf, b""
            return msg
        return None


class MConnection(BaseService):
    def __init__(self, conn, channel_descs, on_receive, on_error,
                 send_rate: int = DEFAULT_SEND_RATE,
                 recv_rate: int = DEFAULT_RECV_RATE,
                 ping_interval: float = PING_INTERVAL,
                 pong_timeout: float = PONG_TIMEOUT,
                 flush_throttle: float = FLUSH_THROTTLE):
        """conn: a SecretConnection-like object (write/read/close);
        on_receive(channel_id, msg_bytes[, tctx]); on_error(exc)."""
        super().__init__("MConnection")
        self._conn = conn
        # trace-context carry (libs/tracetl.py): conns that can ship a
        # per-message context list with each frame (the simnet conn)
        # expose write_with_ctx/pop_recv_ctx; everything else (real
        # TCP + SecretConnection, chaos wrappers) degrades to plain
        # writes and contexts simply do not travel
        self._write_with_ctx = getattr(conn, "write_with_ctx", None)
        self._pop_recv_ctx = getattr(conn, "pop_recv_ctx", None)
        try:
            import inspect
            params = inspect.signature(on_receive).parameters
            self._recv_takes_ctx = len(params) >= 3 or any(
                p.kind == p.VAR_POSITIONAL for p in params.values())
        except (TypeError, ValueError):
            self._recv_takes_ctx = False
        # optional P2PMetrics (libs/metrics.py), assigned by the switch:
        # per-channel framed-byte counters at the wire seam
        self.metrics = None
        self._channels: dict[int, _Channel] = {
            d.id: _Channel(d) for d in channel_descs}
        self._on_receive = on_receive
        self._on_error = on_error
        self._send_rate = send_rate
        self._recv_rate = recv_rate
        self._ping_interval = ping_interval
        self._pong_timeout = pong_timeout
        self._flush_throttle = flush_throttle
        self._send_monitor = Monitor()
        self._recv_monitor = Monitor()
        # per-channel pending recv context from in-band ctx packets
        # (real-TCP carry); only the recv routine's thread touches it
        self._recv_pending_ctx: dict = {}
        self._send_signal = threading.Event()
        self._pong_pending = threading.Event()
        self._pong_deadline: float | None = None
        self._last_ping = time.monotonic()
        self._threads: list[threading.Thread] = []

    def on_start(self) -> None:
        for target, name in ((self._send_routine, "mconn-send"),
                             (self._recv_routine, "mconn-recv")):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def on_stop(self) -> None:
        self._send_signal.set()
        self._conn.close()

    # -- sending -----------------------------------------------------------
    def send(self, channel_id: int, msg_bytes: bytes,
             timeout: float = 10.0, tctx=None) -> bool:
        """Queue a message; False if the channel queue stays full
        (connection.go Send).  `tctx` is an optional trace context
        delivered to the remote reactor with the message."""
        if not self.is_running():
            return False
        ch = self._channels.get(channel_id)
        if ch is None:
            return False
        try:
            ch.send_queue.put((msg_bytes, tctx), timeout=timeout)
        except queue.Full:
            return False
        self._send_signal.set()
        return True

    def try_send(self, channel_id: int, msg_bytes: bytes,
                 tctx=None) -> bool:
        if not self.is_running():
            return False
        ch = self._channels.get(channel_id)
        if ch is None:
            return False
        try:
            ch.send_queue.put_nowait((msg_bytes, tctx))
        except queue.Full:
            return False
        self._send_signal.set()
        return True

    def _select_channel(self) -> _Channel | None:
        """Least ratio of recently_sent/priority wins
        (connection.go sendPacketMsg)."""
        best, best_ratio = None, None
        for ch in self._channels.values():
            if not ch.is_send_pending():
                continue
            ratio = ch.recently_sent / ch.desc.priority
            if best_ratio is None or ratio < best_ratio:
                best, best_ratio = ch, ratio
        return best

    def _send_routine(self) -> None:
        try:
            while self.is_running():
                fired = self._send_signal.wait(
                    timeout=self._ping_interval / 10)
                self._send_signal.clear()
                if not self.is_running():
                    return

                # ping if due; length-prefixed like every packet (the
                # recv routine frames the stream on 4-byte prefixes, so
                # a bare ping would desync everything after it)
                now = time.monotonic()
                if now - self._last_ping >= self._ping_interval:
                    pkt = _pack_ping()
                    self._conn.write(struct.pack(">I", len(pkt)) + pkt)
                    self._last_ping = now
                    self._pong_deadline = now + self._pong_timeout
                if self._pong_pending.is_set():
                    self._pong_pending.clear()
                    pkt = _pack_pong()
                    self._conn.write(struct.pack(">I", len(pkt)) + pkt)
                if self._pong_deadline is not None and \
                        now > self._pong_deadline:
                    raise MConnectionError("pong timeout")

                # drain packets, decaying counters; batch <= throttle
                deadline = time.monotonic() + self._flush_throttle
                batch = []
                batch_ctxs = []          # one entry per msg-EOF packet
                batch_bytes = 0
                rate_limited = False
                while True:
                    allowed = self._send_monitor.limit(
                        MAX_PACKET_MSG_PAYLOAD_SIZE + 64,
                        self._send_rate, block=False)
                    if allowed == 0:
                        rate_limited = True
                        break
                    ch = self._select_channel()
                    if ch is None:
                        break
                    pkt, eof, ctx = ch.next_packet()
                    if eof and ctx is not None \
                            and self._write_with_ctx is None:
                        # real TCP: the context travels in-band as its
                        # own packet just ahead of the EOF it describes
                        cpkt = _pack_ctx(ch.desc.id, ctx)
                        batch.append(cpkt)
                        batch_bytes += len(cpkt)
                        self._send_monitor.update(len(cpkt))
                    batch.append(pkt)
                    if eof:
                        batch_ctxs.append(ctx)
                    batch_bytes += len(pkt)
                    self._send_monitor.update(len(pkt))
                    if self.metrics is not None:
                        # framed length: prefix + packet, the bytes the
                        # wire actually carries for this channel
                        self.metrics.message_send_bytes_total.labels(
                            "%#x" % ch.desc.id).add(4 + len(pkt))
                    if time.monotonic() >= deadline or \
                            batch_bytes > 64 * 1024:
                        self._flush_batch(batch, batch_ctxs)
                        batch, batch_ctxs, batch_bytes = [], [], 0
                        deadline = time.monotonic() + self._flush_throttle
                if batch:
                    self._flush_batch(batch, batch_ctxs)
                # decay sent counters (connection.go: 0.8 every 2s; we
                # decay proportionally per wakeup)
                for ch in self._channels.values():
                    ch.recently_sent = int(ch.recently_sent * 0.95)
                if any(c.is_send_pending()
                       for c in self._channels.values()):
                    if rate_limited:
                        # wait for bucket refill instead of busy-spinning
                        time.sleep(0.002)
                    self._send_signal.set()
        except Exception as e:
            self._stop_for_error(e)

    def _flush_batch(self, batch: list, ctxs: list) -> None:
        """Write one frame of complete packets.  A ctx-capable conn
        gets the per-EOF context list WITH the frame (Nones included:
        the receiver pops exactly one entry per completed message, so
        the list must stay aligned even when most sends carry no ctx)."""
        data = b"".join(struct.pack(">I", len(p)) + p for p in batch)
        w = self._write_with_ctx
        if w is not None:
            w(data, ctxs)
        else:
            self._conn.write(data)

    # -- receiving ---------------------------------------------------------
    def _recv_routine(self) -> None:
        buf = b""
        try:
            while self.is_running():
                data = self._conn.read()
                if data == b"":
                    raise MConnectionError("connection closed by peer")
                self._recv_monitor.update(len(data))
                self._recv_monitor.limit(len(data), self._recv_rate,
                                         block=True)
                buf += data
                while len(buf) >= 4:
                    (plen,) = struct.unpack_from(">I", buf)
                    if plen > MAX_PACKET_MSG_PAYLOAD_SIZE + 1024:
                        raise MConnectionError("oversized packet")
                    if len(buf) < 4 + plen:
                        break
                    payload, buf = buf[4:4 + plen], buf[4 + plen:]
                    self._handle_packet(payload)
        except Exception as e:
            self._stop_for_error(e)

    def _handle_packet(self, payload: bytes) -> None:
        kind, ch_id, eof, data = _unpack_packet(payload)
        if kind == "ping":
            self._pong_pending.set()
            self._send_signal.set()
            return
        if kind == "pong":
            self._pong_deadline = None
            return
        if kind == "ctx":
            # in-band trace context: applies to this channel's next
            # message EOF (the sender emits it immediately ahead)
            self._recv_pending_ctx[ch_id] = data
            return
        ch = self._channels.get(ch_id)
        if ch is None:
            raise MConnectionError(f"unknown channel {ch_id}")
        if self.metrics is not None:
            self.metrics.message_receive_bytes_total.labels(
                "%#x" % ch_id).add(4 + len(payload))
        msg = ch.recv_packet(eof, data)
        if msg is not None:
            pop = self._pop_recv_ctx
            if pop is not None:
                tctx = pop()
            else:
                tctx = self._recv_pending_ctx.pop(ch_id, None)
            if self._recv_takes_ctx:
                self._on_receive(ch_id, msg, tctx)
            else:
                self._on_receive(ch_id, msg)

    def _stop_for_error(self, e: Exception) -> None:
        if self.is_running():
            self.stop()
            if self._on_error is not None:
                self._on_error(e)

    def status(self) -> dict:
        return {
            "send": self._send_monitor.status(),
            "recv": self._recv_monitor.status(),
            "channels": {
                ch_id: {"recently_sent": ch.recently_sent}
                for ch_id, ch in self._channels.items()},
        }
