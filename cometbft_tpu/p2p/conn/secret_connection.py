"""Authenticated encryption for all peer traffic
(reference p2p/conn/secret_connection.go).

Station-to-Station over TCP: ephemeral X25519 ECDH -> HKDF-SHA256 ->
two ChaCha20-Poly1305 AEADs (one per direction, little-endian counter
nonces) -> Ed25519 signature over the transcript challenge proving the
long-term identity. Frames are fixed 1024-byte chunks (length-prefixed
inside), each sealed with a 16-byte MAC.

The transcript binding uses HKDF over the sorted ephemeral pubkeys
(the reference uses a Merlin/STROBE transcript; this framework's nodes
only talk to each other, so the binding construction — not its exact
bytes — is what matters; cited for parity, not wire-compat).
"""

from __future__ import annotations

import struct

from ...libs import lockrank

try:
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey, X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import (
        ChaCha20Poly1305,
    )
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover - env-dependent
    # without the cryptography wheel make() runs on the pure-Python
    # RFC 7748/8439 implementations in crypto/aead.py — same wire
    # bytes, just slower (fine for loopback testnets)
    HAVE_CRYPTOGRAPHY = False

from ...crypto import aead as _py_aead
from ...crypto import ed25519

DATA_LEN_SIZE = 4
DATA_MAX_SIZE = 1024
TOTAL_FRAME_SIZE = DATA_MAX_SIZE + DATA_LEN_SIZE
AEAD_TAG_SIZE = 16
SEALED_FRAME_SIZE = TOTAL_FRAME_SIZE + AEAD_TAG_SIZE

CHALLENGE_INFO = b"TPU_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN"


class SecretConnectionError(Exception):
    pass


def _hkdf_sha256(ikm: bytes, salt: bytes | None, info: bytes,
                 length: int) -> bytes:
    if HAVE_CRYPTOGRAPHY:
        return HKDF(algorithm=hashes.SHA256(), length=length,
                    salt=salt, info=info).derive(ikm)
    # stdlib RFC 5869 (extract-then-expand over HMAC-SHA256)
    import hashlib
    import hmac
    prk = hmac.new(salt if salt else b"\x00" * 32, ikm,
                   hashlib.sha256).digest()
    okm, t, i = b"", b"", 1
    while len(okm) < length:
        t = hmac.new(prk, t + info + bytes([i]),
                     hashlib.sha256).digest()
        okm += t
        i += 1
    return okm[:length]


def derive_secrets(shared: bytes, salt: bytes | None, we_are_lo: bool,
                   info: bytes = CHALLENGE_INFO
                   ) -> tuple[bytes, bytes, bytes]:
    """HKDF-SHA256 -> (recv_key, send_key, challenge).

    Split rule matches the reference's deriveSecrets
    (secret_connection.go + TestDeriveSecretsAndChallengeGolden): the
    lo ("least") side receives with okm[0:32] and sends with
    okm[32:64]; the hi side swaps them; okm[64:96] is the transcript
    challenge both sides sign.  Pinned against independent RFC-5869
    vectors in tests/fixtures/secret_connection_kdf.json."""
    okm = _hkdf_sha256(shared, salt, info, 96)
    if we_are_lo:
        recv_key, send_key = okm[0:32], okm[32:64]
    else:
        send_key, recv_key = okm[0:32], okm[32:64]
    return recv_key, send_key, okm[64:96]


class _NonceCounter:
    """96-bit nonce: 4 zero bytes + 64-bit little-endian counter
    (secret_connection.go incrNonce)."""

    def __init__(self):
        self.counter = 0

    def next(self) -> bytes:
        n = struct.pack("<4xQ", self.counter)
        self.counter += 1
        if self.counter >= 1 << 64:
            raise SecretConnectionError("nonce wrapped")
        return n


class SecretConnection:
    """Wrap a socket-like object (sendall/recv/close) with an
    authenticated encrypted stream."""

    def __init__(self, sock, recv_aead, send_aead, remote_pubkey):
        self._sock = sock
        self._recv_aead = recv_aead
        self._send_aead = send_aead
        self._recv_nonce = _NonceCounter()
        self._send_nonce = _NonceCounter()
        self._recv_buf = b""
        self._recv_frame_buf = b""
        self._send_mtx = lockrank.RankedLock("p2p.conn.send")
        self._recv_mtx = lockrank.RankedLock("p2p.conn.recv")
        self.remote_pubkey = remote_pubkey

    # -- handshake ---------------------------------------------------------
    @staticmethod
    def make(sock, priv_key) -> "SecretConnection":
        """Mutual-auth handshake (secret_connection.go
        MakeSecretConnection). priv_key: our long-term Ed25519 key."""
        if HAVE_CRYPTOGRAPHY:
            eph_priv = X25519PrivateKey.generate()
            eph_pub = eph_priv.public_key().public_bytes_raw()
        else:
            import os
            eph_priv = os.urandom(32)
            eph_pub = _py_aead.x25519_base(eph_priv)

        # 1. exchange ephemerals (plaintext)
        sock.sendall(eph_pub)
        remote_eph = _read_exact(sock, 32)

        # sort to decide directional keys (lo side = "first")
        we_are_lo = eph_pub < remote_eph
        lo, hi = sorted((eph_pub, remote_eph))

        if HAVE_CRYPTOGRAPHY:
            shared = eph_priv.exchange(
                X25519PublicKey.from_public_bytes(remote_eph))
        else:
            shared = _py_aead.x25519(eph_priv, remote_eph)

        # 2. derive: 2 x 32-byte keys + 32-byte challenge, transcript-
        # bound to both ephemerals via the HKDF salt
        recv_key, send_key, challenge = derive_secrets(
            shared, lo + hi, we_are_lo)

        aead_cls = (ChaCha20Poly1305 if HAVE_CRYPTOGRAPHY
                    else _py_aead.ChaCha20Poly1305)
        conn = SecretConnection(sock, aead_cls(recv_key),
                                aead_cls(send_key), None)

        # 3. exchange long-term identity + signature over the challenge
        # (over the now-encrypted channel)
        local_pub = priv_key.pub_key().bytes()
        sig = priv_key.sign(challenge)
        conn.write(local_pub + sig)

        auth = b""
        while len(auth) < 96:
            chunk = conn.read()
            if not chunk:
                raise SecretConnectionError("peer closed during handshake")
            auth += chunk
        remote_pub_bytes, remote_sig = auth[:32], auth[32:96]
        remote_pub = ed25519.PubKey(remote_pub_bytes)
        if not remote_pub.verify_signature(challenge, remote_sig):
            raise SecretConnectionError("challenge signature invalid")
        conn.remote_pubkey = remote_pub
        return conn

    # -- framed IO ---------------------------------------------------------
    def write(self, data: bytes) -> int:
        """Encrypt+send data in sealed 1024-byte frames."""
        n = 0
        with self._send_mtx:
            view = memoryview(data)
            while len(view) > 0:
                chunk = view[:DATA_MAX_SIZE]
                frame = struct.pack("<I", len(chunk)) + bytes(chunk)
                frame += b"\x00" * (TOTAL_FRAME_SIZE - len(frame))
                sealed = self._send_aead.encrypt(
                    self._send_nonce.next(), frame, None)
                self._sock.sendall(sealed)
                n += len(chunk)
                view = view[len(chunk):]
        return n

    def remote_host(self) -> str:
        """Observed IP of the other side (for PEX address learning)."""
        try:
            return self._sock.getpeername()[0]
        except OSError:
            return ""

    def read(self) -> bytes:
        """One decrypted frame's payload (empty bytes = EOF)."""
        with self._recv_mtx:
            sealed = _read_exact(self._sock, SEALED_FRAME_SIZE,
                                 allow_eof=True)
            if sealed is None:
                return b""
            try:
                frame = self._recv_aead.decrypt(
                    self._recv_nonce.next(), sealed, None)
            except Exception as e:
                raise SecretConnectionError(
                    f"frame decryption failed: {e}") from e
            (length,) = struct.unpack_from("<I", frame)
            if length > DATA_MAX_SIZE:
                raise SecretConnectionError("invalid frame length")
            return frame[DATA_LEN_SIZE:DATA_LEN_SIZE + length]

    def close(self) -> None:
        # shutdown() first: close() alone doesn't send FIN (or wake a
        # blocked recv) while another thread holds the fd in recv()
        import socket as _socket
        try:
            self._sock.shutdown(_socket.SHUT_RDWR)
        except (OSError, AttributeError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def _read_exact(sock, n: int, allow_eof: bool = False):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if allow_eof and not buf:
                return None
            raise SecretConnectionError("unexpected EOF")
        buf += chunk
    return buf
