"""Node identity (reference p2p/key.go).

ID = lowercase hex of the Ed25519 pubkey address; persisted as JSON.
"""

from __future__ import annotations

import json
import os

from ..crypto import ed25519


def node_id_from_pubkey(pub_key) -> str:
    """p2p.PubKeyToID."""
    return pub_key.address().hex()


class NodeKey:
    def __init__(self, priv_key):
        self.priv_key = priv_key

    @property
    def id(self) -> str:
        return node_id_from_pubkey(self.priv_key.pub_key())

    def pub_key(self):
        return self.priv_key.pub_key()

    def sign(self, msg: bytes) -> bytes:
        return self.priv_key.sign(msg)

    def save_as(self, path: str) -> None:
        import base64
        payload = json.dumps({
            "priv_key": {"type": "tendermint/PrivKeyEd25519",
                         "value": base64.b64encode(
                             self.priv_key.bytes()).decode()},
        }, indent=2)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(payload)

    @staticmethod
    def load(path: str) -> "NodeKey":
        import base64
        with open(path) as f:
            obj = json.load(f)
        priv = ed25519.PrivKey(
            base64.b64decode(obj["priv_key"]["value"]))
        return NodeKey(priv)

    @staticmethod
    def load_or_gen(path: str) -> "NodeKey":
        """p2p.LoadOrGenNodeKey."""
        if os.path.exists(path):
            return NodeKey.load(path)
        nk = NodeKey(ed25519.PrivKey.generate())
        nk.save_as(path)
        return nk
