"""PEX reactor: peer-exchange gossip + outbound peer maintenance
(reference p2p/pex/pex_reactor.go).

Channel 0x00.  Wire: Message{ oneof: PexRequest=1 | PexAddrs=2 } with
NetAddress{id=1, ip=2, port=3} (proto cometbft/p2p/v1/pex.proto).

An ensure-peers routine tops up outbound connections from the address
book (biased toward new addresses when few peers are connected) and
falls back to seeds when the book is empty.  Request throttling: a peer
may only be asked / may only ask once per interval; unsolicited
PexAddrs are a protocol offense.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

from ...libs import protowire as pw
from ..base_reactor import Envelope, Reactor
from ..conn.connection import ChannelDescriptor
from .addrbook import AddrBook, NetAddress

PEX_CHANNEL = 0x00
DEFAULT_ENSURE_PEERS_PERIOD = 30.0
MIN_RECEIVE_REQUEST_INTERVAL = 1.0   # tests shrink this
MAX_MSG_SIZE = 64 * 1024

_log = logging.getLogger(__name__)


@dataclass
class PexRequest:
    TAG = 1

    def to_proto(self) -> bytes:
        return b""

    @staticmethod
    def from_proto(p: bytes) -> "PexRequest":
        return PexRequest()


@dataclass
class PexAddrs:
    addrs: list = field(default_factory=list)   # list[NetAddress]

    TAG = 2

    def to_proto(self) -> bytes:
        w = pw.Writer()
        for a in self.addrs:
            inner = (pw.Writer().string_field(1, a.node_id)
                     .string_field(2, a.host)
                     .uvarint_field(3, a.port))
            w.message_field(1, inner.bytes())
        return w.bytes()

    @staticmethod
    def from_proto(p: bytes) -> "PexAddrs":
        r = pw.Reader(p)
        m = PexAddrs()
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1 and w == pw.BYTES:
                rr = pw.Reader(r.read_bytes())
                nid, host, port = "", "", 0
                while not rr.at_end():
                    ff, ww = rr.read_tag()
                    if ff == 1 and ww == pw.BYTES:
                        nid = rr.read_string()
                    elif ff == 2 and ww == pw.BYTES:
                        host = rr.read_string()
                    elif ff == 3 and ww == pw.VARINT:
                        port = rr.read_uvarint()
                    else:
                        rr.skip(ww)
                if nid and host and 0 < port < 65536:
                    m.addrs.append(NetAddress(nid, host, port))
            else:
                r.skip(w)
        return m


def _wrap(msg) -> bytes:
    return pw.Writer().message_field(msg.TAG, msg.to_proto()).bytes()


def _unwrap(payload: bytes):
    r = pw.Reader(payload)
    while not r.at_end():
        f, w = r.read_tag()
        if w == pw.BYTES:
            if f == PexRequest.TAG:
                return PexRequest.from_proto(r.read_bytes())
            if f == PexAddrs.TAG:
                return PexAddrs.from_proto(r.read_bytes())
        r.skip(w)
    raise ValueError("empty pex message")


class PexReactor(Reactor):
    def __init__(self, book: AddrBook, seeds: list[str] | None = None,
                 ensure_peers_period: float = DEFAULT_ENSURE_PEERS_PERIOD,
                 min_request_interval: float = MIN_RECEIVE_REQUEST_INTERVAL):
        super().__init__("PexReactor")
        self.book = book
        self.seeds = [NetAddress.parse(s) for s in (seeds or [])]
        self._period = ensure_peers_period
        self._min_interval = min_request_interval
        self._last_received: dict[str, float] = {}
        self._requested: set[str] = set()
        self._stop = threading.Event()

    def get_channels(self) -> list:
        return [ChannelDescriptor(PEX_CHANNEL, priority=1,
                                  send_queue_capacity=10,
                                  recv_message_capacity=MAX_MSG_SIZE)]

    def on_start(self) -> None:
        self._stop.clear()
        threading.Thread(target=self._ensure_peers_routine,
                         name="pex-ensure-peers", daemon=True).start()

    def on_stop(self) -> None:
        self._stop.set()
        self.book.save()

    # -- peer lifecycle ----------------------------------------------------

    def add_peer(self, peer) -> None:
        """pex_reactor.go:183: ask outbound peers (we chose them) for
        more addresses when the book is short; record inbound peers'
        self-reported addresses (but never solicit from them — an
        attacker who connects in must not get to feed us a book)."""
        addr = self._peer_net_address(peer)
        if peer.outbound:
            if addr is not None:
                self.book.mark_good(addr)
            if self.book.need_more_addrs():
                self._request_addrs(peer)
        else:
            if addr is not None:
                self.book.add_address(addr, src=addr)

    def remove_peer(self, peer, reason) -> None:
        self._requested.discard(peer.id)
        self._last_received.pop(peer.id, None)

    @staticmethod
    def _peer_net_address(peer) -> NetAddress | None:
        """Dialable address: socket host (strip any id@ prefix) + the
        peer's self-reported listen port (the socket port is ephemeral
        for inbound peers)."""
        try:
            if peer.socket_addr:
                hostport = peer.socket_addr.split("@", 1)[-1]
                host, _, port = hostport.rpartition(":")
                listen = peer.node_info.listen_addr or ""
                lport = listen.rsplit(":", 1)[-1] if ":" in listen else port
                return NetAddress(peer.id, host, int(lport))
        except (ValueError, AttributeError):
            return None
        return None

    # -- gossip ------------------------------------------------------------

    def _request_addrs(self, peer) -> None:
        if peer.id in self._requested:
            return
        self._requested.add(peer.id)
        peer.try_send(PEX_CHANNEL, _wrap(PexRequest()))

    def receive(self, envelope: Envelope) -> None:
        try:
            msg = _unwrap(envelope.message)
        except ValueError:
            return
        peer = envelope.src
        if isinstance(msg, PexRequest):
            now = time.monotonic()
            last = self._last_received.get(peer.id, 0.0)
            if now - last < self._min_interval:
                # request flooding (pex_reactor.go:292): evict
                if self.switch is not None:
                    self.switch.stop_peer_for_error(
                        peer, "pex request flood")
                return
            self._last_received[peer.id] = now
            sel = self.book.get_selection()
            peer.try_send(PEX_CHANNEL, _wrap(PexAddrs(addrs=sel)))
        elif isinstance(msg, PexAddrs):
            if peer.id not in self._requested:
                # unsolicited addrs (pex_reactor.go:342): protocol abuse
                if self.switch is not None:
                    self.switch.stop_peer_for_error(
                        peer, "unsolicited pex addrs")
                return
            self._requested.discard(peer.id)
            src = self._peer_net_address(peer)
            for addr in msg.addrs[:MAX_MSG_SIZE // 64]:
                self.book.add_address(addr, src=src)

    # -- outbound maintenance ----------------------------------------------

    def _ensure_peers_routine(self) -> None:
        # jittered initial wait like the reference, then periodic
        self._ensure_peers()
        while not self._stop.wait(self._period):
            self._ensure_peers()

    def _ensure_peers(self) -> None:
        """pex_reactor.go:435: top up outbound peers from the book."""
        if self.switch is None:
            return
        nums = self.switch.num_peers()
        out = nums["outbound"] + nums.get("dialing", 0)
        need = self.switch.max_outbound - out
        if need <= 0:
            return
        # few peers -> explore (bias to new); many -> exploit (old)
        total = nums["outbound"] + nums["inbound"]
        bias = max(30, 100 - total * 10)
        tried: set[str] = set()
        dialed = 0
        for _ in range(need * 3):
            if dialed >= need:
                break
            cand = self.book.pick_address(bias)
            if cand is None:
                break
            if cand.node_id in tried or \
                    self.switch.peers.has(cand.node_id):
                tried.add(cand.node_id)
                continue
            tried.add(cand.node_id)
            self.book.mark_attempt(cand)
            try:
                self.switch.dial_peer(str(cand))
                self.book.mark_good(cand)
                dialed += 1
            except Exception:
                self.book.mark_bad(cand)
        # ask a connected peer for more when the book runs dry
        if self.book.need_more_addrs():
            peers = self.switch.peers.list()
            if peers:
                import random
                self._request_addrs(random.choice(peers))
        if dialed == 0 and self.book.empty() and self.seeds:
            self._dial_seeds()

    def _dial_seeds(self) -> None:
        import random
        seeds = list(self.seeds)
        random.shuffle(seeds)
        for seed in seeds:
            try:
                self.switch.dial_peer(str(seed))
                return
            except Exception:
                continue
