from .addrbook import AddrBook, NetAddress, KnownAddress
from .reactor import PexReactor, PEX_CHANNEL

__all__ = ["AddrBook", "NetAddress", "KnownAddress", "PexReactor",
           "PEX_CHANNEL"]
