"""Bucketed peer address book (reference p2p/pex/addrbook.go).

Addresses live in 256 "new" buckets (heard about, unvetted) and 64
"old" buckets (connected successfully).  Bucket placement is a keyed
hash of (address group, source group) so an attacker controlling one
/16 cannot fill the whole book; promotion to old happens on mark_good,
demotion back to new on mark_bad.  The book persists as JSON.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import time
from ...libs import lockrank
from dataclasses import dataclass, field

NEW_BUCKET_COUNT = 256
OLD_BUCKET_COUNT = 64
NEW_BUCKETS_PER_ADDRESS = 4      # addrbook.go:34 maxNewBucketsPerAddress
MAX_GET_SELECTION = 250          # addrbook.go GetSelection cap
GET_SELECTION_PERCENT = 23       # % of book per PEX response
NEED_ADDRESS_THRESHOLD = 1000    # addrbook.go:44
BAD_ATTEMPTS = 3                 # attempts before an address is stale


@dataclass(frozen=True)
class NetAddress:
    """id@host:port."""
    node_id: str
    host: str
    port: int

    @staticmethod
    def parse(s: str) -> "NetAddress":
        node_id, _, hostport = s.partition("@")
        host, _, port = hostport.rpartition(":")
        if not node_id or not host or not port:
            raise ValueError(f"invalid address {s!r}")
        return NetAddress(node_id, host, int(port))

    def __str__(self) -> str:
        return f"{self.node_id}@{self.host}:{self.port}"

    def group(self) -> str:
        """Coarse locality key: /16 for dotted quads, else the host.
        The sybil-resistance unit of bucket placement."""
        parts = self.host.split(".")
        if len(parts) == 4 and all(p.isdigit() for p in parts):
            return ".".join(parts[:2])
        return self.host


@dataclass
class KnownAddress:
    """addrbook.go knownAddress."""
    addr: NetAddress
    src: NetAddress | None = None
    attempts: int = 0
    last_attempt: float = 0.0
    last_success: float = 0.0
    bucket_type: str = "new"
    buckets: list = field(default_factory=list)

    def is_old(self) -> bool:
        return self.bucket_type == "old"

    def is_bad(self) -> bool:
        """Stale: several failed attempts and no success since."""
        return (self.attempts >= BAD_ATTEMPTS and
                self.last_success < self.last_attempt)

    def to_json(self) -> dict:
        return {"addr": str(self.addr),
                "src": str(self.src) if self.src else None,
                "attempts": self.attempts,
                "last_attempt": self.last_attempt,
                "last_success": self.last_success,
                "bucket_type": self.bucket_type,
                "buckets": self.buckets}

    @staticmethod
    def from_json(d: dict) -> "KnownAddress":
        return KnownAddress(
            addr=NetAddress.parse(d["addr"]),
            src=NetAddress.parse(d["src"]) if d.get("src") else None,
            attempts=d.get("attempts", 0),
            last_attempt=d.get("last_attempt", 0.0),
            last_success=d.get("last_success", 0.0),
            bucket_type=d.get("bucket_type", "new"),
            buckets=list(d.get("buckets", [])))


class AddrBook:
    def __init__(self, file_path: str = "", key: bytes | None = None):
        self._path = file_path
        self._key = key or os.urandom(16)    # keyed bucket hashing
        self._mtx = lockrank.RankedLock("p2p.addrbook")
        self._rand = random.Random()
        self._by_id: dict[str, KnownAddress] = {}
        self._new: list[set[str]] = [set() for _ in range(NEW_BUCKET_COUNT)]
        self._old: list[set[str]] = [set() for _ in range(OLD_BUCKET_COUNT)]
        self._our_ids: set[str] = set()
        self._private_ids: set[str] = set()
        if file_path and os.path.exists(file_path):
            self._load()

    # -- identity filters --------------------------------------------------

    def add_our_address(self, addr: NetAddress) -> None:
        with self._mtx:
            self._our_ids.add(addr.node_id)

    def add_private_ids(self, ids: list[str]) -> None:
        with self._mtx:
            self._private_ids.update(ids)

    # -- bucket placement --------------------------------------------------

    def _bucket_idx(self, addr: NetAddress, src: NetAddress | None,
                    n_buckets: int) -> int:
        src_group = src.group() if src else ""
        h = hashlib.sha256(
            self._key + addr.group().encode() + b"|" +
            src_group.encode()).digest()
        return int.from_bytes(h[:4], "big") % n_buckets

    # -- mutation ----------------------------------------------------------

    def add_address(self, addr: NetAddress,
                    src: NetAddress | None = None) -> bool:
        """Heard about addr from src -> a new bucket (addrbook.go:213).
        Re-adds are probabilistic, capped at 4 new buckets per address."""
        with self._mtx:
            if addr.node_id in self._our_ids or \
                    addr.node_id in self._private_ids:
                return False
            ka = self._by_id.get(addr.node_id)
            if ka is not None:
                if ka.is_old():
                    return False
                if len(ka.buckets) >= NEW_BUCKETS_PER_ADDRESS:
                    return False
                # probabilistically spread across more buckets
                if self._rand.random() > 1 / (2 ** len(ka.buckets)):
                    return False
            else:
                ka = KnownAddress(addr=addr, src=src)
                self._by_id[addr.node_id] = ka
            idx = self._bucket_idx(addr, src, NEW_BUCKET_COUNT)
            if idx not in ka.buckets:
                self._evict_if_full(self._new[idx], old=False)
                self._new[idx].add(addr.node_id)
                ka.buckets.append(idx)
            return True

    def _evict_if_full(self, bucket: set, old: bool,
                       cap: int = 64) -> None:
        if len(bucket) < cap:
            return
        # drop the worst (stalest) entry
        worst = max(bucket, key=lambda nid: (
            self._by_id[nid].is_bad(), -self._by_id[nid].last_success))
        self._remove_locked(worst)

    def remove_address(self, addr: NetAddress) -> None:
        with self._mtx:
            self._remove_locked(addr.node_id)

    def _remove_locked(self, node_id: str) -> None:
        ka = self._by_id.pop(node_id, None)
        if ka is None:
            return
        buckets = self._old if ka.is_old() else self._new
        for idx in ka.buckets:
            buckets[idx].discard(node_id)

    def mark_attempt(self, addr: NetAddress) -> None:
        with self._mtx:
            ka = self._by_id.get(addr.node_id)
            if ka:
                ka.attempts += 1
                ka.last_attempt = time.time()

    def mark_good(self, addr: NetAddress) -> None:
        """Successful handshake: promote to an old bucket
        (addrbook.go MarkGood -> moveToOld)."""
        with self._mtx:
            ka = self._by_id.get(addr.node_id)
            if ka is None:
                ka = KnownAddress(addr=addr)
                self._by_id[addr.node_id] = ka
            ka.attempts = 0
            ka.last_success = ka.last_attempt = time.time()
            if ka.is_old():
                return
            for idx in ka.buckets:
                self._new[idx].discard(addr.node_id)
            idx = self._bucket_idx(ka.addr, ka.src, OLD_BUCKET_COUNT)
            self._evict_if_full(self._old[idx], old=True)
            ka.bucket_type = "old"
            ka.buckets = [idx]
            self._old[idx].add(addr.node_id)

    def mark_bad(self, addr: NetAddress) -> None:
        with self._mtx:
            ka = self._by_id.get(addr.node_id)
            if ka:
                ka.attempts += 1
                ka.last_attempt = time.time()
                if ka.is_bad():
                    self._remove_locked(addr.node_id)

    # -- queries -----------------------------------------------------------

    def has_address(self, addr: NetAddress) -> bool:
        with self._mtx:
            return addr.node_id in self._by_id

    def is_good(self, addr: NetAddress) -> bool:
        with self._mtx:
            ka = self._by_id.get(addr.node_id)
            return ka is not None and ka.is_old()

    def size(self) -> int:
        with self._mtx:
            return len(self._by_id)

    def empty(self) -> bool:
        return self.size() == 0

    def need_more_addrs(self) -> bool:
        return self.size() < NEED_ADDRESS_THRESHOLD

    def pick_address(self, bias_towards_new: int = 30) -> NetAddress | None:
        """Random address, biased between old/new books
        (addrbook.go:272 PickAddress)."""
        with self._mtx:
            bias = max(0, min(100, bias_towards_new))
            n_old = sum(len(b) for b in self._old)
            n_new = sum(len(b) for b in self._new)
            if n_old == 0 and n_new == 0:
                return None
            pick_old = n_old > 0 and (
                n_new == 0 or self._rand.random() * 100 >= bias)
            buckets = self._old if pick_old else self._new
            nonempty = [b for b in buckets if b]
            bucket = self._rand.choice(nonempty)
            nid = self._rand.choice(sorted(bucket))
            return self._by_id[nid].addr

    def get_selection(self) -> list[NetAddress]:
        """Random subset for a PEX response (addrbook.go GetSelection):
        23% of the book, capped at 250."""
        with self._mtx:
            all_ids = list(self._by_id)
            n = min(MAX_GET_SELECTION,
                    max(1, len(all_ids) * GET_SELECTION_PERCENT // 100))
            self._rand.shuffle(all_ids)
            return [self._by_id[i].addr for i in all_ids[:n]]

    def addresses(self) -> list[NetAddress]:
        with self._mtx:
            return [ka.addr for ka in self._by_id.values()]

    # -- persistence (addrbook.go saveToFile/loadFromFile) -----------------

    def save(self) -> None:
        if not self._path:
            return
        with self._mtx:
            data = {"key": self._key.hex(),
                    "addrs": [ka.to_json()
                              for ka in self._by_id.values()]}
        tmp = self._path + ".tmp"
        os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, self._path)

    def _load(self) -> None:
        with open(self._path) as f:
            data = json.load(f)
        self._key = bytes.fromhex(data["key"])
        for d in data.get("addrs", []):
            ka = KnownAddress.from_json(d)
            self._by_id[ka.addr.node_id] = ka
            buckets = self._old if ka.is_old() else self._new
            for idx in ka.buckets:
                if 0 <= idx < len(buckets):
                    buckets[idx].add(ka.addr.node_id)
