"""Reactor interface (reference p2p/base_reactor.go).

A reactor owns a set of channels on the Switch and reacts to peer
lifecycle + incoming envelopes.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..libs.service import BaseService


@dataclass
class Envelope:
    """p2p.Envelope: a decoded message from (or to) a peer."""
    src: object = None        # Peer (inbound)
    message: object = None    # decoded message (or raw bytes)
    channel_id: int = 0
    tctx: object = None       # trace context (libs/tracetl.py) when the
    #                           wire carried one; None everywhere else


class Reactor(BaseService):
    """Override get_channels / init_peer / add_peer / remove_peer /
    receive."""

    def __init__(self, name: str = ""):
        super().__init__(name or type(self).__name__)
        self.switch = None

    def set_switch(self, switch) -> None:
        self.switch = switch

    def get_channels(self) -> list:
        """-> list[ChannelDescriptor]."""
        return []

    def init_peer(self, peer) -> object:
        """Called before the peer starts; may attach per-peer state."""
        return peer

    def add_peer(self, peer) -> None:
        pass

    def remove_peer(self, peer, reason) -> None:
        pass

    def receive(self, envelope: Envelope) -> None:
        pass
