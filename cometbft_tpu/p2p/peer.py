"""Peer: MConnection + NodeInfo + per-peer data
(reference p2p/peer.go, peer_set.go)."""

from __future__ import annotations


from ..libs import lockrank

from ..libs.service import BaseService
from .node_info import NodeInfo


class Peer(BaseService):
    def __init__(self, node_info: NodeInfo, mconn, outbound: bool,
                 persistent: bool = False, socket_addr: str = ""):
        super().__init__(f"Peer:{node_info.node_id[:10]}")
        self.node_info = node_info
        self.mconn = mconn
        self.outbound = outbound
        self.persistent = persistent
        self.socket_addr = socket_addr
        self._data: dict = {}
        self._data_mtx = lockrank.RankedLock("p2p.peer_data")

    @property
    def id(self) -> str:
        return self.node_info.node_id

    def on_start(self) -> None:
        self.mconn.start()

    def on_stop(self) -> None:
        self.mconn.stop()

    def send(self, channel_id: int, msg_bytes: bytes,
             timeout: float = 10.0, tctx=None) -> bool:
        """Blocking send onto the channel queue (peer.go Send).
        `tctx` is an optional trace context (libs/tracetl.py) carried
        to the remote reactor's Envelope when the wire supports it."""
        return self.mconn.send(channel_id, msg_bytes, timeout=timeout,
                               tctx=tctx)

    def try_send(self, channel_id: int, msg_bytes: bytes,
                 tctx=None) -> bool:
        return self.mconn.try_send(channel_id, msg_bytes, tctx=tctx)

    # per-peer key/value store (reactors stash PeerState here)
    def set(self, key: str, value) -> None:
        with self._data_mtx:
            self._data[key] = value

    def get(self, key: str):
        with self._data_mtx:
            return self._data.get(key)

    def status(self) -> dict:
        return self.mconn.status()


class PeerSet:
    """Thread-safe peer registry (p2p/peer_set.go)."""

    def __init__(self):
        self._mtx = lockrank.RankedLock("p2p.peer")
        self._by_id: dict[str, Peer] = {}

    def add(self, peer: Peer) -> None:
        with self._mtx:
            if peer.id in self._by_id:
                raise ValueError(f"duplicate peer {peer.id}")
            self._by_id[peer.id] = peer

    def has(self, peer_id: str) -> bool:
        with self._mtx:
            return peer_id in self._by_id

    def get(self, peer_id: str) -> Peer | None:
        with self._mtx:
            return self._by_id.get(peer_id)

    def remove(self, peer: Peer) -> bool:
        with self._mtx:
            return self._by_id.pop(peer.id, None) is not None

    def size(self) -> int:
        with self._mtx:
            return len(self._by_id)

    def list(self) -> list[Peer]:
        with self._mtx:
            return list(self._by_id.values())
