"""MultiplexTransport: TCP listen/dial + connection upgrade
(reference p2p/transport.go).

upgrade = SecretConnection handshake (authenticates the peer key) +
length-prefixed NodeInfo exchange + compatibility filters.
"""

from __future__ import annotations

import socket
import struct
import threading

from .conn.secret_connection import SecretConnection
from .key import NodeKey, node_id_from_pubkey
from .node_info import MAX_NODE_INFO_SIZE, NodeInfo, NodeInfoError

HANDSHAKE_TIMEOUT = 20.0
DIAL_TIMEOUT = 3.0


class TransportError(Exception):
    pass


class ErrRejected(TransportError):
    pass


def parse_addr(addr: str) -> tuple[str, str, int]:
    """'id@host:port' or 'host:port' -> (id, host, port)."""
    peer_id = ""
    if "@" in addr:
        peer_id, addr = addr.split("@", 1)
    addr = addr.replace("tcp://", "")
    host, _, port = addr.rpartition(":")
    return peer_id, host or "127.0.0.1", int(port)


class MultiplexTransport:
    def __init__(self, node_key: NodeKey, node_info: NodeInfo,
                 handshake_timeout: float = HANDSHAKE_TIMEOUT):
        self.node_key = node_key
        self.node_info = node_info
        self.handshake_timeout = handshake_timeout
        self._listener: socket.socket | None = None
        self._accept_cb = None
        self._accept_thread: threading.Thread | None = None
        self._closed = False
        # conn filters: callables(raw socket) raising to reject
        self.conn_filters: list = []

    # -- listening ---------------------------------------------------------
    def listen(self, addr: str, accept_cb) -> str:
        """Start accepting; accept_cb(secret_conn, node_info) runs per
        upgraded inbound connection. Returns the bound address."""
        _, host, port = parse_addr(addr)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self._accept_cb = accept_cb
        self._accept_thread = threading.Thread(
            target=self._accept_routine, name="transport-accept",
            daemon=True)
        self._accept_thread.start()
        bound_host, bound_port = self._listener.getsockname()
        return f"{bound_host}:{bound_port}"

    def _accept_routine(self) -> None:
        while not self._closed:
            try:
                raw, _addr = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._handle_inbound, args=(raw,),
                             daemon=True).start()

    def _handle_inbound(self, raw: socket.socket) -> None:
        try:
            peername = "%s:%d" % raw.getpeername()[:2]
        except OSError:
            peername = ""
        try:
            conn, info = self.upgrade(raw, expected_id="")
            conn.remote_addr = peername
        except Exception:
            try:
                raw.close()
            except OSError:
                pass
            return
        try:
            self._accept_cb(conn, info)
        except Exception:
            conn.close()

    # -- dialing -----------------------------------------------------------
    def dial(self, addr: str) -> tuple[SecretConnection, NodeInfo]:
        """Outbound connect + upgrade; verifies the peer ID when the
        address pins one ('id@host:port')."""
        peer_id, host, port = parse_addr(addr)
        raw = socket.create_connection((host, port), timeout=DIAL_TIMEOUT)
        return self.upgrade(raw, expected_id=peer_id)

    # -- upgrade -----------------------------------------------------------
    def upgrade(self, raw: socket.socket, expected_id: str
                ) -> tuple[SecretConnection, NodeInfo]:
        """transport.go:411: secret handshake, filters, NodeInfo swap."""
        raw.settimeout(self.handshake_timeout)
        raw.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        for f in self.conn_filters:
            f(raw)

        conn = SecretConnection.make(raw, self.node_key.priv_key)
        actual_id = node_id_from_pubkey(conn.remote_pubkey)
        if expected_id and actual_id != expected_id:
            conn.close()
            raise ErrRejected(
                f"peer ID mismatch: dialed {expected_id}, got {actual_id}")

        # NodeInfo exchange: 4-byte length prefix + proto
        payload = self.node_info.to_proto()
        conn.write(struct.pack(">I", len(payload)) + payload)
        their_info = self._read_node_info(conn)

        their_info.validate_basic()
        if their_info.node_id != actual_id:
            conn.close()
            raise ErrRejected(
                f"NodeInfo ID {their_info.node_id} != handshake ID "
                f"{actual_id}")
        if their_info.node_id == self.node_info.node_id:
            conn.close()
            raise ErrRejected("connected to self")
        try:
            self.node_info.compatible_with(their_info)
        except NodeInfoError as e:
            conn.close()
            raise ErrRejected(str(e)) from e

        raw.settimeout(None)
        return conn, their_info

    @staticmethod
    def _read_node_info(conn: SecretConnection) -> NodeInfo:
        buf = b""
        while len(buf) < 4:
            chunk = conn.read()
            if not chunk:
                raise TransportError("EOF during NodeInfo exchange")
            buf += chunk
        (n,) = struct.unpack_from(">I", buf)
        if n > MAX_NODE_INFO_SIZE:
            raise TransportError("NodeInfo too large")
        buf = buf[4:]
        while len(buf) < n:
            chunk = conn.read()
            if not chunk:
                raise TransportError("EOF during NodeInfo exchange")
            buf += chunk
        return NodeInfo.from_proto(buf[:n])

    def close(self) -> None:
        self._closed = True
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
