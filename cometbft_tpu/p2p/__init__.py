"""Distributed communication backend: authenticated, multiplexed,
rate-limited TCP mesh (reference p2p/)."""

from .key import NodeKey, node_id_from_pubkey  # noqa: F401
from .node_info import NodeInfo  # noqa: F401
from .base_reactor import Reactor, Envelope  # noqa: F401
from .switch import Switch  # noqa: F401
