"""Operator CLI (reference cmd/cometbft/main.go + commands/).

    python -m cometbft_tpu.cmd.main --home ~/.cometbft-tpu init
    python -m cometbft_tpu.cmd.main --home ~/.cometbft-tpu start
    ... show-node-id | show-validator | gen-node-key | version |
        unsafe-reset-all | replay
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys

SOFTWARE_VERSION = "0.1.0-tpu"
DEFAULT_HOME = os.path.expanduser("~/.cometbft-tpu")


def _load_config(home: str):
    from ..config import load_config
    cfg = load_config(home)
    cfg.base.root_dir = home
    return cfg


def cmd_init(args) -> int:
    """commands/init.go InitFilesCmd."""
    from ..config import write_config_file
    from ..node import init_files
    cfg = _load_config(args.home)
    genesis = init_files(cfg, chain_id=args.chain_id)
    write_config_file(os.path.join(args.home, "config", "config.toml"),
                      cfg)
    print(f"Initialized node in {args.home} "
          f"(chain_id={genesis.chain_id})")
    return 0


def cmd_start(args) -> int:
    """commands/run_node.go NewRunNodeCmd."""
    from ..node import Node
    cfg = _load_config(args.home)
    if args.proxy_app:
        cfg.base.abci = args.proxy_app
    if args.p2p_laddr:
        cfg.p2p.laddr = args.p2p_laddr
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    if args.persistent_peers:
        cfg.p2p.persistent_peers = args.persistent_peers

    node = Node(cfg, block_sync=args.block_sync)
    node.start()
    print(f"Node started: p2p={node.p2p_addr} rpc={node.rpc_addr}")

    stop = {"flag": False}

    def handle(sig, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, handle)
    signal.signal(signal.SIGTERM, handle)
    try:
        while not stop["flag"]:
            node.wait(0.5)
    finally:
        node.stop()
    return 0


def cmd_show_node_id(args) -> int:
    from ..p2p.key import NodeKey
    cfg = _load_config(args.home)
    print(NodeKey.load_or_gen(cfg.node_key_file()).id)
    return 0


def cmd_show_validator(args) -> int:
    from ..privval import FilePV
    cfg = _load_config(args.home)
    pv = FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                 cfg.priv_validator_state_file())
    import base64
    print(json.dumps({
        "type": "tendermint/PubKeyEd25519",
        "value": base64.b64encode(pv.get_pub_key().bytes()).decode(),
    }))
    return 0


def cmd_gen_node_key(args) -> int:
    from ..crypto import ed25519
    from ..p2p.key import NodeKey
    nk = NodeKey(ed25519.PrivKey.generate())
    print(nk.id)
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """commands/reset.go: wipe data, keep the validator key."""
    cfg = _load_config(args.home)
    data_dir = cfg.db_dir()
    if os.path.isdir(data_dir):
        shutil.rmtree(data_dir)
    os.makedirs(data_dir, exist_ok=True)
    from ..privval import FilePV
    if os.path.exists(cfg.priv_validator_key_file()):
        pv = FilePV.load(cfg.priv_validator_key_file(),
                         cfg.priv_validator_state_file())
        pv.reset()
    print(f"Reset {data_dir}")
    return 0


def cmd_rollback(args) -> int:
    """commands/rollback.go: revert state one height (--hard also
    deletes the block) to recover from app-hash divergence."""
    cfg = _load_config(args.home)
    from ..state.rollback import RollbackError, rollback_state
    from ..state.store import StateStore
    from ..store.blockstore import BlockStore
    from ..store.kv import open_db
    backend = cfg.base.db_backend
    block_store = BlockStore(
        open_db(backend, os.path.join(cfg.db_dir(), "blockstore.db")))
    state_store = StateStore(
        open_db(backend, os.path.join(cfg.db_dir(), "state.db")))
    try:
        height, app_hash = rollback_state(state_store, block_store,
                                          remove_block=args.hard)
    except RollbackError as e:
        print(f"rollback failed: {e}", file=sys.stderr)
        return 1
    print(f"Rolled back state to height {height} and hash "
          f"{app_hash.hex().upper()}")
    return 0


def cmd_gen_validator(args) -> int:
    """commands/gen_validator.go: print a fresh validator key."""
    import base64

    from ..crypto.ed25519 import PrivKey
    priv = PrivKey.generate()
    pub = priv.pub_key()
    print(json.dumps({
        "address": pub.address().hex().upper(),
        "pub_key": {"type": "tendermint/PubKeyEd25519",
                    "value": base64.b64encode(pub.bytes()).decode()},
        "priv_key": {"type": "tendermint/PrivKeyEd25519",
                     "value": base64.b64encode(priv.bytes()).decode()},
    }, indent=2))
    return 0


def cmd_inspect(args) -> int:
    """internal/inspect/inspect.go:51: serve RPC over the stores of a
    crashed/stopped node WITHOUT running consensus."""
    from ..rpc.core import Environment
    from ..rpc.server import RPCServer
    from ..state.store import StateStore
    from ..store.blockstore import BlockStore
    from ..store.kv import open_db
    from ..types.genesis import GenesisDoc

    cfg = _load_config(args.home)
    backend = cfg.base.db_backend
    env = Environment(
        state_store=StateStore(open_db(
            backend, os.path.join(cfg.db_dir(), "state.db"))),
        block_store=BlockStore(open_db(
            backend, os.path.join(cfg.db_dir(), "blockstore.db"))),
        genesis=GenesisDoc.from_file(cfg.genesis_file())
        if os.path.exists(cfg.genesis_file()) else None,
        config=cfg)
    if cfg.tx_index.indexer == "kv":
        from ..state.indexer import BlockIndexer, TxIndexer
        env.tx_indexer = TxIndexer(open_db(
            backend, os.path.join(cfg.db_dir(), "tx_index.db")))
        env.block_indexer = BlockIndexer(open_db(
            backend, os.path.join(cfg.db_dir(), "block_index.db")))
    addr = (args.rpc_laddr or cfg.rpc.laddr).replace("tcp://", "")
    server = RPCServer(env, addr)
    server.start()
    print(f"Inspect RPC serving on {server.bound_addr} (no consensus); "
          "Ctrl-C to stop")
    try:
        signal.pause()
    except KeyboardInterrupt:
        pass
    server.stop()
    return 0


def cmd_light(args) -> int:
    """commands/light.go: verifying RPC proxy over an untrusted node."""
    from ..light.client import Client, TrustOptions
    from ..light.provider import HttpProvider
    from ..light.proxy import LightProxy

    if not args.trusted_height or not args.trusted_hash:
        print("--trusted-height and --trusted-hash are required",
              file=sys.stderr)
        return 1
    def _norm(addr: str) -> str:
        return addr if "://" in addr else "http://" + addr

    primary = HttpProvider(args.chain_id, _norm(args.primary))
    witnesses = [HttpProvider(args.chain_id, _norm(w))
                 for w in (args.witnesses.split(",")
                           if args.witnesses else []) if w]
    client = Client(
        args.chain_id,
        TrustOptions(period_ns=int(args.trust_period * 1e9),
                     height=int(args.trusted_height),
                     hash=bytes.fromhex(args.trusted_hash)),
        primary, witnesses)
    proxy = LightProxy(client, args.laddr)
    proxy.start()
    print(f"Light proxy serving verified RPC on {proxy.bound_addr}; "
          "Ctrl-C to stop")
    try:
        signal.pause()
    except KeyboardInterrupt:
        pass
    proxy.stop()
    return 0


def cmd_testnet(args) -> int:
    """commands/testnet.go: generate N validator homes with a shared
    genesis and fully-meshed persistent peers."""
    from ..config import load_config, write_config_file
    from ..p2p.key import NodeKey
    from ..privval import FilePV
    from ..types.genesis import GenesisDoc, GenesisValidator
    from ..types.timestamp import Timestamp

    n = args.v
    out = args.o or os.path.join(args.home, "testnet")
    chain_id = args.chain_id or "chain-%s" % os.urandom(3).hex()
    homes, validators, node_ids = [], [], []
    for i in range(n):
        home = os.path.join(out, f"{args.node_dir_prefix}{i}")
        cfg = load_config(home)
        cfg.base.root_dir = home
        cfg.ensure_dirs()
        pv = FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                     cfg.priv_validator_state_file())
        key = NodeKey.load_or_gen(cfg.node_key_file())
        homes.append((home, cfg))
        node_ids.append(key.id)
        validators.append(GenesisValidator(pub_key=pv.get_pub_key(),
                                           power=1))
    genesis = GenesisDoc(chain_id=chain_id, genesis_time=Timestamp.now(),
                         validators=validators)
    base_p2p, base_rpc = args.starting_port, args.starting_port + 1000
    peers = ",".join(
        f"{node_ids[i]}@127.0.0.1:{base_p2p + i}" for i in range(n))
    for i, (home, cfg) in enumerate(homes):
        genesis.save_as(cfg.genesis_file())
        cfg.p2p.laddr = f"tcp://0.0.0.0:{base_p2p + i}"
        cfg.rpc.laddr = f"tcp://0.0.0.0:{base_rpc + i}"
        cfg.p2p.persistent_peers = ",".join(
            p for j, p in enumerate(peers.split(",")) if j != i)
        write_config_file(os.path.join(home, "config", "config.toml"),
                          cfg)
    print(f"Generated {n} node homes under {out} (chain_id={chain_id})")
    return 0


def cmd_compact_db(args) -> int:
    """commands/compact.go analog: VACUUM the sqlite stores."""
    import sqlite3
    cfg = _load_config(args.home)
    n = 0
    for name in os.listdir(cfg.db_dir()):
        if not name.endswith(".db"):
            continue
        path = os.path.join(cfg.db_dir(), name)
        try:
            conn = sqlite3.connect(path)
            conn.execute("VACUUM")
            conn.close()
            n += 1
        except sqlite3.DatabaseError as e:
            print(f"skip {name}: {e}", file=sys.stderr)
    print(f"Compacted {n} databases in {cfg.db_dir()}")
    return 0


def cmd_version(args) -> int:
    print(SOFTWARE_VERSION)
    return 0


def cmd_reindex_event(args) -> int:
    """commands/reindex_event.go analog: rebuild the tx/block event
    indexes from the block store + stored FinalizeBlockResponses
    (recovers from indexer corruption or an indexer=null era)."""
    from ..abci import types as at
    from ..state.indexer import BlockIndexer, TxIndexer
    from ..state.store import StateStore
    from ..store.blockstore import BlockStore
    from ..store.kv import open_db
    from ..types import events as ev

    cfg = _load_config(args.home)
    backend = cfg.base.db_backend
    block_store = BlockStore(open_db(
        backend, os.path.join(cfg.db_dir(), "blockstore.db")))
    state_store = StateStore(open_db(
        backend, os.path.join(cfg.db_dir(), "state.db")))
    tx_indexer = TxIndexer(open_db(
        backend, os.path.join(cfg.db_dir(), "tx_index.db")))
    block_indexer = BlockIndexer(open_db(
        backend, os.path.join(cfg.db_dir(), "block_index.db")))

    base = max(block_store.base(), 1)
    height = block_store.height()
    start = args.start_height or base
    end = args.end_height or height
    if start < base or end > height or start > end:
        print(f"height range [{start},{end}] outside stored "
              f"[{base},{height}]", file=sys.stderr)
        return 1
    n_blocks = n_txs = 0
    for h in range(start, end + 1):
        block = block_store.load_block(h)
        raw = state_store.load_finalize_block_response(h)
        if block is None or raw is None:
            print(f"skip height {h}: missing block or results",
                  file=sys.stderr)
            continue
        fin = at.FinalizeBlockResponse.from_proto(raw)
        # the same composite maps the live event bus feeds the indexers
        bev = ev.block_events_map(h, fin.events)
        bev.setdefault(ev.EVENT_TYPE_KEY, []).append(
            ev.EVENT_NEW_BLOCK_EVENTS)
        block_indexer.index(h, bev)
        n_blocks += 1
        for i, tx in enumerate(block.data.txs):
            result = fin.tx_results[i] if i < len(fin.tx_results) else None
            tev = ev.tx_events_map(h, bytes(tx),
                                   getattr(result, "events", None))
            tev.setdefault(ev.EVENT_TYPE_KEY, []).append(ev.EVENT_TX)
            tx_indexer.index(h, i, bytes(tx), result, tev)
            n_txs += 1
    print(f"Reindexed {n_blocks} blocks / {n_txs} txs "
          f"over heights [{start},{end}]")
    if n_blocks == 0:
        print("nothing reindexed (blocks or results missing for the "
              "whole range)", file=sys.stderr)
        return 1
    return 0


def cmd_debug_dump(args) -> int:
    """commands/debug (dump mode) analog: snapshot a running node's
    observable state over RPC into a directory — status, net_info,
    consensus state dumps, unconfirmed txs, optionally at intervals."""
    import json as _json
    import time as _time
    import urllib.request

    os.makedirs(args.output_directory, exist_ok=True)
    routes = ["status", "net_info", "dump_consensus_state",
              "consensus_state", "num_unconfirmed_txs", "abci_info"]

    def snapshot(tag: str) -> None:
        out = {}
        for r in routes:
            url = f"http://{args.rpc_laddr.replace('tcp://', '')}/{r}"
            try:
                with urllib.request.urlopen(url, timeout=10) as resp:
                    body = _json.loads(resp.read())
                    out[r] = body.get("result") or body
            except Exception as e:
                out[r] = {"error": str(e)}
        path = os.path.join(args.output_directory, f"dump_{tag}.json")
        with open(path, "w") as f:
            _json.dump(out, f, indent=1)
        print(f"wrote {path}")

    # --frequency alone means "snapshot forever at that interval";
    # --count bounds the number of snapshots (1 snapshot by default)
    count = args.count if args.count > 1 else \
        (2**62 if args.frequency else 1)
    i = 0
    while i < count:
        snapshot(f"{int(_time.time())}_{i}")
        i += 1
        if i < count:
            _time.sleep(max(args.frequency, 1.0))
    return 0


def cmd_debug_kill(args) -> int:
    """commands/debug/kill.go: aggregate a running node's state
    (status, net_info, consensus state over RPC; WAL + config copies)
    into a zip archive, then kill the process with SIGABRT."""
    import json as _json
    import shutil
    import signal as _signal
    import tempfile
    import urllib.request
    import zipfile

    cfg = _load_config(args.home)
    tmp = tempfile.mkdtemp(prefix="cometbft_debug_")
    try:
        for route, fname in (("status", "status.json"),
                             ("net_info", "net_info.json"),
                             ("dump_consensus_state",
                              "consensus_state.json")):
            url = (f"http://{args.rpc_laddr.replace('tcp://', '')}"
                   f"/{route}")
            try:
                with urllib.request.urlopen(url, timeout=10) as resp:
                    body = _json.loads(resp.read())
                payload = body.get("result") or body
            except Exception as e:
                payload = {"error": str(e)}
            with open(os.path.join(tmp, fname), "w") as f:
                _json.dump(payload, f, indent=1)

        wal_path = cfg.wal_file()
        if os.path.exists(wal_path):
            shutil.copy2(wal_path, os.path.join(tmp, "cs.wal"))
        conf_dir = os.path.join(cfg.base.root_dir, "config")
        if os.path.isdir(conf_dir):
            shutil.copytree(conf_dir, os.path.join(tmp, "config"),
                            dirs_exist_ok=True)

        # SIGABRT, like the reference (stacktrace-on-abort semantics;
        # Python nodes dump a traceback via faulthandler when enabled)
        killed = True
        try:
            os.kill(args.pid, _signal.SIGABRT)
        except ProcessLookupError:
            killed = False
            print(f"process {args.pid} not found", file=sys.stderr)

        with zipfile.ZipFile(args.output_file, "w",
                             zipfile.ZIP_DEFLATED) as zf:
            for root, _, files in os.walk(tmp):
                for fn in files:
                    full = os.path.join(root, fn)
                    zf.write(full, os.path.relpath(full, tmp))
        print(f"wrote {args.output_file}")
        return 0 if killed else 1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def cmd_replay(args) -> int:
    """commands/replay.go: replay the WAL through a fresh consensus
    state (console mode prints each message)."""
    cfg = _load_config(args.home)
    from ..consensus.wal import WAL
    wal = WAL(cfg.wal_file())
    n = 0
    for timed in wal.replay():
        n += 1
        if args.console:
            print(type(timed.msg).__name__, timed.msg)
    print(f"replayed {n} WAL messages")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="cometbft-tpu",
        description="TPU-native BFT state-machine replication engine")
    parser.add_argument("--home", default=DEFAULT_HOME,
                        help="node home directory")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("init", help="initialize config/keys/genesis")
    p.add_argument("--chain-id", default="")
    p.set_defaults(fn=cmd_init)

    p = sub.add_parser("start", help="run the node")
    p.add_argument("--proxy-app", default="",
                   help="ABCI app address or 'kvstore'")
    p.add_argument("--p2p-laddr", default="")
    p.add_argument("--rpc-laddr", default="")
    p.add_argument("--persistent-peers", default="")
    p.add_argument("--block-sync", action="store_true")
    p.set_defaults(fn=cmd_start)

    for name, fn in (("show-node-id", cmd_show_node_id),
                     ("show-validator", cmd_show_validator),
                     ("gen-node-key", cmd_gen_node_key),
                     ("unsafe-reset-all", cmd_unsafe_reset_all),
                     ("version", cmd_version)):
        p = sub.add_parser(name)
        p.set_defaults(fn=fn)

    p = sub.add_parser("replay", help="replay the consensus WAL")
    p.add_argument("--console", action="store_true")
    p.set_defaults(fn=cmd_replay)

    p = sub.add_parser("rollback",
                       help="roll chain state back one height")
    p.add_argument("--hard", action="store_true",
                   help="also delete the invalidated block")
    p.set_defaults(fn=cmd_rollback)

    p = sub.add_parser("gen-validator",
                       help="print a fresh validator keypair")
    p.set_defaults(fn=cmd_gen_validator)

    p = sub.add_parser("inspect",
                       help="serve RPC over the stores, no consensus")
    p.add_argument("--rpc-laddr", default="")
    p.set_defaults(fn=cmd_inspect)

    p = sub.add_parser("light", help="light-verifying RPC proxy")
    p.add_argument("chain_id")
    p.add_argument("--primary", required=True,
                   help="primary full-node RPC address (host:port)")
    p.add_argument("--witnesses", default="",
                   help="comma-separated witness RPC addresses")
    p.add_argument("--trusted-height", type=int, default=0)
    p.add_argument("--trusted-hash", default="")
    p.add_argument("--trust-period", type=float, default=168 * 3600,
                   help="trusting period in seconds")
    p.add_argument("--laddr", default="tcp://127.0.0.1:8888")
    p.set_defaults(fn=cmd_light)

    p = sub.add_parser("testnet", help="generate a local testnet")
    p.add_argument("--v", type=int, default=4,
                   help="number of validators")
    p.add_argument("--o", default="", help="output directory")
    p.add_argument("--chain-id", default="")
    p.add_argument("--node-dir-prefix", default="node")
    p.add_argument("--starting-port", type=int, default=26656)
    p.set_defaults(fn=cmd_testnet)

    p = sub.add_parser("reindex-event",
                       help="rebuild tx/block event indexes from the "
                            "block store")
    p.add_argument("--start-height", type=int, default=0)
    p.add_argument("--end-height", type=int, default=0)
    p.set_defaults(fn=cmd_reindex_event)

    p = sub.add_parser(
        "debug", help="debug a running node (dump | kill)")
    dsub = p.add_subparsers(dest="debug_mode", required=True)
    d = dsub.add_parser("dump", help="snapshot node state over RPC")
    d.add_argument("--rpc-laddr", default="tcp://127.0.0.1:26657")
    d.add_argument("--output-directory", default="debug-dump")
    d.add_argument("--frequency", type=float, default=0.0,
                   help="seconds between snapshots (0 = one snapshot)")
    d.add_argument("--count", type=int, default=1)
    d.set_defaults(fn=cmd_debug_dump)
    k = dsub.add_parser(
        "kill", help="archive node state, then SIGABRT the process")
    k.add_argument("pid", type=int)
    k.add_argument("output_file")
    k.add_argument("--rpc-laddr", default="tcp://127.0.0.1:26657")
    k.set_defaults(fn=cmd_debug_kill)

    p = sub.add_parser("compact-db", help="compact the sqlite stores")
    p.set_defaults(fn=cmd_compact_db)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
