"""Operator CLI (reference cmd/cometbft/main.go + commands/).

    python -m cometbft_tpu.cmd.main --home ~/.cometbft-tpu init
    python -m cometbft_tpu.cmd.main --home ~/.cometbft-tpu start
    ... show-node-id | show-validator | gen-node-key | version |
        unsafe-reset-all | replay
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys

SOFTWARE_VERSION = "0.1.0-tpu"
DEFAULT_HOME = os.path.expanduser("~/.cometbft-tpu")


def _load_config(home: str):
    from ..config import load_config
    cfg = load_config(home)
    cfg.base.root_dir = home
    return cfg


def cmd_init(args) -> int:
    """commands/init.go InitFilesCmd."""
    from ..config import write_config_file
    from ..node import init_files
    cfg = _load_config(args.home)
    genesis = init_files(cfg, chain_id=args.chain_id)
    write_config_file(os.path.join(args.home, "config", "config.toml"),
                      cfg)
    print(f"Initialized node in {args.home} "
          f"(chain_id={genesis.chain_id})")
    return 0


def cmd_start(args) -> int:
    """commands/run_node.go NewRunNodeCmd."""
    from ..node import Node
    cfg = _load_config(args.home)
    if args.proxy_app:
        cfg.base.abci = args.proxy_app
    if args.p2p_laddr:
        cfg.p2p.laddr = args.p2p_laddr
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    if args.persistent_peers:
        cfg.p2p.persistent_peers = args.persistent_peers

    node = Node(cfg, block_sync=args.block_sync)
    node.start()
    print(f"Node started: p2p={node.p2p_addr} rpc={node.rpc_addr}")

    stop = {"flag": False}

    def handle(sig, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, handle)
    signal.signal(signal.SIGTERM, handle)
    try:
        while not stop["flag"]:
            node.wait(0.5)
    finally:
        node.stop()
    return 0


def cmd_show_node_id(args) -> int:
    from ..p2p.key import NodeKey
    cfg = _load_config(args.home)
    print(NodeKey.load_or_gen(cfg.node_key_file()).id)
    return 0


def cmd_show_validator(args) -> int:
    from ..privval import FilePV
    cfg = _load_config(args.home)
    pv = FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                 cfg.priv_validator_state_file())
    import base64
    print(json.dumps({
        "type": "tendermint/PubKeyEd25519",
        "value": base64.b64encode(pv.get_pub_key().bytes()).decode(),
    }))
    return 0


def cmd_gen_node_key(args) -> int:
    from ..crypto import ed25519
    from ..p2p.key import NodeKey
    nk = NodeKey(ed25519.PrivKey.generate())
    print(nk.id)
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """commands/reset.go: wipe data, keep the validator key."""
    cfg = _load_config(args.home)
    data_dir = cfg.db_dir()
    if os.path.isdir(data_dir):
        shutil.rmtree(data_dir)
    os.makedirs(data_dir, exist_ok=True)
    from ..privval import FilePV
    if os.path.exists(cfg.priv_validator_key_file()):
        pv = FilePV.load(cfg.priv_validator_key_file(),
                         cfg.priv_validator_state_file())
        pv.reset()
    print(f"Reset {data_dir}")
    return 0


def cmd_rollback(args) -> int:
    """commands/rollback.go: revert state one height (--hard also
    deletes the block) to recover from app-hash divergence."""
    cfg = _load_config(args.home)
    from ..state.rollback import RollbackError, rollback_state
    from ..state.store import StateStore
    from ..store.blockstore import BlockStore
    from ..store.kv import open_db
    backend = cfg.base.db_backend
    block_store = BlockStore(
        open_db(backend, os.path.join(cfg.db_dir(), "blockstore.db")))
    state_store = StateStore(
        open_db(backend, os.path.join(cfg.db_dir(), "state.db")))
    try:
        height, app_hash = rollback_state(state_store, block_store,
                                          remove_block=args.hard)
    except RollbackError as e:
        print(f"rollback failed: {e}", file=sys.stderr)
        return 1
    print(f"Rolled back state to height {height} and hash "
          f"{app_hash.hex().upper()}")
    return 0


def cmd_version(args) -> int:
    print(SOFTWARE_VERSION)
    return 0


def cmd_replay(args) -> int:
    """commands/replay.go: replay the WAL through a fresh consensus
    state (console mode prints each message)."""
    cfg = _load_config(args.home)
    from ..consensus.wal import WAL
    wal = WAL(cfg.wal_file())
    n = 0
    for timed in wal.replay():
        n += 1
        if args.console:
            print(type(timed.msg).__name__, timed.msg)
    print(f"replayed {n} WAL messages")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="cometbft-tpu",
        description="TPU-native BFT state-machine replication engine")
    parser.add_argument("--home", default=DEFAULT_HOME,
                        help="node home directory")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("init", help="initialize config/keys/genesis")
    p.add_argument("--chain-id", default="")
    p.set_defaults(fn=cmd_init)

    p = sub.add_parser("start", help="run the node")
    p.add_argument("--proxy-app", default="",
                   help="ABCI app address or 'kvstore'")
    p.add_argument("--p2p-laddr", default="")
    p.add_argument("--rpc-laddr", default="")
    p.add_argument("--persistent-peers", default="")
    p.add_argument("--block-sync", action="store_true")
    p.set_defaults(fn=cmd_start)

    for name, fn in (("show-node-id", cmd_show_node_id),
                     ("show-validator", cmd_show_validator),
                     ("gen-node-key", cmd_gen_node_key),
                     ("unsafe-reset-all", cmd_unsafe_reset_all),
                     ("version", cmd_version)):
        p = sub.add_parser(name)
        p.set_defaults(fn=fn)

    p = sub.add_parser("replay", help="replay the consensus WAL")
    p.add_argument("--console", action="store_true")
    p.set_defaults(fn=cmd_replay)

    p = sub.add_parser("rollback",
                       help="roll chain state back one height")
    p.add_argument("--hard", action="store_true",
                   help="also delete the invalidated block")
    p.set_defaults(fn=cmd_rollback)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
