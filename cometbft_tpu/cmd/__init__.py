"""Operator CLI (reference cmd/cometbft/)."""
