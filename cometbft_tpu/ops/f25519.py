"""GF(2**255 - 19) arithmetic for TPU: 16 x 16-bit limbs in uint32 lanes.

Elements are arrays of shape (..., 16), limbs little-endian in [0, 2**16)
("normalized"), representing values in [0, 2**256) that are congruent to
the intended field element (lazy reduction; `freeze` produces the canonical
representative < p).  Everything is branch-free and vmappable.

Reference analog: field ops inside curve25519-voi consumed by
/root/reference/crypto/ed25519/ed25519.go; this is a from-scratch
TPU-oriented design, not a translation.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import limbs as lb

NLIMBS = 16
P = (1 << 255) - 19

# canonical limb constants (host numpy, captured as jit constants)
P_LIMBS = lb.int_to_limbs(P, NLIMBS)
P2_LIMBS = lb.int_to_limbs(2 * P, NLIMBS)

# 4p in a redundant per-limb-padded form: every limb >= 0xFFFF so that
# (a + PAD_4P - b) never underflows in uint32 when a, b are normalized.
_pad = np.full(NLIMBS, (1 << 18) - 4, dtype=np.uint64)
_pad[15] -= 1 << 17
_pad[0] -= 72
assert sum(int(v) << (16 * i) for i, v in enumerate(_pad)) == 4 * P
assert (_pad >= 0xFFFF).all() and (_pad < (1 << 19)).all()
PAD_4P = _pad.astype(np.uint32)

# curve constants as field elements
D_INT = (-121665 * pow(121666, P - 2, P)) % P
D2_INT = (2 * D_INT) % P
SQRT_M1_INT = pow(2, (P - 1) // 4, P)
D_LIMBS = lb.int_to_limbs(D_INT, NLIMBS)
D2_LIMBS = lb.int_to_limbs(D2_INT, NLIMBS)
SQRT_M1_LIMBS = lb.int_to_limbs(SQRT_M1_INT, NLIMBS)
ONE_LIMBS = lb.int_to_limbs(1, NLIMBS)
ZERO_LIMBS = lb.int_to_limbs(0, NLIMBS)


def _fold_carry(x: jnp.ndarray) -> jnp.ndarray:
    """Carry-propagate and fold 2**256 overflow back via 2**256 = 2p + 38."""
    x, c = lb.carry_prop(x)
    x = x.at[..., 0].add(c * jnp.uint32(38))
    x, c = lb.carry_prop(x)
    x = x.at[..., 0].add(c * jnp.uint32(38))
    # after two folds the value is < 2**256 and limb 0 gained at most 38;
    # one last propagation cannot carry out of the top limb.
    x, _ = lb.carry_prop(x)
    return x


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _fold_carry(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _fold_carry(a + jnp.asarray(PAD_4P) - b)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return _fold_carry(jnp.asarray(PAD_4P) - a)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    wide = lb.mul_raw(a, b)                     # (..., 32) limbs < 2**21
    # fold the high 256 bits: 2**256 = 2p + 38  =>  hi*2**256 == hi*38
    folded = wide[..., :NLIMBS] + wide[..., NLIMBS:] * jnp.uint32(38)
    return _fold_carry(folded)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_word(a: jnp.ndarray, w: int) -> jnp.ndarray:
    """Multiply by small constant w < 2**11 (so 16-bit limb * w < 2**27)."""
    return _fold_carry(a * jnp.uint32(w))


def _sq_n(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """x**(2**n) via n squarings (rolled loop keeps the HLO graph small)."""
    return jax.lax.fori_loop(0, n, lambda i, v: sqr(v), x)


def _pow_22501(z: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Shared prefix of the p-2 and (p-5)/8 addition chains.

    Returns (z**(2**250 - 1), z**11).
    """
    z2 = sqr(z)
    z9 = mul(_sq_n(z2, 2), z)
    z11 = mul(z9, z2)
    z2_5_0 = mul(sqr(z11), z9)          # 2**5 - 2**0
    z2_10_0 = mul(_sq_n(z2_5_0, 5), z2_5_0)
    z2_20_0 = mul(_sq_n(z2_10_0, 10), z2_10_0)
    z2_40_0 = mul(_sq_n(z2_20_0, 20), z2_20_0)
    z2_50_0 = mul(_sq_n(z2_40_0, 10), z2_10_0)
    z2_100_0 = mul(_sq_n(z2_50_0, 50), z2_50_0)
    z2_200_0 = mul(_sq_n(z2_100_0, 100), z2_100_0)
    z2_250_0 = mul(_sq_n(z2_200_0, 50), z2_50_0)
    return z2_250_0, z11


def invert(z: jnp.ndarray) -> jnp.ndarray:
    """z**(p-2) = z**(2**255 - 21); returns 0 for z == 0."""
    z2_250_0, z11 = _pow_22501(z)
    return mul(_sq_n(z2_250_0, 5), z11)


def pow_p58(z: jnp.ndarray) -> jnp.ndarray:
    """z**((p-5)/8) = z**(2**252 - 3)."""
    z2_250_0, _ = _pow_22501(z)
    return mul(_sq_n(z2_250_0, 2), z)


def freeze(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical representative in [0, p) from any normalized element."""
    a = lb.cond_sub(a, jnp.asarray(P2_LIMBS))
    return lb.cond_sub(a, jnp.asarray(P_LIMBS))


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return lb.is_zero(freeze(a))


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return lb.eq(freeze(a), freeze(b))


def parity(a: jnp.ndarray) -> jnp.ndarray:
    """Low bit of the canonical representative (uint32 0/1)."""
    return freeze(a)[..., 0] & jnp.uint32(1)


def sqrt_ratio(u: jnp.ndarray, v: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """sqrt(u/v) per RFC 8032 decompression; returns (x, ok).

    ok is False when u/v is not a square.  x satisfies v*x**2 == u when ok.
    """
    v3 = mul(sqr(v), v)
    v7 = mul(sqr(v3), v)
    r = mul(mul(u, v3), pow_p58(mul(u, v7)))    # (u v^3) (u v^7)^((p-5)/8)
    check = mul(v, sqr(r))
    correct = eq(check, u)
    flipped = eq(check, neg(u))
    r_alt = mul(r, jnp.asarray(SQRT_M1_LIMBS))
    x = jnp.where(flipped[..., None], r_alt, r)
    return x, correct | flipped
