"""Batched SHA-256 / SHA-512 for TPU in pure uint32 JAX.

SHA-256 words are native uint32.  SHA-512's 64-bit words are emulated as
(hi, lo) uint32 pairs — TPUs have no native 64-bit integer datapath, so
this keeps everything on the 32-bit VPU lanes.

Layout: a batch of pre-padded messages is shaped (N, B, W) where B is the
(static) max number of blocks and W the words per block (16 for SHA-256,
32 for SHA-512 as hi/lo interleaved).  Per-message block counts mask the
scan so one compiled kernel serves ragged batches.

Reference analog: crypto/tmhash (SHA-256 truncation) and the SHA-512
message hashing inside Ed25519 verification
(/root/reference/crypto/tmhash/hash.go, crypto/ed25519/ed25519.go).
Host-side padding helpers live at the bottom (numpy, vectorized).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# SHA-256
# ---------------------------------------------------------------------------

_K256 = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2], dtype=np.uint32)

_H256 = np.array([0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                  0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19],
                 dtype=np.uint32)


def _rotr32(x, n):
    return (x >> n) | (x << (32 - n))


def _sha256_block(state: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """One compression round.  state (..., 8), w (..., 16) big-endian words."""

    def sched(i, ws):
        w15 = ws[..., (i - 15) % 16]
        w2 = ws[..., (i - 2) % 16]
        s0 = _rotr32(w15, 7) ^ _rotr32(w15, 18) ^ (w15 >> 3)
        s1 = _rotr32(w2, 17) ^ _rotr32(w2, 19) ^ (w2 >> 10)
        nw = ws[..., i % 16] + s0 + ws[..., (i - 7) % 16] + s1
        return ws.at[..., i % 16].set(nw)

    def round_fn(i, carry):
        a, b, c, d, e, f, g, h, ws = carry
        ws = jax.lax.cond(i >= 16, lambda: sched(i, ws), lambda: ws)
        kw = jnp.asarray(_K256)[i] + ws[..., i % 16]
        s1 = _rotr32(e, 6) ^ _rotr32(e, 11) ^ _rotr32(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + kw
        s0 = _rotr32(a, 2) ^ _rotr32(a, 13) ^ _rotr32(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g, ws)

    init = tuple(state[..., i] for i in range(8)) + (w,)
    out = jax.lax.fori_loop(0, 64, round_fn, init)
    return state + jnp.stack(out[:8], axis=-1)


def sha256_blocks(blocks: jnp.ndarray, n_blocks: jnp.ndarray) -> jnp.ndarray:
    """Digest pre-padded messages.

    blocks: (N, B, 16) uint32 big-endian words; n_blocks: (N,) int32.
    Returns (N, 8) uint32 big-endian digest words.
    """
    B = blocks.shape[-2]
    state = jnp.broadcast_to(jnp.asarray(_H256), blocks.shape[:-2] + (8,))

    def step(carry, xs):
        st = carry
        blk, idx = xs
        new = _sha256_block(st, blk)
        keep = (idx < n_blocks)[..., None]
        return jnp.where(keep, new, st), None

    xs = (jnp.moveaxis(blocks, -2, 0), jnp.arange(B, dtype=jnp.int32))
    state, _ = jax.lax.scan(step, state, xs)
    return state


# ---------------------------------------------------------------------------
# SHA-512 (64-bit words as hi/lo uint32 pairs)
# ---------------------------------------------------------------------------

_K512 = [
    0x428a2f98d728ae22, 0x7137449123ef65cd, 0xb5c0fbcfec4d3b2f, 0xe9b5dba58189dbbc,
    0x3956c25bf348b538, 0x59f111f1b605d019, 0x923f82a4af194f9b, 0xab1c5ed5da6d8118,
    0xd807aa98a3030242, 0x12835b0145706fbe, 0x243185be4ee4b28c, 0x550c7dc3d5ffb4e2,
    0x72be5d74f27b896f, 0x80deb1fe3b1696b1, 0x9bdc06a725c71235, 0xc19bf174cf692694,
    0xe49b69c19ef14ad2, 0xefbe4786384f25e3, 0x0fc19dc68b8cd5b5, 0x240ca1cc77ac9c65,
    0x2de92c6f592b0275, 0x4a7484aa6ea6e483, 0x5cb0a9dcbd41fbd4, 0x76f988da831153b5,
    0x983e5152ee66dfab, 0xa831c66d2db43210, 0xb00327c898fb213f, 0xbf597fc7beef0ee4,
    0xc6e00bf33da88fc2, 0xd5a79147930aa725, 0x06ca6351e003826f, 0x142929670a0e6e70,
    0x27b70a8546d22ffc, 0x2e1b21385c26c926, 0x4d2c6dfc5ac42aed, 0x53380d139d95b3df,
    0x650a73548baf63de, 0x766a0abb3c77b2a8, 0x81c2c92e47edaee6, 0x92722c851482353b,
    0xa2bfe8a14cf10364, 0xa81a664bbc423001, 0xc24b8b70d0f89791, 0xc76c51a30654be30,
    0xd192e819d6ef5218, 0xd69906245565a910, 0xf40e35855771202a, 0x106aa07032bbd1b8,
    0x19a4c116b8d2d0c8, 0x1e376c085141ab53, 0x2748774cdf8eeb99, 0x34b0bcb5e19b48a8,
    0x391c0cb3c5c95a63, 0x4ed8aa4ae3418acb, 0x5b9cca4f7763e373, 0x682e6ff3d6b2b8a3,
    0x748f82ee5defb2fc, 0x78a5636f43172f60, 0x84c87814a1f0ab72, 0x8cc702081a6439ec,
    0x90befffa23631e28, 0xa4506cebde82bde9, 0xbef9a3f7b2c67915, 0xc67178f2e372532b,
    0xca273eceea26619c, 0xd186b8c721c0c207, 0xeada7dd6cde0eb1e, 0xf57d4f7fee6ed178,
    0x06f067aa72176fba, 0x0a637dc5a2c898a6, 0x113f9804bef90dae, 0x1b710b35131c471b,
    0x28db77f523047d84, 0x32caab7b40c72493, 0x3c9ebe0a15c9bebc, 0x431d67c49c100d4c,
    0x4cc5d4becb3e42b6, 0x597f299cfc657e2a, 0x5fcb6fab3ad6faec, 0x6c44198c4a475817,
]
_K512_HI = np.array([k >> 32 for k in _K512], dtype=np.uint32)
_K512_LO = np.array([k & 0xFFFFFFFF for k in _K512], dtype=np.uint32)

_H512 = [0x6a09e667f3bcc908, 0xbb67ae8584caa73b, 0x3c6ef372fe94f82b,
         0xa54ff53a5f1d36f1, 0x510e527fade682d1, 0x9b05688c2b3e6c1f,
         0x1f83d9abfb41bd6b, 0x5be0cd19137e2179]
_H512_HI = np.array([h >> 32 for h in _H512], dtype=np.uint32)
_H512_LO = np.array([h & 0xFFFFFFFF for h in _H512], dtype=np.uint32)


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(jnp.uint32)
    return ah + bh + carry, lo


def _rotr64(h, l, n):
    if n == 0:
        return h, l
    if n < 32:
        return (h >> n) | (l << (32 - n)), (l >> n) | (h << (32 - n))
    if n == 32:
        return l, h
    n -= 32
    return (l >> n) | (h << (32 - n)), (h >> n) | (l << (32 - n))


def _shr64(h, l, n):
    if n < 32:
        return h >> n, (l >> n) | (h << (32 - n))
    return jnp.zeros_like(h), h >> (n - 32)


def _sha512_block(sh, sl, wh, wl):
    """One compression.  sh/sl (..., 8); wh/wl (..., 16)."""

    def sched(i, whs, wls):
        i15, i2, i7, i16 = (i - 15) % 16, (i - 2) % 16, (i - 7) % 16, i % 16
        a_h, a_l = whs[..., i15], wls[..., i15]
        s0 = _xor3(_rotr64(a_h, a_l, 1), _rotr64(a_h, a_l, 8), _shr64(a_h, a_l, 7))
        b_h, b_l = whs[..., i2], wls[..., i2]
        s1 = _xor3(_rotr64(b_h, b_l, 19), _rotr64(b_h, b_l, 61), _shr64(b_h, b_l, 6))
        th, tl = _add64(whs[..., i16], wls[..., i16], s0[0], s0[1])
        th, tl = _add64(th, tl, whs[..., i7], wls[..., i7])
        th, tl = _add64(th, tl, s1[0], s1[1])
        return whs.at[..., i16].set(th), wls.at[..., i16].set(tl)

    def round_fn(i, carry):
        (ah, al, bh, bl, ch_, cl, dh, dl,
         eh, el, fh, fl, gh, gl, hh, hl, whs, wls) = carry
        whs, wls = jax.lax.cond(i >= 16, lambda: sched(i, whs, wls),
                                lambda: (whs, wls))
        s1 = _xor3(_rotr64(eh, el, 14), _rotr64(eh, el, 18), _rotr64(eh, el, 41))
        chh = (eh & fh) ^ (~eh & gh)
        chl = (el & fl) ^ (~el & gl)
        t1h, t1l = _add64(hh, hl, s1[0], s1[1])
        t1h, t1l = _add64(t1h, t1l, chh, chl)
        t1h, t1l = _add64(t1h, t1l, jnp.asarray(_K512_HI)[i], jnp.asarray(_K512_LO)[i])
        t1h, t1l = _add64(t1h, t1l, whs[..., i % 16], wls[..., i % 16])
        s0 = _xor3(_rotr64(ah, al, 28), _rotr64(ah, al, 34), _rotr64(ah, al, 39))
        majh = (ah & bh) ^ (ah & ch_) ^ (bh & ch_)
        majl = (al & bl) ^ (al & cl) ^ (bl & cl)
        t2h, t2l = _add64(s0[0], s0[1], majh, majl)
        ndh, ndl = _add64(dh, dl, t1h, t1l)
        nah, nal = _add64(t1h, t1l, t2h, t2l)
        return (nah, nal, ah, al, bh, bl, ch_, cl,
                ndh, ndl, eh, el, fh, fl, gh, gl, whs, wls)

    init = ()
    for i in range(8):
        init = init + (sh[..., i], sl[..., i])
    init = init + (wh, wl)
    out = jax.lax.fori_loop(0, 80, round_fn, init)
    nh, nl = [], []
    for i in range(8):
        h, l = _add64(sh[..., i], sl[..., i], out[2 * i], out[2 * i + 1])
        nh.append(h)
        nl.append(l)
    return jnp.stack(nh, axis=-1), jnp.stack(nl, axis=-1)


def _xor3(a, b, c):
    return (a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1])


def sha512_blocks(blocks_hi: jnp.ndarray, blocks_lo: jnp.ndarray,
                  n_blocks: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Digest pre-padded SHA-512 messages.

    blocks_hi/lo: (N, B, 16) uint32 (hi/lo halves of big-endian 64-bit words);
    n_blocks: (N,).  Returns (N, 8) hi and lo digest words.
    """
    B = blocks_hi.shape[-2]
    sh = jnp.broadcast_to(jnp.asarray(_H512_HI), blocks_hi.shape[:-2] + (8,))
    sl = jnp.broadcast_to(jnp.asarray(_H512_LO), blocks_lo.shape[:-2] + (8,))

    def step(carry, xs):
        csh, csl = carry
        bh, bl, idx = xs
        nh, nl = _sha512_block(csh, csl, bh, bl)
        keep = (idx < n_blocks)[..., None]
        return (jnp.where(keep, nh, csh), jnp.where(keep, nl, csl)), None

    xs = (jnp.moveaxis(blocks_hi, -2, 0), jnp.moveaxis(blocks_lo, -2, 0),
          jnp.arange(B, dtype=jnp.int32))
    (sh, sl), _ = jax.lax.scan(step, (sh, sl), xs)
    return sh, sl


# ---------------------------------------------------------------------------
# host-side padding (numpy)
# ---------------------------------------------------------------------------

def pad_sha256(msgs: list[bytes], max_blocks: int | None = None):
    """Pad a batch of messages; returns (blocks (N,B,16) u32, n_blocks (N,))."""
    return _pad(msgs, 64, max_blocks)


def pad_sha512(msgs: list[bytes], max_blocks: int | None = None):
    """Returns (blocks_hi, blocks_lo (N,B,16) u32, n_blocks (N,))."""
    blocks, n = _pad(msgs, 128, max_blocks)
    # blocks: (N, B, 32) u32 big-endian words; split into 64-bit hi/lo
    hi = blocks[..., 0::2]
    lo = blocks[..., 1::2]
    return hi, lo, n


def pad_sha512_matrix(mat: np.ndarray, lens: np.ndarray):
    """Like pad_sha512, but over a caller-built (N, B*128) u8 matrix:
    row i holds message bytes [0, lens[i]) with zeros beyond.  The
    matrix is padded IN PLACE (0x80 + big-endian bit length) — the
    zero-copy seam for packers that can assemble messages columnarly.
    Returns (blocks_hi, blocks_lo (N,B,16) u32, n_blocks (N,))."""
    blocks, n = _pad_matrix(mat, np.asarray(lens, dtype=np.int64), 128)
    hi = blocks[..., 0::2]
    lo = blocks[..., 1::2]
    return hi, lo, n


def _pad(msgs: list[bytes], block_bytes: int, max_blocks: int | None):
    # vectorized: one C-level join + masked scatter instead of four
    # numpy ops per message — the batch padding is a hot host stage on
    # the device-hash verify path (6-7k messages per block).
    lenbytes = 16 if block_bytes == 128 else 8
    n = len(msgs)
    lens = np.fromiter((len(m) for m in msgs), dtype=np.int64, count=n)
    fit = (lens + 1 + lenbytes + block_bytes - 1) // block_bytes
    B = int(max_blocks or (fit.max() if n else 1))
    out = np.zeros((n, B * block_bytes), dtype=np.uint8)
    if n:
        if int(fit.max()) > B:
            raise ValueError("message exceeds max_blocks")
        # boolean-mask assignment fills row-major, i.e. in exactly the
        # concatenated-message order of `flat`
        col = np.arange(B * block_bytes, dtype=np.int64)
        flat = np.frombuffer(b"".join(msgs), dtype=np.uint8)
        out[col[None, :] < lens[:, None]] = flat
    return _pad_matrix(out, lens, block_bytes)


def _pad_matrix(out: np.ndarray, lens: np.ndarray, block_bytes: int):
    lenbytes = 16 if block_bytes == 128 else 8
    n = out.shape[0]
    B = out.shape[1] // block_bytes
    n_blocks = ((lens + 1 + lenbytes + block_bytes - 1)
                // block_bytes).astype(np.int32)
    if n:
        if int(n_blocks.max()) > B:
            raise ValueError("message exceeds max_blocks")
        rows = np.arange(n)
        out[rows, lens] = 0x80
        end = n_blocks.astype(np.int64) * block_bytes
        # big-endian bit length in the block tail; bytes above the low
        # 8 stay zero for any message under 2^61 bits
        bits = (lens * 8).astype(np.uint64)
        for k in range(8):
            out[rows, end - 1 - k] = \
                ((bits >> np.uint64(8 * k)) & np.uint64(0xFF)).astype(np.uint8)
    # big-endian u32 words via one byteswapping view+copy
    w32 = out.view(">u4").reshape(n, B, block_bytes // 4) \
        .astype(np.uint32)
    return w32, n_blocks


def digest256_to_bytes(words: np.ndarray) -> bytes:
    """(8,) uint32 big-endian digest words -> 32 bytes."""
    return b"".join(int(w).to_bytes(4, "big") for w in np.asarray(words))


def digest512_to_bytes(hi: np.ndarray, lo: np.ndarray) -> bytes:
    out = b""
    for h, l in zip(np.asarray(hi), np.asarray(lo)):
        out += int(h).to_bytes(4, "big") + int(l).to_bytes(4, "big")
    return out
