"""Curve-generic batched signed-window MSM engine.

One engine, two curves: the verify hot path on both ed25519 and
secp256k1 is a multi-scalar multiplication, and until this module each
curve carried a bespoke device path (the RLC/w5 Straus stack in
ops/ed25519.py vs the per-signature 4-bit Shamir ladder in
ops/secp256k1.py).  The engine factors the common structure out into
three curve-independent pieces, parameterized by a small
:class:`CurveSpec` (field ops, unified add formulas, limb layout,
group order):

1. **windowed recode** — the bias trick of PR 10's
   ``_recode_w5_device`` generalized to any window width
   (:func:`recode_biased_digits`), plus a fully-parallel *odd*
   signed-digit recode (:func:`recode_jt`, Joye–Tunstall closed form)
   for the shared-table product path where all-odd digits make every
   in-loop addition structurally nonzero;

2. **bucket accumulation** — ``_segment_sum_mod_l``'s segment-sum
   discipline generalized from scalar limbs to curve points: per
   window, each point lands in the bucket of its digit magnitude.  A
   TPU has no efficient data-dependent scatter for 80-limb points
   (the long-standing comment in ops/ed25519.py), so the buckets are
   formed the way the radix scatter forms byte columns: a masked
   bucket-major selection tensor reduced by the same pairwise
   tree-add used everywhere else (:func:`bucket_accumulate`), then
   combined with the classic running-sum fold
   (:func:`bucket_fold`);

3. **shared-table multi-product** — N *independent* products
   ``k_i·P + l_i·Q_{g(i)}`` computed against shared precomputed
   window tables with zero in-loop doublings
   (:func:`multiprod_shared_tables`); this is the shape ECDSA batch
   verification needs (each signature checks an x-coordinate, so no
   sound whole-batch RLC single-point equation exists — recovering
   R from r is y-parity ambiguous) and the base the BLS12-381
   aggregate work can reuse.

Crossover: on this architecture the masked-selection bucket form
costs ~``B·W`` point-lane-ops per window (B = bucket count) against
Straus' ~``W``, so the bucket arm only wins where a backend makes the
bucket-major tree cheaper than the select cascade — the decision is
an op-count model with measured per-op coefficients
(:func:`choose_engine` / :func:`calibrate`), overridable with
``COMETBFT_TPU_MSM_ENGINE=straus|bucket|auto``.  The honest default
on XLA keeps Straus for the ed25519 RLC shapes; the engine's product
win is the secp256k1 shared-table path (ops/secp256k1.py
``msm_verify_kernel``), which replaces ~4224 field-muls/sig of ladder
with ~1250 and drops the 256 per-window exact-zero freezes.

Soundness note for the all-odd product path: with digits recoded odd
(never zero) and the accumulator blinded by a fresh random point S
(crypto/secp256k1.pack_msm_batch draws the scalar with ``secrets``,
exactly the RLC z_i discipline), every in-loop addition adds a
structurally nonzero table row to ``S + (partial sum)``; an
incomplete-add collision requires the adversary to hit ±S, i.e. a
~2^-247 guess per dispatch — the same soundness class as the RLC
fold.  A collision degrades to the absorbing Z=0 point and the
epilogue rejects Z=0 lanes, so the failure mode is a (negligible)
false *reject*, never a false accept.
"""

from __future__ import annotations

import os
from ..libs import lockrank
from dataclasses import dataclass
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# CurveSpec: what the engine needs to know about a curve
# ---------------------------------------------------------------------------
#
# Point state is a (coords_array, inf_plane) pair.  Curves with
# complete formulas (ed25519 extended coordinates) represent the
# identity in-band and carry inf=None; incomplete short-Weierstrass
# curves (secp256k1 Jacobian) carry an explicit boolean infinity
# plane, and their `add` must be the exact complete addition —
# bucket accumulation feeds masked identity entries through it by
# design.

@dataclass(frozen=True)
class CurveSpec:
    name: str
    order: int                       # prime group order
    coords: int                      # point stack height (4 ext / 3 jac)
    nlimbs: int                      # field limb count
    identity: Callable               # batch_shape -> state
    add: Callable                    # state, state -> state  (complete)
    dbl: Callable                    # state -> state
    cond_neg: Callable               # pts, mask -> pts
    select: Callable = None          # mask, state_a, state_b -> state
    # optional host-side helpers for goldens/tests
    to_affine_int: Callable = None   # state (width-1) -> (x, y) ints


def _where_state(mask, a, b):
    """Generic state select: mask broadcasts against the trailing
    batch dims of the coordinate stack."""
    pa, ia = a
    pb, ib = b
    pt = jnp.where(mask[None, None], pa, pb)
    if ia is None and ib is None:
        return pt, None
    return pt, jnp.where(mask, ia, ib)


def ed25519_spec() -> CurveSpec:
    from . import ed25519 as ed
    from . import fe

    def identity(batch_shape):
        return ed.identity_point(batch_shape), None

    def add(a, b):
        return ed.point_add(a[0], b[0]), None

    def dbl(a):
        return ed.point_double(a[0]), None

    def to_affine_int(state):
        pt = np.asarray(state[0])[..., 0]
        z = fe.limbs_to_int(pt[2])
        p = fe.P
        zi = pow(z, p - 2, p)
        return (fe.limbs_to_int(pt[0]) * zi % p,
                fe.limbs_to_int(pt[1]) * zi % p)

    return CurveSpec(
        name="ed25519", order=(1 << 252) + 27742317777372353535851937790883648493,
        coords=4, nlimbs=fe.NLIMBS,
        identity=identity, add=add, dbl=dbl,
        cond_neg=ed._cond_neg_point, select=_where_state,
        to_affine_int=to_affine_int)


def secp256k1_spec() -> CurveSpec:
    from . import fe_secp as fs
    from . import secp256k1 as sp

    def identity(batch_shape):
        one = sp._one_fe(batch_shape)
        return (sp._pt(one, one, sp._zero_fe(batch_shape)),
                jnp.ones(batch_shape, dtype=bool))

    def add(a, b):
        return sp.jadd_complete(a[0], a[1], b[0], b[1])

    def dbl(a):
        # jdbl is complete for a=0 (Z=0 stays Z=0, no 2-torsion)
        return sp.jdbl(a[0]), a[1]

    def cond_neg(pts, neg):
        y = jnp.where(neg[None], -pts[1], pts[1])
        return sp._pt(pts[0], y, pts[2])

    def to_affine_int(state):
        pt = np.asarray(state[0])[..., 0]
        if bool(np.asarray(state[1])[..., 0]):
            return None
        z = fs.limbs_to_int(pt[2]) % sp_p()
        zi = pow(z, sp_p() - 2, sp_p())
        return (fs.limbs_to_int(pt[0]) * zi * zi % sp_p(),
                fs.limbs_to_int(pt[1]) * zi * zi * zi % sp_p())

    return CurveSpec(
        name="secp256k1", order=sp.N_ORDER,
        coords=3, nlimbs=fs.NLIMBS,
        identity=identity, add=add, dbl=dbl,
        cond_neg=cond_neg, select=_where_state,
        to_affine_int=to_affine_int)


def sp_p() -> int:
    from ..crypto import secp256k1 as host
    return host.P


# ---------------------------------------------------------------------------
# windowed recodes
# ---------------------------------------------------------------------------

def bias_int(width: int, ndig: int) -> int:
    """The per-position bias that linearizes signed-window recoding:
    adding ``2^(w-1)`` at every window position pre-pays the
    worst-case borrow, so the signed digits of x are the plain base
    ``2^w`` digits of x + BIAS minus ``2^(w-1)`` — one limb addition
    plus static bit extraction instead of a data-dependent carry
    loop (PR 10's _recode_w5_device trick, any width)."""
    return sum((1 << (width - 1)) << (width * j) for j in range(ndig))


def recode_biased_digits(xb: jnp.ndarray, width: int, ndig: int):
    """(…, L) uint32 16-bit limbs of x + BIAS -> ((ndig, …), (ndig, …))
    signed-window digit magnitudes and signs, MSB-first.  Static bit
    extraction only; the caller performs the bias addition (it owns
    the scalar-limb carry discipline).  width <= 16."""
    mask = jnp.uint32((1 << width) - 1)
    half = 1 << (width - 1)
    nl = xb.shape[-1]
    mags, negs = [], []
    for j in range(ndig - 1, -1, -1):              # MSB first
        p = width * j
        li, sh = p >> 4, p & 15
        hi = xb[..., li + 1] if li + 1 < nl else 0
        word = xb[..., li] | (hi << 16)
        d = ((word >> sh) & mask).astype(jnp.int32) - half
        negs.append(d < 0)
        mags.append(jnp.abs(d))
    return jnp.stack(mags, axis=0), jnp.stack(negs, axis=0)


def recode_jt(ks, width: int, ndig: int):
    """Odd signed-digit recode (Joye–Tunstall), closed form, host side.

    For ODD k the width-w odd signed digits are::

        d_i = 2 * ((k >> (i*w + 1)) mod 2^w) + 1 - 2^w

    — fully parallel bit extraction, every digit odd in
    [-(2^w - 1), 2^w - 1], and for ``0 < k < 2^(ndig*w + 1)``::

        k = sum_i d_i * 2^(i*w)  +  2^(ndig*w)

    The fixed ``2^(ndig*w)`` remainder is a known per-table
    correction point added once by the kernel.  All-odd digits are
    what lets the in-loop adds skip the exact-zero branch machinery:
    no digit ever selects the identity.

    Returns ``(rows, negs)`` with rows ``(ndig, N)`` int32 in
    ``[0, 2^(w-1))`` indexing the odd multiple ``(2*row + 1)·2^(i*w)``
    and negs ``(ndig, N)`` bool, window index i ascending (LSB
    first — the shared-table product has no doubling order to
    respect).
    """
    n = len(ks)
    nbytes = (ndig * width + 7) // 8 + 3
    buf = np.zeros((n, nbytes), np.uint8)
    for i, k in enumerate(ks):
        k = int(k)
        assert k & 1 and 0 < k < (1 << (ndig * width + 1)), \
            "recode_jt needs odd 0 < k < 2^(ndig*w+1)"
        buf[i] = np.frombuffer(k.to_bytes(nbytes, "little"), np.uint8)
    b = buf.astype(np.uint32)
    mask = np.uint32((1 << width) - 1)
    rows = np.empty((ndig, n), np.int32)
    negs = np.empty((ndig, n), bool)
    for i in range(ndig):
        p = i * width + 1
        byi, sh = p >> 3, p & 7
        word = b[:, byi] | (b[:, byi + 1] << 8) | (b[:, byi + 2] << 16)
        d = (2 * ((word >> sh) & mask).astype(np.int64)
             + 1 - (1 << width))
        neg = d < 0
        mag = np.where(neg, -d, d)                 # odd, >= 1
        rows[i] = ((mag - 1) >> 1).astype(np.int32)
        negs[i] = neg
    return rows, negs


def jt_digit_value(rows: np.ndarray, negs: np.ndarray, width: int) -> int:
    """Reconstruct sum_i d_i 2^(i*w) from a recode_jt column — the
    test oracle for the closed form (add 2^(ndig*w) for k)."""
    ndig = rows.shape[0]
    total = 0
    for i in range(ndig):
        d = int(2 * rows[i] + 1)
        if negs[i]:
            d = -d
        total += d << (i * width)
    return total


# ---------------------------------------------------------------------------
# bucket accumulation + running-sum fold (the Pippenger arm)
# ---------------------------------------------------------------------------

def _tree_reduce_state(spec: CurveSpec, state, target: int = 1):
    """Pairwise tree-add over the LAST batch axis of a state, the
    generic form of ops/ed25519._tree_reduce (works for any leading
    batch dims, carries the infinity plane through spec.add)."""
    pts, inf = state
    while pts.shape[-1] > target:
        w = pts.shape[-1]
        half = w // 2
        a = (pts[..., :half], None if inf is None else inf[..., :half])
        b = (pts[..., half:2 * half],
             None if inf is None else inf[..., half:2 * half])
        left_p, left_i = spec.add(a, b)
        if w % 2:
            left_p = jnp.concatenate([left_p, pts[..., 2 * half:]],
                                     axis=-1)
            if inf is not None:
                left_i = jnp.concatenate([left_i, inf[..., 2 * half:]],
                                         axis=-1)
        pts, inf = left_p, left_i
    return pts, inf


def bucket_accumulate(spec: CurveSpec, pts_state, mag, neg, nbuckets: int):
    """One window's bucket accumulation: (coords, nlimbs, W) points
    with (W,) digit magnitudes in [0, nbuckets] -> per-bucket sums
    (coords, nlimbs, nbuckets) for buckets 1..nbuckets.

    The segment-sum discipline of _segment_sum_mod_l lifted to
    points: a lane contributes its (sign-adjusted) point to exactly
    the bucket of its |digit|; magnitude 0 contributes nowhere.  The
    scatter is expressed as a bucket-major masked selection (the
    identity is the masked filler) reduced by the pairwise tree —
    data-independent shapes, which is the whole trick on a TPU.
    """
    pts, inf = pts_state
    signed = spec.cond_neg(pts, neg)
    ident_p, ident_i = spec.identity(pts.shape[2:])
    # (coords, nlimbs, nbuckets, W) bucket-major selection tensor
    sel_mask = (mag[None, :] ==
                (jnp.arange(1, nbuckets + 1, dtype=mag.dtype)[:, None]))
    stack_p = jnp.where(sel_mask[None, None], signed[:, :, None, :],
                        ident_p[:, :, None, :])
    if inf is None:
        stack_i = None
    else:
        stack_i = jnp.where(sel_mask, inf[None, :],
                            ident_i[None, :])
    bp, bi = _tree_reduce_state(spec, (stack_p, stack_i), 1)
    return bp[..., 0], None if bi is None else bi[..., 0]


def bucket_fold(spec: CurveSpec, buckets_state):
    """Running-sum fold: (coords, nlimbs, B) bucket sums ->
    (coords, nlimbs, 1) window sum ``sum_b b * bucket_b`` via the
    classic descending running sum (2(B-1) adds, no multiplies)."""
    bp, bi = buckets_state
    nb = bp.shape[-1]

    def slot(b):
        return (bp[..., b:b + 1], None if bi is None else bi[..., b:b + 1])

    run = slot(nb - 1)
    tot = run
    for b in range(nb - 2, -1, -1):
        run = spec.add(run, slot(b))
        tot = spec.add(tot, run)
    return tot


def bucket_msm(spec: CurveSpec, pts_state, mags, negs, width: int):
    """Full bucket (Pippenger) MSM: ``sum_i e_i P_i`` over
    (coords, nlimbs, W) points with (nwin, W) MSB-first signed-window
    digit magnitudes/signs of the e_i (the same digit layout
    ops/ed25519._msm_scan consumes).  Returns a width-1 state.

    Window combination is MSB-first Horner: ``acc = 2^w acc + W_j``,
    so the doublings are shared across all buckets exactly like the
    Straus scan — the arms differ only in how a window's contribution
    is reduced (bucket accumulate+fold vs select cascade+tree).
    """
    nbuckets = 1 << (width - 1)

    def step(acc, xs):
        mag, neg = xs
        for _ in range(width):
            acc = spec.dbl(acc)
        wsum = bucket_fold(
            spec, bucket_accumulate(spec, pts_state, mag, neg, nbuckets))
        return spec.add(acc, wsum), None

    acc = spec.identity((1,))
    acc, _ = jax.lax.scan(step, acc, (mags, negs))
    return acc


# ---------------------------------------------------------------------------
# shared-table multi-product (zero in-loop doublings)
# ---------------------------------------------------------------------------

def multiprod_shared_tables(acc, sides):
    """N independent products against shared precomputed window
    tables — zero in-loop doublings.

    ``acc`` seeds the accumulator (the blinding point S broadcast to
    the lane width).  ``sides`` is a sequence of
    ``(tables, rows, negs, gather, add_entry)``: ``tables`` stacks the
    per-window tables along axis 0 (it rides the scan as an xs, so
    each step sees only its own window's slice), ``rows/negs`` are
    (nwin, N) odd-row indices/signs from :func:`recode_jt`,
    ``gather(tab_j, rows_j)`` widens window j's table to one entry
    per lane, and ``add_entry(acc, entry, neg)`` performs the
    (incomplete, blinding-protected) add.  The caller appends the
    per-side ``2^(ndig*w)`` correction points and subtracts S — see
    ops/secp256k1.msm_verify_kernel, the ECDSA instantiation.

    Kept generic and separate from that kernel so the BLS12-381
    aggregate path (ROADMAP item 2) can instantiate it with pairing
    curve specs without touching the ECDSA wiring.
    """
    for tables, rows, negs, gather, add_entry in sides:
        def step(a, xs, gather=gather, add_entry=add_entry):
            tab_j, row, neg = xs
            return add_entry(a, gather(tab_j, row), neg), None
        acc, _ = jax.lax.scan(step, acc, (tables, rows, negs))
    return acc


# ---------------------------------------------------------------------------
# engine choice: op-count model with measurable coefficients
# ---------------------------------------------------------------------------
#
# Lane-op model per window over W lanes with B = 2^(w-1) buckets:
#   straus: select cascade is elementwise (cheap, coefficient c_sel)
#           + tree reduce W -> npart (~W lane-adds) + w doublings on
#           npart lanes;
#   bucket: masked bucket-major tree (~B*W lane-adds) + running-sum
#           fold (2(B-1) adds) + w doublings on 1 lane.
# On XLA both arms' lane-adds cost the same per lane, so bucket wins
# only when a backend's measured add coefficient for the bucket-major
# layout undercuts the cascade (a Pallas bucket kernel could; the XLA
# product path does not).  calibrate() lets a bench measure the two
# coefficients; absent measurements the static model applies.

_COEFF_LOCK = lockrank.RankedLock("msm.coeff")
_COEFFS: dict[str, float] = {}     # "straus"/"bucket" -> ns per lane-op


def straus_window_cost(w_lanes: int, width: int,
                       npart_max: int = 192) -> float:
    npart = w_lanes
    while npart > npart_max:
        npart //= 2
    return w_lanes + width * npart


def bucket_window_cost(w_lanes: int, width: int) -> float:
    nbuckets = 1 << (width - 1)
    return nbuckets * w_lanes + 2 * (nbuckets - 1) + width


def calibrate(straus_ns_per_op: float, bucket_ns_per_op: float) -> None:
    """Install measured per-lane-op coefficients (bench-driven
    auto-tune; see bench.py --secp arms).  Thread-safe, process-wide."""
    with _COEFF_LOCK:
        _COEFFS["straus"] = float(straus_ns_per_op)
        _COEFFS["bucket"] = float(bucket_ns_per_op)


def choose_engine(w_lanes: int, width: int = 5) -> str:
    """'straus' | 'bucket' for one MSM side of ``w_lanes`` lanes.
    Evaluated at trace time (shapes are static), honoring
    COMETBFT_TPU_MSM_ENGINE=straus|bucket|auto."""
    forced = os.environ.get("COMETBFT_TPU_MSM_ENGINE", "auto")
    if forced in ("straus", "bucket"):
        return forced
    with _COEFF_LOCK:
        cs = _COEFFS.get("straus", 1.0)
        cb = _COEFFS.get("bucket", 1.0)
    s = cs * straus_window_cost(w_lanes, width)
    b = cb * bucket_window_cost(w_lanes, width)
    return "bucket" if b < s else "straus"
