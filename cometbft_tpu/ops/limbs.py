"""Multi-limb big-integer arithmetic on TPU-friendly uint32 lanes.

TPU VPUs operate natively on 32-bit integers; there is no native 64-bit
multiply.  We therefore represent big integers in radix 2**16: an n-limb
number is an array of n uint32 values, each in [0, 2**16), little-endian
limb order.  A 16x16-bit product fits exactly in a uint32, and partial
products are accumulated as (lo16, hi16) pairs so no intermediate ever
overflows 32 bits.  All functions are shape-polymorphic over leading batch
dimensions and contain only static control flow, so they can be freely
`jax.vmap`-ed and `jax.jit`-ed (reference's analog: the 64-bit limb field
arithmetic inside curve25519-voi used by /root/reference/crypto/ed25519).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

MASK16 = jnp.uint32(0xFFFF)
LIMB_BITS = 16
LIMB_RADIX = 1 << LIMB_BITS


# ---------------------------------------------------------------------------
# host <-> limb conversion (numpy, host side)
# ---------------------------------------------------------------------------

def int_to_limbs(x: int, n: int) -> np.ndarray:
    """Python int -> n uint32 limbs (radix 2**16, little-endian)."""
    if x < 0:
        raise ValueError("negative")
    out = np.zeros(n, dtype=np.uint32)
    for i in range(n):
        out[i] = x & 0xFFFF
        x >>= 16
    if x:
        raise ValueError("value does not fit in %d limbs" % n)
    return out


def limbs_to_int(limbs) -> int:
    """uint32 limb array -> Python int (host side, accepts un-normalized)."""
    arr = np.asarray(limbs, dtype=np.uint64)
    return sum(int(v) << (16 * i) for i, v in enumerate(arr))


def bytes_le_to_limbs(b: bytes, n: int) -> np.ndarray:
    return int_to_limbs(int.from_bytes(b, "little"), n)


def limbs_to_bytes_le(limbs, nbytes: int) -> bytes:
    return limbs_to_int(limbs).to_bytes(nbytes, "little")


# ---------------------------------------------------------------------------
# device ops
# ---------------------------------------------------------------------------

def carry_prop(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Full carry propagation.  Input limbs may be up to 2**32-1.

    Returns (normalized limbs in [0, 2**16), carry out of the top limb).
    Sequential over limbs (n is small and static: 16..50), vectorized over
    the batch.
    """
    n = x.shape[-1]
    out = []
    carry = jnp.zeros(x.shape[:-1], dtype=jnp.uint32)
    for i in range(n):
        v = x[..., i] + carry
        out.append(v & MASK16)
        carry = v >> LIMB_BITS
    return jnp.stack(out, axis=-1), carry


def mul_raw(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook product of an na-limb and nb-limb number.

    Returns na+nb limbs, each < 2**21 (un-normalized but overflow-free):
    every 16x16 partial product is split into (lo, hi) halves, and at most
    ~2*min(na,nb) halves (< 2**16 each) land on any output limb.
    Inputs must be normalized (< 2**16 per limb).
    """
    na, nb = a.shape[-1], b.shape[-1]
    p = a[..., :, None] * b[..., None, :]          # (..., na, nb) each < 2**32
    lo = p & MASK16
    hi = p >> LIMB_BITS
    # anti-diagonal sums via the skew-reshape trick: pad each row i to width
    # nb+na, flatten, drop the last na elements, reshape to rows of width
    # nb+na-1 -- row i is now the original row right-shifted by i columns.
    t_lo = _antidiag_sum(lo)                       # (..., na+nb-1), < 2**20
    t_hi = _antidiag_sum(hi)
    zero = jnp.zeros_like(t_lo[..., :1])
    return jnp.concatenate([t_lo, zero], axis=-1) + \
        jnp.concatenate([zero, t_hi], axis=-1)


def _antidiag_sum(p: jnp.ndarray) -> jnp.ndarray:
    """Sum p[..., i, j] over equal i+j -> (..., na+nb-1)."""
    na, nb = p.shape[-2], p.shape[-1]
    w = na + nb
    pad = [(0, 0)] * (p.ndim - 1) + [(0, na)]
    skew = jnp.pad(p, pad).reshape(p.shape[:-2] + (na * w,))
    skew = skew[..., :na * (w - 1)].reshape(p.shape[:-2] + (na, w - 1))
    return skew.sum(axis=-2, dtype=jnp.uint32)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Normalized product: na+nb limbs in [0, 2**16)."""
    out, carry = carry_prop(mul_raw(a, b))
    # carry out of the top limb of an exact-width product is always zero
    return out


def ge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a >= b for normalized equal-width limb arrays; returns bool array."""
    # lexicographic compare from the top limb down
    n = a.shape[-1]
    gt = jnp.zeros(a.shape[:-1], dtype=bool)
    eq = jnp.ones(a.shape[:-1], dtype=bool)
    for i in range(n - 1, -1, -1):
        gt = gt | (eq & (a[..., i] > b[..., i]))
        eq = eq & (a[..., i] == b[..., i])
    return gt | eq


def sub_exact(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b for normalized limbs with a >= b (borrow chain)."""
    n = a.shape[-1]
    out = []
    borrow = jnp.zeros(a.shape[:-1], dtype=jnp.uint32)
    for i in range(n):
        v = a[..., i] + jnp.uint32(LIMB_RADIX) - b[..., i] - borrow
        out.append(v & MASK16)
        borrow = jnp.uint32(1) - (v >> LIMB_BITS)
    return jnp.stack(out, axis=-1)


def cond_sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b if a >= b else a (branch-free select)."""
    take = ge(a, b)
    return jnp.where(take[..., None], sub_exact(a, b), a)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


def words32_to_limbs(words: jnp.ndarray) -> jnp.ndarray:
    """(..., n) uint32 little-endian words -> (..., 2n) radix-2**16 limbs."""
    lo = words & MASK16
    hi = words >> LIMB_BITS
    return jnp.stack([lo, hi], axis=-1).reshape(words.shape[:-1] + (2 * words.shape[-1],))


def limbs_to_words32(limbs: jnp.ndarray) -> jnp.ndarray:
    """(..., 2n) normalized limbs -> (..., n) uint32 little-endian words."""
    n2 = limbs.shape[-1]
    pairs = limbs.reshape(limbs.shape[:-1] + (n2 // 2, 2))
    return pairs[..., 0] | (pairs[..., 1] << LIMB_BITS)
