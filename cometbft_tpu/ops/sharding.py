"""Multi-chip signature verification: the batch IS the sequence axis
(SURVEY §5 "long-context"): shard it over a 1-D `jax.sharding.Mesh`
and let XLA insert the verdict collectives over ICI.

This is the production analog of __graft_entry__.dryrun_multichip: the
per-signature kernel is embarrassingly parallel along the batch axis
(each signature verifies independently), so data-parallel sharding
needs no communication until the final verdict gather.  The RLC
whole-batch kernel stays single-chip per dispatch — with >1 chip the
caller splits commits ACROSS chips (one RLC per chip) instead, which
preserves the per-commit verdict structure.

Tests exercise this on the 8-virtual-device CPU mesh from
tests/conftest.py; the driver's dryrun does the same with the full
verify step.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import compile_hook
from . import ed25519 as dev


def device_count() -> int:
    try:
        return len(jax.devices())
    except Exception:
        return 1


def mesh_device_list(k: int | None = None):
    """Devices the DISPATCH layer round-robins windows over
    (crypto/dispatch.VerifyPipeline, crypto/mesh), or None for the
    single-device path.

    k > 1 asks for that many devices (clamped to what exists);
    k == 1 forces single-device; k None/0 defers to the
    COMETBFT_TPU_MESH_DEVICES env knob, which itself defaults to
    single-device — multi-device dispatch is OPT-IN, so a process that
    happens to see a virtual CPU mesh (tests force 8 devices) keeps its
    existing behavior unless a caller or the operator turns the mesh
    on.  0 via the env knob means "all local devices"."""
    if k is None or k == 0:
        raw = os.environ.get("COMETBFT_TPU_MESH_DEVICES")
        if raw is None:
            return None
        k = int(raw)
    try:
        devs = list(jax.devices())
    except Exception:
        return None
    if k <= 0:
        k = len(devs)
    k = min(k, len(devs))
    return devs[:k] if k > 1 else None


def auto_bucket(n: int, n_devices: int | None = None) -> int:
    """Batch bucket for n signatures that the mesh divides evenly:
    dev.bucket_size rounded up to a multiple of the device count, so a
    sharded dispatch never sees a ragged shard.  Buckets and meshes are
    almost always both powers of two, in which case this IS
    dev.bucket_size."""
    b = dev.bucket_size(n)
    nd = n_devices if n_devices is not None else device_count()
    if nd > 1 and b % nd:
        b = math.lcm(b, nd)
    return b


@functools.lru_cache(maxsize=1)
def _mesh() -> Mesh:
    return Mesh(np.array(jax.devices()), ("sig",))


@functools.lru_cache(maxsize=1)
def _sharded_verify():
    """Jitted verify step with batch-axis input/output shardings; the
    jit shards plain numpy inputs itself."""
    mesh = _mesh()
    shard_in = NamedSharding(mesh, P(None, "sig"))
    out = NamedSharding(mesh, P("sig"))
    return jax.jit(dev.verify_kernel,
                   in_shardings=(shard_in,) * 4,
                   out_shardings=out)


def verify_batch_sharded(a_words, r_words, s_limbs, h_limbs):
    """Per-signature verdicts with the batch axis sharded over every
    local device.  Caller guarantees batch % n_devices == 0 (pack to a
    bucket that divides; dev.BATCH_BUCKETS are powers of two)."""
    n = device_count()
    if n < 2 or a_words.shape[-1] % n != 0:
        return dev.verify_batch_device(a_words, r_words, s_limbs, h_limbs)
    with compile_hook.dispatch_scope("ed25519_persig_sharded",
                                     a_words.shape):
        return _sharded_verify()(a_words, r_words, s_limbs, h_limbs)
